"""Round 4: BASELINE config 2 measured through the REAL static-graph path
(static.Executor whole-program replay — VERDICT r3 weak#3) vs the direct
jit step, plus the exact BERT-base MFU row. Appends to /tmp/sweep_r4b.jsonl."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gc
import json
import time

import numpy as np

OUT = "/tmp/sweep_r4b.jsonl"


def log(rec):
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(rec, flush=True)


def resnet50_static(batch=128):
    """ResNet-50 train step built as a static Program and replayed by
    static.Executor (fluid executor.py:1065 role)."""
    import paddle_tpu as paddle
    import paddle_tpu.static as static
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.optimizer.optimizers import Momentum
    from paddle_tpu.vision.models import resnet50 as make

    try:
        paddle.seed(0)
        clear_mesh()
        gc.collect()
        init_mesh({"dp": 1})
        paddle.enable_static()
        try:
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [batch, 3, 224, 224], "float32")
                y = static.data("y", [batch], "int64")
                model = make(num_classes=1000)
                with paddle.amp.auto_cast(dtype="bfloat16", level="O2"):
                    out = model(x)
                    loss = paddle.nn.CrossEntropyLoss()(out, y)
                opt = Momentum(learning_rate=0.1, momentum=0.9,
                               parameters=model.parameters())
                opt.minimize(loss)
            exe = static.Executor()
            exe.run(startup)
            rng = np.random.default_rng(0)
            # pre-uploaded feeds (what the direct path measures too): the
            # tunnel's H2D bandwidth would otherwise dominate the step
            xv = paddle.to_tensor(
                rng.standard_normal((batch, 3, 224, 224)).astype("float32"))
            yv = paddle.to_tensor(
                rng.integers(0, 1000, (batch,)).astype("int64"))
            for _ in range(2):
                (lv,) = exe.run(main, feed={"x": xv, "y": yv},
                                fetch_list=[loss])
            float(np.asarray(lv))
            # return_numpy=True forces a device sync per exe.run (a tunnel
            # round-trip here; ~0.1 ms on a host-local chip). Measure both:
            # the API-faithful per-step-sync form and the lazy-fetch form
            # (return_numpy=False) that syncs once per rep like the direct
            # ParallelTrainer loop.
            for tag, rnumpy in (("sync-fetch", True), ("lazy-fetch", False)):
                times = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    for _ in range(5):
                        (lv,) = exe.run(main, feed={"x": xv, "y": yv},
                                        fetch_list=[loss],
                                        return_numpy=rnumpy)
                    float(np.asarray(lv._data if hasattr(lv, "_data") else lv))
                    times.append(time.perf_counter() - t0)
                med = sorted(times)[len(times) // 2]
                log({"experiment":
                     f"resnet50 b{batch} STATIC executor {tag}",
                     "images_s": round(batch * 5 / med, 1),
                     "times": [round(t, 3) for t in times]})
        finally:
            paddle.disable_static()
    except Exception as e:  # noqa: BLE001
        log({"experiment": f"resnet50 b{batch} STATIC",
             "error": f"{type(e).__name__}: {str(e)[:300]}"})
        gc.collect()


def resnet50_direct(batch=128):
    """Same model through ParallelTrainer (the r3 number) for the gap."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.optimizer.optimizers import Momentum
    from paddle_tpu.vision.models import resnet50 as make

    try:
        paddle.seed(0)
        clear_mesh()
        gc.collect()
        init_mesh({"dp": 1})
        model = make(num_classes=1000)
        ce = paddle.nn.CrossEntropyLoss()
        opt = Momentum(learning_rate=0.1, momentum=0.9,
                       parameters=model.parameters())
        trainer = ParallelTrainer(model, lambda o, y: ce(o, y), opt,
                                  dp_axis=None, compute_dtype="bfloat16")
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(
            rng.standard_normal((batch, 3, 224, 224)).astype("float32"))
        y = paddle.to_tensor(rng.integers(0, 1000, (batch,)).astype("int64"))
        for _ in range(2):
            l = trainer.step(x, y)
        float(np.asarray(l._data))
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(5):
                l = trainer.step(x, y)
            float(np.asarray(l._data))
            times.append(time.perf_counter() - t0)
        med = sorted(times)[len(times) // 2]
        log({"experiment": f"resnet50 b{batch} direct",
             "images_s": round(batch * 5 / med, 1),
             "times": [round(t, 3) for t in times]})
        del trainer, model
        gc.collect()
    except Exception as e:  # noqa: BLE001
        log({"experiment": f"resnet50 b{batch} direct",
             "error": f"{type(e).__name__}: {str(e)[:300]}"})
        gc.collect()


def bert_base_exact(batch=32, seq=512):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.bert import (
        BertForPretraining, BertPretrainingCriterion, bert_config)
    from paddle_tpu.optimizer.optimizers import AdamW

    try:
        cfg = bert_config("bert-base", hidden_dropout_prob=0.0,
                          attention_dropout_prob=0.0)
        paddle.seed(0)
        clear_mesh()
        gc.collect()
        init_mesh({"dp": 1})
        model = BertForPretraining(cfg)
        crit = BertPretrainingCriterion(cfg)
        opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                    moment_dtype="bfloat16")
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(
            rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32"))
        mlm = np.full((batch, seq), -100, "int64")
        mask_pos = rng.random((batch, seq)) < 0.15
        mlm[mask_pos] = rng.integers(0, cfg.vocab_size,
                                     mask_pos.sum()).astype("int64")
        nsp = rng.integers(0, 2, (batch, 1)).astype("int64")
        y = paddle.to_tensor(np.concatenate([mlm, nsp], axis=1))

        def fwd_loss(out, yy):
            pred, nsp_logits = out
            return crit(pred, yy[:, :seq], nsp_logits, yy[:, seq:])

        trainer = ParallelTrainer(model, fwd_loss, opt, dp_axis=None,
                                  compute_dtype="bfloat16")
        for _ in range(2):
            l = trainer.step(ids, y)
        float(np.asarray(l._data))
        times = []
        for _ in range(6):
            t0 = time.perf_counter()
            for _ in range(5):
                l = trainer.step(ids, y)
            float(np.asarray(l._data))
            times.append(time.perf_counter() - t0)
        med = sorted(times)[len(times) // 2]
        tput = batch * seq * 5 / med
        n_params = sum(int(np.prod(p._data.shape))
                       for p in model.parameters())
        flops_tok = (6 * n_params
                     + 12 * cfg.num_layers * seq * cfg.hidden_size
                     + 6 * cfg.hidden_size * cfg.vocab_size)
        mfu = tput * flops_tok / 197e12
        log({"experiment": f"bert-base b{batch} T{seq} exact",
             "tok_s": round(tput, 1), "mfu": round(mfu, 4),
             "params_m": round(n_params / 1e6, 1),
             "times": [round(t, 3) for t in times]})
        del trainer, model
        gc.collect()
    except Exception as e:  # noqa: BLE001
        log({"experiment": f"bert b{batch}",
             "error": f"{type(e).__name__}: {str(e)[:300]}"})
        gc.collect()


if __name__ == "__main__":
    resnet50_direct()
    resnet50_static()
    bert_base_exact()
