"""Round-5 sweep: remat-policy variants now that selective/core_attn save the
flash-attention forward outputs (residuals-as-inputs custom_vjp +
checkpoint_name tags — see ops/pallas/flash_attention.py SAVEABLE_NAMES).

Measures, on the one real chip:
  1.3B:  full+i3 (r4 headline), core_attn+i1, selective+i1, full+i1
  350m:  no-remat (r4 secondary), selective, core_attn
  350m pipeline arm (selective) — for the SAME-remat A/B ratio (VERDICT r4
  weak #3: r4 compared a selective pipeline arm against a no-remat plain arm)

Writes one JSON line per config to benchmarks/sweep_r5.jsonl.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "sweep_r5.jsonl")


def log(rec):
    rec["t"] = round(time.time(), 1)
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def train(name, batch, seq, steps, warmup, **kw):
    import bench
    return bench._train_tput(name, batch, seq, steps, warmup, True, **kw)


def pipeline(name, batch, seq, remat_policy):
    import gc

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
        build_gpt_pipeline_step,
    )
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.optimizer.optimizers import AdamW

    cfg = gpt_config(name, hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"pp": 1})
    model = GPTForPretraining(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                moment_dtype="bfloat16")
    step = build_gpt_pipeline_step(model, opt, microbatches=2,
                                   compute_dtype="bfloat16",
                                   remat_policy=remat_policy)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32")
    float(np.asarray(step(ids, ids)))
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(5):
            loss = step(ids, ids)
        float(np.asarray(loss))
        times.append(time.perf_counter() - t0)
    med = sorted(times)[len(times) // 2]
    del step, model
    gc.collect()
    return batch * seq * 5 / med


def main():
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax
    assert jax.devices()[0].platform == "tpu", "sweep needs the real chip"
    seq = 1024

    # --- 1.3B headline variants ---
    for tag, kw in [
        ("1.3b_full_i3_b4", dict(recompute=True, granularity="full",
                                 recompute_interval=3)),
        ("1.3b_core_attn_i1_b4", dict(recompute=True, granularity="core_attn",
                                      recompute_interval=1)),
        ("1.3b_selective_i1_b4", dict(recompute=True, granularity="selective",
                                      recompute_interval=1)),
        ("1.3b_core_attn_i3_b4", dict(recompute=True, granularity="core_attn",
                                      recompute_interval=3)),
    ]:
        try:
            tput, n, cfg = train("gpt3-1.3b", 4, seq, 10, 2,
                                 moment_dtype="bfloat16", **kw)
            log({"config": tag, "tok_s": round(tput, 1), "n_params": n})
        except Exception as e:
            log({"config": tag, "error": f"{type(e).__name__}: {e}"[:200]})

    # --- 350m plain arms (for same-remat pipeline A/B) ---
    for tag, kw in [
        ("350m_noremat_b8", dict()),
        ("350m_selective_i1_b8", dict(recompute=True, granularity="selective",
                                      recompute_interval=1)),
        ("350m_core_attn_i1_b8", dict(recompute=True, granularity="core_attn",
                                      recompute_interval=1)),
    ]:
        try:
            tput, n, cfg = train("gpt3-350m", 8, seq, 20, 2, **kw)
            log({"config": tag, "tok_s": round(tput, 1), "n_params": n})
        except Exception as e:
            log({"config": tag, "error": f"{type(e).__name__}: {e}"[:200]})

    # --- 350m pipeline arm, selective (same remat as plain selective) ---
    for pol in ("selective", "core_attn"):
        try:
            tp = pipeline("gpt3-350m", 8, seq, pol)
            log({"config": f"350m_pipeline_pp1_{pol}", "tok_s": round(tp, 1)})
        except Exception as e:
            log({"config": f"350m_pipeline_pp1_{pol}",
                 "error": f"{type(e).__name__}: {e}"[:200]})


if __name__ == "__main__":
    main()
