"""Round-3 perf sweep on the real chip: 350m/760m/1.3b variants.

Writes one JSON line per variant to /tmp/sweep_r3.jsonl as it goes
(tunnel runs can die; partial results must survive).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gc
import json
import time

import numpy as np

OUT = "/tmp/sweep_r3.jsonl"


def log(rec):
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(rec, flush=True)


def run_variant(name, batch, seq, *, recompute, granularity, moment_dtype,
                steps=5, reps=6, warmup=2):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining, GPTPretrainingCriterion, gpt_config)
    from paddle_tpu.optimizer.optimizers import AdamW

    cfg = gpt_config(name, hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                     use_recompute=recompute,
                     recompute_granularity=granularity)
    paddle.seed(0)
    clear_mesh()
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                moment_dtype=moment_dtype)
    trainer = ParallelTrainer(model, lambda o, y: crit(o, y), opt,
                              dp_axis=None, compute_dtype="bfloat16",
                              recompute=False)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32"))
    for _ in range(warmup):
        loss = trainer.step(ids, ids)
    float(np.asarray(loss._data))  # scalar readback = real sync
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = trainer.step(ids, ids)
        float(np.asarray(loss._data))
        times.append(time.perf_counter() - t0)
    med = sorted(times)[len(times) // 2]
    tput = batch * seq * steps / med
    n_params = sum(int(np.prod(p._data.shape)) for p in model.parameters())
    flops_tok = 6 * n_params + 6 * cfg.num_layers * seq * cfg.hidden_size
    mfu = tput * flops_tok / 197e12
    rec = {"variant": f"{name} b{batch} {granularity if recompute else 'none'} "
                      f"mom={moment_dtype}",
           "tok_s": round(tput, 1), "mfu": round(mfu, 4),
           "times": [round(t, 3) for t in times]}
    del trainer, model, opt
    gc.collect()
    return rec


VARIANTS = [
    # 350m: r2 best was b8 no-remat f32mom = 43.2k (50.2%)
    ("gpt3-350m", 8, dict(recompute=False, granularity="full", moment_dtype="float32")),
    ("gpt3-350m", 8, dict(recompute=False, granularity="full", moment_dtype="bfloat16")),
    ("gpt3-350m", 16, dict(recompute=False, granularity="full", moment_dtype="bfloat16")),
    ("gpt3-350m", 16, dict(recompute=True, granularity="selective", moment_dtype="bfloat16")),
    # 760m: r2 shipped b4 full-remat f32mom = 13.8k (33.6%); flash now engages (D=96 pad)
    ("gpt3-760m", 4, dict(recompute=True, granularity="selective", moment_dtype="bfloat16")),
    ("gpt3-760m", 8, dict(recompute=True, granularity="selective", moment_dtype="bfloat16")),
    ("gpt3-760m", 8, dict(recompute=True, granularity="full", moment_dtype="bfloat16")),
    ("gpt3-760m", 4, dict(recompute=True, granularity="selective", moment_dtype="float32")),
    ("gpt3-760m", 8, dict(recompute=False, granularity="full", moment_dtype="bfloat16")),
    # 1.3b on-device attempts
    ("gpt3-1.3b", 2, dict(recompute=True, granularity="full", moment_dtype="bfloat16")),
    ("gpt3-1.3b", 4, dict(recompute=True, granularity="full", moment_dtype="bfloat16")),
]


def main():
    seq = 1024
    for name, batch, kw in VARIANTS:
        tag = f"{name} b{batch} {kw}"
        try:
            rec = run_variant(name, batch, seq, **kw)
            log(rec)
        except Exception as e:
            log({"variant": tag, "error": f"{type(e).__name__}: {str(e)[:200]}"})
            gc.collect()


if __name__ == "__main__":
    main()
