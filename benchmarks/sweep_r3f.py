"""1.3B recompute_interval sweep: remat every k-th block only.
Appends to /tmp/sweep_r3f.jsonl."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gc
import json
import time

import numpy as np

OUT = "/tmp/sweep_r3f.jsonl"


def log(rec):
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(rec, flush=True)


def main():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining, GPTPretrainingCriterion, gpt_config)
    from paddle_tpu.optimizer.optimizers import AdamW

    seq = 1024
    for batch, interval in ((4, 2), (2, 2), (4, 3), (2, 3)):
        try:
            cfg = gpt_config("gpt3-1.3b", hidden_dropout_prob=0.0,
                             attention_dropout_prob=0.0, use_recompute=True,
                             recompute_granularity="full",
                             recompute_interval=interval)
            paddle.seed(0)
            clear_mesh()
            gc.collect()
            init_mesh({"dp": 1})
            model = GPTForPretraining(cfg)
            crit = GPTPretrainingCriterion(cfg)
            opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                        moment_dtype="bfloat16")
            trainer = ParallelTrainer(model, lambda o, y: crit(o, y), opt,
                                      dp_axis=None, compute_dtype="bfloat16")
            rng = np.random.default_rng(0)
            ids = paddle.to_tensor(
                rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32"))
            for _ in range(2):
                l = trainer.step(ids, ids)
            float(np.asarray(l._data))
            times = []
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(5):
                    l = trainer.step(ids, ids)
                float(np.asarray(l._data))
                times.append(time.perf_counter() - t0)
            med = sorted(times)[len(times) // 2]
            tput = batch * seq * 5 / med
            n_params = sum(int(np.prod(p._data.shape))
                           for p in model.parameters())
            mfu = tput * (6 * n_params + 6 * cfg.num_layers * seq
                          * cfg.hidden_size) / 197e12  # v5e bf16 peak
            log({"experiment": f"1.3b b{batch} interval{interval}",
                 "tok_s": round(tput, 1), "mfu": round(mfu, 4),
                 "times": [round(t, 3) for t in times]})
            del trainer, model
            gc.collect()
        except Exception as e:
            log({"experiment": f"1.3b b{batch} interval{interval}",
                 "error": f"{type(e).__name__}: {str(e)[:120]}"})
            gc.collect()


if __name__ == "__main__":
    main()
