"""BASELINE config 5: ERNIE-MoE pretrain throughput on one v5e chip
(all experts chip-local; the ep-parallel path is exercised by the CPU-mesh
tests + dryrun legs). Appends to /tmp/sweep_r3h.jsonl."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gc
import json
import time

import numpy as np

OUT = "/tmp/sweep_r3h.jsonl"


def log(rec):
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(rec, flush=True)


def main():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining, GPTPretrainingCriterion, gpt_config)
    from paddle_tpu.optimizer.optimizers import AdamW

    seq = 1024
    for batch, experts in ((8, 16), (4, 64)):
        try:
            cfg = gpt_config("ernie-moe-base", hidden_dropout_prob=0.0,
                             attention_dropout_prob=0.0,
                             num_experts=experts,
                             moe_capacity_factor=1.25)
            paddle.seed(0)
            clear_mesh()
            gc.collect()
            init_mesh({"dp": 1})
            model = GPTForPretraining(cfg)
            crit = GPTPretrainingCriterion(cfg)
            opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                        moment_dtype="bfloat16")
            trainer = ParallelTrainer(
                model, lambda o, y: crit(o, y) + model.aux_loss(), opt,
                dp_axis=None, compute_dtype="bfloat16")
            rng = np.random.default_rng(0)
            ids = paddle.to_tensor(
                rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32"))
            for _ in range(2):
                l = trainer.step(ids, ids)
            float(np.asarray(l._data))
            times = []
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(5):
                    l = trainer.step(ids, ids)
                float(np.asarray(l._data))
                times.append(time.perf_counter() - t0)
            med = sorted(times)[len(times) // 2]
            n_params = sum(int(np.prod(p._data.shape))
                           for p in model.parameters())
            log({"experiment": f"ernie-moe e{experts} b{batch} T{seq}",
                 "tok_s": round(batch * seq * 5 / med, 1),
                 "params_m": round(n_params / 1e6, 1),
                 "times": [round(t, 3) for t in times]})
            del trainer, model
            gc.collect()
        except Exception as e:
            log({"experiment": f"ernie-moe e{experts} b{batch}",
                 "error": f"{type(e).__name__}: {str(e)[:140]}"})
            gc.collect()


if __name__ == "__main__":
    main()
