"""Fair pipeline-overhead A/B on the chip: hybrid ppermute-scan step at
pp=1 (bf16 compute, selective per-layer remat) vs the plain bf16
ParallelTrainer step — gpt3-350m b8. Appends to /tmp/sweep_r3c.jsonl."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gc
import json
import time

import numpy as np

OUT = "/tmp/sweep_r3c.jsonl"


def log(rec):
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(rec, flush=True)


def sync(x):
    return float(np.asarray(x if not hasattr(x, "_data") else x._data))


def main():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
        build_gpt_pipeline_step)
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining, GPTPretrainingCriterion, gpt_config)
    from paddle_tpu.optimizer.optimizers import AdamW

    cfg = gpt_config("gpt3-350m", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    b, seq, steps, reps = 8, 1024, 5, 6
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (b, seq)).astype("int32")

    results = {}
    for m, policy, unroll in ((1, "selective", 1),):
        try:
            paddle.seed(0)
            clear_mesh()
            gc.collect()
            init_mesh({"pp": 1})
            model = GPTForPretraining(cfg)
            opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                        moment_dtype="bfloat16")
            step = build_gpt_pipeline_step(
                model, opt, microbatches=m, compute_dtype="bfloat16",
                remat_policy=policy, scan_unroll=unroll)
            sync(step(ids, ids))
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                for _ in range(steps):
                    l = step(ids, ids)
                sync(l)
                ts.append(time.perf_counter() - t0)
            results[f"pipe_m{m}_{policy}_u{unroll}"] = sorted(ts)[len(ts) // 2]
            log({"experiment": f"pipe_350m_b8_m{m}_{policy}_u{unroll}_bf16",
                 "median_s": round(results[f'pipe_m{m}_{policy}_u{unroll}'], 3),
                 "times": [round(t, 3) for t in ts]})
            del step, model, opt
            gc.collect()
        except Exception as e:
            log({"experiment": f"pipe_350m_b8_m{m}_{policy}_u{unroll}",
                 "error": f"{type(e).__name__}: {str(e)[:150]}"})
            gc.collect()

    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    model2 = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt2 = AdamW(learning_rate=1e-4, parameters=model2.parameters(),
                 moment_dtype="bfloat16")
    trainer = ParallelTrainer(model2, lambda o, y: crit(o, y), opt2,
                              dp_axis=None, compute_dtype="bfloat16")
    tids = paddle.to_tensor(ids)
    sync(trainer.step(tids, tids))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            l = trainer.step(tids, tids)
        sync(l)
        ts.append(time.perf_counter() - t0)
    plain = sorted(ts)[len(ts) // 2]
    log({"experiment": "plain_350m_b8_bf16", "median_s": round(plain, 3),
         "times": [round(t, 3) for t in ts]})
    best = min(results.values()) if results else None
    if best:
        log({"experiment": "pipeline_step_overhead",
             "overhead": round(best / plain - 1, 4),
             "best_pipe_s": round(best, 3), "plain_s": round(plain, 3)})


if __name__ == "__main__":
    main()
