"""Round-3 chip experiments, part 2:
1. pipeline-step overhead: hybrid ppermute-scan step (pp=1 mesh) vs plain
   ParallelTrainer GSPMD step on gpt3-350m — interleaved A/B, medians.
2. eager GPT-block dispatch: op-by-op vs transparent jit-forward.
3. 1.3b selective-remat attempt (beat the 50.2% b4 full-remat number).

Appends JSON lines to /tmp/sweep_r3b.jsonl.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gc
import json
import time

import numpy as np

OUT = "/tmp/sweep_r3b.jsonl"


def log(rec):
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(rec, flush=True)


def sync(x):
    return float(np.asarray(x if not hasattr(x, "_data") else x._data))


def pipeline_overhead():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
        build_gpt_pipeline_step)
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining, GPTPretrainingCriterion, gpt_config)
    from paddle_tpu.optimizer.optimizers import AdamW

    cfg = gpt_config("gpt3-350m", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    b, seq, steps, reps = 4, 1024, 5, 6

    paddle.seed(0)
    clear_mesh()
    init_mesh({"pp": 1})
    model = GPTForPretraining(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                moment_dtype="bfloat16")
    pipe_step = build_gpt_pipeline_step(model, opt, microbatches=2)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (b, seq)).astype("int32")

    sync(pipe_step(ids, ids))
    t_pipe = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            l = pipe_step(ids, ids)
        sync(l)
        t_pipe.append(time.perf_counter() - t0)
    del pipe_step, model, opt
    gc.collect()

    paddle.seed(0)
    clear_mesh()
    init_mesh({"dp": 1})
    model2 = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt2 = AdamW(learning_rate=1e-4, parameters=model2.parameters(),
                 moment_dtype="bfloat16")
    trainer = ParallelTrainer(model2, lambda o, y: crit(o, y), opt2,
                              dp_axis=None, compute_dtype="bfloat16")
    tids = paddle.to_tensor(ids)
    sync(trainer.step(tids, tids))
    t_plain = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            l = trainer.step(tids, tids)
        sync(l)
        t_plain.append(time.perf_counter() - t0)
    mp = sorted(t_pipe)[len(t_pipe) // 2]
    mq = sorted(t_plain)[len(t_plain) // 2]
    log({"experiment": "pipeline_overhead_350m_pp1_m2_b4",
         "pipe_s": round(mp, 3), "plain_s": round(mq, 3),
         "overhead": round(mp / mq - 1, 4),
         "pipe_times": [round(t, 3) for t in t_pipe],
         "plain_times": [round(t, 3) for t in t_plain]})
    del trainer, model2
    gc.collect()


def eager_block():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.models.gpt import GPTDecoderLayer, gpt_config

    cfg = gpt_config("gpt3-350m", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    clear_mesh()
    init_mesh({"dp": 1})
    paddle.seed(0)
    block = GPTDecoderLayer(cfg)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(
        rng.standard_normal((8, 1024, cfg.hidden_size)).astype("float32"))

    def fwd_bwd():
        out = block(x)
        loss = (out * out).mean()
        loss.backward()
        for p in block.parameters():
            p.clear_grad()
        return loss

    results = {}
    for mode, iters in (("false", 3), ("force", 20)):
        paddle.set_flags({"FLAGS_eager_layer_jit": mode})
        sync(fwd_bwd())  # compile/warm
        t0 = time.perf_counter()
        for _ in range(iters):
            l = fwd_bwd()
        sync(l)
        results[mode] = (time.perf_counter() - t0) / iters
    paddle.set_flags({"FLAGS_eager_layer_jit": "true"})
    log({"experiment": "eager_gpt_block_fwdbwd_350m_b8",
         "op_by_op_s": round(results["false"], 4),
         "jit_forward_s": round(results["force"], 4),
         "speedup": round(results["false"] / results["force"], 2)})
    gc.collect()


def big_model_variants():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining, GPTPretrainingCriterion, gpt_config)
    from paddle_tpu.optimizer.optimizers import AdamW

    for name, batch, gran in (("gpt3-1.3b", 4, "selective"),
                              ("gpt3-1.3b", 6, "full"),
                              ("gpt3-1.3b", 8, "full")):
        try:
            cfg = gpt_config(name, hidden_dropout_prob=0.0,
                             attention_dropout_prob=0.0, use_recompute=True,
                             recompute_granularity=gran)
            paddle.seed(0)
            clear_mesh()
            gc.collect()
            init_mesh({"dp": 1})
            model = GPTForPretraining(cfg)
            crit = GPTPretrainingCriterion(cfg)
            opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                        moment_dtype="bfloat16")
            trainer = ParallelTrainer(model, lambda o, y: crit(o, y), opt,
                                      dp_axis=None, compute_dtype="bfloat16")
            rng = np.random.default_rng(0)
            ids = paddle.to_tensor(
                rng.integers(0, cfg.vocab_size, (batch, 1024)).astype("int32"))
            for _ in range(2):
                l = trainer.step(ids, ids)
            sync(l)
            times = []
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(5):
                    l = trainer.step(ids, ids)
                sync(l)
                times.append(time.perf_counter() - t0)
            med = sorted(times)[len(times) // 2]
            tput = batch * 1024 * 5 / med
            n_params = sum(int(np.prod(p._data.shape))
                           for p in model.parameters())
            mfu = tput * (6 * n_params + 6 * 24 * 1024 * cfg.hidden_size) / 197e12
            log({"experiment": f"{name} b{batch} {gran} bf16mom",
                 "tok_s": round(tput, 1), "mfu": round(mfu, 4),
                 "times": [round(t, 3) for t in times]})
            del trainer, model
            gc.collect()
        except Exception as e:
            log({"experiment": f"{name} b{batch} {gran}",
                 "error": f"{type(e).__name__}: {str(e)[:160]}"})
            gc.collect()


if __name__ == "__main__":
    pipeline_overhead()
    big_model_variants()
