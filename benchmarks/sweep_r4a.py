"""Round 4: ERNIE-MoE expert-count scaling on one v5e chip with the
scatter/gather (compact) dispatch — 16/32/64 experts (VERDICT r3 weak#1:
the 64-expert einsum-dispatch variant crashed the remote compiler).
Appends to /tmp/sweep_r4a.jsonl."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gc
import json
import time

import numpy as np

OUT = "/tmp/sweep_r4a.jsonl"


def log(rec):
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(rec, flush=True)


def main():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.models.gpt import (
        GPTForPretraining, GPTPretrainingCriterion, gpt_config)
    from paddle_tpu.optimizer.optimizers import AdamW

    seq = 1024
    for batch, experts in ((8, 16), (8, 32), (4, 64)):
        try:
            cfg = gpt_config("ernie-moe-base", hidden_dropout_prob=0.0,
                             attention_dropout_prob=0.0,
                             num_experts=experts,
                             moe_capacity_factor=1.25)
            paddle.seed(0)
            clear_mesh()
            gc.collect()
            init_mesh({"dp": 1})
            model = GPTForPretraining(cfg)
            crit = GPTPretrainingCriterion(cfg)
            opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                        moment_dtype="bfloat16")
            trainer = ParallelTrainer(
                model, lambda o, y: crit(o, y) + model.aux_loss(), opt,
                dp_axis=None, compute_dtype="bfloat16")
            rng = np.random.default_rng(0)
            ids = paddle.to_tensor(
                rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32"))
            for _ in range(2):
                l = trainer.step(ids, ids)
            float(np.asarray(l._data))
            times = []
            for _ in range(6):
                t0 = time.perf_counter()
                for _ in range(5):
                    l = trainer.step(ids, ids)
                float(np.asarray(l._data))
                times.append(time.perf_counter() - t0)
            med = sorted(times)[len(times) // 2]
            tok_s = batch * seq * 5 / med

            # params: total + activated (dense + top-2/e of expert weights)
            n_params = 0
            n_expert = 0
            for n, p in model.named_parameters():
                sz = int(np.prod(p._data.shape))
                n_params += sz
                if ".experts." in n or n.endswith(
                        (".w1", ".b1", ".w2", ".b2")):
                    n_expert += sz
            n_active = (n_params - n_expert) + n_expert * min(2, experts) / experts
            # MoE MFU convention: 6 * activated params * tokens/s vs peak
            peak = 197e12  # v5e bf16
            mfu = 6 * n_active * tok_s / peak
            log({"experiment": f"ernie-moe e{experts} b{batch} T{seq} compact",
                 "tok_s": round(tok_s, 1),
                 "params_m": round(n_params / 1e6, 1),
                 "active_params_m": round(n_active / 1e6, 1),
                 "mfu_active": round(mfu, 4),
                 "times": [round(t, 3) for t in times]})
            del trainer, model, opt
        except Exception as ex:  # noqa: BLE001
            log({"experiment": f"ernie-moe e{experts} b{batch}",
                 "error": f"{type(ex).__name__}: {str(ex)[:300]}"})
            gc.collect()


if __name__ == "__main__":
    main()
