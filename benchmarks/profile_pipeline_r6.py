"""Round-6 pipeline profile: the measurement VERDICT r5 asked for.

Writes ``benchmarks/pipeline_profile_r6.json`` — a machine-readable
breakdown of the pipeline train step into named, DIRECTLY-probed regions
(paddle_tpu.profiler.pipeline; nothing attributed by elimination):

* a **scheduled leg** (pp=2) exercising the r6 overlap-optimized 1F1B tick:
  per-tick stage compute vs. boundary ppermute vs. inject vs. CE head vs.
  bookkeeping, plus per-step forward/backward vs. grad reduce vs. optimizer
  apply vs. host dispatch.
* a **pp=1 leg** matching the bench.py `pipeline_step_ratio` arm's shape
  (microbatches=2, selective remat) — the machinery the ratio measures.
* a **profiler A/B** on the pp=1 leg: steps/sec with the timer registry
  disabled (default) vs enabled, demonstrating the zero-overhead-when-
  disabled property (annotations compile away; only the host span differs).

On a TPU host the legs run the real bench shapes; on CPU the mesh is the
8-virtual-device harness with scaled shapes (the breakdown structure, not
the absolute times, is the artifact's point there — the device field says
which).
"""
import gc
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "pipeline_profile_r6.json")


def build_leg(name, axes, microbatches, overrides, batch, seq,
              compute_dtype=None, remat_policy="full"):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
        build_gpt_pipeline_step,
    )
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.optimizer.optimizers import AdamW

    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **overrides)
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh(axes)
    model = GPTForPretraining(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                moment_dtype="bfloat16")
    step = build_gpt_pipeline_step(model, opt, microbatches=microbatches,
                                   compute_dtype=compute_dtype,
                                   remat_policy=remat_policy)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32")
    return step, ids


def profiler_ab(step, ids, steps=4, rounds=5):
    """steps/sec with timers disabled vs enabled (the zero-overhead check).
    The arms alternate round-robin and each takes its best round, so host
    load drift cancels out of the comparison."""
    import jax

    from paddle_tpu.profiler import disable_timers, enable_timers, reset_timers

    def run():
        jax.block_until_ready(step(ids, ids))  # warm / sync
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, ids)
        jax.block_until_ready(loss)
        return (time.perf_counter() - t0) / steps

    times = {"off": [], "on": []}
    try:
        for _ in range(rounds):
            disable_timers()
            times["off"].append(run())
            enable_timers()
            times["on"].append(run())
    finally:
        disable_timers()
        reset_timers()
    off, on = min(times["off"]), min(times["on"])
    return {
        "timers_off_steps_per_s": round(1 / off, 4),
        "timers_on_steps_per_s": round(1 / on, 4),
        "enabled_overhead_fraction": round(on / off - 1, 4),
    }


def main():
    import jax

    from paddle_tpu.profiler.pipeline import (
        profile_pipeline_step,
        update_profile,
    )

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    legs = {}

    if on_tpu:
        # the bench.py ratio arm, exactly (350m pp=1 mb=2 selective bf16)
        step, ids = build_leg("gpt3-350m", {"pp": 1}, 2, {}, 8, 1024,
                              compute_dtype="bfloat16",
                              remat_policy="selective")
        legs["pp1_bench_arm"] = profile_pipeline_step(step, ids, ids)
        legs["profiler_ab_pp1"] = profiler_ab(step, ids)
        del step
        gc.collect()
        if len(jax.devices()) >= 2:
            step, ids = build_leg("gpt3-350m", {"pp": 2}, 4, {}, 8, 1024,
                                  compute_dtype="bfloat16",
                                  remat_policy="selective")
            legs["pp2_scheduled"] = profile_pipeline_step(step, ids, ids)
    else:
        # reps=7: this shared CPU box has 2 cores under an 8-device mesh; the
        # interleaved rounds + best-case estimator keep the ratios stable
        overrides = dict(vocab_size=512, hidden_size=256, num_layers=4,
                         num_attention_heads=8, max_position_embeddings=256)
        step, ids = build_leg("gpt2-small", {"pp": 2}, 4, overrides, 8, 256)
        legs["pp2_scheduled"] = profile_pipeline_step(step, ids, ids,
                                                      steps=3, reps=7)
        del step
        gc.collect()
        step, ids = build_leg("gpt2-small", {"pp": 1}, 2, overrides, 8, 256,
                              remat_policy="selective")
        legs["pp1_bench_arm"] = profile_pipeline_step(step, ids, ids,
                                                      steps=3, reps=7)
        legs["profiler_ab_pp1"] = profiler_ab(step, ids, steps=5)

    # read-merge-write (same path bench.py uses), so the two writers'
    # legs compose instead of clobbering each other
    update_profile(OUT, legs,
                   device={"platform": dev.platform,
                           "kind": getattr(dev, "device_kind", "")},
                   generated_by="benchmarks/profile_pipeline_r6.py")
    with open(OUT) as f:
        print(f.read())


if __name__ == "__main__":
    main()
