"""Ring-flash at 8k tokens/shard on the real chip (sp=1 ring: one hop =
the per-hop flash kernel + cross-hop merge machinery), A/B vs the einsum
online-softmax ring hop and the plain flash kernel. fwd+bwd timings.
Appends to /tmp/sweep_r3d.jsonl."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gc
import json
import time

import numpy as np

OUT = "/tmp/sweep_r3d.jsonl"


def log(rec):
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(rec, flush=True)


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.meta_parallel.sequence_parallel import (
        _ring_attention_flash, _ring_attention_raw)
    from paddle_tpu.distributed.spmd import P
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    b, h, t, d = 1, 8, 8192, 128
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.bfloat16)

    dist.init_mesh({"sp": 1})

    def time_fn(f, *args, iters=20, warmup=2):
        for _ in range(warmup):
            out = f(*args)
        float(jnp.sum(out[0] if isinstance(out, tuple) else out).astype(jnp.float32))
        reps = []
        for _ in range(6):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = f(*args)
            float(jnp.sum(out[0] if isinstance(out, tuple) else out)
                  .astype(jnp.float32))
            reps.append((time.perf_counter() - t0) / iters)
        return sorted(reps)[len(reps) // 2]

    # fwd+bwd through each attention path
    def make_fb(attn):
        def fb(q, k, v):
            def loss(q, k, v):
                return jnp.sum(attn(q, k, v).astype(jnp.float32))

            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        return jax.jit(fb)

    ring_flash = dist.run_on_mesh(
        make_fb(lambda q, k, v: _ring_attention_flash(
            q, k, v, "sp", True, None, None)),
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=(P(None, None, "sp", None),) * 3)
    try:
        ms = time_fn(ring_flash, q, k, v) * 1e3
        log({"experiment": "ring_flash_sp1_T8192_D128_bf16_fwdbwd",
             "ms": round(ms, 2)})
    except Exception as e:
        log({"experiment": "ring_flash_8k", "error": str(e)[:200]})
    gc.collect()

    plain_flash = make_fb(lambda q, k, v: flash_attention(q, k, v, causal=True))
    try:
        ms = time_fn(plain_flash, q, k, v) * 1e3
        log({"experiment": "plain_flash_T8192_D128_bf16_fwdbwd",
             "ms": round(ms, 2)})
    except Exception as e:
        log({"experiment": "plain_flash_8k", "error": str(e)[:200]})
    gc.collect()

    ring_einsum = dist.run_on_mesh(
        make_fb(lambda q, k, v: _ring_attention_raw(q, k, v, "sp", True, None)),
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=(P(None, None, "sp", None),) * 3)
    try:
        ms = time_fn(ring_einsum, q, k, v, iters=5) * 1e3
        log({"experiment": "ring_einsum_sp1_T8192_D128_fwdbwd",
             "ms": round(ms, 2)})
    except Exception as e:
        log({"experiment": "ring_einsum_8k",
             "error": f"{type(e).__name__}: {str(e)[:160]}"})


if __name__ == "__main__":
    main()
