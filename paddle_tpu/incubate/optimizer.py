"""Incubate optimizers: LookAhead and ModelAverage.

Parity: python/paddle/incubate/optimizer/lookahead.py (k-step slow/fast
weight interpolation) and fluid/optimizer.py ModelAverage:3927-region
(accumulated parameter averages with apply()/restore()).
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """Wraps an inner optimizer: every k steps the slow weights move
    alpha toward the fast weights and the fast weights reset to them."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        self._slow = None

    def _params(self):
        return list(self.inner_optimizer._param_groups)

    def step(self):
        # slow-weight baseline is the PRE-first-step parameter values
        # (reference lookahead.py initializes slow params at construction)
        if self._slow is None:
            self._slow = [np.asarray(p._data) for p in self._params()]
        self.inner_optimizer.step()
        self._step_count += 1
        params = self._params()
        if self._step_count % self.k == 0:
            for p, slow in zip(params, self._slow):
                new_slow = slow + self.alpha * (np.asarray(p._data) - slow)
                p._set_data(jnp.asarray(new_slow))
            self._slow = [np.asarray(p._data) for p in params]

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, []

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_count
        return sd

    def __getattr__(self, name):
        if name == "inner_optimizer":  # not yet set (e.g. during deepcopy)
            raise AttributeError(name)
        return getattr(self.inner_optimizer, name)


class ModelAverage:
    """Running average of parameter values over a sliding window; apply()
    swaps the averages in for evaluation, restore() swaps back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000, name=None):
        self._rate = float(average_window_rate)
        self._min_w = int(min_average_window)
        self._max_w = int(max_average_window)
        self._params = list(parameters or [])
        shape = lambda p: np.asarray(p._data).shape  # noqa: E731
        # reference average_accumulates scheme: a fresh window (sum_1),
        # a sealed previous window (sum_2) and long history (sum_3)
        self._sum1 = [np.zeros(shape(p), np.float64) for p in self._params]
        self._sum2 = [np.zeros(shape(p), np.float64) for p in self._params]
        self._sum3 = [np.zeros(shape(p), np.float64) for p in self._params]
        self._n1 = self._n2 = self._n3 = 0
        self._updates = 0
        self._backup = None

    def step(self):
        """Accumulate after the training optimizer's step (reference
        average_accumulates op: window = max(min_w, min(max_w,
        num_updates * rate)))."""
        self._updates += 1
        window = max(self._min_w, min(self._max_w,
                                      int(self._updates * self._rate)))
        if self._n1 >= window:
            # seal the fresh window: fold old sealed into history
            for s3, s2 in zip(self._sum3, self._sum2):
                s3 += s2
            self._n3 += self._n2
            self._sum2, self._n2 = self._sum1, self._n1
            self._sum1 = [np.zeros_like(s) for s in self._sum2]
            self._n1 = 0
            # history beyond the window is dropped (restart) like the
            # reference when total exceeds max_average_window
            if self._n3 + self._n2 > self._max_w:
                self._sum3 = [np.zeros_like(s) for s in self._sum3]
                self._n3 = 0
        for s, p in zip(self._sum1, self._params):
            s += np.asarray(p._data, np.float64)
        self._n1 += 1

    update = step

    def _totals(self):
        total_n = self._n1 + self._n2 + self._n3
        sums = [a + b + c for a, b, c in zip(self._sum1, self._sum2, self._sum3)]
        return sums, total_n

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        sums, total_n = self._totals()
        if total_n == 0:
            yield
            return
        self._backup = [np.asarray(p._data) for p in self._params]
        for s, p in zip(sums, self._params):
            p._set_data(jnp.asarray((s / total_n).astype(np.asarray(p._data).dtype)))
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is not None:
            for p, b in zip(self._params, self._backup):
                p._set_data(jnp.asarray(b))
            self._backup = None
