"""paddle_tpu.incubate — experimental subsystems (parity:
python/paddle/incubate + fluid/incubate: auto-checkpoint, ASP sparsity,
LookAhead/ModelAverage optimizers, fused softmax-mask ops, segment
reductions)."""
from . import checkpoint  # noqa: F401
from .operators import (  # noqa: F401
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)
from .optimizer import LookAhead, ModelAverage  # noqa: F401

__all__ = [
    "checkpoint", "asp", "LookAhead", "ModelAverage",
    "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
]


def __getattr__(name):
    if name == "asp":
        import importlib

        return importlib.import_module(".asp", __name__)
    raise AttributeError(name)
