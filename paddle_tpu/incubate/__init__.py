"""paddle_tpu.incubate — experimental subsystems (parity:
python/paddle/incubate + fluid/incubate)."""
from . import checkpoint  # noqa: F401

__all__ = ["checkpoint", "asp"]


def __getattr__(name):
    if name == "asp":
        import importlib

        return importlib.import_module(".asp", __name__)
    raise AttributeError(name)
