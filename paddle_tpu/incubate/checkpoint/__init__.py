from . import auto_checkpoint  # noqa: F401
from .auto_checkpoint import (  # noqa: F401
    AutoCheckpointChecker,
    TrainEpochRange,
    train_epoch_range,
)
