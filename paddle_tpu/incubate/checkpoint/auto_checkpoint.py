"""Auto-checkpoint: transparent epoch-level snapshot/resume.

Parity: ``paddle.fluid.incubate.checkpoint.auto_checkpoint``
(/root/reference/python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py —
``TrainEpochRange``:265 wraps the epoch loop and snapshots executor state,
``AutoCheckpointChecker``:71 reads PADDLE_RUNNING_ENV to decide activation;
snapshots go through checkpoint_saver.py keyed by job id).

TPU-native: snapshots use the sharded CheckpointManager
(framework/checkpoint.py) instead of HDFS scope dumps. Activation protocol is
kept: ``PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT`` plus
``PADDLE_JOB_ID`` and ``PADDLE_EDL_HDFS_CHECKPOINT_PATH`` (any writable dir
here) — reference launch scripts work unchanged.
"""
from __future__ import annotations

import os
import time
from typing import Optional

from ...framework.checkpoint import CheckpointManager

__all__ = ["AutoCheckpointChecker", "TrainEpochRange", "train_epoch_range"]


class AutoCheckpointChecker:
    """Reads the activation env protocol (parity: auto_checkpoint.py:71)."""

    def __init__(self):
        self.running_env = os.environ.get("PADDLE_RUNNING_ENV", "")
        self.job_id = os.environ.get("PADDLE_JOB_ID", "")
        self.ckpt_path = os.environ.get("PADDLE_EDL_HDFS_CHECKPOINT_PATH", "")
        self.save_inter = int(os.environ.get("PADDLE_EDL_SAVE_CHECKPOINT_INTER", "900"))

    def valid(self) -> bool:
        return (
            self.running_env == "PADDLE_EDL_AUTO_CHECKPOINT"
            and bool(self.job_id)
            and bool(self.ckpt_path)
        )

    def job_dir(self, name: str) -> str:
        return os.path.join(self.ckpt_path, self.job_id, name)


class TrainEpochRange:
    """Iterate epochs, persisting progress so a relaunched job resumes where
    it stopped (parity: TrainEpochRange:265).

    Usage::

        r = TrainEpochRange(max_epoch_num=10, name="run1")
        r.attach(model=model, optimizer=opt)      # state to snapshot
        for epoch in r.get():
            train_one_epoch(...)

    On restart with the same env/job id, ``get()`` starts from the first
    unfinished epoch and restores attached model/optimizer state.
    """

    def __init__(self, max_epoch_num: int, name: str,
                 checkpoint_inter: Optional[int] = None, save_dir: Optional[str] = None):
        self.max_epoch_num = max_epoch_num
        self.name = name
        self._checker = AutoCheckpointChecker()
        self._model = None
        self._optimizer = None
        self._last_save = 0.0
        if save_dir is not None:
            self._dir = save_dir
            self._active = True
        elif self._checker.valid():
            self._dir = self._checker.job_dir(name)
            self._active = True
        else:
            self._dir = None
            self._active = False
        self.checkpoint_inter = (
            checkpoint_inter if checkpoint_inter is not None else self._checker.save_inter
        )
        self._mgr = CheckpointManager(self._dir) if self._active else None
        self.restored_from = None

    def attach(self, model=None, optimizer=None):
        self._model = model
        self._optimizer = optimizer
        return self

    @property
    def start_epoch(self) -> int:
        if not self._active:
            return 0
        latest = self._mgr.latest_step()
        return 0 if latest is None else latest + 1

    def get(self):
        start = self.start_epoch
        if start > 0:
            self._restore()
            self.restored_from = start - 1
        for epoch in range(start, self.max_epoch_num):
            yield epoch
            if self._active:
                now = time.time()
                if (now - self._last_save >= self.checkpoint_inter
                        or epoch == self.max_epoch_num - 1):
                    self._snapshot(epoch)
                    self._last_save = now

    # force a snapshot (e.g. from a preemption handler)
    def save(self, epoch: int):
        if self._active:
            self._snapshot(epoch)

    def _snapshot(self, epoch: int):
        state = {"extra": {"name": self.name}}
        if self._model is not None:
            state["model"] = dict(self._model.state_dict())
        if self._optimizer is not None:
            state["optimizer"] = dict(self._optimizer.state_dict())
        self._mgr.save(epoch, state, metadata={"epoch": epoch})

    def _restore(self):
        state, _ = self._mgr.load()
        if self._model is not None and "model" in state:
            self._model.set_state_dict(state["model"])
        if self._optimizer is not None and "optimizer" in state:
            self._optimizer.set_state_dict(state["optimizer"])


def train_epoch_range(max_epoch_num: int, name: str = "default", **kw):
    """Functional façade (parity: acp.train_epoch_range)."""
    r = TrainEpochRange(max_epoch_num, name, **kw)
    return r.get()
