"""Incubate fused operators: softmax_mask_fuse(_upper_triangle) and segment
reductions.

Parity: python/paddle/incubate/operators/softmax_mask_fuse.py (backed by
fused_softmax_mask op, operators/fused_softmax_mask_op.cu) and
incubate/tensor/math.py segment_* (segment_pool ops). TPU-native: softmax
with an added mask is a single XLA fusion — the CUDA op's raison d'être
(avoiding a materialized masked tensor) is what the compiler already does;
segment reductions map to jax.ops.segment_*.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops._primitive import primitive, unwrap, wrap

__all__ = [
    "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle",
    "fused_rotary_position_embedding",
    "fused_swiglu",
    "fused_dropout_add_ln",
    "segment_sum",
    "segment_mean",
    "segment_max",
    "segment_min",
]


@primitive
def _smf(x, mask):
    return jax.nn.softmax(x + mask, axis=-1)


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fused graph. x: (B, H, T, S); mask
    broadcastable additive mask (-10000 at masked positions)."""
    return _smf(x, mask)


@primitive
def _smf_ut(x):
    t, s = x.shape[-2], x.shape[-1]
    causal = jnp.tril(jnp.ones((t, s), bool), k=s - t)
    masked = jnp.where(causal, x, jnp.asarray(-1e4, x.dtype))
    return jax.nn.softmax(masked, axis=-1)


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (fused_softmax_mask_upper_triangle parity)."""
    return _smf_ut(x)


def _num_segments(segment_ids, num_segments):
    """Static segment count: explicit arg, else from concrete eager ids.
    Under jit tracing the count must be given explicitly."""
    if num_segments is not None:
        return int(num_segments)
    import numpy as np

    ids = unwrap(segment_ids)
    if hasattr(ids, "aval") and not hasattr(ids, "__array__"):
        raise ValueError(
            "segment ops need an explicit num_segments when traced under jit "
            "(segment_ids is abstract)")
    arr = np.asarray(ids)
    if arr.size == 0:
        return 0
    return int(arr.max()) + 1


def _seg(fn_name, data, segment_ids, num_segments=None):
    n = _num_segments(segment_ids, num_segments)
    fn = getattr(jax.ops, fn_name)

    @primitive
    def _op(data, ids):
        return fn(data, ids.astype(jnp.int32), num_segments=n)

    return _op(data, segment_ids)


def segment_sum(data, segment_ids, name=None, num_segments=None):
    return _seg("segment_sum", data, segment_ids, num_segments)


def segment_mean(data, segment_ids, name=None, num_segments=None):
    n = _num_segments(segment_ids, num_segments)

    @primitive
    def _mean(data, ids):
        ids = ids.astype(jnp.int32)
        s = jax.ops.segment_sum(data, ids, num_segments=n)
        cnt = jax.ops.segment_sum(
            jnp.ones(ids.shape + (1,) * (data.ndim - 1), data.dtype),
            ids, num_segments=n)
        return s / jnp.maximum(cnt, 1.0)

    return _mean(data, segment_ids)


def segment_max(data, segment_ids, name=None, num_segments=None):
    return _seg("segment_max", data, segment_ids, num_segments)


def segment_min(data, segment_ids, name=None, num_segments=None):
    return _seg("segment_min", data, segment_ids, num_segments)


# ---------------------------------------------------------------------------
# fused Pallas kernels (reference: operators/fused/ CUDA suite)
# ---------------------------------------------------------------------------
def fused_rotary_position_embedding(q, k, cos, sin, name=None):
    """Fused RoPE on [B, H, T, D] q/k (ops/pallas/rope.py; reference analog:
    the fused rotary kernels of the fused-attention family)."""
    from ..ops.pallas.rope import rope

    @primitive
    def _op(q, k):
        return rope(q, unwrap(cos), unwrap(sin)), rope(k, unwrap(cos), unwrap(sin))

    return _op(q, k)


def fused_swiglu(x, w_gate, w_up, name=None):
    """Fused silu(x@w_gate)*(x@w_up) (ops/pallas/swiglu.py; reference analog
    fused_transformer_op.h FFN fusion)."""
    from ..ops.pallas.swiglu import swiglu

    @primitive
    def _op(x, wg, wu):
        lead = x.shape[:-1]
        out = swiglu(x.reshape(-1, x.shape[-1]), wg, wu)
        return out.reshape(*lead, wg.shape[1])

    return _op(x, w_gate, w_up)


def fused_dropout_add_ln(x, residual, gamma, beta, p=0.0, epsilon=1e-5,
                         training=True, name=None):
    """Fused residual+dropout+LayerNorm returning (ln_out, new_residual)
    (ops/pallas/fused_ln.py; reference fused_dropout_helper.h /
    fused_layernorm_residual_dropout_bias.h)."""
    from ..ops.pallas.fused_ln import fused_residual_dropout_ln
    from ..random import split_key

    p_eff = float(p) if training else 0.0

    from ..static.program import recording_active

    if p_eff > 0.0 and recording_active():
        # static mode: sample the mask inside the traced computation from a
        # per-run feed key so each replayed step gets a fresh dropout pattern
        from ..static.program import record_rng_op

        def _traced(key, x, residual, gamma, beta):
            mask = jax.random.bernoulli(key, 1.0 - p_eff, x.shape)
            return fused_residual_dropout_ln(
                x, residual, gamma, beta, p=p_eff, epsilon=float(epsilon),
                mask=mask)

        return record_rng_op(_traced, "fused_dropout_add_ln",
                             (x, residual, gamma, beta))

    mask = None
    if p_eff > 0.0:
        mask = jax.random.bernoulli(split_key(), 1.0 - p_eff, unwrap(x).shape)

    @primitive
    def _op(x, residual, gamma, beta):
        return fused_residual_dropout_ln(
            x, residual, gamma, beta, p=p_eff, epsilon=float(epsilon), mask=mask)

    return _op(x, residual, gamma, beta)
