"""ASP — automatic 2:4 structured sparsity.

Parity: the reference's fluid/contrib/sparsity package (utils.py
create_mask/check_sparsity with MaskAlgo MASK_1D/MASK_2D_GREEDY/MASK_2D_BEST,
asp.py prune_model + decorate(OptimizerWithSparsityGuarantee)) and the fleet
``asp_optimizer`` meta-strategy.

TPU-native: masks are plain jax arrays multiplied into weights; the optimizer
wrapper re-applies masks after every step (the reference instead masks via an
extra op on the grad path). 2:4 patterns keep the MXU-friendly dense layout —
XLA does not exploit 2:4 sparsity hardware-wise, so this is a *model
compression/regularization* capability, kept for parity.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

__all__ = [
    "calculate_density",
    "create_mask",
    "check_mask_1d",
    "prune_model",
    "decorate",
    "reset_excluded_layers",
    "set_excluded_layers",
]

_excluded_layers: List[str] = []


def set_excluded_layers(param_names: List[str]):
    """Parity: sparsity.set_excluded_layers."""
    _excluded_layers.extend(param_names)


def reset_excluded_layers():
    _excluded_layers.clear()


def calculate_density(x) -> float:
    arr = np.asarray(x if not hasattr(x, "numpy") else x.numpy())
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def create_mask(tensor, func_name: str = "MASK_1D", n: int = 2, m: int = 4):
    """n:m mask keeping the n largest-magnitude entries per group of m along
    the last axis (parity: sparsity/utils.py create_mask MASK_1D)."""
    arr = np.asarray(tensor if not hasattr(tensor, "numpy") else tensor.numpy())
    shape = arr.shape
    if shape[-1] % m != 0:
        return np.ones_like(arr)  # reference skips non-multiple dims
    flat = np.abs(arr).reshape(-1, m)
    kth = np.argsort(flat, axis=1)[:, : m - n]  # indices of the m-n smallest
    mask = np.ones_like(flat)
    np.put_along_axis(mask, kth, 0.0, axis=1)
    return mask.reshape(shape).astype(arr.dtype)


def check_mask_1d(mat, n: int = 2, m: int = 4) -> bool:
    arr = np.asarray(mat if not hasattr(mat, "numpy") else mat.numpy())
    if arr.shape[-1] % m:
        return False
    groups = (arr.reshape(-1, m) != 0).sum(axis=1)
    return bool((groups <= n).all())


def _prunable_params(layer):
    """2D weights of Linear-like sublayers, excluding user-excluded names."""
    out = []
    for name, p in layer.named_parameters():
        if p.ndim != 2:
            continue
        if any(ex in (p.name or name) or ex in name for ex in _excluded_layers):
            continue
        out.append((name, p))
    return out


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True) -> Dict[str, np.ndarray]:
    """Apply n:m pruning to every 2D weight in ``model`` (parity:
    sparsity.prune_model). Returns the mask dict keyed by param name."""
    masks = {}
    for name, p in _prunable_params(model):
        mask = create_mask(p, n=n, m=m)
        p.set_value(p.numpy() * mask)
        masks[name] = mask
    model._asp_masks = masks
    return masks


class OptimizerWithSparsityGuarantee:
    """Wraps an optimizer so masks survive updates (parity: asp.py
    OptimizerWithSparsityGuarantee — the reference masks grads; re-masking
    params post-step is equivalent for n:m patterns and one fused op here)."""

    def __init__(self, optimizer, model=None, masks: Optional[Dict] = None):
        self._inner = optimizer
        self._model = model
        self._masks = masks

    def _mask_items(self):
        if self._masks is not None and self._model is not None:
            for name, p in self._model.named_parameters():
                if name in self._masks:
                    yield p, self._masks[name]

    def step(self):
        self._inner.step()
        for p, mask in self._mask_items():
            p.set_value(p.numpy() * mask)

    def minimize(self, loss, **kw):
        ret = self._inner.minimize(loss, **kw)
        for p, mask in self._mask_items():
            p.set_value(p.numpy() * mask)
        return ret

    def __getattr__(self, name):
        return getattr(self._inner, name)


def decorate(optimizer, model=None):
    masks = getattr(model, "_asp_masks", None) if model is not None else None
    return OptimizerWithSparsityGuarantee(optimizer, model, masks)
