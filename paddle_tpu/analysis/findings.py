"""Findings model for the static-analysis layer ("graph doctor").

Parity role: the reference framework reports compile-time program problems
through ProgramDesc verification passes and the inference pass registry's
pass-failure diagnostics; ``FLAGS_check_nan_inf`` instruments at runtime.
Here every check produces a structured :class:`Finding` — severity-ranked,
source-attributed (jaxpr ``source_info`` + the r6 profiler ``scope`` names
that survive into HLO metadata) — collected into an :class:`AnalysisReport`
that serializes to the JSON artifact under ``benchmarks/``.

:class:`AnalysisWarning` is the *warning-channel* form of a Finding: rules
that run inline inside another subsystem (e.g. the dy2static strictness
pass) emit their findings through :func:`warn_finding` so callers see a
normal, filterable Python warning that still carries the structured record.
"""
from __future__ import annotations

import dataclasses
import enum
import json
import time
import warnings
from typing import Any, Dict, List, Optional

__all__ = [
    "Severity",
    "Finding",
    "AnalysisWarning",
    "AnalysisReport",
    "warn_finding",
    "REPORT_SCHEMA_VERSION",
]

#: version of the analysis_report.json layout (bumped when keys change);
#: the ``--memory`` artifact carries its own MEMORY_SCHEMA_VERSION
REPORT_SCHEMA_VERSION = 2


class Severity(enum.IntEnum):
    """Ranked severities; HIGH findings gate CI (zero-HIGH smoke test)."""

    INFO = 0
    LOW = 1
    MEDIUM = 2
    HIGH = 3

    def __str__(self):  # "HIGH" not "Severity.HIGH" in reports
        return self.name


@dataclasses.dataclass
class Finding:
    """One diagnostic from one rule on one program point.

    ``scope`` is the profiler name_stack at the offending eqn (the same
    names ``profiler.scope``/``annotate`` thread into HLO metadata, r6);
    ``source`` is the Python ``file:line (function)`` that traced it.
    """

    rule: str
    severity: Severity
    message: str
    entry_point: str = ""
    scope: str = ""
    source: str = ""
    details: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": str(self.severity),
            "message": self.message,
            "entry_point": self.entry_point,
            "scope": self.scope,
            "source": self.source,
            "details": _jsonable(self.details),
        }

    def __str__(self):
        loc = " @ ".join(x for x in (self.scope, self.source) if x)
        head = f"[{self.severity}] {self.rule}: {self.message}"
        return f"{head} ({loc})" if loc else head


class AnalysisWarning(UserWarning):
    """Structured warning wrapping a :class:`Finding` (``.finding``)."""

    def __init__(self, finding: Finding):
        self.finding = finding
        super().__init__(str(finding))


def warn_finding(finding: Finding, stacklevel: int = 2):
    """Emit ``finding`` through the Python warning machinery (inline rules
    like the dy2static strictness pass report this way)."""
    warnings.warn(AnalysisWarning(finding), stacklevel=stacklevel + 1)
    return finding


class AnalysisReport:
    """Findings for one or more entry points + run metadata."""

    def __init__(self, findings: Optional[List[Finding]] = None,
                 meta: Optional[Dict[str, Any]] = None):
        self.findings: List[Finding] = list(findings or [])
        self.meta: Dict[str, Any] = dict(meta or {})

    def extend(self, findings):
        self.findings.extend(findings)
        return self

    def by_severity(self, severity: Severity) -> List[Finding]:
        return [f for f in self.findings if f.severity == severity]

    def at_least(self, severity: Severity) -> List[Finding]:
        return [f for f in self.findings if f.severity >= severity]

    def high(self) -> List[Finding]:
        return self.by_severity(Severity.HIGH)

    def counts(self) -> Dict[str, int]:
        out = {str(s): 0 for s in Severity}
        for f in self.findings:
            out[str(f.severity)] += 1
        return out

    def counts_by_rule(self) -> Dict[str, int]:
        """Rule name → finding count (sorted by count desc, then name) —
        the per-rule histogram the bench secondaries fold into their
        payloads."""
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items(), key=lambda kv: (-kv[1], kv[0])))

    def to_dict(self) -> dict:
        ordered = sorted(self.findings,
                         key=lambda f: (-int(f.severity), f.entry_point, f.rule))
        return {
            "schema_version": REPORT_SCHEMA_VERSION,
            "meta": dict(self.meta, generated_at=time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime())),
            "counts": self.counts(),
            "findings": [f.to_dict() for f in ordered],
        }

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def save(self, path: str):
        with open(path, "w") as fh:
            fh.write(self.to_json() + "\n")
        return path

    def table(self) -> str:
        """Fixed-width findings table (the CLI's human-readable view)."""
        if not self.findings:
            return "no findings"
        rows = [("SEV", "ENTRY POINT", "RULE", "MESSAGE")]
        for f in sorted(self.findings,
                        key=lambda f: (-int(f.severity), f.entry_point)):
            rows.append((str(f.severity), f.entry_point, f.rule, f.message))
        widths = [min(max(len(r[i]) for r in rows), 44) for i in range(3)]
        lines = []
        for r in rows:
            cells = [r[i][: widths[i]].ljust(widths[i]) for i in range(3)]
            lines.append("  ".join(cells) + "  " + r[3])
        return "\n".join(lines)


def _jsonable(v):
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)
