"""PRNG key-flow lint: dataflow rules over the def-use graph (r9 walker).

Parity role: the reference framework's determinism surface —
``paddle.seed`` / ``FLAGS_cudnn_deterministic`` / deterministic-op lists —
is a *runtime switch*; on TPU the equivalent discipline is structural:
every random draw must consume a key derived exactly once from the chain
(``split``/``fold_in``), or replay (resurrection r21, spec-decode r22,
``fast_forward_key`` continuation joins) silently diverges.  These rules
certify that statically, per entry point, on the flattened jaxpr:

* ``key-reuse``        — one key value consumed by ≥2 drawing prims
  without an intervening split (HIGH).  Sibling ``cond`` branches are
  exclusive and exempt; ``fold_in`` is the sanctioned multi-derivation
  and never pairs.
* ``key-discard``      — ``random_split`` outputs that are never consumed
  and never escape (MEDIUM): a chain desync waiting to happen — the
  producer advanced the chain, nobody owns the subkey.
* ``key-closure-const``— a key/seed baked into the program at trace time
  (closure-captured key constant, or ``random_seed`` of a literal):
  replay across process restarts re-traces with the same stream no matter
  what the caller seeds (HIGH).
* ``key-nonuniform``   — a draw whose key is rank-divergent along mesh
  axes (taint lattice) feeding a collective over those axes: every rank
  samples different values inside a region that must agree (HIGH).

Key identity is resolved through pure aliasing prims (``slice`` index
signatures keep ``split(k)[0]`` and ``split(k)[1]`` distinct); opaque
reshuffles (gather/dynamic_slice/...) resolve to a unique root so they
can never collide into a false pair.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Tuple

from .findings import Finding, Severity
from .graph import COLLECTIVE_PRIMS, DefUseGraph, Node
from .rules import Rule, register_rule

__all__ = [
    "KeyReuseRule",
    "KeyDiscardRule",
    "ClosureKeyRule",
    "NonuniformKeyRule",
    "keyflow_rules",
    "DRAWING_PRIMS",
    "RANDOM_PRIMS",
]


def keyflow_rules():
    """Fresh instances of just the four key-flow rules (the
    ``--determinism`` jaxpr plane; the default gate gets them via
    :func:`..rules.default_rules`)."""
    return [KeyReuseRule(), KeyDiscardRule(), ClosureKeyRule(),
            NonuniformKeyRule()]

#: prims that SPEND a key (each consumption must be a distinct derivation)
DRAWING_PRIMS = frozenset({
    "random_split", "random_bits", "random_gamma", "threefry2x32",
})

#: every PRNG prim (drawing + derivation + packing)
RANDOM_PRIMS = DRAWING_PRIMS | frozenset({
    "random_fold_in", "random_seed", "random_wrap", "random_unwrap",
})

#: value-preserving aliases the resolver walks BACKWARD through; ``slice``
#: contributes an index signature, the rest are transparent
_PASSTHROUGH = frozenset({
    "slice", "squeeze", "reshape", "broadcast_in_dim", "transpose",
    "convert_element_type", "random_wrap", "random_unwrap", "copy",
    "bitcast_convert_type", "stop_gradient",
})

#: reshuffles whose output is *some* key material but with data-dependent
#: or merged identity — resolved to a unique per-node root (conservative:
#: can never produce a reuse pair)
_OPAQUE = frozenset({
    "gather", "dynamic_slice", "select_n", "concatenate", "rev",
    "scatter", "dynamic_update_slice", "pad",
})


def _is_key_aval(aval) -> bool:
    """(shape, dtype, weak) triple: typed PRNG key or raw uint32 pair."""
    if not aval:
        return False
    shape, dtype, _ = aval
    if isinstance(dtype, str) and dtype.startswith("key<"):
        return True
    return (dtype == "uint32" and len(shape) >= 1
            and int(shape[-1]) == 2)


def _resolve(g: DefUseGraph, node: Node, operand: int,
             _max_depth: int = 64):
    """(root, signature) identity of ``node``'s ``operand`` value.

    ``root`` is a node idx, or a negative pseudo-def (entry arg / const /
    literal), or ``("opaque", idx)`` for unresolvable reshuffles.
    ``signature`` records the slice path taken from the root, so the two
    halves of one ``split`` stay distinct keys.
    """
    sig: List[Tuple] = []
    d = node.in_defs[operand]
    cur = g.nodes[d] if d >= 0 else None
    for _ in range(_max_depth):
        if cur is None:
            return d, tuple(sig)
        p = cur.prim
        if p == "slice":
            sig.append(("slice",
                        tuple(cur.params.get("start_indices", ()) or ()),
                        tuple(cur.params.get("limit_indices", ()) or ()),
                        tuple(cur.params.get("strides") or ())))
        elif p in _OPAQUE:
            return ("opaque", cur.idx), ()
        elif p not in _PASSTHROUGH:
            return cur.idx, tuple(sig)
        d = cur.in_defs[0] if cur.in_defs else -1
        cur = g.nodes[d] if d >= 0 else None
    return ("opaque", node.idx), ()  # depth bail-out: unique, no pairs


def _sibling_branches(p1: Tuple[str, ...], p2: Tuple[str, ...]) -> bool:
    """True when the two paths sit in different branches of one cond."""
    for a, b in zip(p1, p2):
        if a != b:
            return a.startswith("branch") and b.startswith("branch")
    return False


def _rev_adjacency(g: DefUseGraph) -> Dict[int, List[int]]:
    rev: Dict[int, List[int]] = defaultdict(list)
    for n in g.nodes:
        for d in set(n.in_defs):
            rev[d].append(n.idx)
    return rev


def _value_used(g: DefUseGraph, idx: int, rev, _seen=None) -> bool:
    """Does the value produced by node ``idx`` reach a real consumer or
    escape a jaxpr level?  Pure-passthrough consumers only count if their
    own outputs are used."""
    if _seen is None:
        _seen = set()
    if idx in _seen:
        return False
    _seen.add(idx)
    if idx in g.escaping:
        return True
    for c in rev.get(idx, ()):
        cn = g.nodes[c]
        if cn.prim in _PASSTHROUGH:
            if _value_used(g, c, rev, _seen):
                return True
        else:
            return True
    return False


def _key_operands(node: Node) -> List[int]:
    """Operand positions of ``node`` that carry key material."""
    if node.prim in ("random_split", "random_bits", "random_fold_in",
                     "random_gamma"):
        return [0] if node.in_avals else []
    if node.prim == "threefry2x32":
        return [0, 1][: len(node.in_avals)]
    return [i for i, a in enumerate(node.in_avals) if _is_key_aval(a)]


@register_rule
class KeyReuseRule(Rule):
    """One key consumed by ≥2 drawing prims without an intervening split."""

    name = "key-reuse"

    def run(self, target) -> List[Finding]:
        g = target.graph()
        groups: Dict[Tuple, List[Tuple[int, int]]] = defaultdict(list)
        for n in g.nodes:
            if n.prim not in DRAWING_PRIMS:
                continue
            for op in _key_operands(n):
                if not _is_key_aval(n.in_avals[op]):
                    continue
                groups[_resolve(g, n, op)].append((n.idx, op))
        findings: List[Finding] = []
        for (root, sig), consumers in groups.items():
            if isinstance(root, tuple):       # opaque: never a proven pair
                continue
            if len(consumers) < 2:
                continue
            # drop pairs that live in mutually-exclusive cond branches
            kept = []
            for c, _ in consumers:
                cn = g.nodes[c]
                if all(not _sibling_branches(cn.path, g.nodes[k].path)
                       for k, _ in kept):
                    kept.append((c, 0))
            if len(kept) < 2:
                continue
            first, second = g.nodes[kept[0][0]], g.nodes[kept[1][0]]
            where = " and ".join(
                f"eqn #{g.nodes[c].idx} '{g.nodes[c].prim}'"
                + (f" [{g.nodes[c].where}]" if g.nodes[c].where else "")
                for c, _ in kept)
            findings.append(self.finding(
                Severity.HIGH,
                f"key reused: one key value spent by {len(kept)} drawing "
                f"prims without an intervening split — {where}",
                node=second,
                root=root if isinstance(root, int) else str(root),
                signature=[list(s) for s in sig],
                consumers=[g.nodes[c].idx for c, _ in kept],
                consumer_prims=[g.nodes[c].prim for c, _ in kept],
                first_scope=first.name_stack, first_source=first.source))
        return findings


@register_rule
class KeyDiscardRule(Rule):
    """Split results (whole or subkey) that nothing consumes or escapes."""

    name = "key-discard"

    def run(self, target) -> List[Finding]:
        g = target.graph()
        rev = _rev_adjacency(g)
        findings: List[Finding] = []
        for n in g.nodes:
            if n.prim != "random_split":
                continue
            if not _value_used(g, n.idx, rev):
                findings.append(self.finding(
                    Severity.MEDIUM,
                    "split result entirely discarded: the chain advanced "
                    "but no subkey is consumed or escapes — dead "
                    "derivation (or a desynced continuation join)",
                    node=n, split=n.idx))
                continue
            # a subkey peeled off (slice/squeeze chain, possibly through
            # a random_unwrap for raw uint32 keys) and then dropped
            frontier = list(rev.get(n.idx, ()))
            seen = set(frontier)
            chain = []
            while frontier:
                c = frontier.pop()
                cn = g.nodes[c]
                if cn.prim == "slice":
                    chain.append(c)
                elif cn.prim in _PASSTHROUGH:
                    for c2 in rev.get(c, ()):
                        if c2 not in seen:
                            seen.add(c2)
                            frontier.append(c2)
            for c in sorted(chain):
                cn = g.nodes[c]
                if not _value_used(g, c, rev):
                    start = tuple(cn.params.get("start_indices", ()) or ())
                    findings.append(self.finding(
                        Severity.MEDIUM,
                        f"subkey discarded: split output index "
                        f"{start[0] if start else '?'} is peeled off but "
                        f"never consumed — a chain desync waiting to "
                        f"happen",
                        node=cn, split=n.idx, slice_start=list(start)))
        return findings


@register_rule
class ClosureKeyRule(Rule):
    """Key/seed baked into the traced program (const or literal)."""

    name = "key-closure-const"

    def run(self, target) -> List[Finding]:
        g = target.graph()
        findings: List[Finding] = []
        for n in g.nodes:
            if n.prim == "random_seed":
                d = n.in_defs[0] if n.in_defs else -1
                lit = bool(n.in_lits[0]) if n.in_lits else False
                if lit or d == -2:
                    what = "literal" if lit else "closure constant"
                    findings.append(self.finding(
                        Severity.HIGH,
                        f"seed baked at trace time ({what}): every replay "
                        f"of this program restarts the same stream "
                        f"regardless of the caller's seed",
                        node=n, kind=what))
                continue
            if n.prim not in DRAWING_PRIMS and n.prim != "random_fold_in":
                continue
            for op in _key_operands(n):
                if not _is_key_aval(n.in_avals[op]):
                    continue
                root, _ = _resolve(g, n, op)
                if root == -2:
                    findings.append(self.finding(
                        Severity.HIGH,
                        f"closure-captured key constant consumed by "
                        f"'{n.prim}': the key chain is frozen into the "
                        f"executable — replay across process restarts "
                        f"diverges from the seeded stream",
                        node=n, operand=op))
        return findings


@register_rule
class NonuniformKeyRule(Rule):
    """Rank-divergent key feeding a draw whose result reaches a
    collective over the divergent axes (taint lattice, r9)."""

    name = "key-nonuniform"

    def run(self, target) -> List[Finding]:
        g = target.graph()
        rev = _rev_adjacency(g)
        findings: List[Finding] = []
        for n in g.nodes:
            if n.prim not in DRAWING_PRIMS:
                continue
            taint = frozenset()
            for op in _key_operands(n):
                d = n.in_defs[op]
                if d >= 0:
                    taint |= g.nodes[d].nonuniform
            if not taint:
                continue
            hit = self._reaches_collective(g, rev, n.idx, taint)
            if hit is None:
                continue
            axes = sorted(set(hit.axes) & taint)
            findings.append(self.finding(
                Severity.HIGH,
                f"rank-divergent sampling: '{n.prim}' draws from a key "
                f"nonuniform along mesh axes {sorted(taint)} and the "
                f"result reaches collective '{hit.prim}' over "
                f"{axes} (eqn #{hit.idx}"
                + (f" [{hit.where}]" if hit.where else "") + ")",
                node=n, key_axes=sorted(taint),
                collective=hit.idx, collective_prim=hit.prim,
                collective_axes=axes))
        return findings

    @staticmethod
    def _reaches_collective(g, rev, start, taint):
        seen = {start}
        frontier = [start]
        while frontier:
            cur = frontier.pop()
            for c in rev.get(cur, ()):
                if c in seen:
                    continue
                seen.add(c)
                cn = g.nodes[c]
                if cn.prim in COLLECTIVE_PRIMS and set(cn.axes) & taint:
                    return cn
                frontier.append(c)
        return None
