"""Host-plane determinism rules + replay-certificate (seam) coverage.

The jaxpr half of the determinism doctor lives in
:mod:`paddle_tpu.analysis.keyflow`; this module is the HOST half, on the
r18 lockmodel machinery's turf (same module set, same annotation
philosophy as ``# hostrace:``):

* ``det-unordered-iter`` — iteration over a ``set``/``frozenset`` (or a
  ``next(iter(...))`` pick from one) feeding code in the serving /
  resilience planes.  CPython dicts iterate in insertion order, so the
  only iteration-order nondeterminism that can enter this codebase is a
  set — HIGH inside an ordering-decision function (tick/admit/schedule/
  route/...), MEDIUM elsewhere.
* ``det-wallclock`` — ``time.time``/``monotonic``/``perf_counter``
  influencing control flow inside an ordering-decision function: replay
  of the same transcript takes a different branch on a slower machine.
* ``det-ambient-rng`` — ambient ``random.*`` (the module-global stream),
  ``os.urandom``/``secrets``, ``uuid.uuid4`` and builtin ``hash()`` in
  the scanned planes.  ``random.Random(seed)`` instances are the
  sanctioned spelling and are exempt.

Audited intentional uses carry ``# det-ok: <reason>`` on the offending
line (or a comment-only line directly above, exactly like ``hostrace:``);
a suppressed site is reported at INFO with its reason so the audit trail
stays in the artifact.

**Replay-certificate coverage** (:func:`seam_coverage`): every seam name
registered in ``resilience/inject.py::POINTS`` must be (a) actually fired
somewhere in the package and (b) exercised by at least one *two-run
identical-fired-log twin test* — a test that runs a workload twice under
one schedule and asserts the ``fired_log()`` transcripts equal.  The scan
is static (AST over ``paddle_tpu/`` fire sites and ``tests/``), so a new
seam cannot land uncertified: uncovered ⇒ HIGH, fired-but-unregistered or
registered-but-never-fired ⇒ MEDIUM.
"""
from __future__ import annotations

import ast
import os
import re
import time
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .findings import AnalysisReport, Finding, Severity
from .lockmodel import default_host_paths

__all__ = [
    "DET_SCHEMA_VERSION",
    "DetFileContext",
    "det_rule_names",
    "run_det_rules",
    "seam_coverage",
    "coverage_findings",
    "analyze_determinism",
]

#: layout version of benchmarks/analysis_determinism.json
DET_SCHEMA_VERSION = 1

_DET_OK_RE = re.compile(r"#\s*det-ok:\s*(.*\S)")

#: function names that make an ordering DECISION (who runs / in what
#: order / who is evicted) — wall-clock or set-order inside these changes
#: the schedule itself, not just a metric
_ORDER_RE = re.compile(
    r"(tick|admit|schedul|rout|pick|select|take_|victim|sweep|assign|"
    r"shed|evict|order)", re.I)

_CLOCK_CALLS = {("time", "time"), ("time", "monotonic"),
                ("time", "perf_counter"), ("time", "time_ns"),
                ("time", "monotonic_ns"), ("time", "perf_counter_ns")}


class _DetAnnotations:
    """``# det-ok: reason`` sites (line → reason), with the same binding
    rule as the r18 hostrace annotations: a trailing comment binds to its
    own statement; a comment-ONLY line binds to the statement below."""

    def __init__(self, source: str):
        self.ok: Dict[int, str] = {}
        self.comment_only: Set[int] = set()
        for i, text in enumerate(source.splitlines(), start=1):
            if text.lstrip().startswith("#"):
                self.comment_only.add(i)
            m = _DET_OK_RE.search(text)
            if m:
                self.ok[i] = m.group(1).strip()

    def reason_at(self, line: int) -> Optional[str]:
        if line in self.ok:
            return self.ok[line]
        # a contiguous comment-only block directly above binds to this
        # statement (multi-line reasons read naturally)
        ln = line - 1
        while ln in self.comment_only:
            if ln in self.ok:
                return self.ok[ln]
            ln -= 1
        return None


class DetFileContext:
    """One scanned module: parsed tree + annotations + attribution."""

    def __init__(self, modname: str, path: str):
        self.modname = modname
        self.path = path
        with open(path, "r") as fh:
            self.source = fh.read()
        self.tree = ast.parse(self.source)
        self.ann = _DetAnnotations(self.source)
        self._func_of: Dict[int, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                end = getattr(node, "end_lineno", node.lineno)
                for ln in range(node.lineno, end + 1):
                    # innermost wins: later (nested) defs overwrite
                    self._func_of.setdefault(ln, node.name)

    def func_at(self, line: int) -> str:
        return self._func_of.get(line, "<module>")

    def where(self, line: int) -> Tuple[str, str]:
        fn = self.func_at(line)
        return (f"{self.modname}:{fn}",
                f"{os.path.basename(self.path)}:{line} ({fn})")


def _call_name(func: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """(base, attr) for ``base.attr(...)``, (None, name) for ``name(...)``."""
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return func.value.id, func.attr
    if isinstance(func, ast.Name):
        return None, func.id
    return None, None


def _mk(ctx: DetFileContext, rule: str, sev: Severity, line: int,
        message: str, **details) -> Finding:
    reason = ctx.ann.reason_at(line)
    scope, source = ctx.where(line)
    if reason is not None:
        sev = Severity.INFO
        message = f"audited (det-ok: {reason}) — {message}"
        details["det_ok"] = reason
    return Finding(rule=rule, severity=sev, message=message,
                   entry_point=ctx.modname, scope=scope, source=source,
                   details=dict(details, line=line))


# ---------------------------------------------------------------------------
# rule 1: unordered set iteration
# ---------------------------------------------------------------------------
def _set_names(fn: ast.AST) -> Set[str]:
    """Local names bound to set-typed values inside ``fn``."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_set_expr(node.value, out):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.target, ast.Name) and \
                _is_set_expr(node.value, out):
            out.add(node.target.id)
    return out


def _is_set_expr(node: ast.AST, set_names: Set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Name) and node.id in set_names:
        return True
    if isinstance(node, ast.Call):
        base, attr = _call_name(node.func)
        if base is None and attr in ("set", "frozenset"):
            return True
        # s.union(...), s.intersection(...), s.difference(...) on a set
        if attr in ("union", "intersection", "difference",
                    "symmetric_difference", "copy") and \
                isinstance(node.func, ast.Attribute) and \
                _is_set_expr(node.func.value, set_names):
            return True
    if isinstance(node, ast.BinOp) and \
            isinstance(node.op, (ast.BitOr, ast.BitAnd, ast.Sub,
                                 ast.BitXor)):
        return _is_set_expr(node.left, set_names) or \
            _is_set_expr(node.right, set_names)
    return False


def _rule_unordered_iter(ctx: DetFileContext) -> List[Finding]:
    findings: List[Finding] = []
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        names = _set_names(fn)
        ordering = bool(_ORDER_RE.search(fn.name))
        sev = Severity.HIGH if ordering else Severity.MEDIUM

        def flag(node, what):
            findings.append(_mk(
                ctx, "det-unordered-iter", sev, node.lineno,
                f"{what} in {'ordering-decision ' if ordering else ''}"
                f"function '{fn.name}': set iteration order varies per "
                f"process (PYTHONHASHSEED) — sort or use an "
                f"insertion-ordered structure", function=fn.name))

        for node in ast.walk(fn):
            if isinstance(node, ast.For) and \
                    _is_set_expr(node.iter, names):
                flag(node, "iteration over a set")
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                for gen in node.generators:
                    if _is_set_expr(gen.iter, names):
                        flag(node, "comprehension over a set")
            elif isinstance(node, ast.Call):
                # next(iter(s)) / min-free pick from a set
                base, attr = _call_name(node.func)
                if base is None and attr == "next" and node.args and \
                        isinstance(node.args[0], ast.Call):
                    inner = node.args[0]
                    ib, ia = _call_name(inner.func)
                    if ib is None and ia == "iter" and inner.args and \
                            _is_set_expr(inner.args[0], names):
                        flag(node, "next(iter(<set>)) pick")
                elif base is None and attr in ("sorted", "min", "max"):
                    continue  # order-normalizing consumers are the fix
    return findings


# ---------------------------------------------------------------------------
# rule 2: wall-clock influencing ordering decisions
# ---------------------------------------------------------------------------
def _clock_calls(fn: ast.AST) -> List[ast.Call]:
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                _call_name(node.func) in _CLOCK_CALLS:
            out.append(node)
    return out


def _test_exprs(fn: ast.AST) -> List[ast.AST]:
    """Every expression that steers control flow inside ``fn``."""
    tests: List[ast.AST] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            tests.append(node.test)
        elif isinstance(node, ast.Assert):
            tests.append(node.test)
        elif isinstance(node, (ast.ListComp, ast.SetComp,
                               ast.GeneratorExp, ast.DictComp)):
            for gen in node.generators:
                tests.extend(gen.ifs)
    return tests


def _rule_wallclock(ctx: DetFileContext) -> List[Finding]:
    findings: List[Finding] = []
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _ORDER_RE.search(fn.name):
            continue
        clocks = _clock_calls(fn)
        if not clocks:
            continue
        tests = _test_exprs(fn)
        test_nodes = set()
        for t in tests:
            test_nodes.update(id(x) for x in ast.walk(t))
        # names assigned a clock VALUE: the call itself or arithmetic on
        # it (a clock passed as an argument to another call — telemetry
        # spans, log records — does not make the result a time)
        def clock_valued(e: ast.AST) -> bool:
            if isinstance(e, ast.Call):
                return _call_name(e.func) in _CLOCK_CALLS
            if isinstance(e, ast.BinOp):
                return clock_valued(e.left) or clock_valued(e.right)
            if isinstance(e, ast.UnaryOp):
                return clock_valued(e.operand)
            if isinstance(e, ast.IfExp):
                return clock_valued(e.body) or clock_valued(e.orelse)
            return False

        clock_names: Dict[str, int] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and clock_valued(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        clock_names[t.id] = node.lineno
        flagged: Set[int] = set()

        def flag(line, how):
            if line in flagged:
                return
            flagged.add(line)
            findings.append(_mk(
                ctx, "det-wallclock", Severity.HIGH, line,
                f"wall-clock {how} steers control flow in "
                f"ordering-decision function '{fn.name}': replay takes a "
                f"different branch at a different speed — thread an "
                f"injectable 'now' (tick time) instead",
                function=fn.name))

        for c in clocks:                       # clock call inside a test
            if id(c) in test_nodes:
                flag(c.lineno, "call")
        for t in tests:                        # clock-derived name in one
            for x in ast.walk(t):
                if isinstance(x, ast.Name) and x.id in clock_names:
                    flag(clock_names[x.id], f"value '{x.id}'")
    return findings


# ---------------------------------------------------------------------------
# rule 3: ambient RNG / hash / urandom
# ---------------------------------------------------------------------------
def _rule_ambient_rng(ctx: DetFileContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        base, attr = _call_name(node.func)
        if base == "random" and attr is not None:
            if attr in ("Random", "SystemRandom"):
                continue  # seeded instance: the sanctioned spelling
            findings.append(_mk(
                ctx, "det-ambient-rng", Severity.HIGH, node.lineno,
                f"ambient random.{attr}(): the module-global stream is "
                f"invisible to replay — derive from a seeded "
                f"random.Random or the key chain", call=f"random.{attr}"))
        elif base == "os" and attr == "urandom":
            findings.append(_mk(
                ctx, "det-ambient-rng", Severity.HIGH, node.lineno,
                "os.urandom(): kernel entropy can never replay",
                call="os.urandom"))
        elif base == "secrets":
            findings.append(_mk(
                ctx, "det-ambient-rng", Severity.HIGH, node.lineno,
                f"secrets.{attr}(): CSPRNG output can never replay",
                call=f"secrets.{attr}"))
        elif base == "uuid" and attr in ("uuid1", "uuid4"):
            findings.append(_mk(
                ctx, "det-ambient-rng", Severity.MEDIUM, node.lineno,
                f"uuid.{attr}(): random ids diverge across twin runs — "
                f"fine for telemetry, poison for anything ordered or "
                f"persisted", call=f"uuid.{attr}"))
        elif base is None and attr == "hash" and node.args:
            findings.append(_mk(
                ctx, "det-ambient-rng", Severity.MEDIUM, node.lineno,
                "builtin hash(): salted per process (PYTHONHASHSEED) — "
                "use a stable digest", call="hash"))
    return findings


_DET_RULES = (
    ("det-unordered-iter", _rule_unordered_iter),
    ("det-wallclock", _rule_wallclock),
    ("det-ambient-rng", _rule_ambient_rng),
)


def det_rule_names() -> List[str]:
    return [n for n, _ in _DET_RULES]


def run_det_rules(paths: Optional[Sequence[Tuple[str, str]]] = None
                  ) -> List[Finding]:
    """The three AST rules over the host control plane (r18 module set)."""
    findings: List[Finding] = []
    for modname, path in (paths if paths is not None
                          else default_host_paths()):
        try:
            ctx = DetFileContext(modname, path)
        except (OSError, SyntaxError) as e:
            findings.append(Finding(
                rule="det-scan", severity=Severity.MEDIUM,
                message=f"could not scan {modname}: {e}",
                entry_point=modname))
            continue
        for name, rule in _DET_RULES:
            try:
                findings.extend(rule(ctx))
            except Exception as e:  # a broken rule must stay visible
                findings.append(Finding(
                    rule=name, severity=Severity.MEDIUM,
                    message=f"rule crashed on {modname}: "
                            f"{type(e).__name__}: {e}",
                    entry_point=modname))
    return findings


# ---------------------------------------------------------------------------
# replay-certificate (seam) coverage
# ---------------------------------------------------------------------------
def _registered_points(pkg_root: str) -> List[str]:
    from ..resilience.inject import POINTS

    return list(POINTS)


_FIRE_FUNCS = {"fire", "_fire", "_inject_fire", "_message_op",
               "_retrying"}
_SEAM_RE = re.compile(r"^[a-z_]+(\.[a-z_]+)+$")


def _fire_sites(pkg_root: str) -> Tuple[Dict[str, List[str]],
                                        Dict[str, List[str]]]:
    """(exact fire literals, f-string fire prefixes), each → [modname]."""
    exact: Dict[str, List[str]] = {}
    prefix: Dict[str, List[str]] = {}
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, pkg_root)
            modname = rel[:-3].replace(os.sep, ".")
            try:
                with open(path) as fh:
                    tree = ast.parse(fh.read())
            except (OSError, SyntaxError):
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                _, attr = _call_name(node.func)
                if attr not in _FIRE_FUNCS:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    exact.setdefault(arg.value, []).append(modname)
                elif isinstance(arg, ast.JoinedStr) and arg.values and \
                        isinstance(arg.values[0], ast.Constant) and \
                        isinstance(arg.values[0].value, str):
                    prefix.setdefault(arg.values[0].value,
                                      []).append(modname)
    return exact, prefix


class _TestFn:
    def __init__(self, qualname: str, node: ast.AST):
        self.qualname = qualname
        self.node = node
        self.literals: Set[str] = set()
        self.calls: Set[str] = set()
        self.names: Set[str] = set()
        self.uses_fired_log = False
        for x in ast.walk(node):
            if isinstance(x, ast.Constant) and isinstance(x.value, str):
                self.literals.add(x.value)
            elif isinstance(x, ast.Attribute) and x.attr == "fired_log":
                self.uses_fired_log = True
            elif isinstance(x, ast.Call):
                _, attr = _call_name(x.func)
                if attr:
                    self.calls.add(attr)
            elif isinstance(x, ast.Name):
                self.names.add(x.id)


def _is_twin(fn: _TestFn, log_sources: Set[str]) -> bool:
    """``assert <log-ish> == <log-ish>`` — both sides derived from
    ``fired_log()`` output (directly, via tainted locals, or via calls to
    same-module log-returning helpers)."""

    def logish_expr(e: ast.AST, tainted: Set[str]) -> bool:
        for x in ast.walk(e):
            if isinstance(x, ast.Attribute) and x.attr == "fired_log":
                return True
            if isinstance(x, ast.Name) and x.id in tainted:
                return True
            if isinstance(x, ast.Call):
                _, attr = _call_name(x.func)
                if attr in log_sources:
                    return True
        return False

    tainted: Set[str] = set()
    for _ in range(2):  # two passes: taint through one reassignment level
        for x in ast.walk(fn.node):
            if isinstance(x, ast.Assign) and \
                    logish_expr(x.value, tainted):
                for t in x.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
            elif isinstance(x, ast.Expr) and isinstance(x.value, ast.Call):
                # logs.append(<log-ish>) taints the list
                f = x.value.func
                if isinstance(f, ast.Attribute) and f.attr == "append" \
                        and isinstance(f.value, ast.Name) \
                        and x.value.args \
                        and logish_expr(x.value.args[0], tainted):
                    tainted.add(f.value.id)
    for x in ast.walk(fn.node):
        if isinstance(x, ast.Assert) and isinstance(x.test, ast.Compare) \
                and all(isinstance(op, ast.Eq) for op in x.test.ops):
            sides = [x.test.left] + list(x.test.comparators)
            if sum(logish_expr(s, tainted) for s in sides) >= 2:
                return True
    return False


def _scan_test_module(path: str, modname: str
                      ) -> Tuple[List[_TestFn], Dict[str, Set[str]]]:
    with open(path) as fh:
        tree = ast.parse(fh.read())
    module_lits: Dict[str, Set[str]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            lits = {x.value for x in ast.walk(node.value)
                    if isinstance(x, ast.Constant)
                    and isinstance(x.value, str)}
            if lits:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        module_lits[t.id] = lits
    fns: List[_TestFn] = []

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.append(_TestFn(f"{prefix}{child.name}", child))
                visit(child, f"{prefix}{child.name}.")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")

    visit(tree, f"{modname}::")
    return fns, module_lits


def seam_coverage(pkg_root: Optional[str] = None,
                  tests_dir: Optional[str] = None) -> dict:
    """Static cross-check: registry ↔ fire sites ↔ twin-certificate
    tests.  Returns the per-seam report the CLI serializes."""
    pkg = pkg_root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    tests = tests_dir or os.path.join(os.path.dirname(pkg), "tests")
    points = _registered_points(pkg)
    exact, prefixes = _fire_sites(pkg)

    # -- twin-test scan ----------------------------------------------------
    certified: Dict[str, List[str]] = {p: [] for p in points}
    test_files = []
    if os.path.isdir(tests):
        test_files = [os.path.join(tests, f) for f in sorted(
            os.listdir(tests)) if f.endswith(".py")]
    for path in test_files:
        modname = os.path.splitext(os.path.basename(path))[0]
        try:
            fns, module_lits = _scan_test_module(path, modname)
        except (OSError, SyntaxError):
            continue
        by_name: Dict[str, List[_TestFn]] = {}
        for f in fns:
            by_name.setdefault(f.qualname.rsplit(".", 1)[-1]
                               .rsplit("::", 1)[-1], []).append(f)
        log_sources = {f.qualname.rsplit(".", 1)[-1].rsplit("::", 1)[-1]
                       for f in fns if f.uses_fired_log}
        for f in fns:
            name = f.qualname.rsplit("::", 1)[-1].rsplit(".", 1)[-1]
            if not name.startswith("test"):
                continue
            # closure: literals + fired_log reach through same-module
            # helper calls (one level is how these tests are written)
            lits = set(f.literals)
            uses_log = f.uses_fired_log
            for callee in f.calls:
                for g in by_name.get(callee, ()):
                    lits |= g.literals
                    uses_log = uses_log or g.uses_fired_log
            for ref in (f.names | f.calls):
                lits |= module_lits.get(ref, set())
            if not uses_log or not _is_twin(f, log_sources):
                continue
            for p in points:
                if p in lits:
                    certified[p].append(f.qualname)

    fired = {p: sorted(set(exact.get(p, ())))
             for p in points if p in exact}
    for p in points:
        if p in fired:
            continue
        mods = sorted({m for pre, ms in prefixes.items()
                       if p.startswith(pre) for m in ms})
        if mods:
            fired[p] = mods
    unregistered = sorted(
        lit for lit in exact
        if _SEAM_RE.match(lit) and lit not in points)
    return {
        "points": list(points),
        "covered": {p: sorted(set(ts)) for p, ts in certified.items()
                    if ts},
        "uncovered": [p for p in points if not certified[p]],
        "never_fired": [p for p in points if p not in fired],
        "fired_in": fired,
        "unregistered_fire_literals": unregistered,
        "n_points": len(points),
        "n_covered": sum(1 for p in points if certified[p]),
    }


def coverage_findings(cov: dict) -> List[Finding]:
    out: List[Finding] = []
    for p in cov["uncovered"]:
        out.append(Finding(
            rule="det-seam-coverage", severity=Severity.HIGH,
            message=f"inject seam '{p}' has no two-run identical-"
                    f"fired-log twin certificate test — replay of this "
                    f"fault path is unverified",
            entry_point="seam-coverage", details={"seam": p}))
    for p in cov["never_fired"]:
        out.append(Finding(
            rule="det-seam-coverage", severity=Severity.MEDIUM,
            message=f"registered seam '{p}' is never fired anywhere in "
                    f"the package — dead registry entry",
            entry_point="seam-coverage", details={"seam": p}))
    for lit in cov["unregistered_fire_literals"]:
        out.append(Finding(
            rule="det-seam-coverage", severity=Severity.MEDIUM,
            message=f"fire site uses literal '{lit}' that is not in the "
                    f"POINTS registry — schedules can never match it",
            entry_point="seam-coverage", details={"literal": lit}))
    return out


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------
def analyze_determinism(paths: Optional[Sequence[Tuple[str, str]]] = None,
                        pkg_root: Optional[str] = None,
                        tests_dir: Optional[str] = None,
                        include_seams: bool = True) -> AnalysisReport:
    """Full host-determinism plane: AST rules + seam coverage."""
    t0 = time.perf_counter()
    findings = run_det_rules(paths)
    cov = None
    if include_seams:
        cov = seam_coverage(pkg_root, tests_dir)
        findings.extend(coverage_findings(cov))
    report = AnalysisReport(findings, meta={
        "plane": "determinism",
        "det_schema_version": DET_SCHEMA_VERSION,
        "det_rules": det_rule_names() + ["det-seam-coverage"],
        "n_modules": len(paths if paths is not None
                         else default_host_paths()),
        "scan_s": round(time.perf_counter() - t0, 4),
    })
    if cov is not None:
        report.meta["seam_coverage"] = {
            "n_points": cov["n_points"], "n_covered": cov["n_covered"],
            "uncovered": cov["uncovered"],
            "never_fired": cov["never_fired"],
            "unregistered_fire_literals": cov["unregistered_fire_literals"],
        }
    return report
