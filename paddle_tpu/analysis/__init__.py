"""paddle_tpu.analysis — the jaxpr/HLO static-analysis layer ("graph doctor").

Parity role: the reference framework's compile-time program checks —
ProgramDesc verification passes, the inference pass registry's graph
validation, ``FLAGS_check_nan_inf``-style instrumentation — rebuilt over
the three IR surfaces this TPU-native reproduction actually produces:
closed jaxprs (recursing through scan/cond/while/pjit/shard_map/custom_vjp),
the ``static.Program`` op-record IR, and lowered StableHLO text.

Quick use::

    from paddle_tpu import analysis

    target = analysis.AnalysisTarget("step", jitted_fn, example_args)
    for f in analysis.run_rules(target):
        print(f)

    guard = analysis.TraceGuard(jitted_fn)       # runtime recompile doctor
    ...
    guard.findings()

``python -m paddle_tpu.analysis`` lints every shipped entry point and
writes ``benchmarks/analysis_report.json``; ``--memory`` adds the
liveness-based peak-HBM report (``analysis_memory.json``),
``--sanitize`` replays each entry point eqn-by-eqn hunting the first
non-finite intermediate (``FLAGS_check_nan_inf`` parity with *where*),
and ``--determinism`` runs the determinism doctor: PRNG key-flow lint +
host-nondeterminism rules + replay-certificate seam coverage
(``paddle.seed`` / ``FLAGS_cudnn_deterministic`` parity), with
``--bisect-demo`` exercising the twin-replay divergence bisector.
``--kernels`` runs the Pallas kernel doctor (block-spec coverage proofs
+ f32-accumulation lint + VMEM budget + cost-registry drift
certification over the shipped kernel manifest — OpDesc/InferShape
verification parity for the kernel plane), and ``--kernels-sweep``
prints the predicted VMEM/roofline table over serving shapes.
"""
from .findings import (
    AnalysisReport,
    AnalysisWarning,
    Finding,
    Severity,
    warn_finding,
)
from .graph import (
    AnalysisTarget,
    DefUseGraph,
    build_graph,
    target_from_program,
)
from .rules import (
    CollectiveOrderRule,
    ConstantBloatRule,
    DonationRule,
    DtypePromotionRule,
    HostSyncRule,
    ProgramRule,
    RecompileHazardRule,
    Rule,
    ShardingPropagationRule,
    analyze_targets,
    default_rules,
    register_rule,
    run_rules,
)
from .cost import (
    EqnCost,
    GraphCost,
    classify_intensity,
    cost_eqn,
    graph_cost,
)
from .memory import (
    LowIntensityDotRule,
    MemoryBudgetRule,
    MemoryEstimate,
    RematAdvisorRule,
    estimate_memory,
    memory_estimate,
    planner_drift_findings,
)
from .plan import (
    CandidateSpec,
    DeviceSpec,
    PlannedCandidate,
    PlanV2,
    RematPolicy,
    plan_consistency_findings,
    plan_gpt,
)
from .sanitizer import (
    NonFiniteReport,
    SanitizeResult,
    SanitizerConfig,
    sanitize,
    sanitize_target,
)
from .keyflow import (
    ClosureKeyRule,
    KeyDiscardRule,
    KeyReuseRule,
    NonuniformKeyRule,
    keyflow_rules,
)
from .determinism import (
    analyze_determinism,
    coverage_findings,
    run_det_rules,
    seam_coverage,
)
from .bisect import (
    BisectConfig,
    BisectResult,
    DivergenceReport,
    bisect_runs,
    demo_divergence,
    diff_fired_logs,
)
from .kernels import (
    KernelAudit,
    analyze_kernels,
    collect_pallas_eqns,
    kernel_sweep,
)
from .traceguard import RecompileEvent, TraceGuard

__all__ = [
    "EqnCost",
    "GraphCost",
    "classify_intensity",
    "cost_eqn",
    "graph_cost",
    "MemoryEstimate",
    "MemoryBudgetRule",
    "LowIntensityDotRule",
    "RematAdvisorRule",
    "estimate_memory",
    "memory_estimate",
    "planner_drift_findings",
    "CandidateSpec",
    "DeviceSpec",
    "PlannedCandidate",
    "PlanV2",
    "RematPolicy",
    "plan_consistency_findings",
    "plan_gpt",
    "NonFiniteReport",
    "SanitizeResult",
    "SanitizerConfig",
    "sanitize",
    "sanitize_target",
    "AnalysisReport",
    "AnalysisWarning",
    "Finding",
    "Severity",
    "warn_finding",
    "AnalysisTarget",
    "DefUseGraph",
    "build_graph",
    "target_from_program",
    "Rule",
    "register_rule",
    "default_rules",
    "run_rules",
    "analyze_targets",
    "DtypePromotionRule",
    "ConstantBloatRule",
    "DonationRule",
    "HostSyncRule",
    "RecompileHazardRule",
    "CollectiveOrderRule",
    "ShardingPropagationRule",
    "ProgramRule",
    "KeyReuseRule",
    "KeyDiscardRule",
    "ClosureKeyRule",
    "NonuniformKeyRule",
    "keyflow_rules",
    "analyze_determinism",
    "run_det_rules",
    "seam_coverage",
    "coverage_findings",
    "BisectConfig",
    "BisectResult",
    "DivergenceReport",
    "bisect_runs",
    "demo_divergence",
    "diff_fired_logs",
    "KernelAudit",
    "analyze_kernels",
    "collect_pallas_eqns",
    "kernel_sweep",
    "TraceGuard",
    "RecompileEvent",
]
