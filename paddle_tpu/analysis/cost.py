"""Static per-eqn cost model: FLOPs, bytes accessed, arithmetic intensity.

The Roofline question (Williams et al.): for every eqn the graph walker
records, how much compute does it do and how many HBM bytes does it touch?
The ratio (flops / bytes) against the device ridge point classifies the eqn
compute-bound or memory-bound — the static form of "this dot will not feed
the MXU".  The liveness analyzer (:mod:`.memory`) reuses the same per-eqn
costs to price rematerialization candidates.

Conventions (pinned so tests can hand-compute them — estimates, not
simulator truth):

* ``dot_general``      — ``2 * out_elems * K`` (K = contracted extent).
* ``conv``             — ``2 * out_elems * rhs_elems / out_channels``.
* elementwise          — 1 flop/element; transcendentals (exp, log, tanh,
  rsqrt, pow, erf, ...) cost :data:`TRANSCENDENTAL_FLOPS` each.
* reductions           — 1 flop per *input* element; windowed reductions
  ``out_elems * window``.
* data movement        — 0 flops (bytes only): reshape/transpose/slice/
  gather/convert/iota/select_n/...
* collectives          — ``comm_bytes`` over the wire from the per-axis
  mesh sizes (ring allreduce ``2(n-1)/n``, all_gather ``(n-1)/n``, ...);
  the axis extents come from :class:`AnalysisTarget.mesh_axes`.
* control-flow containers (pjit/scan/while/cond/shard_map/custom_*) cost
  nothing themselves — their inner eqns are separate walker nodes;
  :func:`graph_cost` multiplies scan bodies by trip count.

Unknown primitives are NEVER silently zero-costed: they fall back to
bytes-only with ``known=False`` and are tallied in ``GraphCost.unknown``
(the CLI and the memory report surface the list).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, Optional, Tuple

import numpy as np

from .graph import COLLECTIVE_PRIMS, _axes_of, scope_components

__all__ = [
    "EqnCost",
    "GraphCost",
    "ScopeCost",
    "cost_eqn",
    "graph_cost",
    "scope_costs",
    "execution_multiplier",
    "classify_intensity",
    "collective_comm_bytes",
    "ring_all_reduce_bytes",
    "all_gather_bytes",
    "reduce_scatter_bytes",
    "all_to_all_bytes",
    "TRANSCENDENTAL_FLOPS",
    "DEFAULT_RIDGE_FLOPS_PER_BYTE",
    "CONTAINER_PRIMS",
]

#: nominal flop cost of one transcendental evaluation (polynomial approx)
TRANSCENDENTAL_FLOPS = 8

#: v5e ridge point: 197 TFLOP/s bf16 over ~819 GB/s HBM ≈ 240 flops/byte
DEFAULT_RIDGE_FLOPS_PER_BYTE = 240.0

# control-flow / call containers: the walker records their inner eqns as
# separate nodes, so the container itself contributes no flops or bytes
CONTAINER_PRIMS = frozenset({
    "pjit", "scan", "while", "cond", "shard_map", "remat", "remat2",
    "checkpoint", "closed_call", "core_call", "named_call", "custom_lin",
    "custom_vjp_call", "custom_jvp_call", "custom_vjp_call_jaxpr",
    "custom_jvp_call_jaxpr",
})

_MOVEMENT = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "convert_element_type",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "rev", "squeeze", "expand_dims", "copy", "gather", "iota", "select_n",
    "stop_gradient", "bitcast_convert_type", "device_put", "real", "imag",
    "scatter", "random_seed", "random_wrap", "random_unwrap",
    "random_fold_in", "random_bits", "random_split", "split",
    "sharding_constraint",
})

_ELEMENTWISE_1 = frozenset({
    "add", "add_any", "sub", "mul", "div", "max", "min", "neg", "abs",
    "sign",
    "floor", "ceil", "round", "rem", "nextafter", "clamp", "square",
    "integer_pow", "and", "or", "xor", "not", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "eq", "ne", "lt",
    "gt", "le", "ge", "is_finite", "reduce_precision", "complex", "conj",
})

_TRANSCENDENTAL = frozenset({
    "exp", "exp2", "expm1", "log", "log1p", "tanh", "sin", "cos", "tan",
    "asin", "acos", "atan", "atan2", "sinh", "cosh", "asinh", "acosh",
    "atanh", "erf", "erfc", "erf_inv", "logistic", "sqrt", "rsqrt",
    "cbrt", "pow", "lgamma", "digamma", "igamma", "igammac",
    "bessel_i0e", "bessel_i1e", "threefry2x32",
})

_REDUCTION = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin", "cumsum", "cumprod",
    "cummax", "cummin",
})

_SCATTER_COMBINE = frozenset({
    "scatter-add", "scatter_add", "scatter-mul", "scatter_mul",
    "scatter-min", "scatter_min", "scatter-max", "scatter_max",
})


@dataclasses.dataclass
class EqnCost:
    """Estimated cost of one eqn (per execution, per device)."""

    flops: float = 0.0
    bytes_in: int = 0
    bytes_out: int = 0
    comm_bytes: float = 0.0        # inter-chip payload (collectives)
    container: bool = False        # inner eqns carry the cost
    known: bool = True             # False = fallback estimate
    estimated: bool = False        # some input (axis size) was guessed

    @property
    def bytes_accessed(self) -> int:
        return self.bytes_in + self.bytes_out

    @property
    def intensity(self) -> float:
        b = self.bytes_accessed
        return self.flops / b if b else 0.0


def classify_intensity(intensity: float,
                       ridge: float = DEFAULT_RIDGE_FLOPS_PER_BYTE) -> str:
    return "compute-bound" if intensity >= ridge else "memory-bound"


# ---------------------------------------------------------------------------
# first-class collective payload models
# ---------------------------------------------------------------------------
# One definition per collective family: bytes moved over the slowest link per
# participating device, as a function of payload and group size n.  Shared by
# the per-eqn cost model below AND the auto-parallel planner's analytic
# collective pricing (analysis/plan.py prices dp grad sync, ZeRO
# reduce_scatter/all_gather, mp activation allreduces and MoE all_to_all with
# THESE functions, so the two never drift apart).

def ring_all_reduce_bytes(payload_bytes: float, n: int) -> float:
    """Ring allreduce: reduce-scatter + all-gather, ``2(n-1)/n`` each way."""
    return 2.0 * (n - 1) / n * payload_bytes if n > 1 else 0.0


def all_gather_bytes(out_bytes: float, n: int) -> float:
    """Each device receives the other ``n-1`` shards of the gathered OUT."""
    return (n - 1) / n * out_bytes if n > 1 else 0.0


def reduce_scatter_bytes(in_bytes: float, n: int) -> float:
    """Each device sends ``(n-1)/n`` of its INPUT around the ring (the half
    of ring-allreduce that lands sharded — the honest ZeRO-2 grad-sync
    term)."""
    return (n - 1) / n * in_bytes if n > 1 else 0.0


def all_to_all_bytes(payload_bytes: float, n: int) -> float:
    """Every device keeps ``1/n`` of its payload and ships the remaining
    ``(n-1)/n`` (the MoE dispatch/combine term)."""
    return (n - 1) / n * payload_bytes if n > 1 else 0.0


def _point_to_point_bytes(payload_bytes: float, n: int) -> float:
    return float(payload_bytes)


#: collective prim → (bytes_in, bytes_out, n) → wire bytes.  A prim listed
#: in COLLECTIVE_PRIMS but absent here is priced bytes-only with
#: ``known=False`` and tallied in ``GraphCost.unknown`` — never silently
#: zero-costed.
_COLLECTIVE_MODELS = {
    "psum": lambda bi, bo, n: ring_all_reduce_bytes(max(bi, bo), n),
    "pmin": lambda bi, bo, n: ring_all_reduce_bytes(max(bi, bo), n),
    "pmax": lambda bi, bo, n: ring_all_reduce_bytes(max(bi, bo), n),
    "all_gather": lambda bi, bo, n: all_gather_bytes(bo, n),
    "psum_scatter": lambda bi, bo, n: reduce_scatter_bytes(bi, n),
    "reduce_scatter": lambda bi, bo, n: reduce_scatter_bytes(bi, n),
    "all_to_all": lambda bi, bo, n: all_to_all_bytes(max(bi, bo), n),
    "ppermute": lambda bi, bo, n: _point_to_point_bytes(max(bi, bo), n),
    "pshuffle": lambda bi, bo, n: _point_to_point_bytes(max(bi, bo), n),
    "pgather": lambda bi, bo, n: _point_to_point_bytes(max(bi, bo), n),
}


def collective_comm_bytes(prim: str, bytes_in: float, bytes_out: float,
                          n: int) -> Tuple[float, bool]:
    """(wire bytes, modeled?) for one collective execution over an
    ``n``-rank group.  ``modeled=False`` = unknown collective family — the
    caller must surface it (bytes-only fallback, GraphCost.unknown)."""
    model = _COLLECTIVE_MODELS.get(prim)
    if model is None:
        return _point_to_point_bytes(max(bytes_in, bytes_out), n), False
    return model(float(bytes_in), float(bytes_out), int(n)), True


def _elems(aval_info) -> int:
    shape = aval_info[0]
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _nbytes(aval_info) -> int:
    dtype = aval_info[1]
    if dtype is None:
        return 0
    try:
        item = np.dtype(dtype).itemsize
    except TypeError:  # extended dtypes (typed PRNG keys)
        item = 16
    return _elems(aval_info) * item


def _group_size(params, mesh_axes) -> Tuple[int, bool]:
    """(#ranks in the collective's group, any-axis-size-guessed)."""
    n, estimated = 1, False
    for a in _axes_of(params):
        if mesh_axes and a in mesh_axes:
            n *= int(mesh_axes[a])
        else:
            estimated = True
    return n, estimated


def cost_eqn(prim: str, in_avals, out_avals, params: dict,
             mesh_axes: Optional[Dict[str, int]] = None) -> EqnCost:
    """Cost one eqn given the walker's ``(shape, dtype, weak)`` aval infos
    and its (light) params.  Unknown primitives return ``known=False`` with
    bytes-only cost — never a silent zero."""
    bytes_in = sum(_nbytes(a) for a in in_avals)
    bytes_out = sum(_nbytes(a) for a in out_avals)
    out_elems = sum(_elems(a) for a in out_avals)
    in_elems = sum(_elems(a) for a in in_avals)

    if prim in CONTAINER_PRIMS:
        return EqnCost(container=True)

    if prim == "dot_general":
        (lhs_c, _), (lhs_b, _) = params["dimension_numbers"]
        lhs_shape = in_avals[0][0]
        k = 1
        for d in lhs_c:
            k *= int(lhs_shape[d])
        return EqnCost(flops=2.0 * out_elems * k,
                       bytes_in=bytes_in, bytes_out=bytes_out)

    if prim == "conv_general_dilated":
        dn = params.get("dimension_numbers")
        rhs_shape = in_avals[1][0]
        rhs_elems = _elems(in_avals[1])
        out_ch = 1
        if dn is not None and hasattr(dn, "rhs_spec") and rhs_shape:
            out_ch = int(rhs_shape[dn.rhs_spec[0]])
        return EqnCost(flops=2.0 * out_elems * rhs_elems / max(out_ch, 1),
                       bytes_in=bytes_in, bytes_out=bytes_out)

    if prim in COLLECTIVE_PRIMS:
        n, est = _group_size(params, mesh_axes)
        comm, modeled = collective_comm_bytes(prim, bytes_in, bytes_out, n)
        reduce_flops = in_elems if prim in ("psum", "pmin", "pmax") else 0
        return EqnCost(flops=float(reduce_flops),
                       bytes_in=bytes_in, bytes_out=bytes_out,
                       comm_bytes=comm, estimated=est or not modeled,
                       known=modeled)

    if prim == "axis_index":
        return EqnCost(bytes_out=bytes_out)

    if prim in _TRANSCENDENTAL:
        return EqnCost(flops=float(TRANSCENDENTAL_FLOPS * out_elems),
                       bytes_in=bytes_in, bytes_out=bytes_out)
    if prim in _ELEMENTWISE_1:
        return EqnCost(flops=float(out_elems),
                       bytes_in=bytes_in, bytes_out=bytes_out)
    if prim in _REDUCTION:
        return EqnCost(flops=float(in_elems),
                       bytes_in=bytes_in, bytes_out=bytes_out)
    if prim in ("reduce_window_sum", "reduce_window_max",
                "reduce_window_min"):
        window = 1
        for w in params.get("window_dimensions", ()):
            window *= int(w)
        return EqnCost(flops=float(out_elems * window),
                       bytes_in=bytes_in, bytes_out=bytes_out)
    if prim in _SCATTER_COMBINE:
        updates = _elems(in_avals[2]) if len(in_avals) >= 3 else in_elems
        return EqnCost(flops=float(updates),
                       bytes_in=bytes_in, bytes_out=bytes_out)
    if prim in _MOVEMENT:
        return EqnCost(bytes_in=bytes_in, bytes_out=bytes_out)

    if prim == "pallas_call":
        # price from the kernel cost registry (r20): kernels register
        # analytic (flops, bytes) models under the explicit name= they
        # pass to pl.pallas_call.  Unregistered kernels keep the loud
        # bytes-only fallback below — never silently zero-costed.
        name = getattr(params.get("name_and_src_info"), "name", None)
        try:
            from ..ops.pallas.cost_registry import kernel_cost_model
            model = kernel_cost_model(name)
            if model is not None:
                flops, bts = model(in_avals, out_avals, params)
                return EqnCost(flops=float(flops), bytes_in=int(bts),
                               bytes_out=0)
        except Exception:
            pass  # malformed model → loud fallback, same as unregistered

    # unknown primitive: bytes-only fallback, reported via GraphCost.unknown
    return EqnCost(bytes_in=bytes_in, bytes_out=bytes_out, known=False,
                   estimated=True)


@dataclasses.dataclass
class GraphCost:
    """Whole-program totals over a :class:`DefUseGraph` walk."""

    flops: float = 0.0
    bytes_accessed: float = 0.0
    comm_bytes: float = 0.0
    by_prim: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict)
    unknown: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: prim → r14 scope path of the FIRST offending eqn, so an unpriced
    #: primitive is attributable to a model region without a jaxpr dig
    unknown_where: Dict[str, str] = dataclasses.field(default_factory=dict)
    estimated: bool = False        # while trip counts / guessed axis sizes
    n_eqns: int = 0

    @property
    def intensity(self) -> float:
        return self.flops / self.bytes_accessed if self.bytes_accessed else 0.0

    def to_dict(self) -> dict:
        top = sorted(self.by_prim.items(),
                     key=lambda kv: -kv[1]["flops"])[:12]
        return {
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "comm_bytes": self.comm_bytes,
            "intensity_flops_per_byte": round(self.intensity, 3),
            "classification": classify_intensity(self.intensity),
            "n_eqns": self.n_eqns,
            "estimated": self.estimated,
            "unknown_prims": dict(self.unknown),
            "unknown_where": dict(self.unknown_where),
            "by_prim_top": {k: {m: round(x, 1) for m, x in v.items()}
                            for k, v in top},
        }


_SCAN_AT = re.compile(r"^scan@(\d+)$")
_ESTIMATED_AT = re.compile(r"^(while|cond)@(\d+)$")
_PALLAS_AT = re.compile(r"^pallas_call@\d+$")


def _inside_pallas_body(path) -> bool:
    """True for nodes the walker recorded INSIDE a pallas_call body jaxpr.
    The pallas_call eqn itself carries the whole kernel's cost (registry
    model or bytes-only fallback); pricing the body's per-block eqns too
    would double count — and at per-BLOCK shapes, not per-launch ones."""
    return any(_PALLAS_AT.match(p) for p in path)


def execution_multiplier(graph, path) -> Tuple[float, bool]:
    """Execution count of a node from its enclosing scans ('scan@IDX' path
    elements carry the trip count in the container node's params); while
    loops (unknown trip count, multiplier 1) and cond branches (BOTH
    counted — an upper bound) flag the totals estimated."""
    mult, estimated = 1.0, False
    for part in path:
        m = _SCAN_AT.match(part)
        if m:
            node = graph.nodes[int(m.group(1))]
            mult *= float(node.params.get("length", 1) or 1)
            continue
        if _ESTIMATED_AT.match(part):
            estimated = True
    return mult, estimated


_multiplier = execution_multiplier  # r10 internal name, kept for callers


def graph_cost(graph, mesh_axes: Optional[Dict[str, int]] = None) -> GraphCost:
    """Aggregate :func:`cost_eqn` over every non-container node, scaling
    scan bodies by trip count.  Both cond branches are counted (an upper
    bound, flagged ``estimated``)."""
    total = GraphCost()
    for node in graph.nodes:
        if _inside_pallas_body(node.path):
            continue
        c = cost_eqn(node.prim, node.in_avals, node.out_avals, node.params,
                     mesh_axes)
        if c.container:
            continue
        mult, est = _multiplier(graph, node.path)
        if est or c.estimated:
            total.estimated = True
        if not c.known:
            total.unknown[node.prim] = total.unknown.get(node.prim, 0) + 1
            total.unknown_where.setdefault(
                node.prim,
                "/".join(scope_components(node.name_stack)) or "(unscoped)")
        total.flops += c.flops * mult
        total.bytes_accessed += c.bytes_accessed * mult
        total.comm_bytes += c.comm_bytes * mult
        total.n_eqns += 1
        agg = total.by_prim.setdefault(
            node.prim, {"count": 0, "flops": 0.0, "bytes": 0.0})
        agg["count"] += 1
        agg["flops"] += c.flops * mult
        agg["bytes"] += c.bytes_accessed * mult
    return total


@dataclasses.dataclass
class ScopeCost:
    """Aggregated roofline cost of one profiler-scope path (r14).

    One row of the scope-attribution table: every non-container eqn whose
    normalized ``name_stack`` (:func:`~.graph.scope_components`) equals
    ``scope`` contributes its :func:`cost_eqn`, scaled by the same scan
    trip-count multipliers :func:`graph_cost` applies — so the rows sum to
    the whole-graph totals EXACTLY (the reconciliation invariant the perf
    doctor pins)."""

    scope: Tuple[str, ...]
    flops: float = 0.0
    bytes_accessed: float = 0.0
    comm_bytes: float = 0.0
    n_eqns: int = 0
    estimated: bool = False
    by_prim: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict)

    @property
    def name(self) -> str:
        return "/".join(self.scope) if self.scope else "(unscoped)"

    @property
    def intensity(self) -> float:
        b = self.bytes_accessed
        return self.flops / b if b else 0.0

    def bound(self, ridge: float = DEFAULT_RIDGE_FLOPS_PER_BYTE) -> str:
        return classify_intensity(self.intensity, ridge)

    @property
    def dominant_prim(self) -> Optional[str]:
        """The primitive contributing the most flops in this scope (falls
        back to most bytes for flop-free scopes) — lets a report say 'this
        scope is a dot_general scope' without the reader re-deriving it."""
        if not self.by_prim:
            return None
        return max(self.by_prim.items(),
                   key=lambda kv: (kv[1]["flops"], kv[1]["bytes"]))[0]


def scope_costs(graph, mesh_axes: Optional[Dict[str, int]] = None,
                ) -> Dict[Tuple[str, ...], ScopeCost]:
    """Slice the graph's roofline cost by profiler scope (r6 ``scope``/
    ``annotate`` names surviving in eqn ``name_stack`` metadata): scope
    path → :class:`ScopeCost`. Eqns outside any scope land under the
    ``()`` key (reported as ``(unscoped)``); containers are skipped and
    scan bodies scaled exactly as :func:`graph_cost` does, so summing the
    returned rows reproduces its totals."""
    from .graph import scope_components

    out: Dict[Tuple[str, ...], ScopeCost] = {}
    for node in graph.nodes:
        if _inside_pallas_body(node.path):
            continue
        c = cost_eqn(node.prim, node.in_avals, node.out_avals, node.params,
                     mesh_axes)
        if c.container:
            continue
        mult, est = execution_multiplier(graph, node.path)
        key = scope_components(node.name_stack)
        sc = out.get(key)
        if sc is None:
            sc = out[key] = ScopeCost(scope=key)
        sc.flops += c.flops * mult
        sc.bytes_accessed += c.bytes_accessed * mult
        sc.comm_bytes += c.comm_bytes * mult
        sc.n_eqns += 1
        if est or c.estimated:
            sc.estimated = True
        agg = sc.by_prim.setdefault(
            node.prim, {"count": 0, "flops": 0.0, "bytes": 0.0})
        agg["count"] += 1
        agg["flops"] += c.flops * mult
        agg["bytes"] += c.bytes_accessed * mult
    return out
