"""Concurrency doctor: lock-discipline & race rules over the host plane.

Parity role: the reference framework statically verifies its *device*
programs (ProgramDesc checks) and stress-tests its threaded C++ runtime in
CI (``WITH_TESTING`` thread-stress suites); this module gives the jax_graft
host runtime the static half of that story — lockdep-style lock-order
validation plus RacerD/Clang-``GUARDED_BY``-style annotation checking over
the ~6k-line threaded control plane (serving/, resilience/,
distributed/fleet/, observability/).  Four ranked rules, same
:class:`~paddle_tpu.analysis.findings.Finding` schema as the jaxpr rules,
driven by ``python -m paddle_tpu.analysis --host``:

* ``host-guarded-by``      — a ``# guarded-by: self._lock`` annotation on a
  shared mutable attribute makes every bare access a finding (HIGH for
  writes); with no annotation, an attribute accessed under one lock in
  >=80% of its sites is flagged wherever accessed bare (inference,
  MEDIUM/LOW — heuristics never gate alone).
* ``host-lock-order``      — static ``with a: ... with b:`` nesting edges
  (plus one-level call-through footprints) unioned with the runtime
  instrumented-lock journal; any cycle is a HIGH potential deadlock.
* ``host-blocking-under-lock`` — socket/HTTP/sleep/thread-join/compile
  calls while a lock is held (the r11 health-loop stall class).  Locks
  annotated ``hostrace: blocking-ok`` (tick locks, trace locks, failover
  serializers) and sites annotated ``hostrace: ok(...)`` report INFO —
  recognized as intentional, never silently dropped.
* ``host-toctou``          — a guarded read whose value feeds a branch
  that re-acquires the same lock before the dependent write: the state
  may have changed between check and act (the r11 drain / r16
  admission-gate bug shapes; atomic ``setdefault`` writes are exempt).

The model layer (AST scan, annotations, order graph, runtime recorder)
lives in :mod:`paddle_tpu.analysis.lockmodel`.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import lockmodel
from .findings import AnalysisReport, Finding, Severity
from .lockmodel import HostModel, LockOrderGraph
from .rules import HostRule, default_host_rules, register_host_rule

__all__ = [
    "HOST_SCHEMA_VERSION",
    "HostAnalysisContext",
    "GuardedByRule",
    "LockOrderRule",
    "BlockingUnderLockRule",
    "ToctouRule",
    "build_context",
    "analyze_host",
    "default_journal_path",
]

HOST_SCHEMA_VERSION = 1

#: guard-inference thresholds: an attribute needs this many access sites,
#: at least one write, and one lock covering this fraction of sites
#: before bare sites are flagged (inference stays MEDIUM — only declared
#: annotations produce gating HIGHs)
INFER_MIN_SITES = 5
INFER_FRACTION = 0.8


class HostAnalysisContext:
    """Everything the host rules consume: the scanned model, the merged
    lock-order graph, and where the journal came from."""

    def __init__(self, model: HostModel, graph: LockOrderGraph,
                 journal_edges: Sequence[dict] = (),
                 journal_path: Optional[str] = None,
                 journal_error: Optional[str] = None):
        self.model = model
        self.graph = graph
        self.journal_edges = list(journal_edges)
        self.journal_path = journal_path
        self.journal_error = journal_error

    def scan_errors(self) -> Dict[str, str]:
        return {name: m.error for name, m in self.model.modules.items()
                if m.error}


def _src(path: str, line: int, method: str = "") -> str:
    rel = path
    for marker in ("paddle_tpu" + os.sep, "tests" + os.sep):
        idx = path.rfind(marker)
        if idx >= 0:
            rel = path[idx:]
            break
    loc = f"{rel}:{line}"
    return f"{loc} ({method})" if method else loc


# ---------------------------------------------------------------------------
@register_host_rule
class GuardedByRule(HostRule):
    name = "host-guarded-by"

    def __init__(self, infer_min_sites: int = INFER_MIN_SITES,
                 infer_fraction: float = INFER_FRACTION):
        self.infer_min_sites = int(infer_min_sites)
        self.infer_fraction = float(infer_fraction)

    def run(self, ctx: HostAnalysisContext) -> List[Finding]:
        out: List[Finding] = []
        for mod in ctx.model.modules.values():
            for cls in mod.classes.values():
                out.extend(self._check_class(mod, cls))
                out.extend(self._check_requires_callers(ctx, mod, cls))
        return out

    def _check_requires_callers(self, ctx, mod, cls) -> List[Finding]:
        """A ``hostrace: requires(L)`` method trusted with a seeded held
        set must actually be called with L held — verify every recorded
        call site (same-class and typed cross-class receivers)."""
        out: List[Finding] = []
        targets = {mi.name: mi for mi in cls.methods.values()
                   if mi.requires}
        if not targets:
            return out
        for m2 in ctx.model.modules.values():
            for c2 in m2.classes.values():
                for caller in c2.methods.values():
                    for recv_cls, meth, line, held in caller.calls:
                        mi = targets.get(meth)
                        if mi is None or (recv_cls or c2.name) != cls.name:
                            continue
                        for lid in mi.requires:
                            if held & cls.guard_equiv(lid):
                                continue
                            # a requires-method calling a sibling
                            # requires-method inherits the seeded set via
                            # `held`, so only genuinely bare calls land here
                            out.append(Finding(
                                rule=self.name, severity=Severity.HIGH,
                                entry_point=m2.modname,
                                message=(
                                    f"{c2.name}.{caller.name}() calls "
                                    f"{cls.name}.{meth}() which is declared "
                                    f"hostrace: requires({_short(lid)}) "
                                    "(line "
                                    f"{mi.line}) without holding it — the "
                                    "helper mutates guarded state assuming "
                                    "the caller's lock"),
                                source=_src(m2.path, line, caller.name),
                                details={"callee": f"{cls.name}.{meth}",
                                         "requires": lid,
                                         "held": sorted(held)}))
        return out

    def _check_class(self, mod, cls) -> List[Finding]:
        out: List[Finding] = []
        by_attr: Dict[str, list] = {}
        for acc in cls.accesses:
            if acc.method == "__init__":
                continue  # pre-publication: the object is not shared yet
            by_attr.setdefault(acc.attr, []).append(acc)
        # declared guards first
        for attr, decl in cls.guards.items():
            if decl.guard_id is None:
                out.append(Finding(
                    rule=self.name, severity=Severity.MEDIUM,
                    entry_point=mod.modname,
                    message=f"{cls.name}.{attr} declares guarded-by: "
                            f"{decl.guard_expr} but no such lock exists on "
                            f"{cls.name} — annotation names an unknown "
                            "lock (typo, or the lock was removed)",
                    source=_src(mod.path, decl.line)))
                continue
            equiv = cls.guard_equiv(decl.guard_id)
            for acc in by_attr.get(attr, ()):
                if acc.held & equiv:
                    continue
                if self.name in acc.suppressed:
                    out.append(self._finding(
                        mod, cls, attr, acc, decl, Severity.INFO,
                        suppressed=True))
                    continue
                sev = (Severity.HIGH if acc.kind == "write"
                       else Severity.MEDIUM)
                out.append(self._finding(mod, cls, attr, acc, decl, sev))
        # inference for annotation-less attributes
        for attr, accs in sorted(by_attr.items()):
            if attr in cls.guards or attr.startswith("__"):
                continue
            if len(accs) < self.infer_min_sites:
                continue
            if not any(a.kind == "write" for a in accs):
                continue
            counts: Dict[str, int] = {}
            for a in accs:
                for lid in a.held:
                    if lid.startswith(f"{mod.modname}.{cls.name}."):
                        counts[lid] = counts.get(lid, 0) + 1
            if not counts:
                continue
            guard, n = max(counts.items(), key=lambda kv: kv[1])
            if n / len(accs) < self.infer_fraction:
                continue
            equiv = cls.guard_equiv(guard)
            for a in accs:
                if a.held & equiv:
                    continue
                if self.name in a.suppressed:
                    continue
                sev = Severity.MEDIUM if a.kind == "write" else Severity.LOW
                out.append(Finding(
                    rule=self.name, severity=sev, entry_point=mod.modname,
                    message=(
                        f"{cls.name}.{attr} is accessed under "
                        f"{_short(guard)} at {n}/{len(accs)} sites but "
                        f"{a.kind} bare in {a.method}() — either take the "
                        "lock or declare the real discipline with a "
                        "`# guarded-by:` annotation"),
                    source=_src(mod.path, a.line, a.method),
                    details={"attr": attr, "inferred_guard": guard,
                             "guarded_sites": n, "total_sites": len(accs),
                             "kind": a.kind}))
        return out

    def _finding(self, mod, cls, attr, acc, decl, sev,
                 suppressed: bool = False) -> Finding:
        note = (" [suppressed: hostrace ok — intentional, e.g. a "
                "read-after-publication]" if suppressed else "")
        return Finding(
            rule=self.name, severity=sev, entry_point=mod.modname,
            message=(
                f"{cls.name}.{attr} is declared guarded-by "
                f"{decl.guard_expr} (line {decl.line}) but {acc.kind}s "
                f"WITHOUT it in {acc.method}() — a concurrent holder can "
                f"observe or destroy the update{note}"),
            source=_src(mod.path, acc.line, acc.method),
            details={"attr": attr, "guard": decl.guard_id,
                     "declared_at": decl.line, "kind": acc.kind,
                     "held": sorted(acc.held), "suppressed": suppressed})


def _short(node_id: str) -> str:
    return node_id.rsplit(".", 2)[-2] + "." + node_id.rsplit(".", 1)[-1] \
        if node_id.count(".") >= 2 else node_id


# ---------------------------------------------------------------------------
@register_host_rule
class LockOrderRule(HostRule):
    name = "host-lock-order"

    def run(self, ctx: HostAnalysisContext) -> List[Finding]:
        out: List[Finding] = []
        cycles = ctx.graph.cycles()
        for cyc in cycles:
            hops = []
            sites = []
            for a, b in zip(cyc, cyc[1:]):
                e = ctx.graph.site(a, b)
                where = (f"{_src(e.file, e.line)} [{e.origin}]"
                         if e else "?")
                hops.append(f"{a} -> {b} at {where}")
                if e:
                    sites.append({"src": a, "dst": b, "file": e.file,
                                  "line": e.line, "origin": e.origin})
            out.append(Finding(
                rule=self.name, severity=Severity.HIGH,
                entry_point="lock-graph",
                message=("lock-order cycle (potential deadlock): two "
                         "threads entering from different points block "
                         "forever — " + "; ".join(hops)),
                source=_src(sites[0]["file"], sites[0]["line"])
                if sites else "",
                details={"cycle": cyc, "edges": sites}))
        return out


# ---------------------------------------------------------------------------
@register_host_rule
class BlockingUnderLockRule(HostRule):
    name = "host-blocking-under-lock"

    #: categories that stall every other waiter for an UNBOUNDED time
    _HIGH = {"net", "sleep", "join", "proc"}

    def run(self, ctx: HostAnalysisContext) -> List[Finding]:
        out: List[Finding] = []
        locks = ctx.model.locks()
        for mod in ctx.model.modules.values():
            for cls in mod.classes.values():
                for bc in cls.blocking:
                    if not bc.held:
                        continue
                    f = self._one(mod, cls, bc, locks)
                    if f is not None:
                        out.append(f)
        return out

    def _one(self, mod, cls, bc, locks) -> Optional[Finding]:
        strict = [lid for lid in sorted(bc.held)
                  if not (locks.get(lid) and locks[lid].blocking_ok)]
        allowed = not strict
        suppressed = self.name in bc.suppressed
        if allowed or suppressed:
            why = ("every held lock is annotated hostrace: blocking-ok "
                   "(an intentional serialization lock)" if allowed
                   else "site annotated hostrace: ok")
            sev, note = Severity.INFO, f" [intentional: {why}]"
        elif bc.category in self._HIGH:
            sev, note = Severity.HIGH, ""
        else:
            sev, note = Severity.MEDIUM, ""
        kind = {"net": "a network round-trip", "sleep": "a sleep",
                "join": "a thread join/wait", "proc": "a subprocess",
                "compile": "a trace/compile"}.get(bc.category, bc.category)
        held_txt = ", ".join(sorted(bc.held))
        return Finding(
            rule=self.name, severity=sev, entry_point=mod.modname,
            message=(
                f"{cls.name}.{bc.method}() performs {kind} "
                f"({bc.what}) while holding {held_txt} — every thread "
                "queued on the lock stalls for the full call "
                f"(the r11 health-loop class){note}"),
            source=_src(mod.path, bc.line, bc.method),
            details={"call": bc.what, "category": bc.category,
                     "held": sorted(bc.held),
                     "intentional": allowed or suppressed})


# ---------------------------------------------------------------------------
@register_host_rule
class ToctouRule(HostRule):
    name = "host-toctou"

    def run(self, ctx: HostAnalysisContext) -> List[Finding]:
        out: List[Finding] = []
        for mod in ctx.model.modules.values():
            for cls in mod.classes.values():
                for t in cls.toctou:
                    suppressed = self.name in t.suppressed
                    sev = Severity.INFO if suppressed else Severity.HIGH
                    note = (" [suppressed: hostrace ok — revalidated "
                            "under the lock]" if suppressed else "")
                    out.append(Finding(
                        rule=self.name, severity=sev,
                        entry_point=mod.modname,
                        message=(
                            f"check-then-act on {cls.name}.{t.attr}: read "
                            f"under {_short(t.lock)} (line {t.read_line}), "
                            f"lock released, branch at line {t.test_line} "
                            "decides on the STALE value, then re-acquires "
                            "the lock for the dependent write (line "
                            f"{t.write_line}) — the state may have changed "
                            "in the window; hold the lock across "
                            "check+act, or re-validate before the "
                            f"write{note}"),
                        source=_src(mod.path, t.test_line, t.method),
                        details={"attr": t.attr, "lock": t.lock,
                                 "read_line": t.read_line,
                                 "test_line": t.test_line,
                                 "write_line": t.write_line,
                                 "suppressed": suppressed}))
        return out


# ---------------------------------------------------------------------------
def default_journal_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "benchmarks", "hostrace_journal.json")


def build_context(paths: Optional[Sequence[Tuple[str, str]]] = None,
                  journal: Optional[str] = None) -> HostAnalysisContext:
    """Scan the host modules and merge the runtime journal (explicit path,
    else the committed default when present)."""
    paths = list(paths) if paths else lockmodel.default_host_paths()
    model = lockmodel.scan_modules(paths)
    jpath = journal
    implicit = False
    if jpath in ("", "none"):
        jpath = None  # explicit off: skip even the committed default
    elif jpath is None and os.path.exists(default_journal_path()):
        jpath = default_journal_path()
        implicit = True
    edges: List[dict] = []
    journal_error = None
    if jpath:
        try:
            edges = lockmodel.load_journal(jpath)
        except (OSError, ValueError) as e:
            if not implicit:
                raise  # an explicitly named journal must not half-work
            # the COMMITTED default is stale/corrupt: degrade to a
            # static-only scan and surface it as a finding — nothing
            # about the user's invocation is wrong
            journal_error = f"{type(e).__name__}: {e}"
            jpath = None
    graph = lockmodel.build_order_graph(model, edges)
    return HostAnalysisContext(model, graph, edges, jpath, journal_error)


def analyze_host(paths: Optional[Sequence[Tuple[str, str]]] = None,
                 journal: Optional[str] = None,
                 rules: Optional[Sequence[HostRule]] = None,
                 meta: Optional[dict] = None) -> AnalysisReport:
    """Run the host rules over the control plane -> AnalysisReport.

    Crashed rules report MEDIUM (never silently pass the gate); modules
    that fail to parse do the same and are listed in ``meta``.
    """
    t0 = time.perf_counter()
    ctx = build_context(paths, journal)
    report = AnalysisReport(meta=dict(meta or {}))
    if ctx.journal_error:
        msg = (f"committed lock-order journal failed to load "
               f"({ctx.journal_error}) — the cycle check ran on static "
               "edges only; regenerate with HOSTRACE_JOURNAL_OUT over "
               "the armed suites")
        report.extend([Finding(rule="host-journal",
                               severity=Severity.MEDIUM,
                               entry_point="lock-graph", message=msg)])
    errors = ctx.scan_errors()
    for name, err in errors.items():
        report.extend([Finding(
            rule="host-scan", severity=Severity.MEDIUM, entry_point=name,
            message=f"module failed to parse ({err}) — its locks and "
                    "accesses are INVISIBLE to every host rule")])
    timings = {}
    for rule in (rules if rules is not None else default_host_rules()):
        r0 = time.perf_counter()
        try:
            report.extend(rule.run(ctx))
        except Exception as e:
            report.extend([Finding(
                rule=rule.name, severity=Severity.MEDIUM,
                message=f"rule crashed: {type(e).__name__}: {e}")])
        timings[rule.name] = round(time.perf_counter() - r0, 4)
    modules = sorted(ctx.model.modules)
    n_locks = len(ctx.model.locks())
    report.meta.update({
        "mode": "host",
        "host_schema_version": HOST_SCHEMA_VERSION,
        "modules": modules,
        "n_modules": len(modules),
        "n_classes": len(ctx.model.classes),
        "n_locks": n_locks,
        "n_static_edges": sum(1 for e in ctx.graph.edges
                              if e.origin != "runtime"),
        "n_runtime_edges": sum(1 for e in ctx.graph.edges
                               if e.origin == "runtime"),
        "journal": ctx.journal_path,
        "lock_graph_acyclic": not ctx.graph.cycles(),
        "scan_errors": errors,
        "rule_timings_s": timings,
        "total_s": round(time.perf_counter() - t0, 3),
    })
    return report
