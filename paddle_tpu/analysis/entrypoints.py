"""Shipped entry points as :class:`AnalysisTarget`\\ s — the lint surface.

Every program family this framework actually ships is built here at
CPU-lintable size and handed to the rule engine:

* ``trainer_step``      — the eager ``ParallelTrainer`` hybrid step (dp
  mesh, bf16 compute, GradScaler + anomaly sentinel carries, donation).
* ``pipeline_step``     — the 1F1B ppermute-scan shard_map step
  (``build_gpt_pipeline_step``; collectives + cond-gated CE head).
* ``serving_prefill`` / ``serving_decode`` — the continuous-batching
  engine's two jitted programs over the slot KV cache.  These are linted
  against the engine's *intended* donation (the live jit gates donation
  off on CPU where XLA ignores aliasing), so the report reflects the TPU
  deployment.
* ``serving_*_int8kv`` / ``serving_*_int8w`` — the quantized serving
  plane (int8 paged KV, int8 weights); the dequant-materialization
  check must come back clean here.
* ``exported_infer``    — a ``jit.save``/``jit.load`` StableHLO artifact
  replayed through ``Exported.call``.
* ``static_program``    — a ``static.Program`` op-record IR with
  ``minimize`` attached, compiled exactly as ``Executor.run`` would.

Builders restore global mesh/static state; sizes are small enough that the
whole sweep lints in seconds on CPU (asserted by ``bench._analysis_overhead``).
"""
from __future__ import annotations

import contextlib
import tempfile
from typing import Dict, List, Tuple

import numpy as np

from .graph import AnalysisTarget, target_from_program

__all__ = [
    "trainer_target",
    "pipeline_target",
    "serving_targets",
    "serving_int8_targets",
    "spec_verify_target",
    "exported_target",
    "static_program_target",
    "kernel_targets",
    "shipped_entry_points",
]


@contextlib.contextmanager
def _mesh(axes: Dict[str, int]):
    from ..distributed import env as dist_env

    prev = dist_env.get_mesh()
    dist_env.init_mesh(axes)
    try:
        yield dist_env.get_mesh()
    finally:
        dist_env.set_mesh(prev)


def trainer_target() -> AnalysisTarget:
    """Eager hybrid train step: dp=2, bf16 compute, scaler + sentinel."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..amp.grad_scaler import GradScaler
    from ..distributed.parallel_trainer import ParallelTrainer
    from ..nn import BatchNorm1D, Linear, ReLU, Sequential
    from ..optimizer.optimizers import SGD
    from ..resilience.sentinel import SentinelConfig

    n_dev = len(jax.devices())
    dp = 2 if n_dev >= 2 else 1
    with _mesh({"dp": dp}):
        paddle.seed(0)
        model = Sequential(Linear(32, 256), BatchNorm1D(256), ReLU(),
                           Linear(256, 8))
        trainer = ParallelTrainer(
            model, lambda out, y: ((out - y) ** 2).mean(), SGD(0.01),
            dp_axis="dp", compute_dtype=jnp.bfloat16,
            scaler=GradScaler(init_loss_scaling=1024.0),
            sentinel=SentinelConfig())
        trainer._build()
        xb = jnp.zeros((8, 32), jnp.float32)
        yb = jnp.zeros((8, 8), jnp.float32)
        from ..random import split_key

        args = (trainer.params, trainer.opt_state, trainer.buffers, xb, yb,
                split_key(), trainer.scale_state, trainer.sentinel_state,
                jnp.asarray(0.01, jnp.float32))
        t = AnalysisTarget("trainer_step", trainer._jit_step, args,
                           tags=("train", "spmd"),
                           compute_dtype="bfloat16",
                           mesh_axes={"dp": dp})
        t.jaxpr()  # materialize while the mesh is installed
        return t


def pipeline_target() -> AnalysisTarget:
    """1F1B ppermute-scan pipeline step (pp=2) with the sentinel wired in."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..distributed.meta_parallel.pipeline_schedule import (
        build_gpt_pipeline_step,
    )
    from ..models.gpt import GPTForPretraining, gpt_config
    from ..optimizer.optimizers import AdamW
    from ..resilience.sentinel import SentinelConfig

    if len(jax.devices()) < 2:
        raise RuntimeError("pipeline entry point needs >= 2 devices")
    with _mesh({"pp": 2}):
        paddle.seed(0)
        cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                         num_layers=2, num_attention_heads=4,
                         max_position_embeddings=32, hidden_dropout_prob=0.0,
                         attention_dropout_prob=0.0)
        model = GPTForPretraining(cfg)
        step = build_gpt_pipeline_step(
            model, AdamW(1e-3, parameters=model.parameters()),
            microbatches=2, sentinel=SentinelConfig())
        from ..random import split_key

        x = jnp.zeros((4, 16), jnp.int32)
        kd = jax.random.key_data(split_key())
        args = (step.state["params"], step.state["opt"], x, x, kd,
                jnp.asarray(1e-3, jnp.float32), step.state["sentinel"])
        t = AnalysisTarget("pipeline_step", step.jitted, args,
                           tags=("train", "spmd", "pipeline"),
                           mesh_axes={"pp": 2})
        t.jaxpr()
        return t


def serving_targets() -> List[AnalysisTarget]:
    """The continuous-batching engine's prefill + decode programs (paged
    KV layout — the production default since ISSUE 11; the rules must
    prove the page pool donated and the gather-based attention free of
    per-tick copies)."""
    import paddle_tpu as paddle
    from ..models.gpt import GPTForPretraining, gpt_config
    from ..serving.engine import ContinuousBatchingEngine

    paddle.seed(0)
    cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                     num_layers=2, num_attention_heads=4,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    model = GPTForPretraining(cfg)
    model.eval()
    eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=4)
    prefill = AnalysisTarget(
        "serving_prefill", eng._prefill_jit, eng._prefill_arg_specs(8),
        tags=("serving",),
        donate_argnums=getattr(eng, "_donate_prefill", ()))
    decode = AnalysisTarget(
        "serving_decode", eng._step_jit, eng._step_args_example(),
        tags=("serving",),
        donate_argnums=getattr(eng, "_donate_step", ()))
    # kernel-on arm (r20): same model, paged flash-decode Pallas kernel in
    # place of the XLA gather — linted side by side so the cost registry's
    # pricing of the pallas_call eqns is itself under test
    eng_pl = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=4,
                                      attn_impl="pallas")
    prefill_pl = AnalysisTarget(
        "serving_prefill_pallas", eng_pl._prefill_jit,
        eng_pl._prefill_arg_specs(8),
        tags=("serving", "pallas"),
        donate_argnums=getattr(eng_pl, "_donate_prefill", ()))
    decode_pl = AnalysisTarget(
        "serving_decode_pallas", eng_pl._step_jit,
        eng_pl._step_args_example(),
        tags=("serving", "pallas"),
        donate_argnums=getattr(eng_pl, "_donate_step", ()))
    return [prefill, decode, prefill_pl, decode_pl]


def serving_int8_targets() -> List[AnalysisTarget]:
    """The quantized serving plane (ISSUE 18): the engine's programs with
    int8 paged KV and with int8 weights, linted side by side with the fp
    arm.  The dtype-promotion rule's dequant-materialization check must
    come back clean: the weight matmuls stay ``int8 x int8 -> int32``
    with scales folded into the accumulator, and the per-page KV dequant
    (gather-fed) is exempt by construction."""
    import paddle_tpu as paddle
    from ..models.gpt import GPTForPretraining, gpt_config
    from ..serving.engine import ContinuousBatchingEngine

    cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                     num_layers=2, num_attention_heads=4,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    out: List[AnalysisTarget] = []
    paddle.seed(0)
    kv_model = GPTForPretraining(cfg)
    kv_model.eval()
    kv = ContinuousBatchingEngine(kv_model, max_seq_len=32, n_slots=4,
                                  kv_dtype="int8")
    out.append(AnalysisTarget(
        "serving_prefill_int8kv", kv._prefill_jit, kv._prefill_arg_specs(8),
        tags=("serving", "int8"),
        donate_argnums=getattr(kv, "_donate_prefill", ())))
    out.append(AnalysisTarget(
        "serving_decode_int8kv", kv._step_jit, kv._step_args_example(),
        tags=("serving", "int8"),
        donate_argnums=getattr(kv, "_donate_step", ())))
    paddle.seed(0)
    w8_model = GPTForPretraining(cfg)
    w8_model.eval()
    w8 = ContinuousBatchingEngine(w8_model, max_seq_len=32, n_slots=4,
                                  weight_dtype="int8")
    out.append(AnalysisTarget(
        "serving_prefill_int8w", w8._prefill_jit, w8._prefill_arg_specs(8),
        tags=("serving", "int8"),
        donate_argnums=getattr(w8, "_donate_prefill", ())))
    out.append(AnalysisTarget(
        "serving_decode_int8w", w8._step_jit, w8._step_args_example(),
        tags=("serving", "int8"),
        donate_argnums=getattr(w8, "_donate_step", ())))
    return out


def spec_verify_target() -> AnalysisTarget:
    """The speculative-decoding verify program (ISSUE 19 lint surface):
    one batched target forward + the unrolled k+1 accept loop whose key
    chain must advance by exactly the emitted count per slot — the
    program the key-flow rules exist to certify."""
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from ..models.gpt import GPTForPretraining, gpt_config
    from ..serving.engine import ContinuousBatchingEngine
    from ..serving.spec_decode import SpecDecodeConfig

    paddle.seed(0)
    cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                     num_layers=2, num_attention_heads=4,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    model = GPTForPretraining(cfg)
    model.eval()
    paddle.seed(1)
    dcfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=16,
                      num_layers=1, num_attention_heads=2,
                      max_position_embeddings=64, hidden_dropout_prob=0.0,
                      attention_dropout_prob=0.0)
    draft = GPTForPretraining(dcfg)
    draft.eval()
    k = 2
    eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=4,
                                   page_size=4,
                                   spec_decode=SpecDecodeConfig(draft, k=k))
    sd = eng._spec
    args = (eng._params, eng._buffers,
            jnp.zeros((eng.n_slots, k + 1), jnp.int32),
            jnp.asarray(eng._pos),
            jnp.asarray(np.ones((eng.n_slots,), bool)),
            jnp.asarray(eng._temp), jnp.asarray(eng._topk),
            jnp.asarray(eng._topp), jnp.asarray(eng._keys),
            eng._decode_tables(), eng._pool_k, eng._pool_v)
    t = AnalysisTarget("serving_spec_verify", sd._verify_jit, args,
                       tags=("serving", "spec"),
                       donate_argnums=getattr(sd, "_donate_verify", ()))
    t.jaxpr()
    return t


def exported_target() -> AnalysisTarget:
    """jit.save → jit.load StableHLO artifact, replayed via Exported.call."""
    import os

    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..jit import load, save
    from ..jit.input_spec import InputSpec
    from ..nn import Linear

    import shutil

    paddle.seed(0)
    layer = Linear(16, 8)
    d = tempfile.mkdtemp(prefix="pd_analysis_")
    try:
        path = os.path.join(d, "exported")
        save(layer, path, input_spec=[InputSpec([4, 16], "float32")])
        loaded = load(path)  # artifact fully in memory past this point
    finally:
        shutil.rmtree(d, ignore_errors=True)
    ex = loaded._exported
    params = {n: p._data for n, p in loaded.named_parameters()}
    buffers = {n: b._data for n, b in loaded.named_buffers()}
    args = (params, buffers, jax.random.PRNGKey(0),
            jnp.zeros((4, 16), jnp.float32))
    return AnalysisTarget(
        "exported_infer",
        lambda p, b, k, x: ex.call(p, b, k, x), args,
        tags=("inference",))


def static_program_target() -> AnalysisTarget:
    """static.Program op-record IR with SGD.minimize attached."""
    import paddle_tpu as paddle
    from .. import static
    from ..nn import Linear
    from ..optimizer.optimizers import SGD

    was_static = bool(getattr(paddle, "_static_mode", False))
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            paddle.seed(0)
            x = static.data("x", [None, 8], "float32")
            t = static.data("t", [None, 1], "float32")
            lin = Linear(8, 1)
            pred = lin(x)
            loss = ((pred - t) ** 2).mean()
            opt = SGD(learning_rate=0.1, parameters=lin.parameters())
            opt.minimize(loss)
    finally:
        if not was_static:
            paddle.disable_static()
    return target_from_program(main, name="static_program",
                               feed={"x": np.zeros((4, 8), np.float32),
                                     "t": np.zeros((4, 1), np.float32)})


def kernel_targets() -> List[AnalysisTarget]:
    """One :class:`AnalysisTarget` per shipped Pallas kernel manifest
    case (r24) — lets the generic rule registry / sanitizer replay the
    kernel *launch* programs too, not just the model entry points.  The
    kernel doctor itself (``analysis.kernels``) consumes the manifest
    directly (it needs the raw eqns, not a target)."""
    from ..ops.pallas import kernel_manifest

    out = []
    for case in kernel_manifest():
        fn, args = case.build()
        out.append(AnalysisTarget(f"kernel_{case.name}", fn, args,
                                  tags={"kernel"}))
    return out


_BUILDERS = (
    ("trainer_step", lambda: [trainer_target()]),
    ("pipeline_step", lambda: [pipeline_target()]),
    ("serving", serving_targets),
    ("serving_int8", serving_int8_targets),
    ("spec_verify", lambda: [spec_verify_target()]),
    ("exported_infer", lambda: [exported_target()]),
    ("static_program", lambda: [static_program_target()]),
)


def builder_names() -> List[str]:
    return [name for name, _ in _BUILDERS]


def shipped_entry_points(skip_errors: bool = False,
                         only: Tuple[str, ...] = ()):
    """Build every shipped entry point.  Returns ``(targets, errors)`` —
    ``errors`` maps builder name → repr of the failure (only populated with
    ``skip_errors=True``; otherwise the first failure raises).  Unknown
    ``only`` names raise: a filter that silently matches nothing would turn
    the zero-HIGH CI gate into a no-op."""
    unknown = [n for n in only if n not in dict(_BUILDERS)]
    if unknown:
        raise ValueError(
            f"unknown entry-point builder(s) {unknown}; "
            f"known: {builder_names()}")
    targets: List[AnalysisTarget] = []
    errors: Dict[str, str] = {}
    for name, builder in _BUILDERS:
        if only and name not in only:
            continue
        try:
            targets.extend(builder())
        except Exception as e:
            if not skip_errors:
                raise
            errors[name] = f"{type(e).__name__}: {e}"
    return targets, errors
