"""``python -m paddle_tpu.analysis`` entry.

The lint wants >= 2 devices for the pipeline entry point, but by the time
this module runs the parent package import has already initialized the jax
backend — env changes here are too late.  When the host-device-count flag
is absent, re-exec once with it set (its presence breaks the recursion).
The flag only affects the CPU host platform, so a TPU/GPU host still lints
on its real backend; JAX_PLATFORMS is never overridden.
"""
import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
    os.execv(sys.executable,
             [sys.executable, "-m", "paddle_tpu.analysis"] + sys.argv[1:])

from .cli import main  # noqa: E402

sys.exit(main())
