"""Pluggable TPU-hazard rules over the def-use graph (+ StableHLO text).

Parity role: the reference's compile-time program checks — ProgramDesc
verification passes, the inference pass registry's graph validations, and
the ``FLAGS_check_nan_inf`` instrumentation — reimagined for the jaxpr/HLO
IR that actually reaches a TPU:

* ``dtype-promotion``   — f32/f64 leaks inside bf16/amp programs, traced to
  the producing eqn (the r5 bf16-vs-f32 CE divergence was this).
* ``constant-bloat``    — closure-captured arrays baked into the executable
  (bytes reported; every re-compile re-uploads them, and they bypass
  sharding).
* ``donation-miss``     — entry args with a matching output that are not
  donated ⇒ XLA must keep both copies live (silent HBM copy per step);
  also donated-but-unmatched buffers (donation that aliases nothing).
* ``host-sync``         — callbacks inside hot jitted steps (each one
  stalls the TPU pipeline on a host round-trip).
* ``recompile-hazard``  — weak-typed (Python-scalar) entry args whose dtype
  flips between calls re-trace the program (the runtime half lives in
  :class:`paddle_tpu.analysis.traceguard.TraceGuard`).
* ``collective-order``  — collectives under a ``lax.cond``/``while`` whose
  predicate may differ across the collective's own mesh axis: the static
  deadlock/divergence detector (complements the r7 pmin'd sentinel verdict,
  which is the *runtime* fix for exactly this class of bug).
* ``sharding-propagation`` — lowered-StableHLO check that sharding
  annotations survived for spmd entry points, plus non-splat dense
  constants XLA materialized behind the jaxpr's back.
* ``program-check``     — static.Program op-record IR sanity (dead feeds,
  trainable captures the optimizer never updates).
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence

from .findings import AnalysisReport, Finding, Severity
from .graph import (
    CALLBACK_PRIMS,
    AnalysisTarget,
    DefUseGraph,
    _nbytes,
)

__all__ = [
    "Rule",
    "HostRule",
    "register_rule",
    "register_host_rule",
    "default_rules",
    "default_host_rules",
    "host_rule_names",
    "run_rules",
    "analyze_targets",
    "DtypePromotionRule",
    "ConstantBloatRule",
    "DonationRule",
    "HostSyncRule",
    "RecompileHazardRule",
    "CollectiveOrderRule",
    "ShardingPropagationRule",
    "ProgramRule",
]

_HALF = ("bfloat16", "float16")
_DOT_PRIMS = ("dot_general", "conv_general_dilated")


class Rule:
    """One check. Subclasses set ``name`` and implement :meth:`run`."""

    name = "rule"

    def run(self, target: AnalysisTarget) -> List[Finding]:
        raise NotImplementedError

    def finding(self, severity, message, node=None, **details) -> Finding:
        f = Finding(rule=self.name, severity=severity, message=message,
                    details=details)
        if node is not None:
            f.scope = node.name_stack
            f.source = node.source
        return f


_RULES: Dict[str, type] = {}


def register_rule(cls):
    _RULES[cls.name] = cls
    return cls


def default_rules(**overrides) -> List[Rule]:
    """Fresh instances of every registered rule; ``overrides`` maps rule
    name → ctor kwargs (e.g. thresholds for tests)."""
    from . import keyflow  # noqa: F401 — populate the key-flow rules

    return [cls(**overrides.get(name, {})) for name, cls in _RULES.items()]


class HostRule(Rule):
    """A rule over the HOST control plane (``--host`` mode): ``run`` takes
    a :class:`~paddle_tpu.analysis.hostrace.HostAnalysisContext` — the
    whole-program lock model — instead of a per-entry-point jaxpr target.
    Registered separately so the jaxpr sweep never tries to feed a host
    rule an AnalysisTarget (and vice versa)."""


_HOST_RULES: Dict[str, type] = {}


def register_host_rule(cls):
    _HOST_RULES[cls.name] = cls
    return cls


def host_rule_names() -> List[str]:
    from . import hostrace  # noqa: F401 — populate the registry

    return sorted(_HOST_RULES)


def default_host_rules(only=(), **overrides) -> List[Rule]:
    """Fresh instances of the host-rule registry (optionally narrowed to
    ``only`` — names are validated by the CLI's argparse choices)."""
    from . import hostrace  # noqa: F401 — populate the registry

    names = sorted(_HOST_RULES)
    if only:
        names = [n for n in names if n in set(only)]
    return [_HOST_RULES[n](**overrides.get(n, {})) for n in names]


# ---------------------------------------------------------------------------
@register_rule
class DtypePromotionRule(Rule):
    name = "dtype-promotion"

    #: ops the int8-dequant walk descends through (the rescale/reshape
    #: chain between a dequantized weight and the dot that consumes it)
    _DEQUANT_WALK = ("mul", "add", "sub", "div", "broadcast_in_dim",
                     "reshape", "transpose", "convert_element_type")

    def _int8_weight_dequant(self, g, dot, operand, max_depth=4):
        """The ``convert_element_type`` node dequantizing an int8 ENTRY
        array into this dot operand at full precision, or None.

        A convert fed by a producer (gather, dynamic_slice, ...) is the
        paged-KV per-page dequant — bounded by the gathered working set,
        not a weight copy — and is deliberately not matched."""
        frontier = [(g.producer(dot, operand), 0)]
        seen = set()
        while frontier:
            node, depth = frontier.pop()
            if node is None or node.idx in seen or depth > max_depth:
                continue
            seen.add(node.idx)
            if (node.prim == "convert_element_type" and node.in_avals
                    and node.in_avals[0][1] in ("int8", "uint8")):
                if g.producer(node, 0) is None:
                    return node  # entry array/const: a stored weight
                continue  # gather-fed: per-page KV dequant, exempt
            if node.prim in self._DEQUANT_WALK:
                for j in range(len(node.in_avals)):
                    frontier.append((g.producer(node, j), depth + 1))
        return None

    def run(self, target):
        g = target.graph()
        findings: List[Finding] = []
        dots = [n for n in g.nodes if n.prim in _DOT_PRIMS]
        half_dots = [n for n in dots
                     if n.out_avals and n.out_avals[0][1] in _HALF]
        flagged = set()
        for n in dots:
            if not n.out_avals or n.out_avals[0][1] not in ("float32",
                                                            "float64"):
                continue
            for i in range(len(n.in_avals)):
                prod = g.producer(n, i)
                if (prod is not None and prod.prim == "convert_element_type"
                        and prod.in_avals and prod.in_avals[0][1] in _HALF):
                    findings.append(self.finding(
                        Severity.HIGH,
                        f"{n.out_avals[0][1]} {n.prim} fed by a "
                        f"{prod.in_avals[0][1]}->{n.out_avals[0][1]} upcast "
                        "(half-precision operand silently promoted into a "
                        "full-precision matmul)",
                        node=n, operand=i,
                        upcast_source=prod.source))
                    flagged.add(n.idx)
                    break
        # int8 dequant materialization (ISSUE 18): a float dot fed by a
        # dequantized int8 WEIGHT (int8->float convert on an entry array,
        # rescaled/reshaped on the way in) re-materializes the full-
        # precision weight copy on every call — the quantized path must
        # keep the matmul int8 x int8 -> int32 and fold both scales into
        # the accumulator (nn/functional._linear_int8 does)
        for n in dots:
            if not n.out_avals or n.out_avals[0][1] not in (
                    ("float32", "float64") + _HALF):
                continue
            for i in range(len(n.in_avals)):
                src = self._int8_weight_dequant(g, n, i)
                if src is not None:
                    findings.append(self.finding(
                        Severity.HIGH,
                        f"{n.out_avals[0][1]} {n.prim} fed by a dequantized "
                        f"int8 weight ({src.in_avals[0][1]}->float "
                        "convert_element_type of an entry array): the full-"
                        "precision weight copy is materialized on every "
                        "call; keep the matmul int8 x int8 -> int32 and "
                        "fold the scales into the accumulator",
                        node=n, operand=i, dequant_source=src.source))
                    break
        # "predominantly half-precision" means a MAJORITY of the matmuls:
        # one incidental bf16 dot in an ordinary f32 program is not an amp
        # program and must not flood it with promotion findings
        if len(half_dots) * 2 >= len(dots) and half_dots:
            for n in dots:
                if n.idx in flagged or not n.out_avals:
                    continue
                if n.out_avals[0][1] == "float32":
                    findings.append(self.finding(
                        Severity.MEDIUM,
                        f"float32 {n.prim} inside a predominantly "
                        f"half-precision program ({len(half_dots)}/"
                        f"{len(dots)} matmuls are bf16/f16)",
                        node=n))
        # f64 compute in a program that is otherwise sub-f64
        has_sub64 = any(n.out_avals and n.out_avals[0][1]
                        in ("float32",) + _HALF for n in dots)
        for n in dots:
            if has_sub64 and n.out_avals and n.out_avals[0][1] == "float64":
                findings.append(self.finding(
                    Severity.HIGH,
                    f"float64 {n.prim} in a mixed-precision program "
                    "(accidental x64 promotion doubles HBM traffic and "
                    "falls off the MXU)", node=n))
        return findings


@register_rule
class ConstantBloatRule(Rule):
    name = "constant-bloat"

    def __init__(self, high_bytes: int = 64 << 10,
                 total_bytes: int = 256 << 10):
        self.high_bytes = high_bytes
        self.total_bytes = total_bytes

    def run(self, target):
        g = target.graph()
        findings = []
        for c in g.consts:
            if c.nbytes >= self.high_bytes:
                findings.append(self.finding(
                    Severity.HIGH,
                    f"{c.nbytes} B constant ({c.dtype}{list(c.shape)}) baked "
                    "into the executable — closure-captured weights are "
                    "re-uploaded per compile and bypass sharding; pass them "
                    "as arguments",
                    bytes=c.nbytes, shape=c.shape, dtype=c.dtype,
                    path=c.path))
        total = g.const_bytes()
        if not findings and total >= self.total_bytes:
            findings.append(self.finding(
                Severity.MEDIUM,
                f"{total} B of constants baked into the executable across "
                f"{len(g.consts)} arrays",
                total_bytes=total, n_consts=len(g.consts)))
        return findings


@register_rule
class DonationRule(Rule):
    name = "donation-miss"

    def __init__(self, min_bytes: int = 256, high_bytes: int = 1024):
        self.min_bytes = min_bytes
        self.high_bytes = high_bytes

    def _inputs_outputs(self, target):
        """(label, aval, donated) per input + output avals, from the
        donate_argnums override or the top-level pjit eqn."""
        mask = target.donated_mask()
        g = target.graph()
        if mask is not None:
            closed = target.jaxpr()
            labels = target.arg_labels()
            ins = [(labels[i] if i < len(labels) else "",
                    (tuple(v.aval.shape), str(v.aval.dtype),
                     bool(getattr(v.aval, "weak_type", False))),
                    mask[i] if i < len(mask) else False)
                   for i, v in enumerate(closed.jaxpr.invars)]
            outs = [(tuple(v.aval.shape), str(v.aval.dtype), False)
                    for v in closed.jaxpr.outvars]
            return ins, outs
        sites = [s for s in g.donation_sites if s.path == ()]
        if not sites:
            return None, None
        s = sites[0]
        ins = [(s.in_labels[i] if i < len(s.in_labels) else "",
                s.in_avals[i],
                s.donated[i] if i < len(s.donated) else False)
               for i in range(len(s.in_avals))]
        # skip closure-const invars (unlabeled): constant-bloat owns those
        ins = [x for x in ins if x[0]]
        return ins, list(s.out_avals)

    def run(self, target):
        ins, outs = self._inputs_outputs(target)
        if ins is None:
            return []
        findings = []
        by_sig: Dict[tuple, Dict[str, list]] = {}
        for label, aval, donated in ins:
            sig = (aval[0], aval[1])
            d = by_sig.setdefault(sig, {"donated": [], "live": [], "out": 0})
            d["donated" if donated else "live"].append((label, aval))
        for aval in outs:
            sig = (aval[0], aval[1])
            if sig in by_sig:
                by_sig[sig]["out"] += 1
        for sig, d in by_sig.items():
            free_outputs = d["out"] - len(d["donated"])
            for label, aval in d["live"][: max(free_outputs, 0)]:
                nbytes = _nbytes(aval)
                if nbytes < self.min_bytes:
                    continue
                sev = (Severity.HIGH if nbytes >= self.high_bytes
                       else Severity.INFO)
                findings.append(self.finding(
                    sev,
                    f"entry arg {label} ({aval[1]}{list(aval[0])}, "
                    f"{nbytes} B) has a matching output but is not donated "
                    "— XLA keeps both copies live (a silent HBM copy every "
                    "step); add it to donate_argnums",
                    arg=label, bytes=nbytes))
            if len(d["donated"]) > d["out"]:
                for label, aval in d["donated"][d["out"]:]:
                    if _nbytes(aval) < self.min_bytes:
                        continue
                    findings.append(self.finding(
                        Severity.MEDIUM,
                        f"donated arg {label} ({aval[1]}{list(aval[0])}) has "
                        "no matching output to alias — the buffer is "
                        "invalidated for nothing (donated-but-live callers "
                        "will read garbage)",
                        arg=label))
        return findings


@register_rule
class HostSyncRule(Rule):
    name = "host-sync"

    def run(self, target):
        findings = []
        for n in target.graph().nodes:
            if n.prim not in CALLBACK_PRIMS:
                continue
            sev = (Severity.MEDIUM if n.prim == "debug_callback"
                   else Severity.HIGH)
            findings.append(self.finding(
                sev,
                f"{n.prim} inside a jitted hot path — every call round-trips "
                "to the host and stalls the device pipeline",
                node=n))
        return findings


@register_rule
class RecompileHazardRule(Rule):
    name = "recompile-hazard"

    def run(self, target):
        findings = []
        closed = target.jaxpr()
        labels = target.arg_labels()
        for i, v in enumerate(closed.jaxpr.invars):
            if getattr(v.aval, "weak_type", False):
                label = labels[i] if i < len(labels) else f"arg{i}"
                findings.append(self.finding(
                    Severity.LOW,
                    f"entry arg {label} is weak-typed (a Python scalar): a "
                    "numpy/jax array or a different Python type at the same "
                    "position re-traces the program; pass an explicit array "
                    "(see TraceGuard for runtime attribution)",
                    arg=label))
        return findings


@register_rule
class CollectiveOrderRule(Rule):
    name = "collective-order"

    def run(self, target):
        g = target.graph()
        findings = []
        for site in g.conds:
            seqs = site.branch_collectives
            if not seqs or all(s == seqs[0] for s in seqs[1:]):
                continue
            axes = set()
            for s in seqs:
                for _, ax in s:
                    axes.update(ax)
            unsafe = site.pred_nonuniform & axes
            if unsafe:
                node = g.nodes[site.node]
                findings.append(self.finding(
                    Severity.HIGH,
                    "collective sequence differs between cond branches "
                    f"{[list(s) for s in seqs]} and the predicate may "
                    f"differ across mesh axis/axes {sorted(unsafe)} — ranks "
                    "would issue mismatched collectives (deadlock on TPU, "
                    "silent divergence on CPU emulation); make the "
                    "predicate uniform (psum/pmin it) or hoist the "
                    "collective out of the cond",
                    node=node, axes=sorted(unsafe),
                    pred_nonuniform=sorted(site.pred_nonuniform)))
        for site in g.whiles:
            if not site.body_collectives:
                continue
            axes = set()
            for _, ax in site.body_collectives:
                axes.update(ax)
            unsafe = site.pred_nonuniform & axes
            if unsafe:
                node = g.nodes[site.node]
                findings.append(self.finding(
                    Severity.HIGH,
                    "while-loop body issues collectives over axis/axes "
                    f"{sorted(unsafe)} but the trip count may differ across "
                    "those ranks — mismatched collective counts deadlock",
                    node=node, axes=sorted(unsafe)))
        return findings


@register_rule
class ShardingPropagationRule(Rule):
    name = "sharding-propagation"

    _DENSE = re.compile(
        r"dense<\[[^>]*\]>\s*:\s*tensor<((?:\d+x)*\d+)x[a-z]\w*>")

    def __init__(self, const_bytes: int = 64 << 10,
                 max_text: int = 20_000_000):
        self.const_bytes = const_bytes
        self.max_text = max_text

    def run(self, target):
        if "spmd" not in target.tags:
            return []
        text = target.stablehlo()
        findings = []
        if len(text) > self.max_text:
            return [self.finding(
                Severity.INFO,
                f"lowered StableHLO too large to scan ({len(text)} chars)")]
        if "sharding" not in text:
            findings.append(self.finding(
                Severity.MEDIUM,
                "no sharding annotations survived lowering for an spmd "
                "entry point — every array would be replicated"))
        for m in self._DENSE.finditer(text):
            dims = [int(d) for d in m.group(1).split("x")]
            n = 1
            for d in dims:
                n *= d
            if n * 4 >= self.const_bytes:  # >= f32 bytes lower bound
                findings.append(self.finding(
                    Severity.MEDIUM,
                    f"non-splat dense constant tensor<{m.group(1)}x..> "
                    "materialized in lowered HLO (beyond the jaxpr's "
                    "consts)", elements=n))
        return findings


@register_rule
class ProgramRule(Rule):
    name = "program-check"

    def run(self, target):
        prog = target.program
        if prog is None:
            return []
        findings = []
        used = set()
        for op in prog.ops:
            for x in op.flat_args:
                name = getattr(x, "name", None)
                if name is not None:
                    used.add(name)
        for n, v in prog.feed_vars.items():
            if n != "__rng_key__" and n not in used:
                findings.append(self.finding(
                    Severity.LOW,
                    f"feed '{n}' is declared but never consumed by any op "
                    "(dead feed — the caller pays H2D transfer for "
                    "nothing)", feed=n))
        if prog.optimizer is not None:
            updated = {id(p) for p in prog.opt_params}
            for t, v in prog.captures():
                if v.trainable and id(t) not in updated:
                    findings.append(self.finding(
                        Severity.MEDIUM,
                        f"trainable capture '{v.name}' is never updated by "
                        "the attached optimizer (frozen by accident?)",
                        capture=v.name))
        return findings


# ---------------------------------------------------------------------------
def run_rules(target: AnalysisTarget,
              rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """All findings of ``rules`` (default: registry) on one target."""
    out: List[Finding] = []
    for rule in (rules if rules is not None else default_rules()):
        try:
            fs = rule.run(target)
        except Exception as e:  # a broken rule must not mask other rules,
            # but neither may it silently pass for "no hazards" — MEDIUM
            # keeps it visible in reports (the entry-point smoke test
            # additionally asserts zero crashed rules)
            fs = [Finding(rule=rule.name, severity=Severity.MEDIUM,
                          message=f"rule crashed: {type(e).__name__}: {e}")]
        for f in fs:
            if not f.entry_point:
                f.entry_point = target.name
        out.extend(fs)
    return out


def analyze_targets(targets: Sequence[AnalysisTarget],
                    rules: Optional[Sequence[Rule]] = None,
                    meta: Optional[dict] = None) -> AnalysisReport:
    """Lint every target; per-target wall time lands in
    ``report.meta['timings_s']`` (the bench `_analysis_overhead` source)."""
    import time

    report = AnalysisReport(meta=dict(meta or {}))
    timings = {}
    for t in targets:
        t0 = time.perf_counter()
        report.extend(run_rules(t, rules))
        timings[t.name] = round(time.perf_counter() - t0, 4)
    report.meta["timings_s"] = timings
    report.meta["entry_points"] = [t.name for t in targets]
    return report
