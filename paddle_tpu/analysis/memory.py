"""Liveness-based peak-HBM estimator + the quantitative memory rules.

The question the qualitative graph doctor (PR 4) could not answer: **will
this step fit in HBM?**  This module runs a def-use liveness pass over the
same jaxpr surfaces the walker covers — the analysis underlying Checkmate's
rematerialization planning (Jain et al.) — and produces an estimated
peak-HBM watermark plus a live-set timeline per entry point.

Accounting conventions (pinned; tests hand-compute against them):

* **args** — entry arguments are resident for the whole step *unless
  donated* (donation read from the pjit ``donated_invars`` or the target's
  intended-donation override); donated args are freed at their last use
  and their bytes are reused by matching outputs.
* **consts** — closure-baked constants are resident for the whole program
  (the executable holds them across calls).
* **intermediates** — allocated when their eqn executes (the eqn's inputs
  and outputs are live simultaneously — the transient term), freed after
  their last consumer.
* **scan** — the stacked ``ys`` accumulators and the final carry are
  allocated up front; the body is walked once (per-iteration peak) with
  consts/carry/one xs-slice live; the full stacked xs stays live in the
  enclosing scope for the duration.
* **while/cond** — carry/operands held across the sub-walk; both cond
  branches are walked (peak = max over branches, conservatively).
* **sharding** — per-*device* bytes: ``pjit`` ``in_shardings``/
  ``out_shardings`` divide entry sizes by the product of their mesh axis
  extents; ``shard_map`` bodies use the inner (per-shard) avals directly.

Everything is a static upper-bound estimate of XLA's allocator, not a
simulation — the bench secondary tracks estimator-vs-measured on the real
trainer step.

Rules fed by the estimate: ``oom-risk`` (peak vs a configurable device
budget), ``low-intensity-dot`` (Roofline-memory-bound matmuls), and
``remat-advisor`` (cheapest recompute candidates live on the peak path).
:func:`planner_drift_findings` cross-checks the auto_parallel planner's
analytic byte model against this analyzer on a GPT config.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from .cost import cost_eqn
from .findings import Finding, Severity
from .graph import (
    AnalysisTarget,
    _aval_info,
    _jcore,
    _light_params,
    _name_stack_of,
    _nbytes,
    _source_of,
)
from .rules import Rule, register_rule

__all__ = [
    "MemoryEstimate",
    "TimelinePoint",
    "estimate_memory",
    "memory_estimate",
    "MemoryBudgetRule",
    "LowIntensityDotRule",
    "RematAdvisorRule",
    "planner_drift_findings",
    "MEMORY_SCHEMA_VERSION",
]

#: version of the ``--memory`` JSON artifact layout
MEMORY_SCHEMA_VERSION = 1

_DEFAULT_BUDGET = 16 * 1024 ** 3        # one v5e chip's HBM


@dataclasses.dataclass
class TimelinePoint:
    step: int
    prim: str
    scope: str
    source: str
    live_bytes: int


@dataclasses.dataclass
class MemoryEstimate:
    """Per-device peak/residency estimate for one program."""

    peak_bytes: int = 0
    peak_step: int = -1
    peak_prim: str = ""
    peak_scope: str = ""
    peak_source: str = ""
    args_bytes: int = 0
    consts_bytes: int = 0
    donated_bytes: int = 0
    out_bytes: int = 0
    live_at_peak: List[dict] = dataclasses.field(default_factory=list)
    timeline: List[TimelinePoint] = dataclasses.field(default_factory=list)
    sharded: bool = False
    estimated: bool = False
    n_eqns: int = 0
    #: per-device bytes per entry-arg leaf, labelled ``args[i]<keypath>``
    #: (the planner-drift cross-check sums these by prefix)
    arg_entries: List[dict] = dataclasses.field(default_factory=list)

    def arg_bytes(self, label_prefix: str) -> int:
        """Sum of per-device input bytes whose label starts with
        ``label_prefix`` (e.g. ``"args[0]"`` for the first arg's tree)."""
        return sum(e["bytes"] for e in self.arg_entries
                   if e["label"].startswith(label_prefix))

    @property
    def resident_bytes(self) -> int:
        """Steady-state residency across repeated calls: args + consts +
        the output bytes that cannot alias a donated input."""
        return (self.args_bytes + self.consts_bytes
                + max(self.out_bytes - self.donated_bytes, 0))

    @property
    def peak_where(self) -> str:
        return " @ ".join(x for x in (self.peak_scope, self.peak_source)
                          if x)

    def to_dict(self, timeline_points: int = 256) -> dict:
        tl = self.timeline
        if len(tl) > timeline_points:
            stride = len(tl) // timeline_points + 1
            tl = tl[::stride]
        return {
            "schema_version": MEMORY_SCHEMA_VERSION,
            "peak_hbm_bytes": int(self.peak_bytes),
            "resident_bytes": int(self.resident_bytes),
            "args_bytes": int(self.args_bytes),
            "consts_bytes": int(self.consts_bytes),
            "donated_bytes": int(self.donated_bytes),
            "out_bytes": int(self.out_bytes),
            "peak_site": {"step": self.peak_step, "prim": self.peak_prim,
                          "scope": self.peak_scope,
                          "source": self.peak_source},
            "sharded": self.sharded,
            "estimated": self.estimated,
            "n_eqns": self.n_eqns,
            "live_at_peak_top": [
                {"bytes": int(e["bytes"]), "origin": e["origin"],
                 "label": e["label"], "scope": e["scope"]}
                for e in sorted(self.live_at_peak,
                                key=lambda e: -e["bytes"])[:16]],
            "timeline": [
                {"step": p.step, "prim": p.prim,
                 "live_bytes": int(p.live_bytes)} for p in tl],
        }


def _entry(nbytes, origin, label="", scope="", source="", flops=0.0,
           held=True):
    return {"bytes": int(nbytes), "origin": origin, "label": label,
            "scope": scope, "source": source, "flops": float(flops),
            "held": held, "donated": False}


def _sharding_divisor(sh) -> int:
    """#shards a NamedSharding splits an array into (1 when unknown)."""
    spec = getattr(sh, "spec", None)
    mesh = getattr(sh, "mesh", None)
    if spec is None or mesh is None:
        return 1
    sizes = dict(mesh.shape)
    d = 1
    for part in spec:
        axes = part if isinstance(part, (tuple, list)) else (part,)
        for a in axes:
            if isinstance(a, str):
                d *= int(sizes.get(a, 1))
    return d


def _names_divisor(names, mesh_axes: Dict[str, int]) -> int:
    """#shards from a shard_map in_names/out_names entry ({dim: axes})."""
    d = 1
    values = names.values() if hasattr(names, "values") else ()
    for v in values:
        axes = v if isinstance(v, (tuple, list)) else (v,)
        for a in axes:
            if isinstance(a, str):
                d *= int(mesh_axes.get(a, 1))
    return d


def _is_var(v) -> bool:
    return isinstance(v, _jcore.Var)


class _LivenessWalker:
    def __init__(self, mesh_axes: Optional[Dict[str, int]] = None):
        self.mesh_axes = dict(mesh_axes or {})
        self.step = 0
        self.peak = 0
        self.peak_info = (-1, "", "", "")
        self.live_at_peak: List[dict] = []
        self.timeline: List[TimelinePoint] = []
        self.sharded = False
        self.estimated = False
        self.consts_bytes = 0      # across ALL scopes (executable-held)

    # -- bookkeeping ----------------------------------------------------
    def _point(self, eqn, live, snapshot_fn):
        """``snapshot_fn`` is a thunk: the full live-entry snapshot is
        only materialised when this eqn sets a new peak — building it
        eagerly per eqn would make the sweep O(eqns * live-entries)."""
        self.step += 1
        prim = eqn.primitive.name
        scope = _name_stack_of(eqn)
        source = _source_of(eqn)
        self.timeline.append(
            TimelinePoint(self.step, prim, scope, source, int(live)))
        if live > self.peak:
            self.peak = int(live)
            self.peak_info = (self.step, prim, scope, source)
            self.live_at_peak = [dict(e) for e in snapshot_fn()
                                 if e["bytes"] > 0]

    def _out_entries(self, eqn, last_use, sizes=None):
        """Entries for the eqn's consumed outputs (dead outvars skipped —
        XLA DCEs them)."""
        out = []
        c = cost_eqn(eqn.primitive.name,
                     tuple(_aval_info(v) for v in eqn.invars),
                     tuple(_aval_info(v) for v in eqn.outvars),
                     _light_params(eqn.params), self.mesh_axes)
        if not c.known:
            self.estimated = True
        n_out = max(len(eqn.outvars), 1)
        for j, v in enumerate(eqn.outvars):
            if not _is_var(v) or v not in last_use:
                out.append((v, None))
                continue
            nb = (sizes[j] if sizes is not None
                  else _nbytes(_aval_info(v)))
            out.append((v, _entry(
                nb, "intermediate", eqn.primitive.name,
                _name_stack_of(eqn), _source_of(eqn),
                flops=c.flops / n_out, held=False)))
        return out

    # -- the pass -------------------------------------------------------
    def walk(self, closed, in_entries, ambient, outer_entries, path):
        """Walk one (Closed)Jaxpr scope.  ``in_entries`` align with its
        invars and are counted HERE (the caller subtracted any bytes it had
        already counted for passed-through operands); ``ambient`` is
        everything live in enclosing scopes beyond those entries."""
        jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
        local: Dict = {}
        total = 0
        consts = list(getattr(closed, "consts", ()))
        for k, cv in enumerate(jaxpr.constvars):
            nb = (_nbytes(_aval_info(consts[k])) if k < len(consts)
                  else _nbytes(_aval_info(cv)))
            e = _entry(nb, "const", "const")
            local[cv] = e
            total += e["bytes"]
            self.consts_bytes += e["bytes"]
        for v, e in zip(jaxpr.invars, in_entries):
            local[v] = e
            total += e["bytes"]

        last_use: Dict = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for v in eqn.invars:
                if _is_var(v):
                    last_use[v] = i
        n = len(jaxpr.eqns)
        for v in jaxpr.outvars:
            if _is_var(v):
                last_use[v] = n

        for i, eqn in enumerate(jaxpr.eqns):
            total = self._eqn(eqn, i, local, total, ambient,
                              outer_entries, path, last_use)
        return total

    def _free_dead(self, eqn, i, local, total, last_use):
        for v in set(x for x in eqn.invars if _is_var(x)):
            e = local.get(v)
            if e is None or last_use.get(v) != i:
                continue
            if e["held"] and not e["donated"]:
                continue
            total -= e["bytes"]
            del local[v]
        return total

    def _snapshot(self, outer_entries, local, exclude=()):
        ex = set(map(id, exclude))
        return outer_entries + [e for e in local.values()
                                if id(e) not in ex]

    def _eqn(self, eqn, i, local, total, ambient, outer_entries, path,
             last_use):
        prim = eqn.primitive.name
        params = eqn.params

        if prim == "pjit":
            return self._pjit(eqn, i, local, total, ambient, outer_entries,
                              path, last_use)
        if prim == "scan":
            return self._scan(eqn, i, local, total, ambient, outer_entries,
                              path, last_use)
        if prim == "while":
            return self._while(eqn, i, local, total, ambient, outer_entries,
                               path, last_use)
        if prim == "cond":
            return self._cond(eqn, i, local, total, ambient, outer_entries,
                              path, last_use)
        if prim == "shard_map":
            return self._shard_map(eqn, i, local, total, ambient,
                                   outer_entries, path, last_use)
        subs = [(k, v) for k, v in params.items()
                if isinstance(v, (_jcore.Jaxpr, _jcore.ClosedJaxpr))]
        if subs:
            return self._generic(eqn, i, local, total, ambient,
                                 outer_entries, path, last_use, subs)

        # -- leaf eqn ---------------------------------------------------
        outs = self._out_entries(eqn, last_use)
        out_total = sum(e["bytes"] for _, e in outs if e is not None)
        self._point(eqn, ambient + total + out_total,
                    lambda: self._snapshot(outer_entries, local)
                    + [e for _, e in outs if e is not None])
        for v, e in outs:
            if e is not None:
                local[v] = e
                total += e["bytes"]
        return self._free_dead(eqn, i, local, total, last_use)

    def _passthrough(self, eqn, operands, local):
        """Held copies of operand entries for a sub-scope (the sub-scope
        must not free the enclosing scope's buffers), plus the bytes the
        caller should subtract from its ambient (the copies are re-counted
        inside)."""
        entries, live, shared = [], 0, []
        for v in operands:
            if _is_var(v) and v in local:
                e = local[v]
                c = dict(e, held=True, donated=False)
                entries.append(c)
                live += e["bytes"]
                shared.append(e)
            else:
                nb = _nbytes(_aval_info(v))
                entries.append(_entry(nb, "intermediate", "literal",
                                      held=True))
                shared.append(None)
        return entries, live, shared

    def _alloc_outs(self, eqn, i, local, total, last_use, label=None,
                    sizes=None, accumulator_from=None):
        outs = self._out_entries(eqn, last_use, sizes=sizes)
        out_total = 0
        for j, (v, e) in enumerate(outs):
            if e is None:
                continue
            if label:
                e["label"] = label
            if accumulator_from is not None and j >= accumulator_from:
                e["origin"] = "accumulator"
            local[v] = e
            total += e["bytes"]
            out_total += e["bytes"]
        return total, out_total

    def _pjit(self, eqn, i, local, total, ambient, outer_entries, path,
              last_use):
        params = eqn.params
        inner = params["jaxpr"]
        donated = tuple(params.get("donated_invars", ()))
        inner_entries, passthrough_live = [], 0
        shared_ops = []
        for k, v in enumerate(eqn.invars):
            if _is_var(v) and v in local:
                e = local[v]
                if k < len(donated) and donated[k]:
                    e["held"] = False
                    e["donated"] = True
                inner_entries.append(e)      # shared: donation frees it
                passthrough_live += e["bytes"]
                shared_ops.append((v, e))
            else:
                inner_entries.append(_entry(
                    _nbytes(_aval_info(v)), "intermediate", "literal",
                    held=True))
                shared_ops.append((None, None))
        sub_outer = self._snapshot(
            outer_entries, local, exclude=[e for _, e in shared_ops if e])
        self.walk(inner, inner_entries,
                  ambient + total - passthrough_live, sub_outer,
                  path + (f"pjit:{params.get('name', '')}",))
        # call returns: donated operands are consumed, outputs alias them
        donated_live = 0
        for v, e in shared_ops:
            if e is not None and e["donated"] and v in local:
                donated_live += e["bytes"]
                total -= e["bytes"]
                del local[v]
        out_sizes = []
        out_sh = params.get("out_shardings", ())
        for j, ov in enumerate(eqn.outvars):
            nb = _nbytes(_aval_info(ov))
            if j < len(out_sh):
                nb //= max(_sharding_divisor(out_sh[j]), 1)
            out_sizes.append(nb)
        out_total_probe = sum(
            s for s, v in zip(out_sizes, eqn.outvars)
            if _is_var(v) and v in last_use)
        self._point(eqn, ambient + total + out_total_probe,
                    lambda: self._snapshot(outer_entries, local))
        total, _ = self._alloc_outs(eqn, i, local, total, last_use,
                                    sizes=out_sizes)
        return self._free_dead(eqn, i, local, total, last_use)

    def _scan(self, eqn, i, local, total, ambient, outer_entries, path,
              last_use):
        params = eqn.params
        nc = params.get("num_consts", 0)
        nk = params.get("num_carry", 0)
        body = params["jaxpr"]
        inner_jaxpr = body.jaxpr if hasattr(body, "jaxpr") else body
        # stacked ys accumulators + final carry allocated up front
        probe = sum(_nbytes(_aval_info(v)) for v in eqn.outvars
                    if _is_var(v) and v in last_use)
        self._point(eqn, ambient + total + probe,
                    lambda: self._snapshot(outer_entries, local))
        total, _ = self._alloc_outs(eqn, i, local, total, last_use,
                                    label="scan", accumulator_from=nk)
        held_ops = eqn.invars[:nc + nk]
        pt_entries, pt_live, _ = self._passthrough(eqn, held_ops, local)
        # xs enter the body as per-iteration slices (inner avals)
        xs_entries = [
            _entry(_nbytes(_aval_info(v)), "intermediate", "scan:x-slice",
                   held=True)
            for v in inner_jaxpr.invars[nc + nk:]]
        self.walk(body, pt_entries + xs_entries,
                  ambient + total - pt_live,
                  self._snapshot(outer_entries, local),
                  path + (f"scan@{self.step}",))
        return self._free_dead(eqn, i, local, total, last_use)

    def _while(self, eqn, i, local, total, ambient, outer_entries, path,
               last_use):
        params = eqn.params
        cn = params.get("cond_nconsts", 0)
        bn = params.get("body_nconsts", 0)
        probe = sum(_nbytes(_aval_info(v)) for v in eqn.outvars
                    if _is_var(v) and v in last_use)
        self._point(eqn, ambient + total + probe,
                    lambda: self._snapshot(outer_entries, local))
        total, _ = self._alloc_outs(eqn, i, local, total, last_use,
                                    label="while-carry")
        carry = eqn.invars[cn + bn:]
        self.estimated = True        # trip count unknowable statically
        for label, sub, ops in (
                ("cond", params["cond_jaxpr"], eqn.invars[:cn] + list(carry)),
                ("body", params["body_jaxpr"],
                 eqn.invars[cn:cn + bn] + list(carry))):
            entries, live, _ = self._passthrough(eqn, ops, local)
            self.walk(sub, entries, ambient + total - live,
                      self._snapshot(outer_entries, local),
                      path + (f"while@{self.step}", label))
        return self._free_dead(eqn, i, local, total, last_use)

    def _cond(self, eqn, i, local, total, ambient, outer_entries, path,
              last_use):
        branches = eqn.params.get("branches", ())
        probe = sum(_nbytes(_aval_info(v)) for v in eqn.outvars
                    if _is_var(v) and v in last_use)
        self._point(eqn, ambient + total + probe,
                    lambda: self._snapshot(outer_entries, local))
        total, _ = self._alloc_outs(eqn, i, local, total, last_use,
                                    label="cond")
        args = eqn.invars[1:]
        for bi, br in enumerate(branches):
            entries, live, _ = self._passthrough(eqn, args, local)
            self.walk(br, entries, ambient + total - live,
                      self._snapshot(outer_entries, local),
                      path + (f"cond@{self.step}", f"branch{bi}"))
        return self._free_dead(eqn, i, local, total, last_use)

    def _shard_map(self, eqn, i, local, total, ambient, outer_entries,
                   path, last_use):
        params = eqn.params
        inner = params["jaxpr"]
        inner_jaxpr = inner.jaxpr if hasattr(inner, "jaxpr") else inner
        self.sharded = True
        # inner avals are the per-shard shapes — the per-device truth; the
        # outer (global-view) operand bytes are swapped out for them
        op_live = sum(local[v]["bytes"] for v in eqn.invars
                      if _is_var(v) and v in local)
        inner_entries = [
            _entry(_nbytes(_aval_info(v)), "intermediate", "shard-input",
                   held=True)
            for v in inner_jaxpr.invars]
        ops = [e for v in eqn.invars
               if _is_var(v) and (e := local.get(v)) is not None]
        self.walk(inner, inner_entries, ambient + total - op_live,
                  self._snapshot(outer_entries, local, exclude=ops),
                  path + (f"shard_map@{self.step}",))
        out_names = params.get("out_names", ())
        out_sizes = []
        for j, ov in enumerate(eqn.outvars):
            nb = _nbytes(_aval_info(ov))
            if j < len(out_names):
                nb //= max(_names_divisor(out_names[j], self.mesh_axes), 1)
            out_sizes.append(nb)
        probe = sum(s for s, v in zip(out_sizes, eqn.outvars)
                    if _is_var(v) and v in last_use)
        self._point(eqn, ambient + total - op_live + probe,
                    lambda: self._snapshot(outer_entries, local,
                                           exclude=ops))
        total, _ = self._alloc_outs(eqn, i, local, total, last_use,
                                    sizes=out_sizes)
        return self._free_dead(eqn, i, local, total, last_use)

    def _generic(self, eqn, i, local, total, ambient, outer_entries, path,
                 last_use, subs):
        recursed = False
        for k, sub in subs:
            sub_jaxpr = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            if len(sub_jaxpr.outvars) != len(eqn.outvars):
                continue
            entries, live, _ = self._passthrough(eqn, eqn.invars, local)
            if len(entries) != len(sub_jaxpr.invars):
                continue
            self.walk(sub, entries, ambient + total - live,
                      self._snapshot(outer_entries, local),
                      path + (f"{eqn.primitive.name}@{self.step}", k))
            recursed = True
        if not recursed:  # opaque call: cost it as a leaf
            self.estimated = True
        outs = self._out_entries(eqn, last_use)
        out_total = sum(e["bytes"] for _, e in outs if e is not None)
        self._point(eqn, ambient + total + out_total,
                    lambda: self._snapshot(outer_entries, local))
        for v, e in outs:
            if e is not None:
                local[v] = e
                total += e["bytes"]
        return self._free_dead(eqn, i, local, total, last_use)


def _top_divisors_and_donation(jaxpr, override_mask):
    """Per-top-invar (divisor, donated) via a single-eqn lookahead: a
    jitted entry point is one top pjit eqn (in_shardings + donated_invars),
    a bare shard_map entry is one shard_map eqn (in_names)."""
    n = len(jaxpr.invars)
    div = [1] * n
    don = [bool(override_mask[i]) if override_mask and i < len(override_mask)
           else False for i in range(n)]
    if len(jaxpr.eqns) == 1:
        eqn = jaxpr.eqns[0]
        pos = {v: k for k, v in enumerate(eqn.invars) if _is_var(v)}
        if eqn.primitive.name == "pjit":
            ins = eqn.params.get("in_shardings", ())
            dnv = eqn.params.get("donated_invars", ())
            for i, v in enumerate(jaxpr.invars):
                k = pos.get(v)
                if k is None:
                    continue
                if k < len(ins):
                    div[i] = max(_sharding_divisor(ins[k]), 1)
                if k < len(dnv) and dnv[k]:
                    don[i] = True
        elif eqn.primitive.name == "shard_map":
            mesh = eqn.params.get("mesh")
            sizes = dict(getattr(mesh, "shape", {}) or {})
            in_names = eqn.params.get("in_names", ())
            for i, v in enumerate(jaxpr.invars):
                k = pos.get(v)
                if k is not None and k < len(in_names):
                    div[i] = max(_names_divisor(in_names[k], sizes), 1)
    return div, don


def estimate_memory(target, *, donated_mask=None,
                    mesh_axes: Optional[Dict[str, int]] = None,
                    labels: Optional[List[str]] = None) -> MemoryEstimate:
    """Liveness-based peak-HBM estimate for an :class:`AnalysisTarget` or a
    ClosedJaxpr.  ``donated_mask`` marks entry leaves *intended* donated
    (defaults to the target's override)."""
    if isinstance(target, AnalysisTarget):
        closed = target.jaxpr()
        if donated_mask is None:
            donated_mask = target.donated_mask()
        if mesh_axes is None:
            mesh_axes = target.mesh_axes
        if labels is None:
            labels = target.arg_labels()
    else:
        closed = target
    jaxpr = closed.jaxpr
    labels = labels or []

    div, don = _top_divisors_and_donation(jaxpr, donated_mask)
    w = _LivenessWalker(mesh_axes)
    in_entries = []
    for i, v in enumerate(jaxpr.invars):
        nb = _nbytes(_aval_info(v)) // div[i]
        label = labels[i] if i < len(labels) else f"arg{i}"
        in_entries.append(_entry(nb, "arg", label,
                                 held=not don[i]))
        if don[i]:
            in_entries[-1]["donated"] = True
    args_bytes = sum(e["bytes"] for e in in_entries)
    donated_bytes = sum(e["bytes"] for e in in_entries if e["donated"])

    w.walk(closed, in_entries, 0, [], ())
    consts_bytes = w.consts_bytes   # all scopes (the pjit's closure too)

    # output bytes through the single-top-eqn shardings when present
    out_div = [1] * len(jaxpr.outvars)
    if len(jaxpr.eqns) == 1:
        eqn = jaxpr.eqns[0]
        opos = {v: k for k, v in enumerate(eqn.outvars)}
        if eqn.primitive.name == "pjit":
            osh = eqn.params.get("out_shardings", ())
            for j, ov in enumerate(jaxpr.outvars):
                k = opos.get(ov)
                if k is not None and k < len(osh):
                    out_div[j] = max(_sharding_divisor(osh[k]), 1)
        elif eqn.primitive.name == "shard_map":
            mesh = eqn.params.get("mesh")
            sizes = dict(getattr(mesh, "shape", {}) or {})
            onames = eqn.params.get("out_names", ())
            for j, ov in enumerate(jaxpr.outvars):
                k = opos.get(ov)
                if k is not None and k < len(onames):
                    out_div[j] = max(_names_divisor(onames[k], sizes), 1)
    out_bytes = sum(_nbytes(_aval_info(v)) // out_div[j]
                    for j, v in enumerate(jaxpr.outvars))

    est = MemoryEstimate(
        peak_bytes=int(w.peak), peak_step=w.peak_info[0],
        peak_prim=w.peak_info[1], peak_scope=w.peak_info[2],
        peak_source=w.peak_info[3],
        args_bytes=int(args_bytes), consts_bytes=int(consts_bytes),
        donated_bytes=int(donated_bytes), out_bytes=int(out_bytes),
        live_at_peak=w.live_at_peak, timeline=w.timeline,
        sharded=w.sharded, estimated=w.estimated, n_eqns=w.step,
        arg_entries=[{"label": e["label"], "bytes": e["bytes"],
                      "donated": e["donated"]} for e in in_entries])
    return est


def memory_estimate(target: AnalysisTarget) -> MemoryEstimate:
    """Memoized :func:`estimate_memory` (several rules share one pass)."""
    est = getattr(target, "_memory_estimate", None)
    if est is None:
        est = estimate_memory(target)
        target._memory_estimate = est
    return est


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------
@register_rule
class MemoryBudgetRule(Rule):
    """``oom-risk``: estimated peak HBM vs a configurable device budget."""

    name = "oom-risk"

    def __init__(self, budget_bytes: int = _DEFAULT_BUDGET,
                 headroom: float = 0.92):
        self.budget_bytes = int(budget_bytes)
        self.headroom = headroom

    def run(self, target):
        est = memory_estimate(target)
        peak = est.peak_bytes
        if peak <= self.headroom * self.budget_bytes:
            return []
        top = sorted(est.live_at_peak, key=lambda e: -e["bytes"])[:5]
        hot = ", ".join(f"{e['label'] or e['origin']}={e['bytes']}B"
                        for e in top)
        sev = (Severity.HIGH if peak > self.budget_bytes
               else Severity.MEDIUM)
        verb = ("exceeds" if sev is Severity.HIGH
                else f"is within {100 * (1 - self.headroom):.0f}% of")
        f = self.finding(
            sev,
            f"estimated peak HBM {peak} B {verb} the device budget "
            f"{self.budget_bytes} B at {est.peak_prim} "
            f"(largest live: {hot}) — shrink the batch, shard, donate, "
            "or rematerialize (see remat-advisor)",
            peak_bytes=peak, budget_bytes=self.budget_bytes,
            peak_prim=est.peak_prim, estimated=est.estimated)
        f.scope = est.peak_scope
        f.source = est.peak_source
        return [f]


@register_rule
class LowIntensityDotRule(Rule):
    """``low-intensity-dot``: matmuls far below the Roofline ridge."""

    name = "low-intensity-dot"

    def __init__(self, threshold: float = 16.0, min_bytes: int = 1 << 20,
                 max_findings: int = 8):
        self.threshold = threshold
        self.min_bytes = int(min_bytes)
        self.max_findings = max_findings

    def run(self, target):
        findings = []
        g = target.graph()
        for n in g.nodes:
            if n.prim != "dot_general":
                continue
            c = cost_eqn(n.prim, n.in_avals, n.out_avals, n.params,
                         target.mesh_axes)
            if c.bytes_accessed < self.min_bytes:
                continue
            if c.intensity >= self.threshold:
                continue
            findings.append(self.finding(
                Severity.MEDIUM,
                f"dot_general moves {c.bytes_accessed} B for only "
                f"{c.flops:.0f} flops ({c.intensity:.1f} flops/byte, "
                f"threshold {self.threshold}) — memory-bound on TPU; "
                "batch more rows into the matmul or fuse it with its "
                "neighbours",
                node=n, flops=c.flops, bytes=c.bytes_accessed,
                intensity=round(c.intensity, 2)))
            if len(findings) >= self.max_findings:
                break
        return findings


@register_rule
class RematAdvisorRule(Rule):
    """``remat-advisor``: cheapest recompute candidates on the peak path."""

    name = "remat-advisor"

    def __init__(self, min_bytes: int = 1 << 20,
                 cheap_flops_per_byte: float = 4.0, top_k: int = 3,
                 budget_bytes: int = _DEFAULT_BUDGET):
        self.min_bytes = int(min_bytes)
        self.cheap = cheap_flops_per_byte
        self.top_k = top_k
        self.budget_bytes = int(budget_bytes)

    def run(self, target):
        est = memory_estimate(target)
        inter = [e for e in est.live_at_peak
                 if e["origin"] in ("intermediate", "accumulator")
                 and e["bytes"] > 0]
        inter_bytes = sum(e["bytes"] for e in inter)
        if inter_bytes < self.min_bytes:
            return []
        cands = sorted(
            (e for e in inter
             if e["origin"] == "intermediate" and not e["held"]
             and e["flops"] / max(e["bytes"], 1) <= self.cheap),
            key=lambda e: -e["bytes"])[: self.top_k]
        if not cands:
            return []
        named = "; ".join(
            f"{e['label']}({e['bytes']}B, ~{e['flops']:.0f} flops to "
            f"recompute{', ' + e['scope'] if e['scope'] else ''})"
            for e in cands)
        sev = (Severity.MEDIUM if est.peak_bytes > self.budget_bytes
               else Severity.LOW)
        f = self.finding(
            sev,
            f"{inter_bytes} B of intermediates live at the peak "
            f"({est.peak_bytes} B @ {est.peak_prim}); cheapest recompute "
            f"candidates: {named} — jax.checkpoint the producing segment "
            "to trade these bytes for flops",
            peak_bytes=est.peak_bytes, intermediate_bytes=inter_bytes,
            candidates=[{"label": e["label"], "bytes": e["bytes"],
                         "flops": e["flops"], "scope": e["scope"]}
                        for e in cands])
        f.scope = est.peak_scope
        f.source = est.peak_source
        return [f]


# ---------------------------------------------------------------------------
# planner cross-check (satellite: planner-drift)
# ---------------------------------------------------------------------------
def planner_drift_findings(tolerance: float = 0.15,
                           stats=None) -> List[Finding]:
    """Cross-check the auto_parallel planner's analytic byte model against
    the liveness analyzer's exact per-arg accounting on a (CPU-sized) GPT
    trainer step.  Components compared: parameter bytes and optimizer
    moment bytes (the statically exact ones); drift beyond ``tolerance``
    is a MEDIUM ``planner-drift`` finding.  ``stats`` overrides the
    planner-side :class:`ModelStats` (tests)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..distributed import env as dist_env
    from ..distributed.auto_parallel.planner import ModelStats
    from ..distributed.parallel_trainer import ParallelTrainer
    from ..models.gpt import (
        GPTForPretraining,
        GPTPretrainingCriterion,
        gpt_config,
    )
    from ..optimizer.optimizers import AdamW
    from ..random import split_key

    seq = 16
    cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                     num_layers=2, num_attention_heads=4,
                     max_position_embeddings=32, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    prev = dist_env.get_mesh()
    dist_env.init_mesh({"dp": 1})
    try:
        paddle.seed(0)
        model = GPTForPretraining(cfg)
        crit = GPTPretrainingCriterion(cfg)
        trainer = ParallelTrainer(
            model, lambda out, y: crit(out, y),
            AdamW(learning_rate=1e-4, parameters=model.parameters()),
            dp_axis=None)
        trainer._build()
        x = jnp.zeros((2, seq), jnp.int32)
        args = (trainer.params, trainer.opt_state, trainer.buffers, x, x,
                split_key(), trainer.scale_state, trainer.sentinel_state,
                jnp.asarray(1e-4, jnp.float32))
        target = AnalysisTarget("planner_drift_gpt", trainer._jit_step,
                                args, tags=("train",),
                                mesh_axes={"dp": 1})
        target.jaxpr()
    finally:
        dist_env.set_mesh(prev)

    # baseline = the liveness analyzer's per-arg accounting of the traced
    # step (args: params, opt_state, buffers, x, y, key, ...)
    est = memory_estimate(target)
    measured_params = est.arg_bytes("args[0]")
    measured_moments = est.arg_bytes("args[1]['slots']")
    if not (measured_params and measured_moments):  # label scheme drifted
        measured_params = sum(
            int(a.nbytes) for a in trainer.params.values())
        measured_moments = sum(
            int(a.nbytes)
            for a in jax.tree_util.tree_leaves(trainer.opt_state["slots"]))

    if stats is None:
        stats = ModelStats.from_gpt_config(cfg, seq_len=seq)
    est_params = stats.n_params * stats.param_bytes
    est_moments = 2 * stats.n_params * stats.moment_bytes

    findings: List[Finding] = []
    comps = (("params", est_params, measured_params),
             ("moments", est_moments, measured_moments))
    for name, planned, measured in comps:
        drift = abs(planned - measured) / max(measured, 1)
        if drift > tolerance:
            findings.append(Finding(
                rule="planner-drift", severity=Severity.MEDIUM,
                entry_point="planner_drift_gpt",
                message=(
                    f"auto_parallel planner {name} estimate {planned} B "
                    f"drifts {drift:.0%} from the liveness analyzer's "
                    f"{measured} B (tolerance {tolerance:.0%}) — "
                    "ModelStats' analytic param count no longer matches "
                    "the model family"),
                details={"component": name, "planner_bytes": planned,
                         "measured_bytes": measured,
                         "drift": round(drift, 4)}))
    findings.append(Finding(
        rule="planner-drift", severity=Severity.INFO,
        entry_point="planner_drift_gpt",
        message=(
            "planner-vs-liveness cross-check: "
            + ", ".join(f"{n} {p}B planned / {m}B measured "
                        f"({abs(p - m) / max(m, 1):.1%} drift)"
                        for n, p, m in comps)),
        details={"tolerance": tolerance,
                 "liveness_resident_bytes": est.resident_bytes,
                 "liveness_peak_bytes": est.peak_bytes}))
    return findings
