"""Cost-model-driven auto-parallel planner v2 — the graph doctor plans.

The r5 analytic planner (``distributed/auto_parallel/planner.py``) prices
(dp, mp, pp, ZeRO, remat) candidates with hand-calibrated byte constants;
r10 merely cross-checked those constants against the liveness analyzer
*after the fact* (the 3.1% planner-drift finding).  This module inverts the
dependency — the Alpa-style search the reference fills with
``auto_parallel/cost_model.py`` + fleet ``meta_optimizers`` ProgramDesc
rewrites is done here natively, priced by the r9/r10 static-analysis plane:

1. **enumerate** dp x mp x pp x ZeRO x remat candidates (same divisor
   lattice as the legacy planner);
2. **lower** each candidate's *actual* trainer step to a jaxpr
   :class:`~.graph.AnalysisTarget` — the model is constructed under
   :func:`~paddle_tpu.nn.initializer.abstract_init` (parameters are
   ShapeDtypeStructs) and the step through ``ParallelTrainer(abstract=True)``
   so a 1.3B candidate lowers in seconds without allocating a byte, and is
   never compiled or executed;
3. **price** the lowered program with :func:`~.memory.estimate_memory`
   (per-device liveness watermark: donation frees the f32 params at last
   use, ZeRO slot in_shardings divide the moments, remat2 bodies are
   walked like XLA schedules them) and :func:`~.cost.graph_cost`
   (roofline step time — recompute flops are IN the traced program, no
   4/3 fudge) plus the first-class collective models of :mod:`.cost`
   (ring allreduce, ``reduce_scatter``/``all_gather`` for ZeRO,
   ``all_to_all`` for MoE) applied per mesh axis;
4. **gate** feasibility against the device HBM budget and emit a ranked,
   schema-versioned plan table (``benchmarks/plan_table.json``) with each
   candidate's predicted step time, peak HBM, collective bytes and binding
   roofline term;
5. when the chosen plan needs remat, emit a concrete
   :class:`RematPolicy` (``jax.checkpoint`` over the profiler-scope
   regions on the peak path) that ``ParallelTrainer`` applies.

Lowering convention (pinned; the tests hand-check it): candidates are
lowered as the **data-parallel-local** step — batch = global_batch/dp and
no batch axis on the mesh, so activation/grad intermediates carry their
true per-device sizes; the mp axis and the ZeRO ``sharding`` axis ARE on
the lowering mesh, so parameter/moment entry bytes divide exactly as the
runtime in_shardings divide them.  dp grad-sync traffic (invisible in a
GSPMD jaxpr — XLA inserts it at compile time) is priced analytically with
the shared collective models.  mp-sharded *intermediates* are counted at
global size — a documented conservative upper bound, flagged per row.

The legacy constant model is kept as the **fast-path prior** (candidate
ordering + pruning) and the **fallback** pricer for candidates this CPU
cannot lower (pp > 1 pipelines, meshes wider than the host device count);
fallback-priced rows stay drift-checked against the liveness analyzer
(:func:`plan_consistency_findings`), while analysis-priced rows are
self-consistent with it to <0.5% *by construction* — same estimator, same
target.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "PLAN_SCHEMA_VERSION",
    "DeviceSpec",
    "CandidateSpec",
    "PlannedCandidate",
    "PlanV2",
    "RematPolicy",
    "enumerate_candidates",
    "lower_candidate",
    "plan_gpt",
    "plan_consistency_findings",
    "default_consistency_findings",
    "validation_scenarios",
    "run_validation_scenarios",
]

#: layout version of benchmarks/plan_table.json
PLAN_SCHEMA_VERSION = 1

_GiB = 1024 ** 3


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """One accelerator's roofline corners (defaults: TPU v5e)."""

    hbm_bytes: int = 16 * _GiB
    peak_flops_bf16: float = 197e12
    hbm_bytes_per_s: float = 819e9
    ici_bytes_per_s: float = 4.5e10
    mfu_guess: float = 0.55

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class CandidateSpec:
    """One point of the dp x mp x pp x ZeRO x remat search lattice.

    ``zero_stage`` follows what ``ParallelTrainer`` actually builds: 0 =
    replicated optimizer, 1 = optimizer slots sharded over the ``sharding``
    axis (stage 2 collapses into it — the fused donated step never *holds*
    grads, so there is nothing extra to shard), 3 = params fsdp-sharded
    too."""

    dp: int = 1
    mp: int = 1
    pp: int = 1
    zero_stage: int = 0
    microbatches: int = 1
    remat: bool = False

    @property
    def plan_id(self) -> str:
        return (f"dp{self.dp}-mp{self.mp}-pp{self.pp}-zero{self.zero_stage}"
                f"-m{self.microbatches}-remat{int(self.remat)}")

    @property
    def runtime_axes(self) -> Dict[str, int]:
        """Mesh axes a realized deployment would install (legacy
        ``Candidate.axes`` parity)."""
        out: Dict[str, int] = {}
        if self.pp > 1:
            out["pp"] = self.pp
        if self.mp > 1:
            out["mp"] = self.mp
        if self.dp > 1:
            out["sharding" if self.zero_stage >= 1 else "dp"] = self.dp
        return out or {"dp": 1}

    @property
    def lowering_axes(self) -> Dict[str, int]:
        """Mesh axes the LOWERED (dp-local) step needs: model axes plus the
        ZeRO sharding axis; never a batch axis (the batch is local)."""
        out: Dict[str, int] = {}
        if self.mp > 1:
            out["mp"] = self.mp
        if self.zero_stage >= 1 and self.dp > 1:
            out["sharding"] = self.dp
        return out

    def to_dict(self) -> dict:
        return {"dp": self.dp, "mp": self.mp, "pp": self.pp,
                "zero_stage": self.zero_stage,
                "microbatches": self.microbatches, "remat": self.remat}


@dataclasses.dataclass
class PlannedCandidate:
    """One priced row of the plan table."""

    spec: CandidateSpec
    priced_by: str                      # "analysis" | "legacy-prior"
    feasible: bool = False
    step_time_s: float = float("inf")
    peak_hbm_bytes: int = 0
    binding_term: str = ""              # "compute" | "hbm" | "collective"
    compute_s: float = 0.0
    hbm_s: float = 0.0
    comm_s: float = 0.0
    flops_per_device: float = 0.0
    hbm_bytes_per_device: float = 0.0
    collective_bytes: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    refusal: Optional[str] = None
    peak_site: Dict[str, object] = dataclasses.field(default_factory=dict)
    live_at_peak_top: List[dict] = dataclasses.field(default_factory=list)
    legacy_prior: Dict[str, float] = dataclasses.field(default_factory=dict)
    estimated: bool = False             # any guessed input in the pricing
    lowering_error: Optional[str] = None
    #: the lowered-but-never-executed target (analysis-priced rows only; not
    #: serialized — plan_consistency_findings re-estimates from it)
    target: object = dataclasses.field(default=None, repr=False)

    def to_row(self) -> dict:
        row = {
            "plan_id": self.spec.plan_id,
            **self.spec.to_dict(),
            "priced_by": self.priced_by,
            "feasible": self.feasible,
            "predicted_step_s": (None if self.step_time_s == float("inf")
                                 else round(self.step_time_s, 6)),
            "predicted_peak_hbm_bytes": int(self.peak_hbm_bytes),
            "binding_term": self.binding_term,
            "compute_s": round(self.compute_s, 6),
            "hbm_s": round(self.hbm_s, 6),
            "comm_s": round(self.comm_s, 6),
            "flops_per_device": self.flops_per_device,
            "hbm_bytes_per_device": self.hbm_bytes_per_device,
            "collective_bytes": {k: round(v, 1)
                                 for k, v in self.collective_bytes.items()},
            "estimated": self.estimated,
            "runtime_axes": self.spec.runtime_axes,
        }
        if self.refusal:
            row["refusal"] = self.refusal
        if self.peak_site:
            row["peak_site"] = self.peak_site
        if self.live_at_peak_top:
            row["live_at_peak_top"] = self.live_at_peak_top
        if self.legacy_prior:
            row["legacy_prior"] = self.legacy_prior
        if self.lowering_error:
            row["lowering_error"] = self.lowering_error
        return row


@dataclasses.dataclass(frozen=True)
class RematPolicy:
    """Planner-emitted ``jax.checkpoint`` policy.

    ``scopes`` are the r6 profiler-scope regions on the liveness peak path
    of the *unremated* step — the regions whose intermediates the policy
    trades for recompute flops.  ``ParallelTrainer(remat_policy=...)`` calls
    :meth:`apply`: a model exposing ``set_recompute`` (the GPT family) gets
    per-block ``jax.checkpoint`` at the given granularity/interval — the
    exact program the planner priced; any other model falls back to
    checkpointing the whole loss.  A disabled policy is a strict no-op (the
    trainer's jaxpr is bit-identical to one built without a policy)."""

    enabled: bool = False
    granularity: str = "full"
    interval: int = 1
    scopes: Tuple[str, ...] = ()
    plan_id: str = ""

    def apply(self, trainer) -> None:
        if not self.enabled:
            return
        setter = getattr(trainer.model, "set_recompute", None)
        if setter is not None:
            setter(True, granularity=self.granularity,
                   interval=self.interval)
        else:
            trainer.recompute = True

    def to_dict(self) -> dict:
        return {"enabled": self.enabled, "granularity": self.granularity,
                "interval": self.interval, "scopes": list(self.scopes),
                "plan_id": self.plan_id}


@dataclasses.dataclass
class PlanV2:
    """Ranked result of one planner-v2 search."""

    model_desc: dict
    n_devices: int
    global_batch: int
    seq_len: int
    device: DeviceSpec
    budget_bytes: int
    candidates: List[PlannedCandidate]
    chosen: Optional[PlannedCandidate]
    n_enumerated: int = 0
    n_lowered: int = 0
    search_wall_s: float = 0.0

    def require_feasible(self) -> PlannedCandidate:
        if self.chosen is None:
            lines = [c.refusal or f"{c.spec.plan_id}: infeasible"
                     for c in self.candidates[:12]]
            raise ValueError(
                "planner v2: no candidate fits the device budget "
                f"({self.budget_bytes} B); refused candidates:\n"
                + "\n".join(lines))
        return self.chosen

    def remat_policy(self) -> RematPolicy:
        """The checkpoint policy the chosen plan implies (disabled when the
        plan needs no remat or nothing was feasible)."""
        if self.chosen is None or not self.chosen.spec.remat:
            return RematPolicy(enabled=False)
        # the scopes worth checkpointing come from the UNREMATED twin's
        # peak path (that is the memory the policy removes); fall back to
        # the chosen row's own attribution
        twin = dataclasses.replace(self.chosen.spec, remat=False)
        src = next((c for c in self.candidates
                    if c.spec == twin and c.live_at_peak_top), self.chosen)
        scopes: List[str] = []
        for e in src.live_at_peak_top:
            for comp in _scope_components(e.get("scope", "")):
                if comp not in scopes:
                    scopes.append(comp)
        return RematPolicy(enabled=True, granularity="full",
                           interval=1, scopes=tuple(scopes),
                           plan_id=self.chosen.spec.plan_id)

    def explain(self) -> str:
        lines = ["plan_id                          priced        mem(GiB) "
                 "step(ms) bind        feasible"]
        for c in self.candidates:
            step = ("     inf" if c.step_time_s == float("inf")
                    else f"{c.step_time_s * 1e3:8.2f}")
            lines.append(
                f"{c.spec.plan_id:32s} {c.priced_by:12s} "
                f"{c.peak_hbm_bytes / _GiB:8.2f} {step} "
                f"{c.binding_term or '-':11s} "
                f"{'yes' if c.feasible else 'NO'}")
        return "\n".join(lines)

    def table(self) -> dict:
        return {
            "schema_version": PLAN_SCHEMA_VERSION,
            "model": self.model_desc,
            "n_devices": self.n_devices,
            "global_batch": self.global_batch,
            "seq_len": self.seq_len,
            "device": self.device.to_dict(),
            "budget_bytes": int(self.budget_bytes),
            "chosen": self.chosen.spec.plan_id if self.chosen else None,
            "remat_policy": self.remat_policy().to_dict(),
            "n_enumerated": self.n_enumerated,
            "n_lowered": self.n_lowered,
            "search_wall_s": round(self.search_wall_s, 3),
            "candidates": [c.to_row() for c in self.candidates],
        }


def _scope_components(scope: str) -> Tuple[str, ...]:
    from .graph import scope_components

    return scope_components(scope)


def _divisors(n: int) -> List[int]:
    from ..distributed.auto_parallel.planner import _divisors as d

    return d(n)


def enumerate_candidates(stats, n_devices: int,
                         global_batch: int) -> List[CandidateSpec]:
    """The search lattice, constrained to realizable configurations (hidden
    divisible by mp, layers by pp, batch by dp and microbatches)."""
    out: List[CandidateSpec] = []
    for mp in _divisors(n_devices):
        if stats.hidden % mp:
            continue
        for pp in _divisors(n_devices // mp):
            if stats.n_layers % pp:
                continue
            dp = n_devices // (mp * pp)
            if global_batch % dp:
                continue
            zeros = (0,) if dp == 1 else (0, 1, 3)
            for zero in zeros:
                for m in ((1,) if pp == 1 else (1, 2, 4)):
                    if (global_batch // dp) % m:
                        continue
                    for remat in (False, True):
                        out.append(CandidateSpec(
                            dp=dp, mp=mp, pp=pp, zero_stage=zero,
                            microbatches=m, remat=remat))
    return out


# ---------------------------------------------------------------------------
# legacy prior (the calibrated constant model, kept for ordering + fallback)
# ---------------------------------------------------------------------------
def _legacy_prior(spec: CandidateSpec, stats, global_batch: int,
                  device: DeviceSpec):
    from ..distributed.auto_parallel.planner import (
        GRAD_FACTOR_ALIASED,
        GRAD_FACTOR_HELD,
        _score,
    )

    aliased = spec.microbatches <= 1 and spec.pp == 1
    return _score(stats, stats.n_params, spec.dp, spec.mp, spec.pp,
                  spec.zero_stage, spec.microbatches, spec.remat,
                  global_batch, device.hbm_bytes, device.peak_flops_bf16,
                  device.ici_bytes_per_s, device.mfu_guess,
                  grad_factor=(GRAD_FACTOR_ALIASED if aliased
                               else GRAD_FACTOR_HELD))


class LoweringUnavailable(RuntimeError):
    """This candidate cannot be lowered on this host (pp pipeline, or a
    mesh wider than the local device count) — priced by the legacy prior."""


# ---------------------------------------------------------------------------
# candidate lowering (ShapeDtypeStruct targets — never compiled or executed)
# ---------------------------------------------------------------------------
def _gpt_builder(cfg, moment_dtype: str = "bfloat16"):
    """(spec -> (model, loss_fn, optimizer)) for the GPT family, built
    under ``abstract_init`` so construction allocates nothing."""
    def build(spec: CandidateSpec):
        from ..models.gpt import (
            GPTForPretraining,
            GPTPretrainingCriterion,
        )
        from ..nn.initializer import abstract_init
        from ..optimizer.optimizers import AdamW

        cfg2 = dataclasses.replace(
            cfg, use_recompute=spec.remat, recompute_granularity="full",
            recompute_interval=1)
        with abstract_init():
            model = GPTForPretraining(cfg2)
        crit = GPTPretrainingCriterion(cfg2)
        opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                    moment_dtype=moment_dtype)
        return model, (lambda out, y: crit(out, y)), opt
    return build


def lower_candidate(spec: CandidateSpec, builder: Callable, *,
                    global_batch: int, seq_len: int,
                    compute_dtype="bfloat16"):
    """Lower one candidate's dp-local trainer step to an AnalysisTarget.

    Raises :class:`LoweringUnavailable` for pp > 1 (the 1F1B pipeline is a
    different program family — legacy-prior priced) and for lowering meshes
    wider than the host's device count."""
    import jax
    import jax.numpy as jnp

    from .entrypoints import _mesh
    from .graph import AnalysisTarget

    if spec.pp > 1:
        raise LoweringUnavailable(
            "pp > 1 candidates are priced by the legacy prior (the 1F1B "
            "pipeline step is not abstractly lowerable yet)")
    axes = spec.lowering_axes
    need = 1
    for v in axes.values():
        need *= v
    if need > len(jax.devices()):
        raise LoweringUnavailable(
            f"lowering mesh {axes} needs {need} devices, "
            f"host has {len(jax.devices())}")

    local_batch = global_batch // spec.dp
    with _mesh(axes or {"dp": 1}):
        model, loss_fn, opt = builder(spec)
        from ..distributed.parallel_trainer import ParallelTrainer

        trainer = ParallelTrainer(
            model, loss_fn, opt,
            dp_axis=None,
            fsdp_axis="sharding" if spec.zero_stage >= 3 else None,
            slot_shard_axis=("sharding" if 1 <= spec.zero_stage < 3
                             else None),
            compute_dtype=compute_dtype,
            accumulate_steps=spec.microbatches,
            abstract=True)
        trainer._build()
        xb = jax.ShapeDtypeStruct((local_batch, seq_len), jnp.int32)
        target = AnalysisTarget(
            f"plan:{spec.plan_id}", trainer._jit_step,
            trainer.lowered_step_args(xb, xb),
            tags=("train", "plan"), compute_dtype=compute_dtype,
            mesh_axes=dict(axes))
        target.jaxpr()   # materialize inside the mesh context
    return target


# ---------------------------------------------------------------------------
# pricing
# ---------------------------------------------------------------------------
def _analytic_collectives(spec: CandidateSpec, stats, cfg,
                          global_batch: int) -> Dict[str, float]:
    """dp/ZeRO/mp/MoE wire bytes per step per device — the collectives GSPMD
    will insert at compile time, priced with the shared first-class models
    so the planner and the per-eqn cost model can never drift apart."""
    from .cost import (
        all_gather_bytes,
        all_to_all_bytes,
        reduce_scatter_bytes,
        ring_all_reduce_bytes,
    )

    terms: Dict[str, float] = {}
    shard = spec.mp * spec.pp
    param_shard_bytes = stats.n_params * stats.param_bytes / shard
    b_local = global_batch // spec.dp
    t, h = stats.seq_len, stats.hidden
    layers_local = stats.n_layers // spec.pp

    if spec.dp > 1:
        if spec.zero_stage >= 3:
            # grads land sharded; params are re-gathered for fwd AND bwd
            terms["reduce_scatter:grads@dp"] = reduce_scatter_bytes(
                param_shard_bytes, spec.dp)
            terms["all_gather:params@dp"] = 2 * all_gather_bytes(
                param_shard_bytes, spec.dp)
        else:
            terms["all_reduce:grads@dp"] = ring_all_reduce_bytes(
                param_shard_bytes, spec.dp)
    if spec.mp > 1:
        # 2 activation allreduces per block forward (attn out + mlp out),
        # mirrored in backward
        act = b_local * t * h * stats.act_bytes
        terms["all_reduce:activations@mp"] = 4 * layers_local * \
            ring_all_reduce_bytes(act, spec.mp)
    n_experts = int(getattr(cfg, "num_experts", 0) or 0)
    if n_experts > 0 and spec.dp > 1:
        # MoE dispatch+combine, fwd+bwd, expert-parallel over dp (ROADMAP
        # item 5 — priced now so the planner is ready for the workload)
        every = max(int(getattr(cfg, "moe_every", 1) or 1), 1)
        moe_layers = layers_local // every
        act = b_local * t * h * stats.act_bytes
        cap = float(getattr(cfg, "moe_capacity_factor", 1.0) or 1.0)
        terms["all_to_all:moe@dp"] = 4 * moe_layers * all_to_all_bytes(
            act * cap, spec.dp)
    return terms


def _price_lowered(spec: CandidateSpec, target, stats, cfg,
                   global_batch: int, device: DeviceSpec,
                   budget_bytes: int) -> PlannedCandidate:
    from ..distributed.auto_parallel.planner import OVERLAP_TAX
    from .cost import graph_cost
    from .memory import estimate_memory

    est = estimate_memory(target)
    cost = graph_cost(target.graph(), target.mesh_axes)

    # dp is already local (the lowering convention); mp shards the matmuls
    flops_dev = cost.flops / max(spec.mp, 1)
    bytes_dev = cost.bytes_accessed / max(spec.mp, 1)
    compute_s = flops_dev / (device.peak_flops_bf16 * device.mfu_guess)
    hbm_s = bytes_dev / device.hbm_bytes_per_s

    terms = _analytic_collectives(spec, stats, cfg, global_batch)
    if cost.comm_bytes:
        terms["graph-collectives"] = float(cost.comm_bytes)
    comm_s = sum(terms.values()) / device.ici_bytes_per_s

    roofline_s = max(compute_s, hbm_s)
    step_s = max(roofline_s, comm_s) + OVERLAP_TAX * comm_s
    binding = max((("compute", compute_s), ("hbm", hbm_s),
                   ("collective", comm_s)), key=lambda kv: kv[1])[0]

    peak = int(est.peak_bytes)
    feasible = peak <= budget_bytes
    refusal = None
    if not feasible:
        refusal = (f"{spec.plan_id}: predicted peak HBM {peak} B "
                   f"({peak / _GiB:.2f} GiB) exceeds the device budget "
                   f"{budget_bytes} B at {est.peak_prim}"
                   + (f" [{est.peak_scope}]" if est.peak_scope else ""))
    top = [{"bytes": int(e["bytes"]), "origin": e["origin"],
            "label": e["label"], "scope": e["scope"]}
           for e in sorted(est.live_at_peak,
                           key=lambda e: -e["bytes"])[:5]]
    return PlannedCandidate(
        spec=spec, priced_by="analysis", feasible=feasible,
        step_time_s=step_s, peak_hbm_bytes=peak, binding_term=binding,
        compute_s=compute_s, hbm_s=hbm_s, comm_s=comm_s,
        flops_per_device=flops_dev, hbm_bytes_per_device=bytes_dev,
        collective_bytes=terms, refusal=refusal,
        peak_site={"prim": est.peak_prim, "scope": est.peak_scope,
                   "source": est.peak_source},
        live_at_peak_top=top,
        estimated=bool(est.estimated or cost.estimated),
        target=target)


def _price_legacy(spec: CandidateSpec, prior, budget_bytes: int,
                  reason: str) -> PlannedCandidate:
    c = prior
    feasible = c.mem_bytes <= budget_bytes
    refusal = None
    if not feasible:
        refusal = (f"{spec.plan_id}: legacy-prior memory model "
                   f"{c.mem_bytes / _GiB:.2f} GiB exceeds the device "
                   f"budget {budget_bytes} B")
    return PlannedCandidate(
        spec=spec, priced_by="legacy-prior", feasible=feasible,
        step_time_s=float(c.step_time_s), peak_hbm_bytes=int(c.mem_bytes),
        binding_term="legacy", refusal=refusal,
        legacy_prior={"mem_bytes": float(c.mem_bytes),
                      "step_time_s": float(c.step_time_s),
                      **{f"mem.{k}": float(v)
                         for k, v in c.mem_breakdown.items()}},
        estimated=True, lowering_error=reason)


# ---------------------------------------------------------------------------
# the search
# ---------------------------------------------------------------------------
def plan_gpt(cfg, n_devices: int, global_batch: int, *,
             seq_len: Optional[int] = None,
             device: Optional[DeviceSpec] = None,
             budget_bytes: Optional[int] = None,
             moment_dtype: str = "bfloat16",
             compute_dtype="bfloat16",
             max_lowered: int = 8,
             builder: Optional[Callable] = None) -> PlanV2:
    """Planner-v2 search for a GPT-family config.

    Every candidate gets a legacy-prior score (ordering); the best
    ``max_lowered`` lowerable candidates are lowered to ShapeDtypeStruct
    targets and priced by the liveness estimator + roofline cost model;
    the rest keep the prior (``priced_by="legacy-prior"``).  The returned
    :class:`PlanV2` ranks feasible candidates by predicted step time."""
    from ..distributed.auto_parallel.planner import ModelStats

    t0 = time.perf_counter()
    device = device or DeviceSpec()
    budget = int(budget_bytes if budget_bytes is not None
                 else device.hbm_bytes)
    seq = int(seq_len or getattr(cfg, "max_position_embeddings", 1024))
    stats = ModelStats.from_gpt_config(cfg, seq_len=seq,
                                       moment_dtype=moment_dtype)
    builder = builder or _gpt_builder(cfg, moment_dtype=moment_dtype)

    specs = enumerate_candidates(stats, n_devices, global_batch)
    # prior ordering: feasible-by-prior first, then prior step time — the
    # prior RANKS the lowering queue, it never silently drops a candidate
    priors = {s: _legacy_prior(s, stats, global_batch, device)
              for s in specs}
    order = sorted(specs, key=lambda s: (
        priors[s].mem_bytes > budget, priors[s].step_time_s))

    rows: List[PlannedCandidate] = []
    n_lowered = 0
    for spec in order:
        if n_lowered < max_lowered:
            try:
                target = lower_candidate(
                    spec, builder, global_batch=global_batch, seq_len=seq,
                    compute_dtype=compute_dtype)
            except LoweringUnavailable as e:
                rows.append(_price_legacy(spec, priors[spec], budget,
                                          str(e)))
                continue
            n_lowered += 1
            row = _price_lowered(spec, target, stats, cfg, global_batch,
                                 device, budget)
        else:
            row = _price_legacy(spec, priors[spec], budget,
                                f"pruned (max_lowered={max_lowered}"
                                " reached; legacy prior retained)")
        row.legacy_prior.setdefault("mem_bytes",
                                    float(priors[spec].mem_bytes))
        row.legacy_prior.setdefault("step_time_s",
                                    float(priors[spec].step_time_s))
        rows.append(row)

    # ranking: feasible first; within feasible, ANALYSIS-priced rows
    # outrank legacy-prior rows (the two step-time models are not on the
    # same scale — the prior is the fallback, not a competitor), then
    # predicted step time
    rows.sort(key=lambda r: (not r.feasible,
                             r.priced_by != "analysis", r.step_time_s))
    chosen = next((r for r in rows if r.feasible), None)
    return PlanV2(
        model_desc={"family": "gpt",
                    "hidden": stats.hidden, "layers": stats.n_layers,
                    "n_params": stats.n_params, "seq_len": seq,
                    "moment_dtype": moment_dtype,
                    "vocab_size": int(getattr(cfg, "vocab_size", 0))},
        n_devices=n_devices, global_batch=global_batch, seq_len=seq,
        device=device, budget_bytes=budget, candidates=rows, chosen=chosen,
        n_enumerated=len(specs), n_lowered=n_lowered,
        search_wall_s=time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# self-consistency (retires the r10 after-the-fact drift cross-check)
# ---------------------------------------------------------------------------
def plan_consistency_findings(plan: PlanV2,
                              tolerance: float = 0.005) -> List:
    """The planner-v2 replacement for ``planner_drift_findings``: the
    chosen plan's recorded peak must match a FRESH liveness estimate on its
    own lowered target to < ``tolerance`` (same estimator, same target —
    equality by construction; a drift here means the pricing path mutated
    state it must not).  When the chosen plan was priced by the legacy
    fallback, the old constant-model drift check still applies — that is
    the only mode the constants still gate."""
    from .findings import Finding, Severity
    from .memory import estimate_memory, planner_drift_findings

    if plan.chosen is None:
        return [Finding(
            rule="planner-consistency", severity=Severity.INFO,
            entry_point="planner_v2",
            message="no feasible candidate — nothing to cross-check "
                    "(the refusal table is the result)")]
    chosen = plan.chosen
    if chosen.priced_by != "analysis" or chosen.target is None:
        fs = planner_drift_findings(
            stats=None) if chosen.target is None else []
        fs.append(Finding(
            rule="planner-consistency", severity=Severity.INFO,
            entry_point="planner_v2",
            message=(f"chosen plan {chosen.spec.plan_id} was priced by the "
                     "legacy prior (not lowerable here) — the constant "
                     "model stays drift-checked above")))
        return fs
    fresh = estimate_memory(chosen.target)
    drift = (abs(fresh.peak_bytes - chosen.peak_hbm_bytes)
             / max(chosen.peak_hbm_bytes, 1))
    if drift >= tolerance:
        return [Finding(
            rule="planner-consistency", severity=Severity.HIGH,
            entry_point="planner_v2",
            message=(f"chosen plan {chosen.spec.plan_id} peak "
                     f"{chosen.peak_hbm_bytes} B drifts {drift:.2%} from a "
                     f"fresh liveness estimate {fresh.peak_bytes} B on the "
                     f"SAME target (tolerance {tolerance:.1%}) — the "
                     "pricing path mutated shared state"),
            details={"plan_id": chosen.spec.plan_id,
                     "recorded_peak": chosen.peak_hbm_bytes,
                     "fresh_peak": fresh.peak_bytes,
                     "drift": round(drift, 6)})]
    return [Finding(
        rule="planner-consistency", severity=Severity.INFO,
        entry_point="planner_v2",
        message=(f"chosen plan {chosen.spec.plan_id}: recorded peak "
                 f"{chosen.peak_hbm_bytes} B == fresh liveness estimate "
                 f"{fresh.peak_bytes} B ({drift:.4%} drift, tolerance "
                 f"{tolerance:.1%}) — planner and analyzer are the same "
                 "estimator by construction"),
        details={"plan_id": chosen.spec.plan_id,
                 "drift": round(drift, 6)})]


def default_consistency_findings() -> List:
    """CPU-sized planner-v2 self-consistency sweep for the ``--memory``
    report: a tiny GPT search whose chosen plan is analysis-priced, so the
    <0.5% assertion exercises the real path in a couple of seconds."""
    from ..models.gpt import gpt_config

    cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                     num_layers=2, num_attention_heads=4,
                     max_position_embeddings=32, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    plan = plan_gpt(cfg, 1, 2, seq_len=16, max_lowered=2)
    return plan_consistency_findings(plan)


# ---------------------------------------------------------------------------
# validation scenarios (the committed benchmarks/plan_table.json)
# ---------------------------------------------------------------------------
def validation_scenarios() -> Dict[str, dict]:
    """The two measured single-chip boundaries the ROADMAP mandates:

    * the known-good 1.3B config (bf16 Adam moments, batch 4, seq 1024 —
      the BENCH_r05 lineage ran it at 14.8k tok/s/chip with remat) — the
      planner must CHOOSE a remat plan;
    * the BENCH_r02 16 GB OOM config (f32 moments: "params + Adam moments
      ~15.6 GB", measured OOM with AND without remat) — the planner must
      refuse every candidate and name the violators."""
    return {
        "gpt3-1.3b_v5e1_bf16moments": dict(
            model="gpt3-1.3b", n_devices=1, global_batch=4, seq_len=1024,
            moment_dtype="bfloat16", expect="feasible"),
        "gpt3-1.3b_v5e1_f32moments_bench_r02": dict(
            model="gpt3-1.3b", n_devices=1, global_batch=4, seq_len=1024,
            moment_dtype="float32", expect="infeasible"),
    }


def _scenario_cfg(name: str, seq_len: int):
    from ..models.gpt import gpt_config

    return gpt_config(name, hidden_dropout_prob=0.0,
                      attention_dropout_prob=0.0,
                      max_position_embeddings=seq_len)


def run_validation_scenarios(device: Optional[DeviceSpec] = None,
                             budget_bytes: Optional[int] = None,
                             scenarios: Optional[Dict[str, dict]] = None,
                             max_lowered: int = 4) -> dict:
    """Run the validation scenarios and return the plan_table.json payload
    (``schema_version`` + per-scenario ranked tables + expectation
    verdicts)."""
    device = device or DeviceSpec()
    out = {"schema_version": PLAN_SCHEMA_VERSION, "scenarios": {},
           "all_expectations_met": True}
    for key, sc in (scenarios or validation_scenarios()).items():
        cfg = _scenario_cfg(sc["model"], sc["seq_len"])
        plan = plan_gpt(cfg, sc["n_devices"], sc["global_batch"],
                        seq_len=sc["seq_len"], device=device,
                        budget_bytes=budget_bytes,
                        moment_dtype=sc["moment_dtype"],
                        max_lowered=max_lowered)
        outcome = "feasible" if plan.chosen is not None else "infeasible"
        met = (sc.get("expect") is None) or (outcome == sc["expect"])
        out["scenarios"][key] = dict(
            plan.table(), expect=sc.get("expect"), outcome=outcome,
            expectation_met=met)
        if not met:
            out["all_expectations_met"] = False
    return out
