"""Jaxpr walker: def-use dataflow graph over every IR surface we produce.

The walker recurses through ``pjit`` / ``scan`` / ``while`` / ``cond`` /
``shard_map`` / ``custom_vjp`` sub-jaxprs and flattens the whole program
into a list of :class:`Node` records carrying

* **source attribution** — the eqn's ``source_info`` traceback summary plus
  the ``name_stack`` (the r6 profiler ``scope``/``annotate`` names that
  survive into HLO metadata), so a finding points at *our* region names,
* **def-use edges** — global producer index per operand, crossing sub-jaxpr
  boundaries (an outer convert feeding an inner dot is one edge),
* **mesh-uniformity taint** — per value, the set of mesh axes along which
  it MAY differ between ranks.  ``axis_index('x')`` taints with ``{x}``, a
  ``shard_map`` input sharded over 'x' likewise; ``psum``/``pmin``/
  ``pmax``/``all_gather`` over 'x' REMOVE 'x' (the result is provably
  uniform along the reduced axis).  The collective-order rule uses this to
  prove a ``lax.cond`` predicate uniform along the axes of the collectives
  it gates — the static form of the r7 sentinel's pmin'd verdict.

Three IR front doors:

* :class:`AnalysisTarget` — any callable (jitted or not) + example args;
  ``.jaxpr()`` / ``.graph()`` / ``.stablehlo()`` are built lazily and
  cached.
* :func:`target_from_program` — wraps a ``paddle_tpu.static.Program``
  (op-record IR) by compiling its Executor replay, so every jaxpr rule
  applies to static-mode programs too.
* ``donate_argnums`` override — lints the *intended* donation of entry
  points whose live jit gates donation on backend (serving gates it off on
  CPU where XLA ignores aliasing hints).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

try:  # pinned-version internal (public jax.core deprecates these re-exports)
    from jax._src import core as _jcore
except ImportError:  # pragma: no cover
    import jax.core as _jcore

try:
    from jax._src import source_info_util as _siu
except ImportError:  # pragma: no cover
    _siu = None

__all__ = [
    "Node",
    "DefUseGraph",
    "AnalysisTarget",
    "build_graph",
    "target_from_program",
    "scope_components",
    "COLLECTIVE_PRIMS",
    "UNIFORMIZING_PRIMS",
]

# collectives that must execute in lockstep across the ranks of their axes
# (psum2 / all_gather_invariant are the spellings shard_map bodies lower
# psum / all_gather to on jax 0.4.x — same lockstep semantics)
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmin", "pmax", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter", "pgather",
    "psum2", "all_gather_invariant",
})
# collectives whose OUTPUT is uniform along the reduced/gathered axes
UNIFORMIZING_PRIMS = frozenset({"psum", "pmin", "pmax", "all_gather",
                                "psum2", "all_gather_invariant"})

# host round-trip primitives (the host-sync rule's trigger set)
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "outside_call",
    "host_callback_call",
})


# jax transform wrappers that decorate name-stack components: the scope
# NAME is what attribution groups by, so `transpose(jvp(gpt.attn))` (the
# backward pass of the gpt.attn region) must collapse to `gpt.attn`
_NAME_STACK_WRAPPERS = (
    "jvp", "transpose", "vmap", "pmap", "remat", "checkpoint", "rematted",
    "custom_jvp", "custom_vjp", "vjp",
)
_WRAP_RE = re.compile(
    r"^(?:%s)\((.*)\)$" % "|".join(_NAME_STACK_WRAPPERS))


def scope_components(name_stack: str) -> Tuple[str, ...]:
    """Normalize an eqn's rendered ``name_stack`` into the profiler-scope
    path it belongs to: strip transform wrappers (``jvp(x)`` /
    ``transpose(jvp(x))`` → ``x``) and drop re-entries of an enclosing
    scope (``trainer.loss_grad/transpose(trainer.loss_grad)/jvp(gpt.attn)``
    → ``('trainer.loss_grad', 'gpt.attn')``), so the forward and backward
    halves of one :func:`profiler.scope` region land in the SAME row of
    the scope-attribution table."""
    out: List[str] = []
    for comp in (name_stack or "").split("/"):
        comp = comp.strip()
        while True:
            m = _WRAP_RE.match(comp)
            if m is None:
                break
            comp = m.group(1)
        if not comp or comp in out:
            continue
        out.append(comp)
    return tuple(out)


def _axes_of(params: dict) -> Tuple[str, ...]:
    """Mesh axis names referenced by a collective eqn's params."""
    ax = params.get("axes", params.get("axis_name", ()))
    if isinstance(ax, (str, int)):
        ax = (ax,)
    return tuple(a for a in ax if isinstance(a, str))


def _aval_info(v):
    aval = getattr(v, "aval", v)
    shape = tuple(getattr(aval, "shape", ()))
    dtype = getattr(aval, "dtype", None)
    return (shape, str(dtype) if dtype is not None else None,
            bool(getattr(aval, "weak_type", False)))


def _light_params(params: dict) -> dict:
    """Eqn params minus sub-jaxprs (which the walker recurses separately):
    keeps the scalars the cost model needs (dimension_numbers, scan length,
    collective axes, donated_invars, in_shardings, ...)."""
    out = {}
    for k, v in params.items():
        if isinstance(v, (_jcore.Jaxpr, _jcore.ClosedJaxpr)):
            continue
        if isinstance(v, (tuple, list)) and any(
                isinstance(x, (_jcore.Jaxpr, _jcore.ClosedJaxpr))
                for x in v):
            continue
        out[k] = v
    return out


def _nbytes(aval_info) -> int:
    shape, dtype, _ = aval_info
    if dtype is None:
        return 0
    try:
        item = np.dtype(dtype).itemsize
    except TypeError:  # extended dtypes (typed PRNG keys)
        item = 16
    n = 1
    for s in shape:
        n *= int(s)
    return n * item


@dataclasses.dataclass
class Node:
    """One eqn, anywhere in the (possibly nested) program."""

    idx: int
    prim: str
    path: Tuple[str, ...]          # enclosing sub-jaxpr labels
    name_stack: str                # profiler scope names (HLO metadata)
    source: str                    # "file:line (function)"
    in_avals: Tuple                # ((shape, dtype, weak_type), ...)
    out_avals: Tuple
    in_defs: Tuple[int, ...]       # producing Node idx; -1 literal/unknown,
    #                                -2 const, <= -3 top-level arg (-3 - pos)
    axes: Tuple[str, ...]          # collective axes ((),) for others
    nonuniform: FrozenSet[str]     # mesh axes the outputs may differ along
    in_lits: Tuple[bool, ...] = () # per-operand: jaxpr Literal?
    params: dict = dataclasses.field(default_factory=dict)  # _light_params

    @property
    def where(self) -> str:
        return " @ ".join(x for x in (self.name_stack, self.source) if x)


@dataclasses.dataclass
class DonationSite:
    path: Tuple[str, ...]
    name: str
    donated: Tuple[bool, ...]          # per pjit invar
    in_avals: Tuple                    # per pjit invar
    out_avals: Tuple
    in_labels: Tuple[str, ...]         # arg paths where known, else ""


@dataclasses.dataclass
class ConstInfo:
    path: Tuple[str, ...]
    shape: Tuple[int, ...]
    dtype: str
    nbytes: int


@dataclasses.dataclass
class CondSite:
    node: int
    pred_nonuniform: FrozenSet[str]
    branch_collectives: Tuple[Tuple[Tuple[str, Tuple[str, ...]], ...], ...]
    name_stack: str
    source: str


@dataclasses.dataclass
class WhileSite:
    node: int
    pred_nonuniform: FrozenSet[str]
    body_collectives: Tuple[Tuple[str, Tuple[str, ...]], ...]
    name_stack: str
    source: str


class DefUseGraph:
    """Flattened def-use view of one closed jaxpr (all nesting levels)."""

    def __init__(self, closed_jaxpr):
        self.closed = closed_jaxpr
        self.nodes: List[Node] = []
        self.donation_sites: List[DonationSite] = []
        self.consts: List[ConstInfo] = []
        self.conds: List[CondSite] = []
        self.whiles: List[WhileSite] = []
        self.invar_labels: Dict[Any, str] = {}  # top-level Var -> arg path
        # def ids whose value escapes some jaxpr level (reaches outvars of
        # the top program or any sub-jaxpr: carries, branch outputs, ...)
        self.escaping: set = set()

    # -- queries --------------------------------------------------------
    def producer(self, node: Node, operand: int) -> Optional[Node]:
        i = node.in_defs[operand]
        return self.nodes[i] if i >= 0 else None

    def prims(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for n in self.nodes:
            out[n.prim] = out.get(n.prim, 0) + 1
        return out

    def const_bytes(self) -> int:
        return sum(c.nbytes for c in self.consts)


def _source_of(eqn) -> str:
    if _siu is None:
        return ""
    try:
        return _siu.summarize(eqn.source_info)
    except Exception:
        return ""


def _name_stack_of(eqn) -> str:
    try:
        return str(eqn.source_info.name_stack)
    except Exception:
        return ""


def _taint_out(prim, params, union):
    """Output nonuniformity of one eqn given the union of input taints."""
    if prim == "axis_index":
        return union | set(_axes_of(params))
    if prim in UNIFORMIZING_PRIMS:
        return union - set(_axes_of(params))
    if prim in COLLECTIVE_PRIMS:
        return union | set(_axes_of(params))
    return union


def _taint_closed(closed, in_taints):
    """Taint-only propagation through a (Closed)Jaxpr — no node recording.
    Used to stabilize while/scan loop-carry taints to a FIXPOINT before the
    recorded walk: a body that writes ``axis_index`` into a carry the
    predicate reads makes the trip count rank-divergent, which a single
    forward pass over the initial carry taints cannot see."""
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    env = {cv: frozenset() for cv in jaxpr.constvars}
    invars = jaxpr.invars
    if len(in_taints) == len(invars):
        env.update(zip(invars, in_taints))
    else:
        union = frozenset().union(*in_taints) if in_taints else frozenset()
        for v in invars:
            env[v] = union

    def read(v):
        return frozenset() if isinstance(v, _jcore.Literal) \
            else env.get(v, frozenset())

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        union = frozenset().union(*(read(v) for v in eqn.invars)) \
            if eqn.invars else frozenset()
        out = _taint_out(prim, eqn.params, union)
        if prim == "cond":
            branch_outs = [
                _taint_closed(br, [read(v) for v in eqn.invars[1:]])
                for br in eqn.params.get("branches", ())]
            pred = read(eqn.invars[0])
            outs = [frozenset().union(pred, *(b[i] for b in branch_outs))
                    for i in range(len(eqn.outvars))] if branch_outs else None
            for v, t in zip(eqn.outvars, outs or []):
                env[v] = t
            if outs is not None:
                continue
        elif prim == "while":
            cn = eqn.params.get("cond_nconsts", 0)
            bn = eqn.params.get("body_nconsts", 0)
            ins = [read(v) for v in eqn.invars]
            carry = _while_fixpoint(eqn.params, ins[:cn], ins[cn:cn + bn],
                                    ins[cn + bn:])
            for v, t in zip(eqn.outvars, carry):
                env[v] = t
            continue
        elif prim == "scan":
            ins = [read(v) for v in eqn.invars]
            outs = _scan_fixpoint(eqn.params, ins)
            for v, t in zip(eqn.outvars, outs):
                env[v] = t
            continue
        elif prim == "shard_map":
            # mirror _Walker._recurse: sharded inputs are nonuniform along
            # their in_names axes (the generic branch would under-taint a
            # shard_map inside a while/scan body and certify a deadlock)
            in_names = eqn.params.get("in_names", ())
            mapped = []
            for i, v in enumerate(eqn.invars):
                names = in_names[i] if i < len(in_names) else {}
                ax = set()
                for nv in (names.values() if hasattr(names, "values")
                           else ()):
                    ax.update(a for a in (nv if isinstance(nv, (tuple, list))
                                          else (nv,)) if isinstance(a, str))
                mapped.append(read(v) | ax)
            o = _taint_closed(eqn.params["jaxpr"], mapped)
            if len(o) == len(eqn.outvars):
                for v, t in zip(eqn.outvars, o):
                    env[v] = t
                continue
        else:
            subs = [v for v in eqn.params.values()
                    if isinstance(v, (_jcore.Jaxpr, _jcore.ClosedJaxpr))]
            done = False
            for sub in subs:
                o = _taint_closed(sub, [read(v) for v in eqn.invars])
                if len(o) == len(eqn.outvars):
                    for v, t in zip(eqn.outvars, o):
                        env[v] = t | out
                    done = True
            if done:
                continue
        for v in eqn.outvars:
            env[v] = out
    return [read(v) for v in jaxpr.outvars]


def _while_fixpoint(params, cond_consts, body_consts, carry):
    """Stabilized per-carry-slot taints for a while loop (taints only grow;
    the lattice is finite, so this terminates)."""
    carry = list(carry)
    for _ in range(32):
        out = _taint_closed(params["body_jaxpr"], body_consts + carry)
        pred = _taint_closed(params["cond_jaxpr"], cond_consts + carry)
        pred_t = pred[0] if pred else frozenset()
        # a rank-divergent trip count taints every carry slot
        new = [c | o | pred_t for c, o in zip(carry, out)]
        if new == carry:
            break
        carry = new
    return carry


def _scan_fixpoint(params, in_taints):
    """Stabilized taints for scan (consts + carry + xs -> carry + ys)."""
    nc = params.get("num_consts", 0)
    nk = params.get("num_carry", 0)
    consts, carry, xs = (in_taints[:nc], list(in_taints[nc:nc + nk]),
                         in_taints[nc + nk:])
    out = None
    for _ in range(32):
        out = _taint_closed(params["jaxpr"], consts + carry + xs)
        new = [c | o for c, o in zip(carry, out[:nk])]
        if new == carry:
            break
        carry = new
    ys = out[nk:] if out is not None else []
    return carry + list(ys)


class _Walker:
    def __init__(self, graph: DefUseGraph):
        self.g = graph
        # enclosing eqns' rendered name stacks: jax stores the profiler
        # scope path on the WRAPPING eqn only (an inner-jit body eqn has
        # an empty name_stack), so inner nodes inherit the prefix here —
        # without it every eqn under e.g. jnp.sort's internal jit lands
        # in the "(unscoped)" row
        self._ns: List[str] = []

    def _record_consts(self, closed, path):
        for c in getattr(closed, "consts", ()):
            shape = tuple(getattr(c, "shape", ()))
            dtype = getattr(c, "dtype", None)
            if dtype is None:
                continue
            self.g.consts.append(ConstInfo(
                path, shape, str(dtype),
                _nbytes((shape, str(dtype), False))))

    def walk_closed(self, closed, operand_info, path):
        """Walk a ClosedJaxpr given per-operand (taint, def) info aligned
        with its jaxpr invars; returns per-outvar (taint, def)."""
        self._record_consts(closed, path)
        jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
        env: Dict[Any, Tuple[FrozenSet[str], int]] = {}
        for cv in jaxpr.constvars:
            env[cv] = (frozenset(), -2)
        invars = jaxpr.invars
        if len(operand_info) == len(invars):
            pairs = zip(invars, operand_info)
        else:  # conservative alignment: trailing args match, rest union
            union = frozenset().union(*(t for t, _ in operand_info)) \
                if operand_info else frozenset()
            k = min(len(operand_info), len(invars))
            pairs = [(v, (union, -1)) for v in invars[: len(invars) - k]]
            pairs += list(zip(invars[len(invars) - k:], operand_info[-k:] if k else []))
        for v, info in pairs:
            env[v] = info
        return self._walk_jaxpr(jaxpr, env, path)

    def _read(self, env, v):
        if isinstance(v, _jcore.Literal):
            return (frozenset(), -1)
        return env.get(v, (frozenset(), -1))

    def _walk_jaxpr(self, jaxpr, env, path):
        g = self.g
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            in_info = [self._read(env, v) for v in eqn.invars]
            in_taints = [t for t, _ in in_info]
            union = frozenset().union(*in_taints) if in_taints else frozenset()
            axes = _axes_of(eqn.params) if (
                prim in COLLECTIVE_PRIMS or prim == "axis_index") else ()
            # ONE transfer function, shared with the fixpoint pre-pass —
            # diverging copies would silently corrupt collective verdicts
            out_taint = _taint_out(prim, eqn.params, union)

            own_ns = _name_stack_of(eqn)
            prefix = self._ns[-1] if self._ns else ""
            full_ns = "/".join(x for x in (prefix, own_ns) if x)
            idx = len(g.nodes)
            node = Node(
                idx=idx, prim=prim, path=path,
                name_stack=full_ns, source=_source_of(eqn),
                in_avals=tuple(_aval_info(v) for v in eqn.invars),
                out_avals=tuple(_aval_info(v) for v in eqn.outvars),
                in_defs=tuple(d for _, d in in_info),
                axes=axes, nonuniform=out_taint,
                in_lits=tuple(isinstance(v, _jcore.Literal)
                              for v in eqn.invars),
                params=_light_params(eqn.params),
            )
            g.nodes.append(node)

            self._ns.append(full_ns)
            try:
                out_info = self._recurse(eqn, node, in_info, out_taint, path)
            finally:
                self._ns.pop()
            if out_info is None:
                out_info = [(out_taint, idx)] * len(eqn.outvars)
            for v, info in zip(eqn.outvars, out_info):
                env[v] = info
        outs = [self._read(env, v) for v in jaxpr.outvars]
        # every level's outvars escape: top-level results, loop carries,
        # branch outputs — consumers the def-use edges can't see
        self.g.escaping.update(d for _, d in outs if d >= 0)
        return outs

    # -- sub-jaxpr recursion -------------------------------------------
    def _recurse(self, eqn, node, in_info, out_taint, path):
        prim = eqn.primitive.name
        params = eqn.params
        g = self.g
        sub_path = path + (f"{prim}@{node.idx}",)

        if prim == "pjit":
            closed = params["jaxpr"]
            donated = tuple(params.get("donated_invars", ()))
            labels = tuple(
                "" if isinstance(v, _jcore.Literal)
                else g.invar_labels.get(v, "") for v in eqn.invars)
            g.donation_sites.append(DonationSite(
                path=path, name=str(params.get("name", "")),
                donated=donated,
                in_avals=node.in_avals, out_avals=node.out_avals,
                in_labels=labels))
            return self.walk_closed(closed, in_info, sub_path)

        if prim == "shard_map":
            inner = params["jaxpr"]
            in_names = params.get("in_names", ())
            mapped = []
            for i, (t, d) in enumerate(in_info):
                names = in_names[i] if i < len(in_names) else {}
                ax = set()
                for v in (names.values() if hasattr(names, "values") else ()):
                    ax.update(a for a in (v if isinstance(v, (tuple, list))
                                          else (v,)) if isinstance(a, str))
                mapped.append((t | ax, d))
            return self.walk_closed(inner, mapped, sub_path)

        if prim == "cond":
            branches = params.get("branches", ())
            pred_t, _ = in_info[0]
            seqs = []
            outs = None
            for bi, br in enumerate(branches):
                mark = len(g.nodes)
                o = self.walk_closed(br, in_info[1:],
                                     sub_path + (f"branch{bi}",))
                seqs.append(tuple(
                    (n.prim, n.axes) for n in g.nodes[mark:]
                    if n.prim in COLLECTIVE_PRIMS))
                outs = o if outs is None else [
                    (a[0] | b[0], node.idx) for a, b in zip(outs, o)]
            g.conds.append(CondSite(
                node=node.idx, pred_nonuniform=pred_t,
                branch_collectives=tuple(seqs),
                name_stack=node.name_stack, source=node.source))
            if outs is not None:
                return [(t | pred_t, node.idx) for t, _ in outs]
            return None

        if prim == "while":
            cn = params.get("cond_nconsts", 0)
            bn = params.get("body_nconsts", 0)
            # stabilize loop-carry taints to a fixpoint FIRST: a body that
            # writes axis_index into a carry slot the predicate reads makes
            # the trip count rank-divergent, invisible to a single pass
            stable = _while_fixpoint(
                params, [t for t, _ in in_info[:cn]],
                [t for t, _ in in_info[cn:cn + bn]],
                [t for t, _ in in_info[cn + bn:]])
            carry = [(t, d) for t, (_, d) in zip(stable, in_info[cn + bn:])]
            mark = len(self.g.nodes)
            cond_out = self.walk_closed(
                params["cond_jaxpr"], in_info[:cn] + carry,
                sub_path + ("cond",))
            pred_t = cond_out[0][0] if cond_out else frozenset()
            body_out = self.walk_closed(
                params["body_jaxpr"], in_info[cn:cn + bn] + carry,
                sub_path + ("body",))
            # the cond jaxpr executes once per iteration too: its
            # collectives must match across ranks just like the body's
            body_seq = tuple((n.prim, n.axes) for n in g.nodes[mark:]
                             if n.prim in COLLECTIVE_PRIMS)
            g.whiles.append(WhileSite(
                node=node.idx, pred_nonuniform=pred_t,
                body_collectives=body_seq,
                name_stack=node.name_stack, source=node.source))
            return [(t | pred_t, node.idx) for t, _ in body_out]

        if prim == "scan":
            nc = params.get("num_consts", 0)
            nk = params.get("num_carry", 0)
            stable = _scan_fixpoint(params, [t for t, _ in in_info])
            mapped = list(in_info[:nc]) + [
                (t, d) for t, (_, d) in zip(stable[:nk], in_info[nc:nc + nk])
            ] + list(in_info[nc + nk:])
            return self.walk_closed(params["jaxpr"], mapped, sub_path)

        # generic: custom_vjp/jvp, remat, closed_call, named_call, ...
        subs = [(k, v) for k, v in params.items()
                if isinstance(v, (_jcore.Jaxpr, _jcore.ClosedJaxpr))]
        outs = None
        for k, sub in subs:
            o = self.walk_closed(sub, in_info, sub_path + (k,))
            if len(o) == len(eqn.outvars):
                outs = o
        return outs


def build_graph(closed_jaxpr, invar_labels: Optional[Dict] = None) -> DefUseGraph:
    g = DefUseGraph(closed_jaxpr)
    if invar_labels:
        g.invar_labels.update(invar_labels)
    w = _Walker(g)
    jaxpr = closed_jaxpr.jaxpr
    w._record_consts(closed_jaxpr, ())
    env = {cv: (frozenset(), -2) for cv in jaxpr.constvars}
    for k, v in enumerate(jaxpr.invars):
        # distinct pseudo-def per entry arg so dataflow rules can tell two
        # different inputs apart (both used to collapse to -1)
        env[v] = (frozenset(), -3 - k)
    w._walk_jaxpr(jaxpr, env, ())
    return g


# ---------------------------------------------------------------------------
# analysis targets
# ---------------------------------------------------------------------------
class AnalysisTarget:
    """A lintable entry point: callable + example args (+ metadata).

    ``donate_argnums`` overrides donation info for the donation rule —
    positions into ``args`` whose leaves are *intended* donated (used when
    the live jit gates donation on backend, e.g. serving on CPU).
    ``tags`` steer rule applicability ({"train", "serving", "inference",
    "static", "spmd"}).  ``mesh_axes`` records the mesh the program was
    traced under ({axis: size}) for the quantitative rules — collective
    comm bytes and per-device sharded sizes need the axis extents after the
    builder's mesh context has been torn down.
    """

    def __init__(self, name: str, fn: Callable, args: Sequence = (),
                 kwargs: Optional[dict] = None, *,
                 tags: Sequence[str] = (),
                 donate_argnums: Optional[Sequence[int]] = None,
                 program=None, compute_dtype=None,
                 mesh_axes: Optional[Dict[str, int]] = None):
        self.name = name
        self.fn = fn
        self.args = tuple(args)
        self.kwargs = dict(kwargs or {})
        self.tags = frozenset(tags)
        self.donate_argnums = (tuple(donate_argnums)
                               if donate_argnums is not None else None)
        self.program = program
        self.compute_dtype = compute_dtype
        self.mesh_axes = dict(mesh_axes) if mesh_axes else {}
        self._jaxpr = None
        self._graph = None
        self._stablehlo = None

    # -- lazy IR surfaces ----------------------------------------------
    def jaxpr(self):
        if self._jaxpr is None:
            self._jaxpr = jax.make_jaxpr(self.fn)(*self.args, **self.kwargs)
        return self._jaxpr

    def arg_labels(self) -> List[str]:
        """Flat leaf labels like ``args[0]['params']['w']`` aligned with the
        top-level jaxpr invars."""
        labels = []
        for i, a in enumerate(self.args):
            leaves = jax.tree_util.tree_flatten_with_path(a)[0]
            for p, _ in leaves:
                labels.append(f"args[{i}]" + jax.tree_util.keystr(p))
        return labels

    def graph(self) -> DefUseGraph:
        if self._graph is None:
            closed = self.jaxpr()
            labels = self.arg_labels()
            invars = closed.jaxpr.invars
            mapping = dict(zip(invars, labels)) \
                if len(labels) == len(invars) else {}
            self._graph = build_graph(closed, mapping)
        return self._graph

    def donated_mask(self) -> Optional[Tuple[bool, ...]]:
        """Flat per-leaf intended-donation mask aligned with arg_labels(),
        from the ``donate_argnums`` override (None when not overridden)."""
        if self.donate_argnums is None:
            return None
        mask = []
        for i, a in enumerate(self.args):
            n = len(jax.tree_util.tree_leaves(a))
            mask.extend([i in self.donate_argnums] * n)
        return tuple(mask)

    def stablehlo(self) -> str:
        if self._stablehlo is None:
            fn = self.fn
            lowered = (fn.lower(*self.args, **self.kwargs)
                       if hasattr(fn, "lower")
                       else jax.jit(fn).lower(*self.args, **self.kwargs))
            self._stablehlo = lowered.as_text()
        return self._stablehlo


def target_from_program(program, name: str = "static_program",
                        feed: Optional[Dict[str, Any]] = None,
                        lr: float = 0.01) -> AnalysisTarget:
    """Wrap a ``static.Program`` as an AnalysisTarget by compiling its
    Executor replay (forward + ``jax.grad`` backward + optimizer update —
    exactly what ``Executor.run`` jits), so every jaxpr rule covers the
    op-record IR too."""
    from ..static.executor import Executor

    feed = feed or {}
    feed_names = sorted(n for n in program.feed_vars if n != "__rng_key__")
    feed_arrays = []
    for n in feed_names:
        if n in feed:
            feed_arrays.append(jnp.asarray(feed[n]))
            continue
        v = program.feed_vars[n]
        decl = v._declared_shape or list(v._data.shape)
        shape = tuple(2 if (d is None or d < 0) else int(d) for d in decl)
        feed_arrays.append(jnp.zeros(shape, v._data.dtype))

    if program.loss_var is not None:
        fetch_vars = [program.loss_var]
    elif program.ops:
        fetch_vars = [program.ops[-1].out_vars[0]]
    else:
        fetch_vars = []
    captures = program.captures()
    capture_arrays = [t._data for (t, _) in captures]
    exe = Executor()
    compiled = exe._compile(program, feed_names, fetch_vars, captures)

    rng_args = ()
    if program.rng_used:
        rng_args = (jax.random.key(0),)
    if program.optimizer is not None:
        opt_state = program._opt_state
        if opt_state is None:
            opt_state = program.optimizer.init_state(
                [p._data for p in program.opt_params])
        args = (feed_arrays, capture_arrays, opt_state,
                jnp.asarray(lr, jnp.float32)) + rng_args
    else:
        args = (feed_arrays, capture_arrays) + rng_args
    return AnalysisTarget(name, compiled, args, tags=("static",),
                          program=program)
