"""Static + runtime lock model of the threaded host control plane.

The r9-r17 analysis plane lints what reaches the TPU (jaxprs, HLO); the
bugs that escaped to review in r11-r16 lived one layer up, in the ~6k-line
threaded HOST runtime (serving/, resilience/, distributed/fleet/,
observability/): the drain TOCTOU, the double-resubmit failover race, the
admission-gate over-admit window, the health-loop stall from a blocking
probe under a shared loop.  This module is the model layer those checks
run on — the host analog of :mod:`paddle_tpu.analysis.graph`:

* **Static half** — an AST scan of each control-plane module extracting
  (a) every lock object (``threading.Lock/RLock/Condition`` attributes,
  aliased locals, Conditions wrapping an explicit lock), (b) the
  ``# guarded-by: self._lock`` annotation convention on shared mutable
  attributes, (c) a per-method def-use walk that tracks the held-lock set
  through ``with`` blocks, manual ``acquire``/``finally: release`` pairs
  and lock-local aliases, recording every ``self.<attr>`` access, every
  potentially-blocking call and every lock-acquired-while-holding edge,
  and (d) a one-level interprocedural pass: each known method's *lock
  footprint* (everything it may acquire, transitively) turns
  ``with self._lock: self.scheduler.take()`` into the static order edge
  ``Engine._lock -> FCFSScheduler._cond``.
* **Runtime half** — an opt-in instrumented-lock recorder (lockdep-style):
  while armed, ``threading.Lock``/``RLock`` constructions inside this
  repo return a recording wrapper that notes *held -> acquired* pairs per
  thread.  The conftest fixture arms it for the serving/router/store
  suites and dumps a journal; :func:`merge_journal` folds those observed
  edges into the static graph (creation ``file:line`` -> static lock name)
  so the cycle check sees orders the AST cannot (callbacks, cross-object
  calls through untyped receivers).

Annotation conventions (all plain comments, parsed from source text):

* ``self.attr = ...  # guarded-by: self._lock`` — declares the guard of a
  shared mutable attribute (same line or the line directly above).
* ``self._lock = threading.Lock()  # hostrace: blocking-ok <why>`` —
  declares a *serialization* lock that intentionally holds across
  blocking work (tick locks, trace locks, failover serializers); blocking
  calls under ONLY such locks report INFO instead of HIGH.
* ``<offending line>  # hostrace: ok(<rule>[, <rule>]) <why>`` —
  suppresses a specific rule at a specific site (the r15 trace-lock-held
  pricing pattern); suppressed findings surface as INFO, never silently.

The four rules that consume this model live in
:mod:`paddle_tpu.analysis.hostrace`.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys
import threading
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

__all__ = [
    "LockInfo",
    "GuardDecl",
    "Access",
    "BlockingCall",
    "ToctouSite",
    "OrderEdge",
    "MethodInfo",
    "ClassModel",
    "ModuleModel",
    "HostModel",
    "scan_module",
    "scan_modules",
    "default_host_paths",
    "LockOrderGraph",
    "LockOrderRecorder",
    "InstrumentedLock",
    "arm",
    "disarm",
    "armed",
    "write_journal",
    "load_journal",
    "JOURNAL_SCHEMA_VERSION",
]

JOURNAL_SCHEMA_VERSION = 1

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
#: attribute names treated as locks even when assigned through a helper
#: (e.g. ``self._trace_lock = _model_trace_lock(model)``)
_LOCKISH_NAME = re.compile(r"(^|_)(lock|cond|rlock|mutex)$")

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*)")
_BLOCKING_OK_RE = re.compile(r"#\s*hostrace:\s*blocking-ok")
_SUPPRESS_RE = re.compile(r"#\s*hostrace:\s*ok\(([\w,\s-]+)\)")
_REQUIRES_RE = re.compile(r"#\s*hostrace:\s*requires\(([A-Za-z_][\w.]*)\)")

#: method names that mutate their receiver (a call on a guarded container
#: attribute counts as a WRITE to it)
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse", "rotate", "inc", "dec",
}
#: check-and-set receivers: atomic by construction, never the "act" half
#: of a check-then-act finding
_ATOMIC_MUTATORS = {"setdefault"}

# -- blocking-call classification -------------------------------------------
#: dotted-call names that block on the host (network / clock / process)
_BLOCKING_CALLS = {
    "time.sleep": "sleep",
    "sleep": "sleep",
    "socket.create_connection": "net",
    "urllib.request.urlopen": "net",
    "urlopen": "net",
    "subprocess.run": "proc",
    "subprocess.check_output": "proc",
    "os.system": "proc",
}
#: method names that block when called on a socket/HTTP-ish receiver
_BLOCKING_METHODS = {
    "connect": "net", "accept": "net", "recv": "net", "recv_into": "net",
    "sendall": "net", "getresponse": "net", "makefile": "net",
}
#: any call on a receiver whose name contains one of these is treated as a
#: network round-trip (``rep.probe_client.metrics()``, ``self.store.get()``)
_NET_RECEIVER_HINTS = ("client", "session", "sock", "conn")
#: compile/trace-shaped stalls: bounded but long (the r15 pricing class)
_COMPILE_METHODS = {"jaxpr", "lower", "compile", "stablehlo", "trace"}
_COMPILE_SUFFIX = "_jit"
#: receiver-name hints for ``.join()`` / ``.wait()`` being thread-ish
_THREADISH = ("thread", "proc", "worker", "loop", "server", "stop", "event",
              "done", "ready")


# ---------------------------------------------------------------------------
# dataclasses
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class LockInfo:
    """One lock-valued attribute (or module global)."""

    node_id: str              # "serving.scheduler.FCFSScheduler._cond"
    attr: str                 # "_cond"
    kind: str                 # "lock" | "rlock" | "condition" | "opaque"
    line: int                 # assignment line (runtime creation site)
    blocking_ok: bool = False
    wraps: Optional[str] = None   # condition wrapping an explicit lock


@dataclasses.dataclass
class GuardDecl:
    attr: str
    guard_expr: str           # raw annotation text, e.g. "self._lock"
    guard_id: Optional[str]   # resolved node_id (None = unresolvable)
    line: int


@dataclasses.dataclass
class Access:
    attr: str
    kind: str                 # "read" | "write"
    method: str
    line: int
    held: FrozenSet[str]
    suppressed: FrozenSet[str] = frozenset()


@dataclasses.dataclass
class BlockingCall:
    what: str                 # dotted call text
    category: str             # "net" | "sleep" | "join" | "proc" | "compile"
    method: str
    line: int
    held: FrozenSet[str]
    suppressed: FrozenSet[str] = frozenset()


@dataclasses.dataclass
class ToctouSite:
    attr: str
    lock: str
    read_line: int
    test_line: int
    write_line: int
    method: str
    suppressed: FrozenSet[str] = frozenset()


@dataclasses.dataclass(frozen=True)
class OrderEdge:
    src: str
    dst: str
    file: str
    line: int
    origin: str               # "static" | "static-call" | "runtime"


@dataclasses.dataclass
class MethodInfo:
    name: str
    acquires: Set[str] = dataclasses.field(default_factory=set)
    calls: List[Tuple[Optional[str], str, int, FrozenSet[str]]] = \
        dataclasses.field(default_factory=list)  # (recv_cls, meth, line, held)
    requires: FrozenSet[str] = frozenset()  # declared held-on-entry locks
    line: int = 0


class ClassModel:
    def __init__(self, name: str, modname: str):
        self.name = name
        self.modname = modname
        self.bases: List[str] = []
        self.locks: Dict[str, LockInfo] = {}
        self.guards: Dict[str, GuardDecl] = {}
        self.accesses: List[Access] = []
        self.blocking: List[BlockingCall] = []
        self.toctou: List[ToctouSite] = []
        self.methods: Dict[str, MethodInfo] = {}
        self.attr_types: Dict[str, str] = {}

    def lock_id(self, attr: str, _seen=None) -> Optional[str]:
        """Resolve a lock attr on this class or (transitively) a base —
        ``Counter._values`` is guarded by ``_Metric._lock``."""
        info = self.locks.get(attr)
        if info:
            return info.node_id
        _seen = _seen or {self.name}
        for b in self.bases:
            bc = _KNOWN_CLASSES.get(b)
            if bc is not None and bc.name not in _seen:
                _seen.add(bc.name)
                lid = bc.lock_id(attr, _seen)
                if lid:
                    return lid
        return None

    def guard_equiv(self, guard_id: str) -> FrozenSet[str]:
        """A guard and every lock equivalent to holding it: a Condition
        wrapping lock L guards the same state as L itself."""
        out = {guard_id}
        for info in self.locks.values():
            if info.wraps == guard_id:
                out.add(info.node_id)
            if info.node_id == guard_id and info.wraps:
                out.add(info.wraps)
        return frozenset(out)


class ModuleModel:
    def __init__(self, modname: str, path: str):
        self.modname = modname
        self.path = path
        self.classes: Dict[str, ClassModel] = {}
        self.module_locks: Dict[str, LockInfo] = {}
        #: (realpath, line) AND (repo-relative path, line) -> node_id for
        #: the runtime journal merge (journals persist relative paths so
        #: they survive checkout moves)
        self.creation_sites: Dict[Tuple[str, int], str] = {}
        self.order_edges: List[OrderEdge] = []
        self.error: Optional[str] = None

    def add_creation_site(self, real: str, line: int, node_id: str):
        self.creation_sites[(real, line)] = node_id
        self.creation_sites[(_rel_site(real), line)] = node_id

    def all_locks(self) -> Dict[str, LockInfo]:
        out = dict(self.module_locks)
        for c in self.classes.values():
            for info in c.locks.values():
                out[info.node_id] = info
        return out


class HostModel:
    """Every scanned module + the whole-program views the rules consume."""

    def __init__(self, modules: Dict[str, ModuleModel]):
        self.modules = modules
        self.classes: Dict[str, ClassModel] = {}
        for m in modules.values():
            for c in m.classes.values():
                # first definition wins on (rare) cross-module name clashes
                self.classes.setdefault(c.name, c)
        self._footprints: Optional[Dict[Tuple[str, str], Set[str]]] = None

    def locks(self) -> Dict[str, LockInfo]:
        out: Dict[str, LockInfo] = {}
        for m in self.modules.values():
            out.update(m.all_locks())
        return out

    def lock_for_site(self, path: str, line: int) -> Optional[str]:
        """Resolve a journal creation site to its static lock name. Sites
        are matched by repo-RELATIVE path (``paddle_tpu/...``) so a
        journal recorded on one checkout resolves on another; absolute
        paths from same-machine journals still match via their realpath
        key."""
        keys = ((os.path.realpath(path), int(line)),
                (_rel_site(path), int(line)))
        for m in self.modules.values():
            for key in keys:
                node = m.creation_sites.get(key)
                if node:
                    return node
        return None

    # -- interprocedural lock footprints --------------------------------
    def footprints(self) -> Dict[Tuple[str, str], Set[str]]:
        """(class, method) -> every lock the method may acquire, including
        through calls to other known methods (fixpoint, bounded)."""
        if self._footprints is not None:
            return self._footprints
        fp: Dict[Tuple[str, str], Set[str]] = {}
        for c in self.classes.values():
            for mi in c.methods.values():
                fp[(c.name, mi.name)] = set(mi.acquires)
        for _ in range(12):
            changed = False
            for c in self.classes.values():
                for mi in c.methods.values():
                    cur = fp[(c.name, mi.name)]
                    for recv_cls, meth, _line, _held in mi.calls:
                        callee = fp.get((recv_cls or c.name, meth))
                        if callee and not callee <= cur:
                            cur |= callee
                            changed = True
            if not changed:
                break
        self._footprints = fp
        return fp

    def static_edges(self) -> List[OrderEdge]:
        """Direct ``with a: with b`` nesting edges plus call-through edges
        (held locks x callee footprint)."""
        edges: List[OrderEdge] = []
        seen: Set[Tuple[str, str, int]] = set()
        for m in self.modules.values():
            for e in m.order_edges:
                key = (e.src, e.dst, e.line)
                if key not in seen:
                    seen.add(key)
                    edges.append(e)
        fp = self.footprints()
        for m in self.modules.values():
            for c in m.classes.values():
                for mi in c.methods.values():
                    for recv_cls, meth, line, held in mi.calls:
                        if not held:
                            continue
                        callee = fp.get((recv_cls or c.name, meth))
                        if not callee:
                            continue
                        for src in held:
                            if src.startswith("?."):
                                continue
                            for dst in callee:
                                if src == dst or dst.startswith("?."):
                                    continue
                                key = (src, dst, line)
                                if key in seen:
                                    continue
                                seen.add(key)
                                edges.append(OrderEdge(
                                    src=src, dst=dst, file=m.path,
                                    line=line, origin="static-call"))
        return edges


# ---------------------------------------------------------------------------
# source-comment annotations
# ---------------------------------------------------------------------------
class _Annotations:
    def __init__(self, source: str):
        self.guarded: Dict[int, str] = {}
        self.blocking_ok: Set[int] = set()
        self.suppress: Dict[int, FrozenSet[str]] = {}
        self.requires: Dict[int, str] = {}
        #: lines that are comment-ONLY: a trailing annotation binds to its
        #: own statement, never to the statement on the next line
        self.comment_only: Set[int] = set()
        for i, text in enumerate(source.splitlines(), start=1):
            if text.lstrip().startswith("#"):
                self.comment_only.add(i)
            m = _GUARDED_BY_RE.search(text)
            if m:
                self.guarded[i] = m.group(1)
            if _BLOCKING_OK_RE.search(text):
                self.blocking_ok.add(i)
            m = _SUPPRESS_RE.search(text)
            if m:
                rules = frozenset(
                    r.strip() for r in m.group(1).split(",") if r.strip())
                self.suppress[i] = rules
            m = _REQUIRES_RE.search(text)
            if m:
                self.requires[i] = m.group(1)

    def _above(self, line: int) -> Optional[int]:
        return line - 1 if (line - 1) in self.comment_only else None

    def guard_at(self, line: int) -> Optional[str]:
        """Annotation on the statement line itself, or a comment-only
        line directly above (a trailing comment never leaks downward)."""
        return self.guarded.get(line) or \
            self.guarded.get(self._above(line) or -1)

    def blocking_ok_at(self, line: int) -> bool:
        return line in self.blocking_ok or \
            (self._above(line) or -1) in self.blocking_ok

    def suppressed_at(self, line: int) -> FrozenSet[str]:
        return self.suppress.get(line, frozenset()) | \
            self.suppress.get(self._above(line) or -1, frozenset())

    def requires_at(self, line: int) -> Optional[str]:
        """``# hostrace: requires(self._lock)`` on the ``def`` line (or
        the comment line above): the method is documented as
        called-with-lock-held — the walker seeds its held set and the
        guarded-by rule verifies every recorded CALLER actually holds
        it."""
        return self.requires.get(line) or \
            self.requires.get(self._above(line) or -1)


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------
def _dotted(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _ctor_kind(call: ast.AST) -> Optional[str]:
    """'lock'/'rlock'/'condition' when ``call`` constructs a lock."""
    if not isinstance(call, ast.Call):
        return None
    name = _dotted(call.func) or ""
    tail = name.rsplit(".", 1)[-1]
    if tail in _LOCK_CTORS:
        return {"Lock": "lock", "RLock": "rlock",
                "Condition": "condition"}[tail]
    if tail == "InstrumentedLock":
        return "lock"
    return None


def _unwrap_annotation(node: Optional[ast.AST]) -> Optional[str]:
    """Optional[Foo] / "Foo" / Foo -> "Foo" (best effort)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        node_txt = node.value
        return node_txt.split("[")[-1].rstrip("]").split(".")[-1] or None
    if isinstance(node, ast.Subscript):
        return _unwrap_annotation(node.slice)
    if isinstance(node, (ast.Name, ast.Attribute)):
        d = _dotted(node)
        return d.rsplit(".", 1)[-1] if d else None
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


# ---------------------------------------------------------------------------
# the per-function walker
# ---------------------------------------------------------------------------
class _FuncWalker:
    """Tracks the held-lock set through one method, recording accesses,
    blocking calls, static nesting edges and callee sites."""

    def __init__(self, module: ModuleModel, cls: Optional[ClassModel],
                 func: ast.AST, ann: _Annotations,
                 param_types: Dict[str, str]):
        self.module = module
        self.cls = cls
        self.func = func
        self.ann = ann
        self.method = func.name
        self.param_types = param_types
        self.info = MethodInfo(name=func.name)
        # flow-insensitive local alias map: name -> lock node_id
        self.lock_aliases: Dict[str, str] = {}
        # name -> attr of self it aliases (for receiver typing)
        self.attr_aliases: Dict[str, str] = {}
        self._prescan_aliases()

    # -- alias prescan ---------------------------------------------------
    def _prescan_aliases(self):
        for node in ast.walk(self.func):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            attr = _self_attr(node.value)
            if attr is None:
                continue
            self.attr_aliases.setdefault(name, attr)
            lid = self._attr_lock_id(attr)
            if lid:
                self.lock_aliases.setdefault(name, lid)

    def _attr_lock_id(self, attr: str) -> Optional[str]:
        if self.cls is not None:
            lid = self.cls.lock_id(attr)
            if lid:
                return lid
        return None

    # -- lock-expression resolution --------------------------------------
    def resolve_lock(self, node: ast.AST) -> Optional[str]:
        """``self._lock`` / module lock / aliased local / (best-effort)
        typed foreign attr -> node_id; None when not a lock."""
        attr = _self_attr(node)
        if attr is not None:
            return self._attr_lock_id(attr)
        if isinstance(node, ast.Name):
            if node.id in self.lock_aliases:
                return self.lock_aliases[node.id]
            info = self.module.module_locks.get(node.id)
            return info.node_id if info else None
        if isinstance(node, ast.Attribute):
            # foreign lock: <recv>.<lockish-attr> — resolve through the
            # receiver's inferred type when known, else an opaque held-id
            # that participates in guard/blocking checks but NOT the order
            # graph (a wildcard "?._lock" node would unify unrelated locks)
            recv_cls = self._receiver_class(node.value)
            if recv_cls is not None:
                lid = recv_cls.lock_id(node.attr)
                if lid:
                    return lid
            if _LOCKISH_NAME.search(node.attr):
                return f"?.{node.attr}"
        return None

    def _receiver_class(self, node: ast.AST) -> Optional[ClassModel]:
        """Type a receiver expression: self, self.<attr>, annotated param,
        or a local aliasing one of those."""
        classes = _KNOWN_CLASSES
        if isinstance(node, ast.Name):
            if node.id == "self":
                return self.cls
            if node.id in self.attr_aliases and self.cls is not None:
                tname = self.cls.attr_types.get(self.attr_aliases[node.id])
                return classes.get(tname) if tname else None
            tname = self.param_types.get(node.id)
            return classes.get(tname) if tname else None
        attr = _self_attr(node)
        if attr is not None and self.cls is not None:
            tname = self.cls.attr_types.get(attr)
            return classes.get(tname) if tname else None
        return None

    # -- main walk --------------------------------------------------------
    def run(self):
        held0: FrozenSet[str] = frozenset()
        req = self.ann.requires_at(self.func.lineno)
        if req is not None:
            try:
                lid = self.resolve_lock(ast.parse(req, mode="eval").body)
            except SyntaxError:
                lid = None
            if lid:
                self.info.requires = frozenset({lid})
                held0 = self._expand(lid)
        self.info.line = self.func.lineno
        held = self.walk_block(self.func.body, held0)
        self._toctou_scan(self.func.body, [], held0)
        return held

    def walk_block(self, stmts: Sequence[ast.stmt],
                   held: FrozenSet[str]) -> FrozenSet[str]:
        for st in stmts:
            held = self.walk_stmt(st, held)
        return held

    def _with_locks(self, node: ast.With, record: bool = True) -> List[str]:
        out = []
        for item in node.items:
            lid = self.resolve_lock(item.context_expr)
            if lid:
                out.append(lid)
            elif record:
                self.scan_expr(item.context_expr, frozenset(), node.lineno)
        return out

    def _expand(self, lid: str) -> FrozenSet[str]:
        """Holding a Condition holds its wrapped lock too."""
        out = {lid}
        info = _lock_info(self.module, self.cls, lid)
        if info is not None and info.wraps:
            out.add(info.wraps)
        return frozenset(out)

    def walk_stmt(self, st: ast.stmt, held: FrozenSet[str]) -> FrozenSet[str]:
        if isinstance(st, ast.With):
            locks = self._with_locks(st)
            new = held
            for lid in locks:
                self._record_acquire(lid, new, st.lineno)
                new = new | self._expand(lid)
            self.walk_block(st.body, new)
            return held
        if isinstance(st, ast.Try):
            after_body = self.walk_block(st.body, held)
            for h in st.handlers:
                self.walk_block(h.body, held)
            after_body = self.walk_block(st.orelse, after_body)
            return self.walk_block(st.finalbody, after_body)
        if isinstance(st, (ast.If,)):
            self.scan_expr(st.test, held, st.lineno)
            self.walk_block(st.body, held)
            self.walk_block(st.orelse, held)
            return held
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self.scan_expr(st.iter, held, st.lineno)
            self._record_store(st.target, held, st.lineno)
            self.walk_block(st.body, held)
            self.walk_block(st.orelse, held)
            return held
        if isinstance(st, ast.While):
            self.scan_expr(st.test, held, st.lineno)
            self.walk_block(st.body, held)
            self.walk_block(st.orelse, held)
            return held
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs (closures, worker bodies): walked with an EMPTY
            # held set — they run later, on some other thread's schedule
            sub = _FuncWalker(self.module, self.cls, st, self.ann,
                              dict(self.param_types))
            sub.method = f"{self.method}.{st.name}"
            sub.info = self.info   # accumulate acquires/calls into parent
            sub.walk_block(st.body, frozenset())
            sub._toctou_scan(st.body, [], frozenset())
            return held
        if isinstance(st, ast.Expr):
            held = self._maybe_acquire_release(st.value, held)
            self.scan_expr(st.value, held, st.lineno)
            return held
        if isinstance(st, ast.Assign):
            self.scan_expr(st.value, held, st.lineno)
            for t in st.targets:
                self._record_store(t, held, st.lineno)
            return held
        if isinstance(st, ast.AugAssign):
            self.scan_expr(st.value, held, st.lineno)
            # aug-assign reads AND writes its target
            self._record_load(st.target, held, st.lineno)
            self._record_store(st.target, held, st.lineno)
            return held
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self.scan_expr(st.value, held, st.lineno)
                self._record_store(st.target, held, st.lineno)
            return held
        if isinstance(st, (ast.Return, ast.Raise)):
            v = st.value if isinstance(st, ast.Return) else st.exc
            if v is not None:
                self.scan_expr(v, held, st.lineno)
            return held
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._record_store(t, held, st.lineno)
            return held
        if isinstance(st, ast.Assert):
            self.scan_expr(st.test, held, st.lineno)
            return held
        return held

    # -- acquire / release -------------------------------------------------
    def _maybe_acquire_release(self, node: ast.AST,
                               held: FrozenSet[str]) -> FrozenSet[str]:
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("acquire", "release")):
            return held
        lid = self.resolve_lock(node.func.value)
        if lid is None:
            return held
        if node.func.attr == "acquire":
            self._record_acquire(lid, held, node.lineno)
            return held | self._expand(lid)
        return held - self._expand(lid)

    def _record_acquire(self, lid: str, held: FrozenSet[str], line: int):
        self.info.acquires.add(lid)
        if lid.startswith("?."):
            return  # opaque locks stay out of the order graph
        for src in held:
            if src == lid or src.startswith("?."):
                continue
            self.module.order_edges.append(OrderEdge(
                src=src, dst=lid, file=self.module.path, line=line,
                origin="static"))

    # -- accesses ----------------------------------------------------------
    def _record(self, attr: str, kind: str, held: FrozenSet[str], line: int):
        if self.cls is None:
            return
        if attr in self.cls.locks:
            return
        self.cls.accesses.append(Access(
            attr=attr, kind=kind, method=self.method, line=line, held=held,
            suppressed=self.ann.suppressed_at(line)))

    def _record_store(self, target: ast.AST, held: FrozenSet[str], line: int):
        attr = _self_attr(target)
        if attr is not None:
            self._record(attr, "write", held, line)
            return
        if isinstance(target, ast.Subscript):
            inner = _self_attr(target.value)
            if inner is not None:
                self._record(inner, "write", held, line)
                return
            self.scan_expr(target.value, held, line)
            self.scan_expr(target.slice, held, line)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._record_store(el, held, line)
            return
        if isinstance(target, ast.Starred):
            self._record_store(target.value, held, line)
            return
        if isinstance(target, ast.Attribute):
            self.scan_expr(target.value, held, line)

    def _record_load(self, node: ast.AST, held: FrozenSet[str], line: int):
        attr = _self_attr(node)
        if attr is not None:
            self._record(attr, "read", held, line)
        elif isinstance(node, ast.Subscript):
            inner = _self_attr(node.value)
            if inner is not None:
                self._record(inner, "read", held, line)

    def scan_expr(self, node: ast.AST, held: FrozenSet[str], line: int):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                attr = _self_attr(sub)
                if attr is not None and isinstance(sub.ctx, ast.Load):
                    self._record(attr, "read", held, sub.lineno)
            elif isinstance(sub, ast.Call):
                self._scan_call(sub, held)

    def _scan_call(self, call: ast.Call, held: FrozenSet[str]):
        line = call.lineno
        func = call.func
        # mutating method call on a guarded container: a WRITE
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            recv_attr = _self_attr(func.value)
            if recv_attr is not None:
                self._record(recv_attr, "write", held, line)
        # callee recording for the interprocedural footprint pass
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                self.info.calls.append(
                    (self.cls.name if self.cls else None, func.attr,
                     line, held))
            else:
                recv_cls = self._receiver_class(func.value)
                if recv_cls is not None:
                    self.info.calls.append(
                        (recv_cls.name, func.attr, line, held))
        # blocking classification
        cat = self._blocking_category(call)
        if cat is not None and self.cls is not None:
            self.cls.blocking.append(BlockingCall(
                what=_dotted(func) or ast.unparse(func),
                category=cat, method=self.method, line=line, held=held,
                suppressed=self.ann.suppressed_at(line)))

    def _blocking_category(self, call: ast.Call) -> Optional[str]:
        name = _dotted(call.func)
        if name:
            tail = name.split(".", 1)[-1] if "." in name else name
            if name in _BLOCKING_CALLS:
                return _BLOCKING_CALLS[name]
            if tail in _BLOCKING_CALLS:
                return _BLOCKING_CALLS[tail]
        if not isinstance(call.func, ast.Attribute):
            return None
        meth = call.func.attr
        recv = call.func.value
        recv_txt = (_dotted(recv) or "").lower()
        if isinstance(recv, ast.Constant):
            return None  # ", ".join(...)
        # lock/condition methods are never "blocking" here (wait releases)
        if self.resolve_lock(recv) is not None:
            return None
        if meth in _BLOCKING_METHODS:
            return _BLOCKING_METHODS[meth]
        if meth in ("join", "wait"):
            if any(h in recv_txt for h in _THREADISH):
                return "join"
            return None
        if meth in _COMPILE_METHODS or meth.endswith(_COMPILE_SUFFIX):
            return "compile"
        if meth in _MUTATORS or meth in ("get", "items", "keys", "values",
                                         "copy", "count", "index"):
            # container ops on client-ish NAMES (self._conns.add) are
            # memory ops, not I/O
            return None
        if any(h in recv_txt for h in _NET_RECEIVER_HINTS):
            return "net"
        return None

    # -- check-then-act (TOCTOU) ------------------------------------------
    def _toctou_scan(self, stmts: Sequence[ast.stmt],
                     candidates: List[Tuple[str, str, str, int]],
                     held: FrozenSet[str]):
        """candidates: (localvar, attr, lock, read_line) read under a lock
        that has since been released; an If testing the stale value whose
        body re-acquires the lock and writes the attr is the bug shape."""
        candidates = list(candidates)
        for st in stmts:
            if isinstance(st, ast.With):
                locks = self._with_locks(st, record=False)
                inner_held = held
                for lid in locks:
                    inner_held = inner_held | self._expand(lid)
                for lid in locks:
                    for var, attr in self._guarded_reads(st.body):
                        candidates.append((var, attr, lid, st.lineno))
                self._toctou_scan(st.body, candidates, inner_held)
            elif isinstance(st, ast.If) and self.cls is not None:
                test_names = {n.id for n in ast.walk(st.test)
                              if isinstance(n, ast.Name)}
                test_attrs = {a for n in ast.walk(st.test)
                              if (a := _self_attr(n)) is not None}
                for var, attr, lock, read_line in candidates:
                    if lock in held:
                        continue  # still held: check and act are atomic
                    if var not in test_names and attr not in test_attrs:
                        continue
                    wl = self._reacquired_write(st, lock, attr)
                    if wl is not None:
                        self.cls.toctou.append(ToctouSite(
                            attr=attr, lock=lock, read_line=read_line,
                            test_line=st.lineno, write_line=wl,
                            method=self.method,
                            suppressed=self.ann.suppressed_at(st.lineno)
                            | self.ann.suppressed_at(read_line)))
                self._toctou_scan(st.body, candidates, held)
                self._toctou_scan(st.orelse, candidates, held)
            elif isinstance(st, (ast.For, ast.While, ast.Try)):
                for block in (getattr(st, "body", []),
                              getattr(st, "orelse", []),
                              getattr(st, "finalbody", [])):
                    self._toctou_scan(block, candidates, held)
                for h in getattr(st, "handlers", []):
                    self._toctou_scan(h.body, candidates, held)

    def _guarded_reads(self, body: Sequence[ast.stmt]):
        """(localvar, attr) pairs assigned from a self-attr read inside a
        with-block body (top level of the body only)."""
        out = []
        for st in body:
            if (isinstance(st, ast.Assign) and len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)):
                for sub in ast.walk(st.value):
                    attr = _self_attr(sub)
                    if attr is not None and self.cls is not None \
                            and attr not in self.cls.locks:
                        out.append((st.targets[0].id, attr))
        return out

    def _reacquired_write(self, if_node: ast.If, lock: str,
                          attr: str) -> Optional[int]:
        """Line of a write to ``attr`` under a re-acquired ``lock`` inside
        the If body (atomic check-and-set receivers excluded)."""
        for sub in ast.walk(if_node):
            if not isinstance(sub, ast.With):
                continue
            if lock not in [self.resolve_lock(i.context_expr)
                            for i in sub.items]:
                continue
            for inner in ast.walk(sub):
                if isinstance(inner, ast.Assign):
                    for t in inner.targets:
                        ta = _self_attr(t) or (
                            _self_attr(t.value)
                            if isinstance(t, ast.Subscript) else None)
                        if ta == attr:
                            return inner.lineno
                elif isinstance(inner, ast.AugAssign):
                    if _self_attr(inner.target) == attr:
                        return inner.lineno
                elif (isinstance(inner, ast.Call)
                      and isinstance(inner.func, ast.Attribute)
                      and inner.func.attr in (_MUTATORS - _ATOMIC_MUTATORS)
                      and _self_attr(inner.func.value) == attr):
                    return inner.lineno
        return None


def _lock_info(module: ModuleModel, cls: Optional[ClassModel],
               lid: str) -> Optional[LockInfo]:
    if cls is not None:
        for info in cls.locks.values():
            if info.node_id == lid:
                return info
    for info in module.module_locks.values():
        if info.node_id == lid:
            return info
    for c in module.classes.values():
        for info in c.locks.values():
            if info.node_id == lid:
                return info
    return None


# ---------------------------------------------------------------------------
# module scan
# ---------------------------------------------------------------------------
_KNOWN_CLASSES: Dict[str, ClassModel] = {}


def scan_module(path: str, modname: Optional[str] = None,
                full: bool = True) -> ModuleModel:
    """Scan one module. ``full=False`` stops after lock/class/annotation
    discovery (what :func:`scan_modules`' first pass needs to seed
    cross-module receiver typing) — the per-method walks are the
    expensive part and only run on the second pass."""
    modname = modname or os.path.splitext(os.path.basename(path))[0]
    model = ModuleModel(modname, path)
    try:
        with open(path) as fh:
            source = fh.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError) as e:
        model.error = f"{type(e).__name__}: {e}"
        return model
    ann = _Annotations(source)
    real = os.path.realpath(path)

    # pass 1: classes, locks, guard declarations, attr types
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            kind = _ctor_kind(node.value)
            if kind:
                name = node.targets[0].id
                info = LockInfo(
                    node_id=f"{modname}.{name}", attr=name, kind=kind,
                    line=node.lineno,
                    blocking_ok=ann.blocking_ok_at(node.lineno))
                model.module_locks[name] = info
                model.add_creation_site(real, node.lineno, info.node_id)
        elif isinstance(node, ast.ClassDef):
            _scan_class(model, node, ann, real)

    if not full:
        return model

    # register classes globally BEFORE the method walk so cross-class
    # receiver typing sees every class of this module set
    for c in model.classes.values():
        _KNOWN_CLASSES.setdefault(c.name, c)

    # pass 2: per-method walks (methods + module functions)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            cls = model.classes[node.name]
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ptypes = _param_types(item)
                    w = _FuncWalker(model, cls, item, ann, ptypes)
                    cls.methods[item.name] = w.info
                    w.run()
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            w = _FuncWalker(model, None, node, ann, _param_types(node))
            w.run()
    return model


def _param_types(func: ast.AST) -> Dict[str, str]:
    out = {}
    for a in list(func.args.args) + list(func.args.kwonlyargs):
        t = _unwrap_annotation(a.annotation)
        if t:
            out[a.arg] = t
    return out


def _scan_class(model: ModuleModel, node: ast.ClassDef, ann: _Annotations,
                real: str):
    cls = ClassModel(node.name, model.modname)
    cls.bases = [d.rsplit(".", 1)[-1] for b in node.bases
                 if (d := _dotted(b))]
    model.classes[node.name] = cls
    base = f"{model.modname}.{node.name}"
    # find lock attrs + guard annotations + attr construction types in
    # EVERY method (locks are usually born in __init__ but not always;
    # guard annotations may precede the lock's assignment — two passes
    # make declaration order irrelevant)
    assigns: List[Tuple[str, ast.AST, int]] = []
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(item):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        assigns.append((attr, sub.value, sub.lineno))
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                attr = _self_attr(sub.target)
                if attr is not None:
                    assigns.append((attr, sub.value, sub.lineno))
    # locks first (guard resolution needs them all)
    for attr, value, line in assigns:
        kind = _ctor_kind(value)
        if kind is None and _LOCKISH_NAME.search(attr):
            # lock-valued attr assigned through a helper or parameter
            # (e.g. self._trace_lock = _model_trace_lock(model)); kind is
            # opaque but it still participates in held-set tracking
            if isinstance(value, ast.Call) or isinstance(value, ast.Name):
                kind = "opaque"
        if kind is None:
            continue
        if attr in cls.locks:
            continue
        info = LockInfo(node_id=f"{base}.{attr}", attr=attr, kind=kind,
                        line=line,
                        blocking_ok=ann.blocking_ok_at(line))
        cls.locks[attr] = info
        if _ctor_kind(value):
            model.add_creation_site(real, line, info.node_id)
    # condition wrapping: self._cond = threading.Condition(self._lock)
    for attr, value, line in assigns:
        info = cls.locks.get(attr)
        if info is None or info.kind != "condition":
            continue
        if isinstance(value, ast.Call) and value.args:
            wrapped = _self_attr(value.args[0])
            if wrapped and wrapped in cls.locks:
                info.wraps = cls.locks[wrapped].node_id
    # guard declarations + attr types
    for attr, value, line in assigns:
        g = ann.guard_at(line)
        if g and attr not in cls.locks:
            cls.guards.setdefault(attr, GuardDecl(
                attr=attr, guard_expr=g,
                guard_id=_resolve_guard(model, cls, g), line=line))
        t = _construction_type(value)
        if t:
            cls.attr_types.setdefault(attr, t)
    # param-annotation types for self.<attr> = <param> in __init__
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and item.name == "__init__":
            ptypes = _param_types(item)
            for sub in ast.walk(item):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    attr = _self_attr(sub.targets[0])
                    if attr is None:
                        continue
                    v = sub.value
                    if isinstance(v, ast.Name) and v.id in ptypes:
                        cls.attr_types.setdefault(attr, ptypes[v.id])
                    elif isinstance(v, ast.BoolOp):
                        for piece in v.values:
                            if isinstance(piece, ast.Name) \
                                    and piece.id in ptypes:
                                cls.attr_types.setdefault(
                                    attr, ptypes[piece.id])
                            t = _construction_type(piece)
                            if t:
                                cls.attr_types.setdefault(attr, t)


def _construction_type(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.Call):
        name = _dotted(value.func)
        if name:
            tail = name.rsplit(".", 1)[-1]
            if tail and tail[0].isupper() and tail not in _LOCK_CTORS:
                return tail
    if isinstance(value, ast.IfExp):
        return _construction_type(value.body) or \
            _construction_type(value.orelse)
    return None


def _resolve_guard(model: ModuleModel, cls: ClassModel,
                   expr: str) -> Optional[str]:
    expr = expr.strip()
    if expr.startswith("self."):
        return cls.lock_id(expr[5:])
    info = model.module_locks.get(expr)
    return info.node_id if info else None


def scan_modules(paths: Sequence[Tuple[str, str]]) -> HostModel:
    """paths: (modname, filesystem path) pairs -> whole-program model."""
    _KNOWN_CLASSES.clear()
    # two passes so cross-module receiver typing is order-independent:
    # first a DISCOVERY-ONLY scan (classes/locks/attr types — no method
    # walks), then the real scan with every class registered
    discovered: Dict[str, ModuleModel] = {}
    for modname, path in paths:
        discovered[modname] = scan_module(path, modname, full=False)
    _KNOWN_CLASSES.clear()
    for m in discovered.values():
        for c in m.classes.values():
            _KNOWN_CLASSES.setdefault(c.name, c)
    modules: Dict[str, ModuleModel] = {}
    for modname, path in paths:
        modules[modname] = scan_module(path, modname)
    return HostModel(modules)


def default_host_paths(root: Optional[str] = None) -> List[Tuple[str, str]]:
    """The host control plane: every module of serving/, resilience/,
    observability/, distributed/fleet/ plus the checkpoint manager."""
    pkg = root or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out: List[Tuple[str, str]] = []

    def add_dir(rel: str):
        d = os.path.join(pkg, rel)
        if not os.path.isdir(d):
            return
        for fn in sorted(os.listdir(d)):
            if not fn.endswith(".py") or fn == "__main__.py":
                continue
            mod = rel.replace(os.sep, ".").replace("/", ".")
            name = os.path.splitext(fn)[0]
            modname = mod if name == "__init__" else f"{mod}.{name}"
            out.append((modname, os.path.join(d, fn)))

    add_dir("serving")
    add_dir("resilience")
    add_dir("observability")
    add_dir(os.path.join("distributed", "fleet"))
    add_dir(os.path.join("distributed", "fleet", "elastic"))
    add_dir(os.path.join("distributed", "fleet", "utils"))
    ckpt = os.path.join(pkg, "framework", "checkpoint.py")
    if os.path.exists(ckpt):
        out.append(("framework.checkpoint", ckpt))
    return out


# ---------------------------------------------------------------------------
# lock-order graph
# ---------------------------------------------------------------------------
class LockOrderGraph:
    """Directed acquired-while-holding graph; any cycle is a potential
    deadlock (two threads taking the cycle from different entry points)."""

    def __init__(self, edges: Sequence[OrderEdge] = ()):
        self.edges: List[OrderEdge] = []
        self._adj: Dict[str, Set[str]] = {}
        self._sites: Dict[Tuple[str, str], OrderEdge] = {}
        for e in edges:
            self.add(e)

    def add(self, e: OrderEdge):
        if e.src == e.dst:
            # same NAME, two instances (recorded by the runtime half when
            # the underlying objects differ): a real same-class nesting
            self.edges.append(e)
            self._adj.setdefault(e.src, set()).add(e.dst)
            self._sites.setdefault((e.src, e.dst), e)
            return
        self.edges.append(e)
        self._adj.setdefault(e.src, set()).add(e.dst)
        self._adj.setdefault(e.dst, set())
        self._sites.setdefault((e.src, e.dst), e)

    def nodes(self) -> List[str]:
        return sorted(self._adj)

    def site(self, src: str, dst: str) -> Optional[OrderEdge]:
        return self._sites.get((src, dst))

    def cycles(self) -> List[List[str]]:
        """One representative cycle per strongly-connected component with
        >1 node (or a self-loop)."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Dict[str, bool] = {}
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str):
            work = [(v, iter(sorted(self._adj.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack[v] = True
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack[w] = True
                        work.append((w, iter(sorted(self._adj.get(w, ())))))
                        advanced = True
                        break
                    elif on_stack.get(w):
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack[w] = False
                        scc.append(w)
                        if w == node:
                            break
                    sccs.append(scc)

        for v in sorted(self._adj):
            if v not in index:
                strongconnect(v)
        out = []
        for scc in sccs:
            if len(scc) > 1:
                out.append(self._order_cycle(scc))
            elif scc[0] in self._adj.get(scc[0], ()):
                out.append([scc[0], scc[0]])
        return out

    def _order_cycle(self, scc: List[str]) -> List[str]:
        """Walk an actual edge path around the SCC for a readable report."""
        members = set(scc)
        start = sorted(scc)[0]
        path = [start]
        seen = {start}
        cur = start
        while True:
            nxts = [n for n in sorted(self._adj.get(cur, ()))
                    if n in members]
            if not nxts:
                break
            nxt = next((n for n in nxts if n not in seen), nxts[0])
            path.append(nxt)
            if nxt in seen:
                break
            seen.add(nxt)
            cur = nxt
        return path


# ---------------------------------------------------------------------------
# runtime recorder (the lockdep half)
# ---------------------------------------------------------------------------
_THIS_FILE = os.path.realpath(__file__)


class _HeldStack(threading.local):
    def __init__(self):
        self.stack: List[object] = []


class LockOrderRecorder:
    """Accumulates (held -> acquired) creation-site pairs per thread.

    No internal locking on purpose: edge inserts are single dict/set ops
    (atomic under the GIL), and the recorder must never serialize the
    code it observes.
    """

    def __init__(self):
        self._tls = _HeldStack()
        self.edges: Dict[Tuple[Tuple[str, int], Tuple[str, int]], int] = {}
        self.acquires = 0
        self.locks_created = 0
        #: cumulative wall seconds spent armed (the denominator of the
        #: bench-side overhead fraction: acquires x per-acquire tax / wall)
        self.armed_wall_s = 0.0
        self.enabled = True

    def _on_acquire(self, lk: "InstrumentedLock"):
        st = self._tls.stack
        if self.enabled:
            self.acquires += 1
            if not any(h is lk for h in st):
                held_sites = []
                seen = set()
                for h in st:
                    if id(h) in seen or h is lk:
                        continue
                    seen.add(id(h))
                    held_sites.append(h._site)
                for src in held_sites:
                    key = (src, lk._site)
                    self.edges[key] = self.edges.get(key, 0) + 1
        st.append(lk)

    def _on_release(self, lk: "InstrumentedLock"):
        st = self._tls.stack
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lk:
                del st[i]
                return
        # released on a thread that never acquired it (hand-off pattern):
        # nothing to pop, nothing to record

    def edge_list(self) -> List[dict]:
        # repo-relative paths: the persisted journal must resolve against
        # the static model on ANY checkout, not just the recording one
        return [
            {"src_file": _rel_site(s[0]), "src_line": s[1],
             "dst_file": _rel_site(d[0]), "dst_line": d[1], "count": n}
            for (s, d), n in sorted(self.edges.items())
        ]


class InstrumentedLock:
    """Recording wrapper around a real Lock/RLock. Transparent: context
    manager, acquire/release signature, and everything else (``locked``,
    ``_is_owned``, ``_release_save`` — Condition needs those on RLocks)
    delegates to the wrapped lock."""

    def __init__(self, inner, site: Tuple[str, int],
                 recorder: LockOrderRecorder):
        self._inner = inner
        self._site = site
        self._recorder = recorder
        recorder.locks_created += 1

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._recorder._on_acquire(self)
        return ok

    def release(self):
        self._inner.release()
        self._recorder._on_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __repr__(self):
        return f"<InstrumentedLock {self._site[0]}:{self._site[1]} " \
               f"of {self._inner!r}>"


_ARM_STATE: Dict[str, object] = {}


def _creation_site() -> Optional[Tuple[str, int]]:
    """(realpath, line) of the first caller frame inside this repo; None
    for foreign locks (left unwrapped: zero overhead, zero noise)."""
    f = sys._getframe(2)
    repo_hint = os.sep + "paddle_tpu" + os.sep
    for _ in range(12):
        if f is None:
            return None
        fn = f.f_code.co_filename
        if fn != _THIS_FILE and not fn.endswith("threading.py"):
            real = os.path.realpath(fn)
            if repo_hint in real and os.sep + "analysis" + os.sep not in real:
                return (real, f.f_lineno)
            return None
        f = f.f_back
    return None


def arm(recorder: LockOrderRecorder):
    """Patch ``threading.Lock``/``RLock`` so locks constructed by repo
    code record into ``recorder``. Idempotent per recorder; :func:`disarm`
    restores the real factories (already-wrapped locks keep recording
    until ``recorder.enabled`` is cleared)."""
    if _ARM_STATE:
        raise RuntimeError("lock instrumentation already armed")
    real_lock, real_rlock = threading.Lock, threading.RLock

    def make(factory):
        def build(*a, **k):
            inner = factory(*a, **k)
            site = _creation_site()
            if site is None:
                return inner
            return InstrumentedLock(inner, site, recorder)
        return build

    import time as _time

    _ARM_STATE.update(lock=real_lock, rlock=real_rlock, recorder=recorder,
                      armed_at=_time.perf_counter())
    threading.Lock = make(real_lock)
    threading.RLock = make(real_rlock)
    recorder.enabled = True
    return recorder


def disarm():
    if not _ARM_STATE:
        return
    import time as _time

    threading.Lock = _ARM_STATE.pop("lock")
    threading.RLock = _ARM_STATE.pop("rlock")
    armed_at = _ARM_STATE.pop("armed_at")
    rec = _ARM_STATE.pop("recorder")
    rec.armed_wall_s += _time.perf_counter() - armed_at
    rec.enabled = False


class armed:
    """``with armed(recorder): ...`` — scoped arm/disarm."""

    def __init__(self, recorder: LockOrderRecorder):
        self.recorder = recorder

    def __enter__(self):
        arm(self.recorder)
        return self.recorder

    def __exit__(self, *exc):
        disarm()
        return False


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------
def write_journal(recorder: LockOrderRecorder, path: str,
                  meta: Optional[dict] = None) -> str:
    doc = {
        "schema_version": JOURNAL_SCHEMA_VERSION,
        "meta": dict(meta or {},
                     acquires=recorder.acquires,
                     locks_created=recorder.locks_created,
                     armed_wall_s=round(recorder.armed_wall_s, 3)),
        "edges": recorder.edge_list(),
    }
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


def load_journal(path: str) -> List[dict]:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema_version") != JOURNAL_SCHEMA_VERSION:
        raise ValueError(
            f"unsupported lock-journal schema {doc.get('schema_version')!r} "
            f"(want {JOURNAL_SCHEMA_VERSION})")
    return list(doc.get("edges", ()))


def journal_order_edges(model: HostModel,
                        journal_edges: Sequence[dict]) -> List[OrderEdge]:
    """Resolve journal creation sites to static lock names; sites the
    static model does not know keep a ``file:line`` identity (they still
    participate in cycle detection — a cycle through an unnamed lock is
    no less a deadlock)."""
    out = []
    for e in journal_edges:
        src = model.lock_for_site(e["src_file"], e["src_line"]) or \
            _site_name(e["src_file"], e["src_line"])
        dst = model.lock_for_site(e["dst_file"], e["dst_line"]) or \
            _site_name(e["dst_file"], e["dst_line"])
        out.append(OrderEdge(src=src, dst=dst, file=e["src_file"],
                             line=int(e["src_line"]), origin="runtime"))
    return out


def _rel_site(path: str) -> str:
    """Repo-relative identity of a creation-site path (the portion from
    ``paddle_tpu/`` on): journals keyed this way survive checkout moves."""
    parts = path.replace("\\", "/").split("/")
    if "paddle_tpu" in parts:
        return "/".join(parts[parts.index("paddle_tpu"):])
    return parts[-1]


def _site_name(path: str, line: int) -> str:
    return f"{_rel_site(path)}:{line}"


def build_order_graph(model: HostModel,
                      journal_edges: Sequence[dict] = ()) -> LockOrderGraph:
    g = LockOrderGraph(model.static_edges())
    for e in journal_order_edges(model, journal_edges):
        g.add(e)
    return g
