"""``python -m paddle_tpu.analysis`` — lint the shipped entry points.

Builds every shipped program family (trainer step, pipeline 1F1B step,
serving prefill/decode, exported inference, static Program), runs the full
rule registry, prints a findings table, and writes the JSON report to
``benchmarks/analysis_report.json`` (the artifact the zero-HIGH CI smoke
test and ``bench.py _analysis_overhead`` read).

Exit status: 0 when no finding reaches ``--fail-on`` (default HIGH), 1
otherwise, 2 when an entry point could not even be built.
"""
from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="Static TPU-hazard linter over shipped entry points")
    parser.add_argument("--out", default=None,
                        help="JSON report path (default "
                             "benchmarks/analysis_report.json)")
    from .entrypoints import builder_names

    parser.add_argument("--only", action="append", default=[],
                        choices=builder_names(),
                        help="entry-point builder(s) to lint; an unknown "
                             "name is a usage error, not an empty lint")
    parser.add_argument("--fail-on", default="high",
                        choices=["high", "medium", "low", "info", "never"],
                        help="exit 1 when a finding at/above this severity "
                             "exists (default high)")
    parser.add_argument("--keep-going", action="store_true",
                        help="lint the buildable entry points even when "
                             "some builders fail")
    args = parser.parse_args(argv)
    # NOTE: platform/device-count env setup lives in __main__.py (re-exec
    # before jax initializes); mutating os.environ here would be both too
    # late for this process and a leak into child processes.

    import jax

    from .entrypoints import shipped_entry_points
    from .findings import Severity
    from .rules import analyze_targets

    t0 = time.perf_counter()
    # always collect builder failures so they reach the report (and exit 2)
    # instead of escaping as a raw traceback
    targets, errors = shipped_entry_points(
        skip_errors=True, only=tuple(args.only))
    report = analyze_targets(
        targets,
        meta={"tool": "paddle_tpu.analysis", "backend": jax.default_backend(),
              "n_devices": len(jax.devices()), "build_errors": errors})
    report.meta["total_s"] = round(time.perf_counter() - t0, 3)

    out = args.out
    if out is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        bench_dir = os.path.join(root, "benchmarks")
        out = (os.path.join(bench_dir, "analysis_report.json")
               if os.path.isdir(bench_dir) else "analysis_report.json")
    report.save(out)

    print(f"linted {len(targets)} entry points in "
          f"{report.meta['total_s']}s -> {out}")
    for name, err in errors.items():
        print(f"  BUILD FAILED {name}: {err}")
    print()
    print(report.table())
    counts = report.counts()
    print()
    print("findings:", ", ".join(f"{k}={v}" for k, v in counts.items()))

    if errors and not args.keep_going:
        return 2
    if args.fail_on != "never":
        gate = Severity[args.fail_on.upper()]
        if report.at_least(gate):
            return 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
