"""``python -m paddle_tpu.analysis`` — lint the shipped entry points.

Builds every shipped program family (trainer step, pipeline 1F1B step,
serving prefill/decode, exported inference, static Program) and runs one of
three modes:

* default          — the full hazard-rule registry (now including the
  quantitative ``oom-risk`` / ``low-intensity-dot`` / ``remat-advisor``
  rules) → ``benchmarks/analysis_report.json``;
* ``--memory``     — the liveness-based peak-HBM/cost report per entry
  point (+ the planner-drift cross-check) →
  ``benchmarks/analysis_memory.json``;
* ``--sanitize``   — eqn-by-eqn non-finite replay of every entry point
  with its example args → ``benchmarks/analysis_sanitize.json``;
* ``--determinism`` — the determinism doctor: PRNG key-flow lint over
  every entry point (jaxpr plane) + host-nondeterminism AST rules +
  replay-certificate seam coverage; ``--bisect-demo`` appends a planted
  key-desync localization → ``benchmarks/analysis_determinism.json``;
* ``--kernels``     — the Pallas kernel doctor: block-spec coverage
  proofs (every output block written exactly once), f32-accumulation
  lint over the kernel-body jaxprs, VMEM budgeting, and cost-registry
  drift certification over the shipped kernel manifest →
  ``benchmarks/analysis_kernels.json``;
* ``--kernels-sweep`` — predicted VMEM/roofline table over serving
  shapes (page_size 16/32 × the real-vocab tiling lattice) →
  ``benchmarks/analysis_kernels_sweep.json``.

``--device-budget <bytes>`` re-parameterizes the memory rules so an
``oom-risk`` HIGH against YOUR chip gates exit-1.  Unknown primitives hit
by the cost model are reported per entry point (never silently
zero-costed).  All artifacts carry a schema_version.

Exit status: 0 when no finding reaches ``--fail-on`` (default HIGH), 1
otherwise, 2 when an entry point could not even be built.
"""
from __future__ import annotations

import argparse
import os
import sys
import time


def _default_out(name: str) -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    bench_dir = os.path.join(root, "benchmarks")
    return (os.path.join(bench_dir, name)
            if os.path.isdir(bench_dir) else name)


def _save_json(path: str, payload: dict):
    import json

    with open(path, "w") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="Static TPU-hazard linter over shipped entry points")
    parser.add_argument("--out", default=None,
                        help="JSON report path (default benchmarks/"
                             "analysis_report.json, or analysis_memory/"
                             "analysis_sanitize.json per mode)")
    from .entrypoints import builder_names

    parser.add_argument("--only", action="append", default=[],
                        choices=builder_names(),
                        help="entry-point builder(s) to lint; an unknown "
                             "name is a usage error, not an empty lint")
    parser.add_argument("--fail-on", default="high",
                        choices=["high", "medium", "low", "info", "never"],
                        help="exit 1 when a finding at/above this severity "
                             "exists (default high)")
    parser.add_argument("--keep-going", action="store_true",
                        help="lint the buildable entry points even when "
                             "some builders fail")
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument("--memory", action="store_true",
                      help="liveness-based peak-HBM + cost report per "
                           "entry point (writes analysis_memory.json)")
    mode.add_argument("--sanitize", action="store_true",
                      help="replay each entry point eqn-by-eqn and "
                           "report the first non-finite intermediate "
                           "(writes analysis_sanitize.json)")
    mode.add_argument("--host", action="store_true",
                      help="concurrency doctor: lock-discipline & race "
                           "lint over the threaded host control plane "
                           "(serving/resilience/fleet/observability) — "
                           "AST only, no entry-point tracing/lowering "
                           "(writes analysis_host.json)")
    from .rules import host_rule_names

    parser.add_argument("--host-only", action="append", default=[],
                        choices=host_rule_names(), metavar="RULE",
                        help="--host: run only these host rules "
                             f"({', '.join(host_rule_names())}); an "
                             "unknown name is a usage error, not an "
                             "empty lint")
    parser.add_argument("--host-path", action="append", default=[],
                        metavar="FILE_OR_DIR",
                        help="--host: scan these files/dirs INSTEAD of "
                             "the default control-plane set (planted-bug "
                             "twins, out-of-tree modules)")
    parser.add_argument("--host-journal", default=None, metavar="PATH",
                        help="--host: runtime lock-order journal to merge "
                             "into the static graph (default: the "
                             "committed benchmarks/hostrace_journal.json "
                             "when present; 'none' disables the merge)")
    mode.add_argument("--determinism", action="store_true",
                      help="determinism doctor: PRNG key-flow lint over "
                           "every entry point + host-nondeterminism AST "
                           "rules + replay-certificate seam coverage "
                           "(writes analysis_determinism.json)")
    parser.add_argument("--bisect-demo", action="store_true",
                        help="--determinism: run the divergence-bisector "
                             "demo (planted key-chain desync in a sampled "
                             "decode loop) and append its localization to "
                             "the artifact")
    parser.add_argument("--bisect-tick", type=int, default=3,
                        metavar="T",
                        help="--bisect-demo: tick at which to plant the "
                             "key desync (default 3)")
    mode.add_argument("--kernels", action="store_true",
                      help="Pallas kernel doctor: coverage proofs + "
                           "f32-accumulation lint + VMEM budget + "
                           "cost-registry drift certification over the "
                           "shipped kernel manifest (writes "
                           "analysis_kernels.json)")
    mode.add_argument("--kernels-sweep", action="store_true",
                      help="predicted VMEM/roofline table over serving "
                           "shapes: page_size 16/32 x real-vocab "
                           "lattice (writes analysis_kernels_sweep"
                           ".json)")
    mode.add_argument("--plan", action="store_true",
                      help="auto-parallel planner v2: enumerate dp/mp/pp/"
                           "ZeRO/remat candidates, price each on a lowered "
                           "ShapeDtypeStruct target, write the ranked "
                           "benchmarks/plan_table.json; exits 1 when the "
                           "requested config is infeasible under "
                           "--device-budget")
    parser.add_argument("--plan-model", default=None, metavar="PRESET",
                        help="--plan: GPT preset (e.g. gpt3-1.3b); default "
                             "runs the two committed validation scenarios")
    parser.add_argument("--plan-devices", type=int, default=1,
                        help="--plan: device count to plan for")
    parser.add_argument("--plan-batch", type=int, default=8,
                        help="--plan: global batch size")
    parser.add_argument("--plan-seq", type=int, default=1024,
                        help="--plan: sequence length")
    parser.add_argument("--plan-moment-dtype", default="bfloat16",
                        choices=["bfloat16", "float32"],
                        help="--plan: Adam moment dtype")
    parser.add_argument("--plan-hidden", type=int, default=None,
                        help="--plan: override the preset hidden size "
                             "(smoke-sized searches)")
    parser.add_argument("--plan-layers", type=int, default=None,
                        help="--plan: override the preset layer count")
    parser.add_argument("--plan-vocab", type=int, default=None,
                        help="--plan: override the preset vocab size")
    parser.add_argument("--plan-heads", type=int, default=None,
                        help="--plan: override the preset attention-head "
                             "count")
    parser.add_argument("--plan-max-lowered", type=int, default=8,
                        help="--plan: candidates to lower/price exactly "
                             "(the rest keep the legacy prior)")
    parser.add_argument("--plan-pin", default=None, metavar="PLAN_ID",
                        help="--plan: gate exit status on THIS candidate "
                             "(e.g. dp1-mp1-pp1-zero0-m1-remat0) being "
                             "feasible")
    parser.add_argument("--device-budget", type=float, default=None,
                        metavar="BYTES",
                        help="HBM budget for oom-risk/remat-advisor "
                             "(default one v5e chip, 16 GiB); an oom-risk "
                             "HIGH against it gates exit-1")
    parser.add_argument("--nan-only", action="store_true",
                        help="--sanitize: flag NaN only (programs that "
                             "mask with infinities)")
    args = parser.parse_args(argv)
    if args.nan_only and not args.sanitize:
        parser.error("--nan-only only applies to --sanitize")
    if args.device_budget is not None and args.sanitize:
        parser.error("--device-budget applies to the lint/--memory/--plan "
                     "modes")
    if (args.plan_pin or args.plan_model) and not args.plan:
        parser.error("--plan-* options apply to --plan")
    if (args.host_only or args.host_path or args.host_journal) \
            and not args.host:
        parser.error("--host-* options apply to --host")
    if args.bisect_demo and not args.determinism:
        parser.error("--bisect-demo applies to --determinism")
    # NOTE: platform/device-count env setup lives in __main__.py (re-exec
    # before jax initializes); mutating os.environ here would be both too
    # late for this process and a leak into child processes.

    if args.host:
        # AST over host source only: no entry point is traced or lowered
        # (the 0.5s lint wall; process startup still pays the package
        # import — paddle_tpu itself imports jax)
        return _host_mode(args)
    if args.plan:
        return _plan_mode(args)
    if args.determinism:
        return _determinism_mode(args)
    if args.kernels or args.kernels_sweep:
        return _kernels_mode(args)

    import jax

    from .entrypoints import shipped_entry_points
    from .findings import Severity

    t0 = time.perf_counter()
    # always collect builder failures so they reach the report (and exit 2)
    # instead of escaping as a raw traceback
    targets, errors = shipped_entry_points(
        skip_errors=True, only=tuple(args.only))
    meta = {"tool": "paddle_tpu.analysis",
            "backend": jax.default_backend(),
            "n_devices": len(jax.devices()), "build_errors": errors}

    overrides = {}
    if args.device_budget is not None:
        budget = int(args.device_budget)
        overrides = {"oom-risk": {"budget_bytes": budget},
                     "remat-advisor": {"budget_bytes": budget}}

    if args.memory:
        report, out, extra = _memory_mode(targets, meta, overrides, args)
    elif args.sanitize:
        report, out, extra = _sanitize_mode(targets, meta, args)
    else:
        report, out, extra = _lint_mode(targets, meta, overrides, args)

    # total_s must land BEFORE the artifact is written (round tracking
    # reads wall time from the JSON, not the console)
    report.meta["total_s"] = round(time.perf_counter() - t0, 3)
    if extra is None:
        report.save(out)
    else:
        _save_json(out, dict(report.to_dict(), **extra))
    print(f"analyzed {len(targets)} entry points in "
          f"{report.meta['total_s']}s -> {out}")
    for name, err in errors.items():
        print(f"  BUILD FAILED {name}: {err}")
    print()
    print(report.table())
    counts = report.counts()
    print()
    print("findings:", ", ".join(f"{k}={v}" for k, v in counts.items()))

    if errors and not args.keep_going:
        return 2
    if args.fail_on != "never":
        gate = Severity[args.fail_on.upper()]
        if report.at_least(gate):
            return 1
    return 0


def _kernels_mode(args) -> int:
    """``--kernels`` / ``--kernels-sweep``: the Pallas kernel doctor.

    ``--kernels`` audits every manifest kernel (coverage proof, dtype
    safety, VMEM budget, registry drift) and gates exit status on the
    standard ``--fail-on`` contract; ``--kernels-sweep`` is pure shape
    arithmetic (no kernel runs) and never gates."""
    from .findings import Severity
    from .kernels import analyze_kernels, kernel_sweep, sweep_table

    if args.kernels_sweep:
        sweep = kernel_sweep()
        out = args.out or _default_out("analysis_kernels_sweep.json")
        _save_json(out, sweep)
        print(f"swept {len(sweep['rows'])} kernel shapes in "
              f"{sweep['elapsed_s']}s -> {out}")
        print()
        print(sweep_table(sweep))
        return 0

    t0 = time.perf_counter()
    report = analyze_kernels()
    report.meta["total_s"] = round(time.perf_counter() - t0, 3)
    out = args.out or _default_out("analysis_kernels.json")
    report.save(out)
    print(f"audited {report.meta['n_cases']} manifest kernels in "
          f"{report.meta['total_s']}s -> {out}")
    print()
    print(report.table())
    counts = report.counts()
    print()
    print("findings:", ", ".join(f"{k}={v}" for k, v in counts.items()))
    if args.fail_on != "never":
        gate = Severity[args.fail_on.upper()]
        if report.at_least(gate):
            return 1
    return 0


def _host_mode(args) -> int:
    """``--host``: concurrency doctor over the host control plane.

    Exit contract mirrors the jaxpr lint: 0 when no finding reaches
    ``--fail-on`` (default HIGH), 1 otherwise.  A crashed rule and an
    unparseable module both surface as MEDIUM findings — a broken check
    must never silently pass the gate."""
    from .findings import Severity
    from .hostrace import analyze_host
    from .lockmodel import default_host_paths
    from .rules import default_host_rules

    paths = None
    if args.host_path:
        paths = []
        seen = set()

        def add(name, full):
            # two files sharing a basename must not shadow each other in
            # the module dict (a shadowed planted HIGH would silently
            # pass the gate) — disambiguate with a stable suffix
            base, n = name, 2
            while name in seen:
                name = f"{base}.{n}"
                n += 1
            seen.add(name)
            paths.append((name, full))

        for p in args.host_path:
            if os.path.isdir(p):
                for fn in sorted(os.listdir(p)):
                    if fn.endswith(".py"):
                        add(os.path.splitext(fn)[0], os.path.join(p, fn))
            elif os.path.exists(p):
                add(os.path.splitext(os.path.basename(p))[0], p)
            else:
                print(f"--host-path {p}: no such file or directory",
                      file=sys.stderr)
                return 2
    else:
        paths = default_host_paths()

    rules = (default_host_rules(only=tuple(args.host_only))
             if args.host_only else None)
    try:
        report = analyze_host(paths=paths, journal=args.host_journal,
                              rules=rules)
    except (OSError, ValueError) as e:
        # an explicitly named journal that is missing/corrupt is a usage
        # error, not an empty merge
        print(f"--host-journal {args.host_journal}: {e}", file=sys.stderr)
        return 2
    out = args.out or _default_out("analysis_host.json")
    report.save(out)
    print(f"linted {report.meta['n_modules']} host modules "
          f"({report.meta['n_locks']} locks, "
          f"{report.meta['n_static_edges']} static + "
          f"{report.meta['n_runtime_edges']} runtime order edges) in "
          f"{report.meta['total_s']}s -> {out}")
    print(f"lock graph acyclic: {report.meta['lock_graph_acyclic']}")
    print()
    print(report.table())
    counts = report.counts()
    print()
    print("findings:", ", ".join(f"{k}={v}" for k, v in counts.items()))
    if args.fail_on != "never":
        gate = Severity[args.fail_on.upper()]
        if report.at_least(gate):
            return 1
    return 0


def _determinism_mode(args) -> int:
    """``--determinism``: the determinism doctor.

    Three planes in one artifact: the key-flow lint (jaxpr) over every
    shipped entry point, the host-nondeterminism AST rules with their
    ``# det-ok:`` downgrades, and the replay-certificate seam coverage
    audit (every ``resilience/inject.py`` seam must be pinned by a
    two-run identical-fired-log twin test).  ``--bisect-demo`` appends a
    planted key-chain desync localized by :mod:`.bisect` to its exact
    tick / eqn / profiler scope.  Exit contract mirrors the jaxpr lint:
    1 when any finding reaches ``--fail-on`` (default HIGH), 2 when an
    entry point could not be built."""
    import jax

    from .determinism import analyze_determinism
    from .entrypoints import shipped_entry_points
    from .findings import Severity
    from .keyflow import keyflow_rules
    from .rules import analyze_targets

    t0 = time.perf_counter()
    # host plane first: pure AST, doubles as the inject-registry audit
    report = analyze_determinism()
    # jaxpr plane: the four key-flow rules over every shipped program
    targets, errors = shipped_entry_points(
        skip_errors=True, only=tuple(args.only))
    rules = keyflow_rules()
    kf = analyze_targets(targets, rules=rules, meta={})
    report.extend(kf.findings)
    report.meta.update(
        tool="paddle_tpu.analysis --determinism",
        backend=jax.default_backend(), n_devices=len(jax.devices()),
        build_errors=errors,
        entry_points=[t.name for t in targets],
        keyflow_rules=[r.name for r in rules])

    extra = {}
    if args.bisect_demo:
        from .bisect import demo_divergence

        res = demo_divergence(desync_tick=args.bisect_tick)
        extra["bisect_demo"] = dict(res.to_dict(),
                                    planted_tick=args.bisect_tick)
        print("bisect demo:",
              str(res.first) if res.first is not None else "identical")

    report.meta["total_s"] = round(time.perf_counter() - t0, 3)
    out = args.out or _default_out("analysis_determinism.json")
    _save_json(out, dict(report.to_dict(), **extra))
    cov = report.meta.get("seam_coverage", {})
    print(f"determinism: {len(targets)} entry points, "
          f"{report.meta['n_modules']} host modules, seam coverage "
          f"{cov.get('n_covered', '?')}/{cov.get('n_points', '?')} in "
          f"{report.meta['total_s']}s -> {out}")
    for name, err in errors.items():
        print(f"  BUILD FAILED {name}: {err}")
    print()
    print(report.table())
    counts = report.counts()
    print()
    print("findings:", ", ".join(f"{k}={v}" for k, v in counts.items()))
    if errors and not args.keep_going:
        return 2
    if args.fail_on != "never":
        gate = Severity[args.fail_on.upper()]
        if report.at_least(gate):
            return 1
    return 0


def _plan_mode(args) -> int:
    """``--plan``: planner-v2 search → ranked plan table artifact.

    Exit contract (the oom-risk-gate analog): 1 when the *requested*
    config is infeasible under ``--device-budget`` — the pinned candidate
    with ``--plan-pin``, the chosen plan for a custom ``--plan-model`` run,
    or any committed validation scenario whose expectation is not met."""
    from .plan import (
        PLAN_SCHEMA_VERSION,
        plan_gpt,
        run_validation_scenarios,
    )

    t0 = time.perf_counter()
    budget = (int(args.device_budget)
              if args.device_budget is not None else None)
    if args.plan_model:
        from ..models.gpt import gpt_config

        overrides = {}
        if args.plan_hidden:
            overrides["hidden_size"] = args.plan_hidden
        if args.plan_layers:
            overrides["num_layers"] = args.plan_layers
        if args.plan_vocab:
            overrides["vocab_size"] = args.plan_vocab
        if args.plan_heads:
            overrides["num_attention_heads"] = args.plan_heads
        cfg = gpt_config(args.plan_model, hidden_dropout_prob=0.0,
                         attention_dropout_prob=0.0,
                         max_position_embeddings=args.plan_seq,
                         **overrides)
        plan = plan_gpt(cfg, args.plan_devices, args.plan_batch,
                        seq_len=args.plan_seq, budget_bytes=budget,
                        moment_dtype=args.plan_moment_dtype,
                        max_lowered=args.plan_max_lowered)
        key = (f"{args.plan_model}_n{args.plan_devices}"
               f"_b{args.plan_batch}_{args.plan_moment_dtype}")
        payload = {"schema_version": PLAN_SCHEMA_VERSION,
                   "scenarios": {key: plan.table()}}
        print(plan.explain())
        ok = plan.chosen is not None
    else:
        payload = run_validation_scenarios(
            budget_bytes=budget, max_lowered=args.plan_max_lowered)
        for key, tb in payload["scenarios"].items():
            print(f"{key}: chosen={tb['chosen']} expect={tb['expect']} "
                  f"outcome={tb['outcome']} "
                  f"({'OK' if tb['expectation_met'] else 'MISMATCH'})")
        ok = payload["all_expectations_met"]

    tables = payload["scenarios"]
    if args.plan_pin:
        # the pin verdict NARROWS the exit contract, it never overrides a
        # scenario-expectation failure (a regressed validation scenario
        # must still exit 1 even when the pinned candidate is feasible)
        pinned = [r for tb in tables.values() for r in tb["candidates"]
                  if r["plan_id"] == args.plan_pin]
        if not pinned:
            known = sorted({r["plan_id"] for tb in tables.values()
                            for r in tb["candidates"]})
            print(f"--plan-pin {args.plan_pin}: no such candidate; "
                  f"known: {', '.join(known[:10])} ...")
            ok = False
        else:
            ok = ok and all(r["feasible"] for r in pinned)
            for r in pinned:
                verdict = "feasible" if r["feasible"] else "INFEASIBLE"
                print(f"pinned {r['plan_id']}: {verdict} "
                      f"(peak {r['predicted_peak_hbm_bytes']} B)")

    payload["total_s"] = round(time.perf_counter() - t0, 3)
    out = args.out or _default_out("plan_table.json")
    _save_json(out, payload)
    print(f"plan table -> {out} ({payload['total_s']}s)")
    return 0 if ok else 1


def _lint_mode(targets, meta, overrides, args):
    from .rules import analyze_targets, default_rules

    rules = default_rules(**overrides) if overrides else None
    report = analyze_targets(targets, rules=rules, meta=meta)
    return report, args.out or _default_out("analysis_report.json"), None


def _memory_mode(targets, meta, overrides, args):
    """Per-entry-point peak-HBM/cost JSON + memory rules + planner drift."""
    from .cost import graph_cost
    from .findings import Finding, Severity
    from .memory import (
        MEMORY_SCHEMA_VERSION,
        LowIntensityDotRule,
        MemoryBudgetRule,
        RematAdvisorRule,
        memory_estimate,
    )
    from .plan import default_consistency_findings
    from .rules import analyze_targets

    rules = [MemoryBudgetRule(**overrides.get("oom-risk", {})),
             LowIntensityDotRule(),
             RematAdvisorRule(**overrides.get("remat-advisor", {}))]
    report = analyze_targets(targets, rules=rules, meta=meta)
    entries = {}
    for t in targets:
        try:
            est = memory_estimate(t)
            cost = graph_cost(t.graph(), t.mesh_axes)
            entries[t.name] = dict(est.to_dict(), cost=cost.to_dict())
            if cost.unknown:
                report.extend([Finding(
                    rule="cost-model", severity=Severity.INFO,
                    entry_point=t.name,
                    message=("unknown primitive(s) fell back to bytes-only "
                             f"cost: {sorted(cost.unknown)} — extend "
                             "analysis/cost.py if they matter"),
                    details={"unknown_prims": dict(cost.unknown),
                             "unknown_where": dict(cost.unknown_where)})])
        except Exception as e:  # mirrors run_rules' crashed-rule policy
            entries[t.name] = {"error": f"{type(e).__name__}: {e}"}
            report.extend([Finding(
                rule="memory-report", severity=Severity.MEDIUM,
                entry_point=t.name,
                message=f"memory estimate crashed: "
                        f"{type(e).__name__}: {e}")])
    # planner-v2 self-consistency (retires the r10 after-the-fact drift
    # cross-check): a CPU-sized search whose chosen plan must match a fresh
    # liveness estimate on its OWN lowered target to <0.5%; the legacy
    # constant model is drift-checked only when v2 falls back to it.  Only
    # worth it on a full sweep, not when --only narrowed the run.
    if targets and not args.only:
        try:
            report.extend(default_consistency_findings())
        except Exception as e:
            report.extend([Finding(
                rule="planner-consistency", severity=Severity.MEDIUM,
                message=f"planner cross-check crashed: "
                        f"{type(e).__name__}: {e}")])
    out = args.out or _default_out("analysis_memory.json")
    for name, e in entries.items():
        peak = e.get("peak_hbm_bytes")
        if peak is not None:
            print(f"  {name}: peak {peak / 1e6:.2f} MB, resident "
                  f"{e['resident_bytes'] / 1e6:.2f} MB @ "
                  f"{e['peak_site']['prim']}")
    return report, out, {"schema_version": MEMORY_SCHEMA_VERSION,
                         "entry_points": entries}


def _sanitize_mode(targets, meta, args):
    from .findings import AnalysisReport, Finding, Severity
    from .sanitizer import SanitizerConfig, sanitize_target

    report = AnalysisReport(meta=dict(
        meta, mode="sanitize", nan_only=bool(args.nan_only)))
    cfg = SanitizerConfig(check_inf=not args.nan_only)
    entries = {}
    timings = {}
    for t in targets:
        t0 = time.perf_counter()
        try:
            res = sanitize_target(t, cfg)
            entries[t.name] = res.to_dict()
            if res.first is not None:
                f = Finding(
                    rule="sanitizer-nonfinite", severity=Severity.HIGH,
                    entry_point=t.name, message=str(res.first),
                    details=res.first.to_dict())
                f.scope = res.first.scope
                f.source = res.first.source
                report.extend([f])
        except Exception as e:
            entries[t.name] = {"error": f"{type(e).__name__}: {e}"}
            report.extend([Finding(
                rule="sanitizer-replay", severity=Severity.MEDIUM,
                entry_point=t.name,
                message=f"sanitizer replay crashed: "
                        f"{type(e).__name__}: {e}")])
        timings[t.name] = round(time.perf_counter() - t0, 4)
    report.meta["timings_s"] = timings
    report.meta["entry_points"] = [t.name for t in targets]
    out = args.out or _default_out("analysis_sanitize.json")
    for name, e in entries.items():
        status = ("ERROR" if "error" in e
                  else "clean" if e.get("ok") else "NON-FINITE")
        print(f"  {name}: {status} ({e.get('checked_values', 0)} values "
              f"checked)")
    return report, out, {"entry_points": entries}


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
