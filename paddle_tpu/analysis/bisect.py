"""Divergence bisector: localize the first differing eqn between two
supposedly-identical runs (sanitizer-style twin replay, r10).

When a twin test goes red — two runs under one fault schedule that should
be bit-identical but aren't — the fired-log diff says *that* they
diverged; this module says *where*.  Both transcripts are replayed
through ONE jaxpr eqn-by-eqn (two environments threaded side by side),
every output pair is compared bitwise ON DEVICE, and the host syncs the
difference flags in chunks of ``check_every`` eqns — the exact execution
strategy of the r10 NaN attributor, with equality in place of
``isfinite``.  The first diverging eqn is reported with its profiler
scope (r6 name_stack), source line, control-flow path, tick index and —
inside scan/while — the iteration.

Control flow descends structurally (pjit/cond/scan/while): a *control*
divergence (the two runs disagree on a cond predicate or a while
continuation) is reported at the container eqn itself, which is exactly
the "rank-divergent branch" failure mode the key-flow rules guard
against.

:func:`diff_fired_logs` is the host-side half: first differing entry of
two replay certificates.  :func:`demo_divergence` builds the CLI demo —
a sampled serving-style decode loop whose key chain is deliberately
desynced at one tick, then localized back to that tick's first drawing
eqn under the ``serving.sample`` scope.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from .graph import _jcore, _name_stack_of, _source_of
from .sanitizer import _bind_whole, _closed_parts

__all__ = [
    "BISECT_SCHEMA_VERSION",
    "BisectConfig",
    "DivergenceReport",
    "BisectResult",
    "bisect_runs",
    "diff_fired_logs",
    "demo_divergence",
]

#: layout version of the bisector's JSON block
BISECT_SCHEMA_VERSION = 1


@dataclasses.dataclass
class BisectConfig:
    check_every: int = 32          # device→host sync chunk (r10 idiom)
    recurse: bool = True
    max_while_iters: int = 100_000


@dataclasses.dataclass
class DivergenceReport:
    """First diverging value (or control decision), attributed."""

    tick: int                      # index into the transcript pairs
    eqn_index: int                 # flattened replay order within the tick
    prim: str
    path: Tuple[str, ...]
    scope: str                     # r6 profiler name_stack
    source: str                    # file:line (function)
    out_slot: int
    shape: Tuple[int, ...]
    dtype: str
    n_diff: int
    n_total: int
    kind: str = "value"            # "value" | "control" | "input"
    iteration: Optional[int] = None

    @property
    def where(self) -> str:
        return " @ ".join(x for x in (self.scope, self.source) if x)

    def __str__(self):
        it = f" (iteration {self.iteration})" if self.iteration is not None \
            else ""
        loc = f" [{self.where}]" if self.where else ""
        if self.kind == "control":
            return (f"runs diverge at tick {self.tick}: control decision "
                    f"of eqn #{self.eqn_index} '{self.prim}'{it} "
                    f"differs{loc}")
        if self.kind == "input":
            return (f"runs diverge at tick {self.tick}: entry argument "
                    f"{self.out_slot} ({self.dtype}{list(self.shape)}) "
                    f"already differs — {self.n_diff}/{self.n_total} "
                    f"elements")
        return (f"runs diverge at tick {self.tick}: first diverging "
                f"value from eqn #{self.eqn_index} '{self.prim}'{it}: "
                f"{self.n_diff}/{self.n_total} elements in output "
                f"{self.out_slot} {self.dtype}{list(self.shape)}{loc}")

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["path"] = list(self.path)
        d["shape"] = list(self.shape)
        d["where"] = self.where
        d["schema_version"] = BISECT_SCHEMA_VERSION
        return d


@dataclasses.dataclass
class BisectResult:
    first: Optional[DivergenceReport]
    checked_ticks: int
    checked_eqns: int

    @property
    def identical(self) -> bool:
        return self.first is None

    def to_dict(self) -> dict:
        return {"identical": self.identical,
                "checked_ticks": self.checked_ticks,
                "checked_eqns": self.checked_eqns,
                "first_divergence": (self.first.to_dict()
                                     if self.first else None)}


class _Stop(Exception):
    pass


def _key_data(x):
    """Comparable view: typed PRNG keys expose their uint32 words."""
    import jax

    dt = getattr(x, "dtype", None)
    if dt is not None and str(dt).startswith("key<"):
        return jax.random.key_data(x)
    return x


def _neq_count(a, b):
    """Device scalar: element count where a != b (bitwise; NaN==NaN)."""
    import jax.numpy as jnp

    a, b = _key_data(a), _key_data(b)
    try:
        ne = a != b
    except TypeError:
        return jnp.asarray(int(not (a == b)))
    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating):
        both_nan = jnp.isnan(a) & jnp.isnan(b)
        ne = ne & ~both_nan
    return jnp.sum(ne)


class _State:
    def __init__(self, config: BisectConfig, tick: int):
        self.config = config
        self.tick = tick
        self.eqn_counter = 0
        self.pending: List[tuple] = []   # (count_dev, meta)
        self.report: Optional[DivergenceReport] = None

    def check(self, eqn, outs_a, outs_b, path, iteration):
        idx = self.eqn_counter
        self.eqn_counter += 1
        for slot, (a, b) in enumerate(zip(outs_a, outs_b)):
            n_total = 1
            for s in np.shape(_key_data(a)):
                n_total *= int(s)
            meta = (idx, eqn.primitive.name, path, _name_stack_of(eqn),
                    _source_of(eqn), slot, tuple(np.shape(a)),
                    str(getattr(a, "dtype", type(a).__name__)),
                    max(n_total, 1), iteration)
            self.pending.append((_neq_count(a, b), meta))
        if len(self.pending) >= self.config.check_every:
            self.flush()

    def flush(self):
        if not self.pending:
            return
        import jax.numpy as jnp

        counts = np.asarray(jnp.stack([c for c, _ in self.pending]))
        pending, self.pending = self.pending, []
        for n_diff, (_, meta) in zip(counts, pending):
            if int(n_diff) == 0:
                continue
            (idx, prim, path, scope, source, slot, shape, dtype,
             n_total, iteration) = meta
            self.report = DivergenceReport(
                tick=self.tick, eqn_index=idx, prim=prim, path=path,
                scope=scope, source=source, out_slot=slot, shape=shape,
                dtype=dtype, n_diff=int(n_diff), n_total=n_total,
                iteration=iteration)
            raise _Stop()

    def control(self, eqn, path, iteration, tag):
        """The two runs took different control decisions: everything
        downstream is incomparable — the container IS the divergence.
        Earlier pending values might still hold the first difference,
        so flush before reporting."""
        self.flush()
        self.report = DivergenceReport(
            tick=self.tick, eqn_index=self.eqn_counter,
            prim=eqn.primitive.name, path=path,
            scope=_name_stack_of(eqn), source=_source_of(eqn),
            out_slot=0, shape=(), dtype=tag, n_diff=1, n_total=1,
            kind="control", iteration=iteration)
        raise _Stop()


def _replay2(jaxpr, consts, args_a, args_b, state: _State, path,
             iteration=None):
    env_a, env_b = {}, {}

    def read(env, v):
        return v.val if isinstance(v, _jcore.Literal) else env[v]

    def write(env, vs, vals):
        for v, val in zip(vs, vals):
            env[v] = val

    write(env_a, jaxpr.constvars, consts)
    write(env_b, jaxpr.constvars, consts)
    write(env_a, jaxpr.invars, args_a)
    write(env_b, jaxpr.invars, args_b)

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        in_a = [read(env_a, v) for v in eqn.invars]
        in_b = [read(env_b, v) for v in eqn.invars]
        outs = None
        if state.config.recurse:
            try:
                outs = _replay2_structured(eqn, prim, in_a, in_b, state,
                                           path, iteration)
            except _Stop:
                raise
            except Exception:
                # partial-descent flags are real computations: drain them
                # before falling back (mirrors the r10 fallback contract)
                state.flush()
                outs = None
        if outs is None:
            oa = _bind_whole(eqn, in_a)
            ob = _bind_whole(eqn, in_b)
            state.check(eqn, oa, ob, path, iteration)
            outs = (oa, ob)
        write(env_a, eqn.outvars, outs[0])
        write(env_b, eqn.outvars, outs[1])
    return ([read(env_a, v) for v in jaxpr.outvars],
            [read(env_b, v) for v in jaxpr.outvars])


def _replay2_structured(eqn, prim, in_a, in_b, state, path, iteration):
    import jax.numpy as jnp

    params = eqn.params
    if prim == "pjit":
        inner, iconsts = _closed_parts(params["jaxpr"])
        name = params.get("name", "")
        return _replay2(inner, iconsts, in_a, in_b, state,
                        path + (f"pjit:{name}",), iteration)

    if prim == "cond":
        ia = int(np.clip(int(np.asarray(in_a[0])), 0,
                         len(params["branches"]) - 1))
        ib = int(np.clip(int(np.asarray(in_b[0])), 0,
                         len(params["branches"]) - 1))
        if ia != ib:
            state.control(eqn, path, iteration, "branch-index")
        inner, iconsts = _closed_parts(params["branches"][ia])
        state.eqn_counter += 1
        return _replay2(inner, iconsts, in_a[1:], in_b[1:], state,
                        path + (f"cond.branch{ia}",), iteration)

    if prim == "scan":
        nc = params.get("num_consts", 0)
        nk = params.get("num_carry", 0)
        length = int(params.get("length", 0))
        reverse = bool(params.get("reverse", False))
        inner, iconsts = _closed_parts(params["jaxpr"])
        ca, cb = list(in_a[nc:nc + nk]), list(in_b[nc:nc + nk])
        xs_a, xs_b = in_a[nc + nk:], in_b[nc + nk:]
        ys_a = ys_b = None
        state.eqn_counter += 1
        order = range(length - 1, -1, -1) if reverse else range(length)
        for t in order:
            oa, ob = _replay2(
                inner, iconsts,
                in_a[:nc] + ca + [x[t] for x in xs_a],
                in_b[:nc] + cb + [x[t] for x in xs_b],
                state, path + ("scan",), iteration=t)
            ca, cb = list(oa[:nk]), list(ob[:nk])
            if ys_a is None:
                ys_a = [[] for _ in oa[nk:]]
                ys_b = [[] for _ in ob[nk:]]
            for acc, y in zip(ys_a, oa[nk:]):
                acc.append(y)
            for acc, y in zip(ys_b, ob[nk:]):
                acc.append(y)
        n_ys = len(eqn.outvars) - nk
        if ys_a is None:
            ys_a = [[] for _ in range(n_ys)]
            ys_b = [[] for _ in range(n_ys)]

        def stack(accs, side):
            out = []
            for j, acc in enumerate(accs):
                if reverse:
                    acc = acc[::-1]
                if acc:
                    out.append(jnp.stack(acc))
                else:
                    ov = eqn.outvars[nk + j].aval
                    out.append(jnp.zeros(ov.shape, ov.dtype))
            return out

        return (ca + stack(ys_a, 0), cb + stack(ys_b, 1))

    if prim == "while":
        cn = params.get("cond_nconsts", 0)
        bn = params.get("body_nconsts", 0)
        cond_j, cond_c = _closed_parts(params["cond_jaxpr"])
        body_j, body_c = _closed_parts(params["body_jaxpr"])
        ca, cb = list(in_a[cn + bn:]), list(in_b[cn + bn:])
        state.eqn_counter += 1
        it = 0
        while True:
            pa, pb = _replay2(cond_j, cond_c,
                              in_a[:cn] + ca, in_b[:cn] + cb,
                              state, path + ("while.cond",), iteration=it)
            cont_a = bool(np.asarray(pa[0]))
            cont_b = bool(np.asarray(pb[0]))
            if cont_a != cont_b:
                state.control(eqn, path, it, "while-continuation")
            if not cont_a:
                break
            oa, ob = _replay2(body_j, body_c,
                              in_a[cn:cn + bn] + ca,
                              in_b[cn:cn + bn] + cb,
                              state, path + ("while.body",), iteration=it)
            ca, cb = list(oa), list(ob)
            it += 1
            if it >= state.config.max_while_iters:
                raise RuntimeError(
                    f"bisect: while loop exceeded "
                    f"{state.config.max_while_iters} iterations")
        return (ca, cb)

    if prim != "shard_map":
        for key in ("call_jaxpr", "fun_jaxpr", "jaxpr"):
            sub = params.get(key)
            if sub is None:
                continue
            inner, iconsts = _closed_parts(sub)
            if (len(inner.invars) == len(in_a)
                    and len(inner.outvars) == len(eqn.outvars)):
                state.eqn_counter += 1
                return _replay2(inner, iconsts, in_a, in_b, state,
                                path + (prim,), iteration)
    return None


def _flatten(args, kwargs=None):
    import jax

    return [a._data if hasattr(a, "_data") else a
            for a in jax.tree_util.tree_leaves((tuple(args),
                                                kwargs or {}))]


def bisect_runs(fn: Callable, ticks_a: Sequence[Sequence],
                ticks_b: Sequence[Sequence],
                config: Optional[BisectConfig] = None) -> BisectResult:
    """Replay two per-tick transcripts of ``fn`` side by side and report
    the first diverging eqn (+ tick, scope, source).

    ``ticks_a``/``ticks_b`` are equal-length sequences of argument tuples
    — one entry per tick of the run (e.g. per decode step).  The jaxpr is
    traced once from tick 0 and reused: identical transcripts by
    construction run the identical program.  A tick whose *inputs*
    already differ still descends, so the report names the first eqn that
    *computes* on the divergent state (usually the key consumer) rather
    than just the arg index; entry-arg divergence is recoverable from the
    report's path being empty and eqn 0.
    """
    import jax

    if len(ticks_a) != len(ticks_b):
        raise ValueError(
            f"transcripts must pair tick-for-tick: {len(ticks_a)} vs "
            f"{len(ticks_b)} ticks")
    config = config or BisectConfig()
    closed = None
    checked_eqns = 0
    for t, (a, b) in enumerate(zip(ticks_a, ticks_b)):
        if closed is None:
            closed = jax.make_jaxpr(fn)(*a)
        state = _State(config, t)
        try:
            _replay2(closed.jaxpr, list(closed.consts),
                     _flatten(a), _flatten(b), state, ())
            state.flush()
        except _Stop:
            checked_eqns += state.eqn_counter
            return BisectResult(first=state.report, checked_ticks=t + 1,
                                checked_eqns=checked_eqns)
        checked_eqns += state.eqn_counter
    return BisectResult(first=None, checked_ticks=len(ticks_a),
                        checked_eqns=checked_eqns)


def diff_fired_logs(log_a: Sequence[dict], log_b: Sequence[dict]
                    ) -> Optional[dict]:
    """First differing entry of two replay certificates (or None)."""
    for i, (a, b) in enumerate(zip(log_a, log_b)):
        if a != b:
            keys = sorted(set(a) | set(b))
            fields = [k for k in keys if a.get(k) != b.get(k)]
            return {"index": i, "a": a, "b": b, "fields": fields}
    if len(log_a) != len(log_b):
        i = min(len(log_a), len(log_b))
        longer = log_a if len(log_a) > len(log_b) else log_b
        return {"index": i,
                "a": log_a[i] if i < len(log_a) else None,
                "b": log_b[i] if i < len(log_b) else None,
                "fields": ["length"],
                "extra_in": "a" if longer is log_a else "b",
                "lengths": [len(log_a), len(log_b)]}
    return None


# ---------------------------------------------------------------------------
# the CLI demo: a planted key-chain desync in a sampled decode loop
# ---------------------------------------------------------------------------
def demo_divergence(n_ticks: int = 6, desync_tick: int = 3,
                    seed: int = 0, vocab: int = 64,
                    config: Optional[BisectConfig] = None) -> BisectResult:
    """Serving-shaped repro: a per-tick sampled decode step (logits →
    split → categorical under the ``serving.sample`` scope).  Transcript
    B's key chain is fold_in-desynced at ``desync_tick``; the bisector
    must localize the first diverging eqn to that exact tick, inside the
    ``serving.sample`` scope, at the drawing prim."""
    import jax
    import jax.numpy as jnp

    from ..profiler.scope import scope

    table = jax.random.normal(jax.random.PRNGKey(seed + 1), (vocab, vocab))

    def step(tok, key):
        with scope("serving.decode"):
            logits = table[tok] * 1.5
        with scope("serving.sample"):
            k_next, k_draw = jax.random.split(key)
            # int32 regardless of the x64 mode: the eager transcript must
            # feed the jaxpr traced from tick 0 at every later tick
            nxt = jax.random.categorical(k_draw, logits).astype(jnp.int32)
        return nxt, k_next

    def transcript(desync_at=None):
        ticks = []
        tok = jnp.asarray(0, jnp.int32)
        key = jax.random.PRNGKey(seed)
        for t in range(n_ticks):
            if t == desync_at:
                # the planted bug: one run folds an extra derivation into
                # the chain (a lost fast_forward join, a double fold_in)
                key = jax.random.fold_in(key, 1)
            ticks.append((tok, key))
            tok, key = step(tok, key)
        return ticks

    return bisect_runs(step, transcript(None), transcript(desync_tick),
                       config=config)
