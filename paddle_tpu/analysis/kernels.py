"""Pallas kernel doctor: block-spec coverage proofs, f32-accumulation
lint, VMEM budgeting, and cost-registry drift certification (r24).

The reference framework ships a per-op shape-inference + OpDesc
verification pass (``InferShapeContext``/``OpProtoMaker`` checks run at
program-build time); the kernels we hand-write in Pallas sit UNDER that
surface — a wrong ``BlockSpec`` index map silently reads garbage or
drops writes, and nothing in the jaxpr type system objects.  This module
is the equivalent compile-time doctor for the kernel plane.  It consumes
the kernel manifest (:func:`paddle_tpu.ops.pallas.kernel_manifest` — one
representative launch per shipped ``pl.pallas_call``) and proves, per
kernel:

**Coverage** — every BlockSpec index map is a pure function of the grid
indices plus the scalar-prefetch arrays, so over a concrete grid it can
be evaluated EXACTLY (no abstraction): every output block must be
written by exactly one contiguous run of grid steps (Pallas revisits a
block legally only while the index is unchanged between consecutive
steps — the pipeline holds the block in VMEM and flushes on change; a
*non-contiguous* revisit overwrites flushed data → write race, and a
never-visited block ships uninitialized HBM → garbage).  Input blocks
must stay in bounds; visits to a non-dividing tail block are legal but
require the kernel body to mask (cross-checked against the body's
iota→compare→select idiom).

**Dtype safety** — the body jaxpr rides the same def-use walker as every
other rule surface (:func:`~.graph.build_graph` consumes the kernel
jaxpr directly): accumulating ops (``dot_general`` without
``preferred_element_type=f32``, ``reduce_sum``/``cumsum``) on half
inputs are HIGH — on the MXU/VPU those accumulate in bf16 and lose the
mantissa the online-softmax algebra depends on.  ``reduce_max`` in bf16
is exact and deliberately NOT flagged.

**VMEM budget** — per-grid-step resident bytes (double-buffered in/out
blocks + scratch) against the per-generation VMEM capacity table; the
``--kernels-sweep`` CLI mode prices real serving shapes (page_size
16/32 × the real-vocab lattice, roadmap item 1a) through the same
estimator plus the registry roofline.

**Registry drift** — flops derived from the body jaxpr
(:func:`~.cost.graph_cost` × grid trip count) and bytes derived from the
coverage proof's block-visit runs are certified against the registered
analytic model (:mod:`paddle_tpu.ops.pallas.cost_registry`).  Derived
bytes form a band: ``unique`` (each distinct block once — perfect reuse)
to ``runs`` (one fetch per contiguous visit run — what the pipeline
actually moves); a registered model outside ``[unique/tol, runs*tol]``
is stale.  Manifest↔registry name mismatches are HIGH in both
directions: an unregistered first-party kernel is priced by the loud
bytes-only fallback (planner v2 regresses), a registry entry with no
manifest kernel is dead weight that will rot.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

try:
    from jax._src import core as _jcore
except ImportError:  # pragma: no cover
    import jax.core as _jcore

from jax._src.state import discharge as _state_discharge

from .findings import Finding, Severity, AnalysisReport
from .graph import build_graph
from .cost import graph_cost

__all__ = [
    "KERNELS_SCHEMA_VERSION",
    "VMEM_BYTES",
    "TPU_GENERATIONS",
    "KernelAudit",
    "analyze_kernels",
    "kernel_sweep",
    "sweep_table",
    "collect_pallas_eqns",
]

#: layout version of the ``analysis_kernels.json`` artifact
KERNELS_SCHEMA_VERSION = 1

#: per-generation VMEM capacity (bytes/core).  All current generations
#: expose ~16 MiB of VMEM to Mosaic (the guide's planning number); kept
#: as a per-generation table so a future part with a different budget is
#: a one-line change, not a refactor.
VMEM_BYTES: Dict[str, int] = {
    "v4": 16 * 2 ** 20,
    "v5e": 16 * 2 ** 20,
    "v5p": 16 * 2 ** 20,
}

#: fraction of VMEM the estimator may claim before warning — Mosaic adds
#: its own spill/semaphore slack on top of our double-buffer lower bound
VMEM_HEADROOM_FRAC = 0.75

#: flops certification band: derived/registered ratio must stay within
#: a factor of (1 + tol).  The analytic models count algorithm flops;
#: the derived number counts every VPU op the body jaxpr executes
#: (compare/select/broadcast overhead), so an exact match is not the
#: contract — catching a forgotten grid factor or a wrong S is.
FLOPS_DRIFT_TOL = 1.0

#: bytes certification band half-width: registered bytes must fall in
#: ``[unique_bytes / tol, runs_bytes * tol]``
BYTES_DRIFT_TOL = 2.0

#: coverage proofs enumerate the full grid; past this many steps the
#: proof is skipped (INFO) rather than stalling the lint — manifest
#: cases are chosen small precisely so the proof stays exact
MAX_COVERAGE_STEPS = 65536

_HALF_DTYPES = frozenset({"bfloat16", "float16"})

#: accumulating reductions — unsafe in half precision (reduce_max /
#: reduce_min are exact in any dtype and deliberately not listed)
_ACCUM_REDUCTIONS = frozenset({
    "reduce_sum", "reduce_prod", "cumsum", "cumprod", "cumlogsumexp",
})

#: transcendentals whose half-precision evaluation loses the tail the
#: online-softmax rescaling algebra needs
_TRANSCENDENTALS = frozenset({"exp", "log", "log1p", "expm1", "logistic"})

_COMPARES = frozenset({"lt", "le", "gt", "ge", "eq", "ne"})
_IOTAS = frozenset({"iota", "broadcasted_iota"})


# ---------------------------------------------------------------------------
# peak tables (shared with the observability plane — import, don't fork)
# ---------------------------------------------------------------------------
def _peaks() -> Dict[str, Dict[str, float]]:
    """Per-generation peak flops / HBM BW, read from the observability
    plane's tables so the doctor and the live gauges can never disagree
    about what a v5e is."""
    from ..observability.gauges import _PEAK_FLOPS_BF16
    from ..observability.perf import _PEAK_HBM_BW
    out: Dict[str, Dict[str, float]] = {}
    for gen, vmem in VMEM_BYTES.items():
        out[gen] = {
            "vmem_bytes": float(vmem),
            "peak_flops_bf16": float(_PEAK_FLOPS_BF16.get(gen, 0.0)),
            "peak_hbm_bw": float(_PEAK_HBM_BW.get(gen, 0.0)),
        }
    return out


def TPU_GENERATIONS() -> Dict[str, Dict[str, float]]:
    """Public accessor for the generation table (function, not constant,
    so the observability import stays lazy)."""
    return _peaks()


# ---------------------------------------------------------------------------
# pallas_call collection
# ---------------------------------------------------------------------------
def collect_pallas_eqns(jaxpr) -> List[Any]:
    """Every ``pallas_call`` eqn anywhere in (possibly nested) ``jaxpr``
    — recurses through pjit/custom_vjp/cond/scan sub-jaxprs, so a
    ``jax.grad`` trace yields the fwd AND bwd kernels."""
    out: List[Any] = []

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "pallas_call":
                out.append(eqn)
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    walk(sub)

    walk(jaxpr)
    return out


def _sub_jaxprs(v):
    if isinstance(v, _jcore.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, _jcore.Jaxpr):
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _sub_jaxprs(x)


def _eqn_name(eqn) -> str:
    info = eqn.params.get("name_and_src_info")
    return getattr(info, "name", "") or eqn.params.get("name", "")


def _aval_triple(v):
    aval = getattr(v, "aval", v)
    shape = tuple(int(s) for s in getattr(aval, "shape", ()))
    dtype = getattr(aval, "dtype", None)
    return (shape, str(dtype) if dtype is not None else None,
            bool(getattr(aval, "weak_type", False)))


def _light_params(params: dict) -> dict:
    out = {}
    for k, v in params.items():
        if isinstance(v, (_jcore.Jaxpr, _jcore.ClosedJaxpr)):
            continue
        if isinstance(v, (tuple, list)) and any(
                isinstance(x, (_jcore.Jaxpr, _jcore.ClosedJaxpr))
                for x in v):
            continue
        out[k] = v
    return out


def _triple_bytes(triple) -> int:
    shape, dtype, _ = triple
    if dtype is None:
        return 0
    try:
        item = np.dtype(dtype).itemsize
    except TypeError:
        item = 16
    n = 1
    for s in shape:
        n *= int(s)
    return n * item


def _block_bytes(block_shape, dtype) -> int:
    try:
        item = np.dtype(dtype).itemsize
    except TypeError:
        item = 16
    n = 1
    for s in block_shape:
        n *= int(s) if s is not None else 1
    return n * item


# ---------------------------------------------------------------------------
# per-operand coverage facts
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class OperandCoverage:
    """Concrete block-visit record for one pallas operand."""

    role: str                       # registry role or BlockSpec origin
    is_output: bool
    block_shape: Tuple[int, ...]
    array_shape: Tuple[int, ...]
    dtype: str
    nblocks: Tuple[int, ...]        # cdiv(array, block) per dim
    visits: List[Tuple[int, ...]]   # block index per grid step (row-major)
    data_dependent: bool            # index map consumes prefetch values

    @property
    def runs(self) -> List[Tuple[int, ...]]:
        """Contiguous-duplicate-merged visit sequence — one entry per
        actual HBM fetch/flush the Pallas pipeline performs."""
        out: List[Tuple[int, ...]] = []
        for b in self.visits:
            if not out or out[-1] != b:
                out.append(b)
        return out

    @property
    def unique(self) -> set:
        return set(self.visits)

    def tail_dims(self) -> List[int]:
        """Dims where a visited last block overhangs the array."""
        dims = []
        for d, (a, b, n) in enumerate(
                zip(self.array_shape, self.block_shape, self.nblocks)):
            if a % b != 0 and any(v[d] == n - 1 for v in self.visits):
                dims.append(d)
        return dims


@dataclasses.dataclass
class KernelAudit:
    """Everything the doctor derived about one manifest kernel — the
    per-kernel row of the ``analysis_kernels.json`` artifact."""

    name: str
    grid: Tuple[int, ...]
    num_prefetch: int
    operands: List[OperandCoverage]
    vmem_bytes: int
    scratch_bytes: int
    derived_flops: float
    derived_bytes_unique: float
    derived_bytes_runs: float
    registered_flops: Optional[float]
    registered_bytes: Optional[float]
    coverage_proved: bool
    mask_idiom: bool

    def to_row(self, peaks: Dict[str, Dict[str, float]]) -> dict:
        reg_f = self.registered_flops
        reg_b = self.registered_bytes
        flops_ratio = (self.derived_flops / reg_f
                       if reg_f else None)
        row = {
            "kernel": self.name,
            "grid": list(self.grid),
            "steps": int(np.prod(self.grid)) if self.grid else 1,
            "vmem_bytes": int(self.vmem_bytes),
            "scratch_bytes": int(self.scratch_bytes),
            "derived_flops": self.derived_flops,
            "derived_bytes_unique": self.derived_bytes_unique,
            "derived_bytes_runs": self.derived_bytes_runs,
            "registered_flops": reg_f,
            "registered_bytes": reg_b,
            "flops_ratio": (round(flops_ratio, 3)
                            if flops_ratio is not None else None),
            "coverage_proved": self.coverage_proved,
            "mask_idiom": self.mask_idiom,
        }
        for gen, p in peaks.items():
            row[f"vmem_frac_{gen}"] = round(
                self.vmem_bytes / p["vmem_bytes"], 4)
        if reg_f and reg_b:
            intensity = reg_f / reg_b
            row["intensity"] = round(intensity, 2)
            for gen, p in peaks.items():
                if p["peak_hbm_bw"]:
                    ridge = p["peak_flops_bf16"] / p["peak_hbm_bw"]
                    row[f"bound_{gen}"] = (
                        "compute" if intensity >= ridge else "memory")
        return row


# ---------------------------------------------------------------------------
# index-map evaluation
# ---------------------------------------------------------------------------
def _index_map_callable(bm):
    """A concrete evaluator for one BlockMapping's index map.

    Scalar-prefetch operands reach the map as SMEM refs; discharging the
    jaxpr (exactly what interpret-mode ``compute_start_indices`` does)
    turns them into plain array args, after which the map is an ordinary
    pure function of ``(*grid_indices, *prefetch_arrays)``."""
    closed = bm.index_map_jaxpr
    dis, consts = _state_discharge.discharge_state(closed.jaxpr,
                                                   closed.consts)
    fn = _jcore.jaxpr_as_fun(_jcore.ClosedJaxpr(dis, consts))
    n_out = len(bm.block_shape)

    def call(step: Tuple[int, ...], prefetch: Tuple[np.ndarray, ...]):
        outs = fn(*(jnp.int32(i) for i in step), *prefetch)
        return tuple(int(np.asarray(o)) for o in outs[:n_out])

    return call


def _map_uses_prefetch(bm, n_grid: int) -> bool:
    """True when the index map actually READS a scalar-prefetch operand
    (every map in a PrefetchScalarGridSpec kernel *receives* them)."""
    jaxpr = bm.index_map_jaxpr.jaxpr
    extra = set(jaxpr.invars[n_grid:])
    if not extra:
        return False
    def used(jx):
        for eqn in jx.eqns:
            if any(v in extra for v in eqn.invars
                   if not isinstance(v, _jcore.Literal)):
                return True
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    if used(sub):
                        return True
        return any(v in extra for v in jx.outvars
                   if not isinstance(v, _jcore.Literal))
    return used(jaxpr)


# ---------------------------------------------------------------------------
# body-jaxpr rules (dtype safety + mask idiom) — ride the r9 walker
# ---------------------------------------------------------------------------
def _body_graph(eqn):
    body = eqn.params["jaxpr"]
    closed = body if isinstance(body, _jcore.ClosedJaxpr) \
        else _jcore.ClosedJaxpr(body, ())
    return build_graph(closed)


def _consumers(graph) -> Dict[int, List[int]]:
    out: Dict[int, List[int]] = {}
    for node in graph.nodes:
        for d in node.in_defs:
            if d >= 0:
                out.setdefault(d, []).append(node.idx)
    return out


def _reaches(graph, cons, start_idx: int, prims: frozenset,
             max_hops: int = 8) -> Optional[int]:
    """BFS forward along def-use edges from node ``start_idx``; returns
    the first reached node whose prim is in ``prims``."""
    seen = {start_idx}
    frontier = [start_idx]
    for _ in range(max_hops):
        nxt: List[int] = []
        for i in frontier:
            for j in cons.get(i, ()):
                if j in seen:
                    continue
                seen.add(j)
                if graph.nodes[j].prim in prims:
                    return j
                nxt.append(j)
        frontier = nxt
        if not frontier:
            break
    return None


def _has_mask_idiom(graph) -> bool:
    """iota → compare → select_n within the body: the canonical Pallas
    tail/validity mask (``jnp.where(col < vocab, x, sentinel)``)."""
    cons = _consumers(graph)
    for node in graph.nodes:
        if node.prim not in _IOTAS:
            continue
        cmp_idx = _reaches(graph, cons, node.idx, _COMPARES)
        if cmp_idx is None:
            continue
        if _reaches(graph, cons, cmp_idx, frozenset({"select_n"})) \
                is not None:
            return True
    return False


def _dtype_findings(name: str, graph) -> List[Finding]:
    """f32-accumulation lint over the kernel body's def-use graph."""
    out: List[Finding] = []
    for node in graph.nodes:
        in_half = any(a[1] in _HALF_DTYPES for a in node.in_avals)
        if not in_half:
            continue
        if node.prim == "dot_general":
            pet = str(node.params.get("preferred_element_type"))
            if pet not in ("float32", "float64"):
                out.append(Finding(
                    "kernel-dot-accum", Severity.HIGH,
                    f"{name}: dot_general on half-precision operands "
                    f"without preferred_element_type=f32 "
                    f"(accumulates in {pet})",
                    entry_point=name, scope=node.name_stack,
                    source=node.source,
                    details={"eqn": node.idx, "prim": node.prim,
                             "in_dtypes": [a[1] for a in node.in_avals],
                             "preferred_element_type": pet}))
        elif node.prim in _ACCUM_REDUCTIONS:
            out.append(Finding(
                "kernel-reduction-dtype", Severity.HIGH,
                f"{name}: {node.prim} accumulates in half precision — "
                f"cast the operand to f32 first",
                entry_point=name, scope=node.name_stack,
                source=node.source,
                details={"eqn": node.idx, "prim": node.prim,
                         "in_dtypes": [a[1] for a in node.in_avals]}))
        elif node.prim in _TRANSCENDENTALS:
            out.append(Finding(
                "kernel-transcendental-halfprec", Severity.MEDIUM,
                f"{name}: {node.prim} evaluated in half precision — "
                f"softmax-style rescaling wants f32 stats",
                entry_point=name, scope=node.name_stack,
                source=node.source,
                details={"eqn": node.idx, "prim": node.prim,
                         "in_dtypes": [a[1] for a in node.in_avals]}))
    return out


def _scratch_findings(name: str, eqn, gm) -> List[Finding]:
    out: List[Finding] = []
    n_scratch = int(getattr(gm, "num_scratch_operands", 0) or 0)
    if not n_scratch:
        return out
    body = eqn.params["jaxpr"]
    jaxpr = body.jaxpr if isinstance(body, _jcore.ClosedJaxpr) else body
    for v in jaxpr.invars[len(jaxpr.invars) - n_scratch:]:
        shape, dtype, _ = _aval_triple(v)
        if dtype in _HALF_DTYPES:
            out.append(Finding(
                "kernel-scratch-halfprec", Severity.MEDIUM,
                f"{name}: VMEM scratch accumulator is {dtype} — online "
                f"accumulation state belongs in f32",
                entry_point=name,
                details={"scratch_shape": list(shape), "dtype": dtype}))
    return out


# ---------------------------------------------------------------------------
# the audit of one kernel eqn
# ---------------------------------------------------------------------------
def _audit_eqn(case, eqn, report: AnalysisReport) -> Optional[KernelAudit]:
    from ..ops.pallas.cost_registry import kernel_cost_model, kernel_meta

    name = case.name
    gm = eqn.params["grid_mapping"]
    grid = tuple(int(g) for g in gm.grid)
    n_steps = int(np.prod(grid)) if grid else 1
    n_prefetch = int(getattr(gm, "num_index_operands", 0) or 0)
    bms = list(gm.block_mappings)
    n_out = int(gm.num_outputs)
    in_bms, out_bms = bms[:len(bms) - n_out], bms[len(bms) - n_out:]

    meta = kernel_meta(name)
    roles = list(meta.operand_roles) if meta else []

    prefetch = tuple(np.asarray(a) for a in case.scalar_prefetch())
    if len(prefetch) != n_prefetch:
        report.findings.append(Finding(
            "kernel-manifest-prefetch", Severity.HIGH,
            f"{name}: manifest provides {len(prefetch)} scalar-prefetch "
            f"arrays but the launch declares {n_prefetch}",
            entry_point=name,
            details={"declared": n_prefetch, "provided": len(prefetch)}))
        return None

    # ---- coverage: evaluate every index map over the concrete grid ----
    proved = n_steps <= MAX_COVERAGE_STEPS
    operands: List[OperandCoverage] = []
    steps = list(np.ndindex(*grid)) if (grid and proved) else [()]
    if not proved:
        report.findings.append(Finding(
            "kernel-coverage-skipped", Severity.INFO,
            f"{name}: grid has {n_steps} steps "
            f"(> {MAX_COVERAGE_STEPS}); coverage proof skipped",
            entry_point=name, details={"grid": list(grid)}))

    for k, bm in enumerate(in_bms + out_bms):
        is_out = k >= len(in_bms)
        role = ""
        if roles:
            ri = n_prefetch + k if not is_out else -1
            if not is_out and ri < len(roles):
                role = roles[ri]
        if not role:
            role = str(getattr(bm, "origin", "") or
                       (f"out[{k - len(in_bms)}]" if is_out
                        else f"args[{k}]"))
        arr_sds = bm.array_shape_dtype
        arr_shape = tuple(int(s) for s in arr_sds.shape)
        block = tuple(int(s) if s is not None else 1
                      for s in bm.block_shape)
        nblocks = tuple(-(-a // b) for a, b in zip(arr_shape, block))
        visits: List[Tuple[int, ...]] = []
        if proved:
            call = _index_map_callable(bm)
            for step in steps:
                visits.append(call(step, prefetch))
        operands.append(OperandCoverage(
            role=role, is_output=is_out, block_shape=block,
            array_shape=arr_shape, dtype=str(arr_sds.dtype),
            nblocks=nblocks, visits=visits,
            data_dependent=_map_uses_prefetch(
                bm, len(grid)) if n_prefetch else False))

    body_graph = _body_graph(eqn)
    mask_idiom = _has_mask_idiom(body_graph)

    if proved:
        _coverage_findings(case, name, grid, steps, operands, mask_idiom,
                           report)

    # ---- dtype safety over the body graph ----
    report.findings.extend(_dtype_findings(name, body_graph))
    report.findings.extend(_scratch_findings(name, eqn, gm))

    # ---- VMEM budget ----
    scratch_bytes = _scratch_vmem_bytes(eqn, gm)
    block_io = sum(_block_bytes(op.block_shape, op.dtype)
                   for op in operands)
    vmem = 2 * block_io + scratch_bytes  # double-buffered pipeline
    peaks = _peaks()
    for gen, p in peaks.items():
        frac = vmem / p["vmem_bytes"]
        if frac > 1.0:
            report.findings.append(Finding(
                "kernel-vmem-over", Severity.HIGH,
                f"{name}: estimated per-step VMEM {vmem} B exceeds "
                f"{gen} capacity {int(p['vmem_bytes'])} B",
                entry_point=name,
                details={"generation": gen, "vmem_bytes": vmem,
                         "capacity": int(p["vmem_bytes"])}))
        elif frac > VMEM_HEADROOM_FRAC:
            report.findings.append(Finding(
                "kernel-vmem-headroom", Severity.MEDIUM,
                f"{name}: estimated per-step VMEM {vmem} B is "
                f"{frac:.0%} of {gen} capacity — Mosaic slack will "
                f"likely spill",
                entry_point=name,
                details={"generation": gen, "vmem_bytes": vmem,
                         "frac": round(frac, 3)}))

    # ---- derived cost + registry drift ----
    body_cost = graph_cost(body_graph)
    derived_flops = body_cost.flops * n_steps
    pf_bytes = sum(a.nbytes for a in prefetch)
    uniq_b = pf_bytes + sum(
        len(op.unique) * _block_bytes(op.block_shape, op.dtype)
        for op in operands) if proved else 0.0
    runs_b = pf_bytes + sum(
        len(op.runs) * _block_bytes(op.block_shape, op.dtype)
        for op in operands) if proved else 0.0

    model = kernel_cost_model(name)
    reg_f = reg_b = None
    if model is not None:
        in_avals = tuple(_aval_triple(v) for v in eqn.invars)
        out_avals = tuple(_aval_triple(v) for v in eqn.outvars)
        reg_f, reg_b = model(in_avals, out_avals,
                             _light_params(eqn.params))
        reg_f, reg_b = float(reg_f), float(reg_b)
        if derived_flops > 0 and reg_f > 0:
            ratio = derived_flops / reg_f
            if ratio > 1.0 + FLOPS_DRIFT_TOL or \
                    ratio < 1.0 / (1.0 + FLOPS_DRIFT_TOL):
                report.findings.append(Finding(
                    "kernel-flops-drift", Severity.MEDIUM,
                    f"{name}: registered flops model drifted from the "
                    f"body jaxpr — derived {derived_flops:.3g} vs "
                    f"registered {reg_f:.3g} (ratio {ratio:.2f})",
                    entry_point=name,
                    details={"derived_flops": derived_flops,
                             "registered_flops": reg_f,
                             "ratio": round(ratio, 3),
                             "tolerance": FLOPS_DRIFT_TOL}))
        if proved and reg_b > 0 and runs_b > 0:
            lo = uniq_b / BYTES_DRIFT_TOL
            hi = runs_b * BYTES_DRIFT_TOL
            if not (lo <= reg_b <= hi):
                report.findings.append(Finding(
                    "kernel-bytes-drift", Severity.MEDIUM,
                    f"{name}: registered bytes {reg_b:.3g} outside the "
                    f"derived traffic band [{uniq_b:.3g} unique, "
                    f"{runs_b:.3g} runs] x{BYTES_DRIFT_TOL}",
                    entry_point=name,
                    details={"registered_bytes": reg_b,
                             "unique_bytes": uniq_b,
                             "runs_bytes": runs_b,
                             "tolerance": BYTES_DRIFT_TOL}))
    if meta is not None:
        if not meta.family or not meta.operand_roles:
            report.findings.append(Finding(
                "kernel-meta-empty", Severity.LOW,
                f"{name}: registry entry has no "
                f"family/operand_roles metadata",
                entry_point=name, details=meta.to_dict() if meta else {}))
        elif len(meta.operand_roles) != len(eqn.invars):
            report.findings.append(Finding(
                "kernel-roles-arity", Severity.MEDIUM,
                f"{name}: registry names {len(meta.operand_roles)} "
                f"operand roles but the launch takes "
                f"{len(eqn.invars)} operands",
                entry_point=name,
                details={"operand_roles": list(meta.operand_roles),
                         "n_operands": len(eqn.invars)}))

    return KernelAudit(
        name=name, grid=grid, num_prefetch=n_prefetch,
        operands=operands, vmem_bytes=int(vmem),
        scratch_bytes=int(scratch_bytes),
        derived_flops=float(derived_flops),
        derived_bytes_unique=float(uniq_b),
        derived_bytes_runs=float(runs_b),
        registered_flops=reg_f, registered_bytes=reg_b,
        coverage_proved=proved, mask_idiom=mask_idiom)


def _scratch_vmem_bytes(eqn, gm) -> int:
    n_scratch = int(getattr(gm, "num_scratch_operands", 0) or 0)
    if not n_scratch:
        return 0
    body = eqn.params["jaxpr"]
    jaxpr = body.jaxpr if isinstance(body, _jcore.ClosedJaxpr) else body
    total = 0
    for v in jaxpr.invars[len(jaxpr.invars) - n_scratch:]:
        total += _triple_bytes(_aval_triple(v))
    return total


def _coverage_findings(case, name, grid, steps, operands, mask_idiom,
                       report: AnalysisReport) -> None:
    overhang_roles: List[str] = []
    for op in operands:
        # bounds: every visited block index inside [0, nblocks) per dim
        for si, v in enumerate(op.visits):
            bad = [d for d, (i, n) in enumerate(zip(v, op.nblocks))
                   if i < 0 or i >= n]
            if bad:
                report.findings.append(Finding(
                    "kernel-block-out-of-range", Severity.HIGH,
                    f"{name}: operand '{op.role}' block index {v} out "
                    f"of range {op.nblocks} at grid step {steps[si]}",
                    entry_point=name,
                    details={"operand": op.role, "block_index": list(v),
                             "nblocks": list(op.nblocks),
                             "grid_step": list(steps[si]),
                             "dims": bad}))
                break  # one example per operand is enough

        if op.tail_dims():
            overhang_roles.append(op.role)

        if op.data_dependent:
            sev = Severity.INFO if op.role in case.data_dependent_ok \
                else Severity.MEDIUM
            report.findings.append(Finding(
                "kernel-data-dependent-map",
                sev,
                f"{name}: operand '{op.role}' index map reads "
                f"scalar-prefetch data — coverage holds for the "
                f"manifest's example table"
                + ("" if sev == Severity.INFO
                   else " but the manifest does not declare it"),
                entry_point=name,
                details={"operand": op.role,
                         "declared": op.role in case.data_dependent_ok}))

        if not op.is_output:
            continue

        # ---- exactly-once write proof ----
        run_count: Dict[Tuple[int, ...], int] = {}
        run_first: Dict[Tuple[int, ...], List[int]] = {}
        prev = None
        for si, v in enumerate(op.visits):
            if v != prev:
                run_count[v] = run_count.get(v, 0) + 1
                run_first.setdefault(v, []).append(si)
            prev = v
        holes = [b for b in np.ndindex(*op.nblocks)
                 if tuple(b) not in run_count]
        if holes:
            report.findings.append(Finding(
                "kernel-write-hole", Severity.HIGH,
                f"{name}: output '{op.role}' block {tuple(holes[0])} "
                f"(of {len(holes)} holes) is never written — it ships "
                f"uninitialized memory",
                entry_point=name,
                details={"operand": op.role,
                         "missing_block": list(holes[0]),
                         "n_holes": len(holes),
                         "nblocks": list(op.nblocks)}))
        races = {b: c for b, c in run_count.items() if c > 1}
        if races:
            b, c = next(iter(sorted(races.items())))
            firsts = [list(steps[i]) for i in run_first[b][:2]]
            report.findings.append(Finding(
                "kernel-write-race", Severity.HIGH,
                f"{name}: output '{op.role}' block {b} is written by "
                f"{c} non-contiguous grid runs (first at steps "
                f"{firsts}) — later runs clobber flushed data",
                entry_point=name,
                details={"operand": op.role, "block_index": list(b),
                         "n_runs": c, "grid_steps": firsts,
                         "n_raced_blocks": len(races)}))

    # ---- tail masking cross-check ----
    if overhang_roles:
        if not mask_idiom:
            report.findings.append(Finding(
                "kernel-unmasked-tail", Severity.HIGH,
                f"{name}: operands {overhang_roles} visit non-dividing "
                f"tail blocks but the body has no iota→compare→select "
                f"mask idiom — tail lanes read/feed garbage",
                entry_point=name,
                details={"operands": overhang_roles}))
        elif not case.tail_masked:
            report.findings.append(Finding(
                "kernel-tail-undeclared", Severity.MEDIUM,
                f"{name}: body masks its non-dividing tails but the "
                f"manifest case does not declare tail_masked=True",
                entry_point=name,
                details={"operands": overhang_roles}))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------
def analyze_kernels(cases: Optional[Sequence] = None, *,
                    check_registry: bool = True) -> AnalysisReport:
    """Run the kernel doctor over ``cases`` (default: the shipped
    manifest) and return the findings report; ``report.meta['kernels']``
    carries the per-kernel audit rows."""
    from ..ops.pallas import kernel_manifest
    from ..ops.pallas.cost_registry import registered_kernels

    t0 = time.time()
    if cases is None:
        cases = kernel_manifest()
    report = AnalysisReport(meta={
        "schema_version": KERNELS_SCHEMA_VERSION,
        "generations": _peaks(),
    })

    if check_registry:
        reg = registered_kernels()
        case_names = {c.name for c in cases}
        for n in sorted(case_names - set(reg)):
            report.findings.append(Finding(
                "kernel-unregistered", Severity.HIGH,
                f"{n}: shipped kernel has no cost-registry entry — "
                f"planner v2 prices it with the bytes-only fallback",
                entry_point=n, details={"registered": sorted(reg)}))
        for n in sorted(set(reg) - case_names):
            report.findings.append(Finding(
                "kernel-registry-stale", Severity.HIGH,
                f"{n}: cost-registry entry has no manifest kernel — "
                f"stale registration (kernel renamed or removed?)",
                entry_point=n, details={"manifest": sorted(case_names)}))

    rows: List[dict] = []
    peaks = _peaks()
    for case in cases:
        try:
            fn, args = case.build()
            jaxpr = jax.make_jaxpr(fn)(*args)
            eqns = [e for e in collect_pallas_eqns(jaxpr.jaxpr)
                    if _eqn_name(e) == case.name]
            if not eqns:
                report.findings.append(Finding(
                    "kernel-manifest-trace", Severity.HIGH,
                    f"{case.name}: manifest case traced no pallas_call "
                    f"with that name",
                    entry_point=case.name,
                    details={"found": sorted({
                        _eqn_name(e) for e in
                        collect_pallas_eqns(jaxpr.jaxpr)})}))
                continue
            audit = _audit_eqn(case, eqns[0], report)
            if audit is not None:
                rows.append(audit.to_row(peaks))
        except Exception as e:  # crashed rule → MEDIUM, house contract
            report.findings.append(Finding(
                "kernel-doctor-crash", Severity.MEDIUM,
                f"{case.name}: kernel audit crashed: "
                f"{type(e).__name__}: {e}",
                entry_point=case.name,
                details={"error": type(e).__name__}))
    report.meta["kernels"] = rows
    report.meta["n_cases"] = len(list(cases))
    report.meta["elapsed_s"] = round(time.time() - t0, 3)
    return report


# ---------------------------------------------------------------------------
# the serving-shape sweep (roadmap 1a: page_size 16/32 × real vocabs)
# ---------------------------------------------------------------------------
#: real model vocab sizes for the softmax-CE tiling lattice
SWEEP_VOCABS = (32000, 50304, 151936)
#: paged-attention sweep: page_size × table capacity (tokens)
SWEEP_PAGE_SIZES = (16, 32)
SWEEP_SEQ_LENS = (1024, 2048)


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _sweep_specs():
    """(label, kernel_name, fn, abstract args) for every sweep point —
    traced with ShapeDtypeStructs, so real-vocab shapes cost nothing."""
    from ..ops.pallas.paged_attention import (
        paged_flash_attention, paged_flash_attention_int8)
    from ..ops.pallas.softmax_ce import softmax_ce_loss
    import functools

    specs = []
    b, h, d, t = 8, 8, 128, 1
    for ps in SWEEP_PAGE_SIZES:
        for s in SWEEP_SEQ_LENS:
            mp = s // ps
            n_pages = b * mp + 1
            common = dict(page_size=ps, interpret=True)
            args_fp = (_sds((b, h, t, d), jnp.bfloat16),
                       _sds((n_pages, h, ps, d), jnp.bfloat16),
                       _sds((n_pages, h, ps, d), jnp.bfloat16),
                       _sds((b, mp), jnp.int32),
                       _sds((b,), jnp.int32))
            specs.append((
                f"paged ps={ps} S={s}", "paged_flash_attention",
                functools.partial(paged_flash_attention, **common),
                args_fp))
            args_i8 = (_sds((b, h, t, d), jnp.bfloat16),
                       _sds((n_pages, h, ps, d), jnp.int8),
                       _sds((n_pages, h, ps, d), jnp.int8),
                       _sds((n_pages, ps), jnp.float32),
                       _sds((n_pages, ps), jnp.float32),
                       _sds((b, mp), jnp.int32),
                       _sds((b,), jnp.int32))
            specs.append((
                f"paged_int8 ps={ps} S={s}",
                "paged_flash_attention_int8",
                functools.partial(paged_flash_attention_int8, **common),
                args_i8))
    rows = 4096
    for vocab in SWEEP_VOCABS:
        specs.append((
            f"softmax_ce vocab={vocab}", "softmax_ce_fwd",
            functools.partial(softmax_ce_loss, interpret=True),
            (_sds((rows, vocab), jnp.float32),
             _sds((rows,), jnp.int32))))
    return specs


def kernel_sweep() -> dict:
    """Predicted VMEM/roofline table over serving shapes.  Pure shape
    arithmetic (abstract tracing + the registered cost models) — no
    kernel execution, so 151k-vocab rows are free."""
    from ..ops.pallas.cost_registry import kernel_cost_model

    t0 = time.time()
    peaks = _peaks()
    rows: List[dict] = []
    for label, name, fn, args in _sweep_specs():
        jaxpr = jax.make_jaxpr(fn)(*args)
        eqns = [e for e in collect_pallas_eqns(jaxpr.jaxpr)
                if _eqn_name(e) == name]
        if not eqns:
            rows.append({"label": label, "kernel": name,
                         "error": "no pallas_call traced"})
            continue
        eqn = eqns[0]
        gm = eqn.params["grid_mapping"]
        grid = tuple(int(g) for g in gm.grid)
        bms = list(gm.block_mappings)
        block_io = sum(
            _block_bytes(tuple(int(s) if s is not None else 1
                               for s in bm.block_shape),
                         bm.array_shape_dtype.dtype)
            for bm in bms)
        scratch = _scratch_vmem_bytes(eqn, gm)
        vmem = 2 * block_io + scratch
        row = {
            "label": label, "kernel": name, "grid": list(grid),
            "steps": int(np.prod(grid)) if grid else 1,
            "vmem_bytes": int(vmem), "scratch_bytes": int(scratch),
        }
        for gen, p in peaks.items():
            row[f"vmem_frac_{gen}"] = round(vmem / p["vmem_bytes"], 4)
        model = kernel_cost_model(name)
        if model is not None:
            in_avals = tuple(_aval_triple(v) for v in eqn.invars)
            out_avals = tuple(_aval_triple(v) for v in eqn.outvars)
            flops, bts = model(in_avals, out_avals,
                               _light_params(eqn.params))
            row["flops"] = float(flops)
            row["bytes"] = float(bts)
            intensity = flops / bts if bts else 0.0
            row["intensity"] = round(intensity, 2)
            for gen, p in peaks.items():
                if not p["peak_hbm_bw"]:
                    continue
                ridge = p["peak_flops_bf16"] / p["peak_hbm_bw"]
                row[f"bound_{gen}"] = (
                    "compute" if intensity >= ridge else "memory")
                row[f"est_us_{gen}"] = round(1e6 * max(
                    flops / p["peak_flops_bf16"],
                    bts / p["peak_hbm_bw"]), 2)
        rows.append(row)
    return {
        "schema_version": KERNELS_SCHEMA_VERSION,
        "generations": peaks,
        "rows": rows,
        "elapsed_s": round(time.time() - t0, 3),
    }


def sweep_table(sweep: dict) -> str:
    """Render the sweep dict as the aligned text table the CLI prints."""
    cols = ("label", "grid", "vmem_bytes", "vmem_frac_v5e", "intensity",
            "bound_v5e", "est_us_v5e", "est_us_v5p")
    lines = ["  ".join(f"{c:>14s}" for c in cols)]
    for row in sweep["rows"]:
        cells = []
        for c in cols:
            v = row.get(c, "")
            if isinstance(v, list):
                v = "x".join(str(x) for x in v)
            cells.append(f"{v!s:>14s}")
        lines.append("  ".join(cells))
    return "\n".join(lines)
