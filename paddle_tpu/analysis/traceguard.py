"""TraceGuard: runtime recompile interception + arg-signature attribution.

The static ``recompile-hazard`` rule can only point at weak-typed entry
args; the expensive failure mode — a training/serving step silently
re-tracing every call because one argument's shape/dtype/static value
drifts — is a *runtime* phenomenon.  ``TraceGuard`` wraps a jitted callable
and, on every call, snapshots the jit cache-key-relevant signature of the
arguments (shape, dtype, weak_type per array leaf; ``repr`` per static
leaf).  When the underlying jit compiles a new program (observed through
``fn._cache_size()``; signature novelty is the fallback for plain
callables) the guard diffs the new signature against the *closest*
previously-seen one and records exactly which components differ — the
answer to "what made step #N recompile?".

Parity role: the reference logs cache misses in its executor scope cache;
this is the TPU-native equivalent for jit program caches, feeding the same
Finding/report machinery as the static rules.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from .findings import Finding, Severity

__all__ = ["TraceGuard", "RecompileEvent"]


@dataclasses.dataclass
class RecompileEvent:
    """One observed recompile, attributed to the differing components."""

    call_index: int                 # which call to the guard recompiled
    n_compiles: int                 # total compiles seen so far
    diffs: List[dict]               # [{component, before, after}]
    signature: Tuple                # full new signature

    def describe(self) -> str:
        if not self.diffs:
            return "recompile with no visible arg-signature change"
        parts = [f"{d['component']}: {d['before']} -> {d['after']}"
                 for d in self.diffs]
        return "; ".join(parts)


def _leaf_sig(leaf):
    data = getattr(leaf, "_data", leaf)  # paddle Tensor -> array
    shape = getattr(data, "shape", None)
    dtype = getattr(data, "dtype", None)
    if shape is not None and dtype is not None:
        weak = bool(getattr(data, "weak_type", False))
        return f"{dtype}[{','.join(str(s) for s in shape)}]" + (
            "~weak" if weak else "")
    return f"static:{repr(leaf)[:80]}"


def signature_of(args, kwargs) -> Tuple[Tuple[str, str], ...]:
    """((component label, component signature), ...) over all leaves."""
    import jax

    out = []
    for i, a in enumerate(args):
        for path, leaf in jax.tree_util.tree_flatten_with_path(a)[0]:
            out.append((f"args[{i}]" + jax.tree_util.keystr(path),
                        _leaf_sig(leaf)))
    for k in sorted(kwargs):
        for path, leaf in jax.tree_util.tree_flatten_with_path(kwargs[k])[0]:
            out.append((f"{k}" + jax.tree_util.keystr(path),
                        _leaf_sig(leaf)))
    return tuple(out)


def _diff(old: Tuple, new: Tuple) -> List[dict]:
    olds, news = dict(old), dict(new)
    diffs = []
    for comp, sig in news.items():
        prev = olds.get(comp)
        if prev is None:
            diffs.append({"component": comp, "before": "<absent>",
                          "after": sig})
        elif prev != sig:
            diffs.append({"component": comp, "before": prev, "after": sig})
    for comp, sig in olds.items():
        if comp not in news:
            diffs.append({"component": comp, "before": sig,
                          "after": "<absent>"})
    return diffs


class TraceGuard:
    """Wrap a (jitted) callable; intercept cache misses; attribute them.

    Usage::

        guard = TraceGuard(trainer._jit_step, name="trainer.step")
        ... run steps through guard(...) ...
        guard.findings()   # -> [Finding(rule="recompile-hazard", ...)]
    """

    def __init__(self, fn: Callable, name: Optional[str] = None,
                 max_compiles: int = 2):
        self._fn = fn
        self.name = name or getattr(fn, "__name__", "jit_fn")
        self.max_compiles = max_compiles
        self.events: List[RecompileEvent] = []
        self.calls = 0
        self._sigs: List[Tuple] = []
        self._compiles = 0
        self._polled = False

    # -- cache probe ----------------------------------------------------
    def _cache_size(self) -> Optional[int]:
        probe = getattr(self._fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:
            return None

    def poll(self) -> bool:
        """Cache-miss probe WITHOUT routing a call through the guard — for
        observers of a jit they do not dispatch themselves (the r12
        ``TrainerTelemetry`` wraps ``trainer.step``, which calls the jit
        internally). Returns True when the underlying jit compiled at
        least one new program since the last ``poll``/``__call__``; the
        first poll absorbs the current cache size (priming is not a miss).
        Always False for plain callables without a cache probe."""
        size = self._cache_size()
        if size is None:
            return False
        missed = self._polled and size > self._compiles
        self._polled = True
        self._compiles = max(self._compiles, size)
        return missed

    def __call__(self, *args, **kwargs):
        sig = signature_of(args, kwargs)
        before = self._cache_size()
        out = self._fn(*args, **kwargs)
        after = self._cache_size()
        if after is not None and before is not None:
            missed = after > before
            self._compiles = after
        else:  # plain callable: signature novelty mirrors the jit cache key
            missed = sig not in self._sigs
            if missed:
                self._compiles += 1
        if missed and self._sigs:
            closest = min(self._sigs, key=lambda s: len(_diff(s, sig)))
            self.events.append(RecompileEvent(
                call_index=self.calls, n_compiles=self._compiles,
                diffs=_diff(closest, sig), signature=sig))
        if sig not in self._sigs:
            self._sigs.append(sig)
        self.calls += 1
        return out

    def reset(self):
        self.events.clear()
        self._sigs.clear()
        self.calls = 0
        self._compiles = 0
        self._polled = False

    # -- reporting ------------------------------------------------------
    def findings(self) -> List[Finding]:
        """Recompile events as Findings (HIGH once the compile count passes
        ``max_compiles`` — a hot step re-tracing repeatedly)."""
        out = []
        for ev in self.events:
            sev = (Severity.HIGH if ev.n_compiles > self.max_compiles
                   else Severity.MEDIUM)
            out.append(Finding(
                rule="recompile-hazard", severity=sev,
                message=(f"{self.name} recompiled on call #{ev.call_index} "
                         f"(compile #{ev.n_compiles}): {ev.describe()}"),
                entry_point=self.name,
                details={"diffs": ev.diffs,
                         "n_compiles": ev.n_compiles}))
        return out

    def report(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "calls": self.calls,
            "compiles": self._compiles,
            "recompiles": len(self.events),
            "events": [dataclasses.asdict(e) for e in self.events],
        }
