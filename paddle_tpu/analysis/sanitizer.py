"""NaN-attributing sanitizer: eqn-by-eqn jaxpr replay with finite checks.

``FLAGS_check_nan_inf`` parity — the reference framework instruments every
op output and aborts on the first nan/inf.  The r7 sentinel is the cheap
in-graph half ("something went non-finite"); this module is the missing
*where*: replay the step's jaxpr one eqn at a time, check every
floating-point intermediate, and attribute the **first** offender to its
producing eqn with the r6 profiler scope (``name_stack``) and Python
traceback.

Execution strategy (the "jitted per-eqn or chunked" requirement): each eqn
is bound eagerly (one compiled XLA op per primitive — no tracing of the
whole program), and the per-output ``isfinite().all()`` flags stay ON
DEVICE; the host syncs them in chunks of ``check_every`` eqns, so the
replay costs one blocking transfer per chunk instead of one per eqn.  On
the first chunk containing a failure the replay stops and reports.

Control flow is replayed structurally, so attribution descends INTO the
region that actually ran:

* ``pjit``   — inner jaxpr replayed eqn-by-eqn;
* ``cond``   — the predicate is concrete, so only the taken branch runs;
* ``scan``   — iterated manually; the report carries the iteration index;
* ``while``  — iterated manually with the real predicate;
* custom_vjp/jvp & friends — the call jaxpr is replayed when its signature
  matches, else the eqn is bound whole (attribution stops at the call).

``shard_map``/collectives are bound whole (their bodies need the mesh
context to execute) and attributed at the eqn level.
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from .graph import _jcore, _name_stack_of, _source_of

__all__ = [
    "SanitizerConfig",
    "NonFiniteReport",
    "SanitizeResult",
    "sanitize",
    "sanitize_target",
]


@dataclasses.dataclass
class SanitizerConfig:
    """``check_inf=False`` restricts to NaN (inf-based masking schemes);
    ``check_every`` is the device→host sync chunk; ``recurse=False`` stays
    at the top scope (container eqns attributed whole)."""

    check_inf: bool = True
    check_every: int = 32
    recurse: bool = True
    max_while_iters: int = 100_000
    # jnp.var/where-style guards materialize a literal nan/inf that a
    # select immediately masks; the materializing eqn (literal operand) is
    # skipped — a genuinely propagating NaN is still caught at its next
    # consumer, whose operands are Vars.  strict=True checks everything.
    skip_nonfinite_literals: bool = True


@dataclasses.dataclass
class NonFiniteReport:
    """First non-finite intermediate, attributed to its producing eqn."""

    eqn_index: int                 # flattened replay order
    prim: str
    path: Tuple[str, ...]          # enclosing control-flow labels
    scope: str                     # r6 profiler name_stack (HLO metadata)
    source: str                    # file:line (function)
    out_slot: int
    shape: Tuple[int, ...]
    dtype: str
    n_nonfinite: int
    n_total: int
    n_nan: int
    iteration: Optional[int] = None   # scan/while iteration, if inside one

    @property
    def where(self) -> str:
        return " @ ".join(x for x in (self.scope, self.source) if x)

    def __str__(self):
        it = f" (iteration {self.iteration})" if self.iteration is not None \
            else ""
        loc = f" [{self.where}]" if self.where else ""
        return (f"first non-finite value produced by eqn #{self.eqn_index} "
                f"'{self.prim}'{it}: {self.n_nonfinite}/{self.n_total} "
                f"bad ({self.n_nan} NaN) in output {self.out_slot} "
                f"{self.dtype}{list(self.shape)}{loc}")

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["path"] = list(self.path)
        d["shape"] = list(self.shape)
        d["where"] = self.where
        return d


@dataclasses.dataclass
class SanitizeResult:
    first: Optional[NonFiniteReport]
    checked_eqns: int
    checked_values: int
    outputs: Any = None            # None when the replay stopped early

    @property
    def ok(self) -> bool:
        return self.first is None

    def to_dict(self) -> dict:
        return {"ok": self.ok,
                "checked_eqns": self.checked_eqns,
                "checked_values": self.checked_values,
                "first_nonfinite": (self.first.to_dict()
                                    if self.first else None)}


class _Stop(Exception):
    """Internal: first offender located — unwind the replay."""


class _State:
    def __init__(self, config: SanitizerConfig):
        self.config = config
        self.eqn_counter = 0
        self.checked_values = 0
        self.pending: List[tuple] = []   # (flag, value, meta) in exec order
        self.report: Optional[NonFiniteReport] = None

    def check(self, eqn, outs, path, iteration):
        import jax.numpy as jnp

        idx = self.eqn_counter
        self.eqn_counter += 1
        for slot, o in enumerate(outs):
            dtype = getattr(o, "dtype", None)
            if dtype is None or not jnp.issubdtype(dtype, jnp.inexact):
                continue
            self.checked_values += 1
            flag = (jnp.isfinite(o).all() if self.config.check_inf
                    else ~jnp.isnan(o).any())
            meta = (idx, eqn.primitive.name, path, _name_stack_of(eqn),
                    _source_of(eqn), slot, tuple(np.shape(o)), str(dtype),
                    iteration)
            self.pending.append((flag, o, meta))
        if len(self.pending) >= self.config.check_every:
            self.flush()

    def flush(self):
        if not self.pending:
            return
        import jax.numpy as jnp

        flags = np.asarray(jnp.stack([f for f, _, _ in self.pending]))
        pending, self.pending = self.pending, []
        for ok, (_, value, meta) in zip(flags, pending):
            if ok:
                continue
            (idx, prim, path, scope, source, slot, shape, dtype,
             iteration) = meta
            if value.dtype != bool:
                asf = np.asarray(value, np.float64)
                nan = np.isnan(asf)
                # nan-only mode: intentional infs must not inflate the
                # bad-value count the report attributes
                bad = (~np.isfinite(asf) if self.config.check_inf
                       else nan)
            else:
                bad = nan = np.zeros(1, bool)
            self.report = NonFiniteReport(
                eqn_index=idx, prim=prim, path=path, scope=scope,
                source=source, out_slot=slot, shape=shape, dtype=dtype,
                n_nonfinite=int(bad.sum()), n_total=int(np.size(value)),
                n_nan=int(nan.sum()), iteration=iteration)
            raise _Stop()


def _as_list(ans, eqn):
    return list(ans) if eqn.primitive.multiple_results else [ans]


def _bind_whole(eqn, invals):
    """Execute one eqn as a unit — with donation STRIPPED: a pjit eqn's
    ``donated_invars`` would otherwise delete the caller's live arrays
    (e.g. the training state ``sanitize_step`` promises to leave intact)."""
    subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
    donated = bind_params.get("donated_invars")
    if donated is not None and any(donated):
        bind_params = dict(bind_params,
                           donated_invars=(False,) * len(donated))
    ans = eqn.primitive.bind(*subfuns, *invals, **bind_params)
    return _as_list(ans, eqn)


def _nonfinite_literal(val) -> bool:
    try:
        import jax.numpy as jnp

        arr = np.asarray(val)
        # jnp.issubdtype, not np: bfloat16/float16 literals (the bf16
        # -inf attention-mask idiom) are ml_dtypes, invisible to
        # np.issubdtype(..., np.floating)
        if not jnp.issubdtype(arr.dtype, jnp.inexact):
            return False
        if not np.issubdtype(arr.dtype, np.complexfloating):
            arr = arr.astype(np.float64)
        return bool(np.any(~np.isfinite(arr)))
    except Exception:
        return False


def _closed_parts(sub):
    if hasattr(sub, "jaxpr"):
        return sub.jaxpr, list(sub.consts)
    return sub, []


def _replay(jaxpr, consts, args, state: _State, path, iteration=None):
    cfg = state.config
    env = {}

    def read(v):
        return v.val if isinstance(v, _jcore.Literal) else env[v]

    def write(vs, vals):
        for v, val in zip(vs, vals):
            env[v] = val

    write(jaxpr.constvars, consts)
    write(jaxpr.invars, args)

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        invals = [read(v) for v in eqn.invars]
        outs = None
        if cfg.skip_nonfinite_literals and any(
                isinstance(v, _jcore.Literal) and _nonfinite_literal(v.val)
                for v in eqn.invars):
            state.eqn_counter += 1
            write(eqn.outvars, _bind_whole(eqn, invals))
            continue
        if cfg.recurse:
            try:
                outs = _replay_structured(eqn, prim, invals, state, path,
                                          iteration)
            except _Stop:
                raise
            except Exception:
                # fall back to binding the eqn whole.  First drain the
                # partial descent's pending flags: those values really
                # were computed, so a bad one must be reported with ITS
                # meta (a flush may already have run mid-descent, so
                # rolling indices back would misattribute whatever was
                # queued after it).
                state.flush()
                outs = None
        if outs is None:
            outs = _bind_whole(eqn, invals)
            state.check(eqn, outs, path, iteration)
        write(eqn.outvars, outs)
    return [read(v) for v in jaxpr.outvars]


def _replay_structured(eqn, prim, invals, state, path, iteration):
    """Descend into the control flow that actually executes; returns None
    when the eqn should be bound whole instead."""
    import jax.numpy as jnp

    params = eqn.params
    if prim == "pjit":
        inner, iconsts = _closed_parts(params["jaxpr"])
        name = params.get("name", "")
        return _replay(inner, iconsts, invals, state,
                       path + (f"pjit:{name}",), iteration)

    if prim == "cond":
        idx = int(np.clip(int(np.asarray(invals[0])), 0,
                          len(params["branches"]) - 1))
        inner, iconsts = _closed_parts(params["branches"][idx])
        state.eqn_counter += 1     # the cond eqn itself
        return _replay(inner, iconsts, invals[1:], state,
                       path + (f"cond.branch{idx}",), iteration)

    if prim == "scan":
        nc = params.get("num_consts", 0)
        nk = params.get("num_carry", 0)
        length = int(params.get("length", 0))
        reverse = bool(params.get("reverse", False))
        inner, iconsts = _closed_parts(params["jaxpr"])
        consts_in = invals[:nc]
        carry = list(invals[nc:nc + nk])
        xs = invals[nc + nk:]
        ys_acc: List[List[Any]] = None
        state.eqn_counter += 1     # the scan eqn itself
        order = range(length - 1, -1, -1) if reverse else range(length)
        for t in order:
            sliced = [x[t] for x in xs]
            outs = _replay(inner, iconsts, consts_in + carry + sliced,
                           state, path + ("scan",), iteration=t)
            carry = list(outs[:nk])
            ys = outs[nk:]
            if ys_acc is None:
                ys_acc = [[] for _ in ys]
            for acc, y in zip(ys_acc, ys):
                acc.append(y)
        if ys_acc is None:
            ys_acc = [[] for _ in range(len(eqn.outvars) - nk)]
        stacked = []
        for j, acc in enumerate(ys_acc):
            if reverse:
                acc = acc[::-1]
            if acc:
                stacked.append(jnp.stack(acc))
            else:  # zero-length scan: shape the empty ys from the outvar
                ov = eqn.outvars[nk + j].aval
                stacked.append(jnp.zeros(ov.shape, ov.dtype))
        return carry + stacked

    if prim == "while":
        cn = params.get("cond_nconsts", 0)
        bn = params.get("body_nconsts", 0)
        cond_j, cond_c = _closed_parts(params["cond_jaxpr"])
        body_j, body_c = _closed_parts(params["body_jaxpr"])
        cond_consts = invals[:cn]
        body_consts = invals[cn:cn + bn]
        carry = list(invals[cn + bn:])
        state.eqn_counter += 1     # the while eqn itself
        it = 0
        while True:
            pred = _replay(cond_j, cond_c, cond_consts + carry, state,
                           path + ("while.cond",), iteration=it)[0]
            if not bool(np.asarray(pred)):
                break
            carry = list(_replay(body_j, body_c, body_consts + carry,
                                 state, path + ("while.body",),
                                 iteration=it))
            it += 1
            if it >= state.config.max_while_iters:
                raise RuntimeError(
                    f"sanitizer: while loop exceeded "
                    f"{state.config.max_while_iters} iterations")
        return carry

    # custom_vjp/jvp, remat, closed_call, ...: replay a sub-jaxpr whose
    # signature matches the eqn (primal path), else bind whole
    if prim != "shard_map":
        for key in ("call_jaxpr", "fun_jaxpr", "jaxpr"):
            sub = params.get(key)
            if sub is None:
                continue
            inner, iconsts = _closed_parts(sub)
            if (len(inner.invars) == len(invals)
                    and len(inner.outvars) == len(eqn.outvars)):
                state.eqn_counter += 1
                return _replay(inner, iconsts, invals, state,
                               path + (f"{prim}",), iteration)
    return None


def sanitize(fn, args: Sequence = (), kwargs: Optional[dict] = None,
             config: Optional[SanitizerConfig] = None,
             closed_jaxpr=None) -> SanitizeResult:
    """Replay ``fn(*args, **kwargs)`` eqn-by-eqn and report the first
    non-finite intermediate (or ``ok``).  ``closed_jaxpr`` skips the
    re-trace when the caller already has one for these args."""
    import jax

    config = config or SanitizerConfig()
    kwargs = kwargs or {}
    closed = (closed_jaxpr if closed_jaxpr is not None
              else jax.make_jaxpr(fn)(*args, **kwargs))
    flat_args = [a._data if hasattr(a, "_data") else a
                 for a in jax.tree_util.tree_leaves((tuple(args), kwargs))]
    state = _State(config)
    outputs = None
    try:
        outputs = _replay(closed.jaxpr, list(closed.consts), flat_args,
                          state, ())
        state.flush()
    except _Stop:
        pass
    return SanitizeResult(first=state.report,
                          checked_eqns=state.eqn_counter,
                          checked_values=state.checked_values,
                          outputs=outputs if state.report is None else None)


def sanitize_target(target, config: Optional[SanitizerConfig] = None
                    ) -> SanitizeResult:
    """Replay an :class:`AnalysisTarget` with its example args."""
    return sanitize(target.fn, target.args, target.kwargs, config=config,
                    closed_jaxpr=target.jaxpr())
