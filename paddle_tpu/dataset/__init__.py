"""paddle_tpu.dataset — legacy dataset namespace.

Parity: python/paddle/dataset/ in the reference (mnist, cifar, imdb,
imikolov, uci_housing, conll05, movielens, wmt14, wmt16 download-and-parse
modules). The modern equivalents live in paddle_tpu.vision.datasets and
paddle_tpu.text.datasets; this namespace re-exports them under the legacy
layout so `paddle.dataset.mnist`-style imports port.
"""
from __future__ import annotations

import importlib
import types

__all__ = ["mnist", "cifar", "imdb", "imikolov", "uci_housing", "conll05",
           "movielens", "wmt14", "wmt16"]

_CLASS_MAP = {
    "mnist": ("paddle_tpu.vision.datasets", "MNIST"),
    "cifar": ("paddle_tpu.vision.datasets", "Cifar10"),
    "imdb": ("paddle_tpu.text.datasets", "Imdb"),
    "imikolov": ("paddle_tpu.text.datasets", "Imikolov"),
    "uci_housing": ("paddle_tpu.text.datasets", "UCIHousing"),
    "conll05": ("paddle_tpu.text.datasets", "Conll05st"),
    "movielens": ("paddle_tpu.text.datasets", "Movielens"),
    "wmt14": ("paddle_tpu.text.datasets", "WMT14"),
    "wmt16": ("paddle_tpu.text.datasets", "WMT16"),
}


def _make_legacy_module(name, mod_path, cls_name):
    mod = types.ModuleType(f"{__name__}.{name}")

    def _dataset(**kw):
        cls = getattr(importlib.import_module(mod_path), cls_name)
        return cls(**kw)

    def train(**kw):
        """Legacy reader: yields samples of the train split."""
        ds = _dataset(mode="train", **kw)

        def reader():
            yield from iter(ds)

        return reader

    def test(**kw):
        """Legacy reader: yields samples of the test split."""
        ds = _dataset(mode="test", **kw)

        def reader():
            yield from iter(ds)

        return reader

    mod.dataset_class = lambda: getattr(importlib.import_module(mod_path), cls_name)
    mod.train = train
    mod.test = test
    return mod


def __getattr__(name):
    if name in _CLASS_MAP:
        mod = _make_legacy_module(name, *_CLASS_MAP[name])
        globals()[name] = mod
        return mod
    raise AttributeError(name)
