"""paddle_tpu.quantization — quantization-aware training and post-training
quantization.

Parity: python/paddle/fluid/contrib/slim/quantization in the reference —
dygraph QAT `ImperativeQuantAware` (imperative/qat.py:40, quantizable types /
abs_max + moving_average_abs_max quantizers :45-56, per-layer `skip_quant`
:157), the fake-quant operator family (operators/fake_quantize_op.cc:
fake_quantize_abs_max, fake_channel_wise_quantize_abs_max,
fake_quantize_moving_average_abs_max, moving_average_abs_max_scale) and
`PostTrainingQuantization` (post_training_quantization.py).

TPU-native redesign: a fake-quant op is a pure quant-dequant function with a
straight-through-estimator gradient (``jax.custom_vjp``), so the whole QAT
graph stays jit-compilable; the reference's separate CUDA kernels and
in-graph state ops become layer buffers updated functionally. INT8 inference
lowering (TensorRT/mkldnn passes) is out of scope on TPU — the deliverable of
QAT here is the quantization-robust weights plus the learned scales, exactly
what the reference's QAT phase produces before engine export.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer
from ..ops._primitive import primitive, unwrap, wrap
from ..tensor import Tensor

from .ptq import (  # noqa: E402  (serving-side PTQ, ISSUE 18)
    calibrate_activations_,
    post_training_quantize_,
    quality_delta,
    quantize_model_weights_,
    quantized_layer_names,
)

__all__ = [
    "fake_quantize_abs_max",
    "fake_channel_wise_quantize_abs_max",
    "fake_quantize_moving_average_abs_max",
    "moving_average_abs_max_scale",
    "QuantizedLinear",
    "QuantizedConv2D",
    "ImperativeQuantAware",
    "PostTrainingQuantization",
    "save_quantized_model",
    "quantize_model_weights_",
    "calibrate_activations_",
    "post_training_quantize_",
    "quantized_layer_names",
    "quality_delta",
]


# ---------------------------------------------------------------------------
# fake-quant primitives (quant->dequant with straight-through gradient)
# ---------------------------------------------------------------------------

@jax.custom_vjp
def _qdq_ste(x, scale, levels):
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * levels), -levels, levels)
    return q * s / levels


def _qdq_fwd(x, scale, levels):
    return _qdq_ste(x, scale, levels), None


def _qdq_bwd(_, g):
    # straight-through estimator: quantization is identity for the gradient
    # (reference fake_quantize_dequantize grad kernels, fake_quantize_op.cc)
    return g, None, None


_qdq_ste.defvjp(_qdq_fwd, _qdq_bwd)


def _levels(bits):
    return float((1 << (bits - 1)) - 1)


@primitive
def _fq_abs_max(x, bits):
    scale = jnp.max(jnp.abs(x))
    return _qdq_ste(x, scale, _levels(bits)), scale


def fake_quantize_abs_max(x, bit_length=8):
    """Quant-dequant by the tensor-wide abs-max scale. Returns (out, scale)."""
    return _fq_abs_max(x, int(bit_length))


@primitive
def _fq_channel(x, bits, axis):
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    out = _qdq_ste(x, scale, _levels(bits))
    return out, scale.reshape(-1)


def fake_channel_wise_quantize_abs_max(x, bit_length=8, quant_axis=0):
    """Per-output-channel abs-max quant-dequant. Returns (out, scales)."""
    return _fq_channel(x, int(bit_length), int(quant_axis))


@primitive
def _fq_fixed(x, scale, bits):
    return _qdq_ste(x, scale, _levels(bits))


def _ma_update(cur, accum, state, moving_rate):
    # bias-corrected moving-average rule (reference fake_quantize_op.h
    # FindMovingAverageAbsMaxFunctor): the first step yields the full
    # abs-max instead of a fraction of it.
    accum = jnp.zeros((), jnp.float32) if accum is None else unwrap(accum)
    state = jnp.zeros((), jnp.float32) if state is None else unwrap(state)
    new_accum = moving_rate * accum + cur
    new_state = moving_rate * state + 1.0
    return new_accum / new_state, new_accum, new_state


def fake_quantize_moving_average_abs_max(x, scale, accum=None, state=None, *,
                                         bit_length=8, moving_rate=0.9,
                                         training=True):
    """Quant-dequant with a bias-corrected moving-average abs-max scale.
    Returns ``(out, scale, accum, state)``.

    state update (reference fake_quantize_op.h moving-average rule):
        accum = rate * accum + abs_max(x)
        state = rate * state + 1
        scale = accum / state
    """
    arr = unwrap(x)
    cur = jnp.max(jnp.abs(arr if not isinstance(arr, Tensor) else arr._data))
    if training:
        new_scale, new_accum, new_state = _ma_update(cur, accum, state,
                                                     moving_rate)
    else:
        zero = jnp.zeros((), jnp.float32)
        new_scale = unwrap(scale)
        new_accum = zero if accum is None else unwrap(accum)
        new_state = zero if state is None else unwrap(state)
    out = _fq_fixed(x, new_scale, int(bit_length))
    return out, wrap(new_scale), wrap(new_accum), wrap(new_state)


def moving_average_abs_max_scale(x, accum=None, state=None, moving_rate=0.9):
    """Track the moving-average abs-max of a tensor without quantizing
    (reference moving_average_abs_max_scale op — used to record output
    scales). Returns ``(scale, accum, state)``."""
    cur = jnp.max(jnp.abs(unwrap(x)))
    new_scale, new_accum, new_state = _ma_update(cur, accum, state, moving_rate)
    return wrap(new_scale), wrap(new_accum), wrap(new_state)


# ---------------------------------------------------------------------------
# quantized layer wrappers
# ---------------------------------------------------------------------------

class _QuantWrapperBase(Layer):
    def __init__(self, layer, weight_bits, activation_bits, moving_rate,
                 weight_quantize_type, weight_quant_axis):
        super().__init__()
        self._inner = layer
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self._wtype = weight_quantize_type
        self._waxis = weight_quant_axis
        self.register_buffer("_act_scale", Tensor(jnp.zeros((), jnp.float32)))
        self.register_buffer("_act_accum", Tensor(jnp.zeros((), jnp.float32)))
        self.register_buffer("_act_state", Tensor(jnp.zeros((), jnp.float32)))
        self._calibrating = False

    def _quant_weight(self, w):
        if self._wtype == "channel_wise_abs_max":
            out, _ = fake_channel_wise_quantize_abs_max(w, self._wbits, self._waxis)
        else:
            out, _ = fake_quantize_abs_max(w, self._wbits)
        return out

    def _quant_act(self, x):
        updating = self.training or self._calibrating
        out, scale, accum, state = fake_quantize_moving_average_abs_max(
            x, self._act_scale, self._act_accum, self._act_state,
            bit_length=self._abits, moving_rate=self._rate, training=updating)
        if updating:
            self._act_scale._set_data(unwrap(scale))
            self._act_accum._set_data(unwrap(accum))
            self._act_state._set_data(unwrap(state))
        return out

    @property
    def act_scale(self):
        return float(np.asarray(self._act_scale._data))


class QuantizedLinear(_QuantWrapperBase):
    """Linear with fake-quantized weight + input activation (parity:
    imperative/quant_layers QuantizedLinear). Weight layout (in, out) →
    channel axis 1."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max"):
        super().__init__(layer, weight_bits, activation_bits, moving_rate,
                         weight_quantize_type, weight_quant_axis=1)

    def forward(self, x):
        from ..nn import functional as F

        xq = self._quant_act(x)
        wq = self._quant_weight(self._inner.weight)
        return F.linear(xq, wq, self._inner.bias)


class QuantizedConv2D(_QuantWrapperBase):
    """Conv2D with fake-quantized weight + input (weight layout (out, in/g,
    kh, kw) → channel axis 0)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max"):
        super().__init__(layer, weight_bits, activation_bits, moving_rate,
                         weight_quantize_type, weight_quant_axis=0)

    def forward(self, x):
        from ..nn import functional as F

        xq = self._quant_act(x)
        wq = self._quant_weight(self._inner.weight)
        inner = self._inner
        return F.conv2d(xq, wq, inner.bias, inner._stride, inner._padding,
                        inner._dilation, inner._groups, inner._data_format)


_WRAPPERS = {"Linear": QuantizedLinear, "Conv2D": QuantizedConv2D}


class ImperativeQuantAware:
    """Dygraph quantization-aware training driver (parity:
    imperative/qat.py:40). ``quantize(model)`` replaces every quantizable
    sublayer in place with its fake-quant wrapper; layers carrying
    ``skip_quant = True`` are left untouched (reference qat.py:157)."""

    def __init__(self, quantizable_layer_type=("Conv2D", "Linear"),
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9,
                 **unused):
        for t in quantizable_layer_type:
            if t not in _WRAPPERS:
                raise ValueError(f"unsupported quantizable layer type: {t}")
        if weight_quantize_type not in ("abs_max", "channel_wise_abs_max"):
            raise ValueError(f"unsupported weight_quantize_type: {weight_quantize_type}")
        if activation_quantize_type != "moving_average_abs_max":
            raise ValueError(
                f"unsupported activation_quantize_type: {activation_quantize_type}")
        self._types = tuple(quantizable_layer_type)
        self._wtype = weight_quantize_type
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate

    def quantize(self, model: Layer) -> Layer:
        for layer in model.sublayers(include_self=True):
            for name, sub in list(layer._sub_layers.items()):
                if type(sub).__name__ in self._types and \
                        not getattr(sub, "skip_quant", False):
                    wrapper = _WRAPPERS[type(sub).__name__](
                        sub, self._wbits, self._abits, self._rate, self._wtype)
                    layer._sub_layers[name] = wrapper
        return model

    def save_quantized_model(self, layer, path, input_spec=None, **config):
        """Export the calibrated/trained quantized model as a deployment
        artifact (reference: imperative/qat.py ImperativeQuantAware.
        save_quantized_model)."""
        save_quantized_model(layer, path, input_spec, **config)


class PostTrainingQuantization:
    """Minimal PTQ (parity: post_training_quantization.py abs_max path):
    wrap the model's quantizable layers, run calibration batches to settle
    the activation EMA scales, then freeze them for eval."""

    def __init__(self, model, data_loader, batch_nums=None,
                 quantizable_layer_type=("Conv2D", "Linear"),
                 weight_quantize_type="channel_wise_abs_max",
                 activation_bits=8, weight_bits=8):
        self._model = ImperativeQuantAware(
            quantizable_layer_type=quantizable_layer_type,
            weight_quantize_type=weight_quantize_type,
            weight_bits=weight_bits, activation_bits=activation_bits,
        ).quantize(model)
        self._loader = data_loader
        self._batch_nums = batch_nums

    def quantize(self):
        self._model.eval()
        wrappers = [l for l in self._model.sublayers()  # noqa: E741
                    if isinstance(l, _QuantWrapperBase)]
        for w in wrappers:
            w._calibrating = True
        for i, batch in enumerate(self._loader):
            if self._batch_nums is not None and i >= self._batch_nums:
                break
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            self._model(x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)))
        for w in wrappers:
            w._calibrating = False
        return self._model


def save_quantized_model(layer, path, input_spec=None, weight_precision="int8",
                         **config):
    """Activation-calibrated int8 PTQ artifact, end to end (VERDICT r4 #6).

    ``layer`` is a calibrated quantized model (from
    ``PostTrainingQuantization.quantize()`` or QAT via
    ``ImperativeQuantAware``): its forward carries quantize→dequantize ops
    whose activation scales are the calibration EMA buffers, so the traced
    StableHLO bakes the calibrated scales into the program (the reference
    analog collects ranges in trt_int8_calibrator.cc and bakes them into
    the TRT engine). Weight storage defaults to ``precision="int8"``
    (per-channel symmetric int8 + scales in the artifact, ~4x smaller);
    the Predictor / ``jit.load`` runs the artifact directly."""
    from ..jit import save as jit_save

    was_training = layer.training
    layer.eval()  # freeze the calibrated scales as constants-by-buffer
    try:
        jit_save(layer, path, input_spec=input_spec,
                 precision=weight_precision, **config)
    finally:
        if was_training:
            layer.train()
