"""Post-training int8 weight quantization for the serving plane (ISSUE 18).

Parity: PaddleSlim ``PostTrainingQuantization``
(python/paddle/fluid/contrib/slim/quantization/post_training_quantization.py)
— the offline calibrate-then-quantize flow that Paddle Inference's int8
passes consume.  The TPU-native shape: instead of rewriting a static
program, we quantize the live layer tree in place — each Linear-family
layer's f32 weight becomes an int8 array plus a per-out-channel f32
``weight_scale`` buffer, and ``F.linear`` dispatches to a scale-fused
``int8 x int8 -> int32`` ``dot_general`` when the buffer is present
(nn/functional.py ``_linear_int8``).  Buffers ride the engine's
``functional_call_with_state`` params/buffers split, so the scales flow
into the jitted serving programs like any other state.

Calibration (optional): run N prompts through the fp model first and
record each target layer's input absmax; the recorded value becomes a
static per-tensor ``act_scale`` buffer (PaddleSlim's ``abs_max``
activation strategy).  Without calibration the int8 path falls back to
dynamic per-tensor activation absmax computed in-graph.

Outlier awareness (LLM.int8, Dettmers et al. 2022 — the cheap variant):
a layer whose per-channel absmax spread is extreme (one channel's scale
``outlier_ratio`` x the median) loses too much precision under pure
absmax int8; such layers are left in fp when a ratio is given.

Bit-exact greedy parity is NOT promised; :func:`quality_delta` pins the
actual per-token logit max-abs-err and greedy divergence rate on a fixed
prompt set — the certificate the tests and bench commit.
"""
from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..autograd.tape import no_grad
from ..tensor import Tensor

__all__ = [
    "quantize_model_weights_",
    "calibrate_activations_",
    "post_training_quantize_",
    "quantized_layer_names",
    "quality_delta",
]

#: layer classes whose forward routes through ``F.linear`` and therefore
#: understands the ``weight_scale`` / ``act_scale`` buffers
_QUANTIZABLE_TYPES = ("Linear", "ColumnParallelLinear", "RowParallelLinear")


def _np_dtype_name(t) -> str:
    """numpy dtype name of a Tensor/array (Tensor.dtype is the paddle
    dtype wrapper, which numpy cannot interpret — read the array's)."""
    d = getattr(t, "_data", t)
    return str(np.dtype(d.dtype))


def _target_layers(model):
    """Yield ``(dotted_name, layer)`` for every quantizable sublayer."""
    for name, layer in model.named_sublayers(include_self=True):
        if type(layer).__name__ not in _QUANTIZABLE_TYPES:
            continue
        w = getattr(layer, "weight", None)
        if w is None or getattr(w, "ndim", 0) != 2:
            continue
        yield name or type(layer).__name__, layer


def quantized_layer_names(model) -> List[str]:
    """Names of sublayers already carrying int8 weights."""
    out = []
    for name, layer in _target_layers(model):
        if _np_dtype_name(layer.weight) == "int8":
            out.append(name)
    return out


def quantize_model_weights_(model, *, skip: Optional[Callable[[str], bool]] = None,
                            outlier_ratio: Optional[float] = None) -> List[str]:
    """Quantize every Linear-family weight in ``model`` to int8, in place.

    Per-out-channel absmax: ``scale[o] = max|W[:, o]| / 127`` (weight
    layout is paddle's ``[in, out]``), weight becomes
    ``round(W / scale).clip(-127, 127).astype(int8)`` and the scale is
    registered as a ``weight_scale`` buffer.  Idempotent — already-int8
    layers are skipped, so two engines sharing one model tree coexist.

    ``skip(name) -> True`` keeps a layer fp; ``outlier_ratio`` keeps
    outlier-heavy layers fp (see module docstring).  Returns the names
    of layers quantized by THIS call.
    """
    done: List[str] = []
    for name, layer in _target_layers(model):
        w = layer.weight
        if _np_dtype_name(w) == "int8":
            continue  # idempotent re-entry
        if skip is not None and skip(name):
            continue
        wd = w._data if isinstance(w._data, jnp.ndarray) else jnp.asarray(
            np.asarray(w._data))
        absmax = jnp.max(jnp.abs(wd), axis=0)              # [out]
        scale = jnp.maximum(absmax.astype(jnp.float32) / 127.0, 1e-8)
        if outlier_ratio is not None:
            med = float(jnp.median(scale))
            if med > 0 and float(jnp.max(scale)) / med > float(outlier_ratio):
                continue  # outlier channel dominates — keep fp
        q = jnp.clip(jnp.round(wd / scale[None, :]), -127, 127).astype(
            jnp.int8)
        w._set_data(q)
        layer.register_buffer("weight_scale", Tensor(scale))
        done.append(name)
    return done


def calibrate_activations_(model, batches: Iterable) -> Dict[str, float]:
    """Run calibration batches through the (still-fp) model and register a
    static per-tensor ``act_scale`` buffer on every quantizable layer.

    ``batches`` is an iterable of model inputs (e.g. ``[B, T]`` token-id
    arrays); each is fed to ``model(batch)`` under ``no_grad``.  The
    recorded per-layer input absmax becomes ``act_scale = absmax / 127``.
    Returns the raw absmax per dotted layer name (for inspection/tests).
    """
    targets = list(_target_layers(model))
    records: Dict[str, float] = {}
    originals = []

    def _hook(name, orig):
        def forward(x, *a, **k):
            arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
            v = float(jnp.max(jnp.abs(arr)))
            if np.isfinite(v):
                records[name] = max(records.get(name, 0.0), v)
            return orig(x, *a, **k)
        return forward

    for name, layer in targets:
        orig = layer.forward
        originals.append((layer, orig))
        layer.forward = _hook(name, orig)
    try:
        with no_grad():
            for batch in batches:
                model(batch if isinstance(batch, Tensor)
                      else Tensor(jnp.asarray(batch)))
    finally:
        for layer, orig in originals:
            layer.forward = orig
    for name, layer in targets:
        amax = records.get(name)
        if amax:
            layer.register_buffer(
                "act_scale",
                Tensor(jnp.asarray(max(amax / 127.0, 1e-8), jnp.float32)))
    return records


def post_training_quantize_(model, calibration_batches: Optional[Iterable] = None,
                            **quant_kwargs) -> List[str]:
    """PaddleSlim-shaped one-call flow: calibrate (optional) then quantize.

    Calibration MUST see the fp weights, so it runs first; the returned
    list names the layers quantized.
    """
    if calibration_batches is not None:
        calibrate_activations_(model, calibration_batches)
    return quantize_model_weights_(model, **quant_kwargs)


def quality_delta(fp_model, quant_model, prompts: Sequence,
                  eps: float = 1e-9) -> Dict[str, float]:
    """The pinned PTQ quality certificate (ISSUE 18): teacher-forced
    forward of both models over a fixed prompt set, reporting

    - ``logit_max_abs_err``: max over all (prompt, position, vocab) of
      ``|logits_fp - logits_int8|``;
    - ``greedy_divergence_rate``: fraction of positions whose argmax
      next-token differs;
    - ``positions``: number of positions compared.

    ``prompts`` is a sequence of 1-D token-id arrays/lists.
    """
    max_err = 0.0
    diverged = 0
    total = 0
    modes = [(m, m.training) for m in (fp_model, quant_model)]
    for m, _ in modes:
        m.eval()
    with no_grad():
        for ids in prompts:
            arr = np.asarray(ids, dtype=np.int32).reshape(1, -1)
            t = Tensor(jnp.asarray(arr))
            lf = np.asarray((fp_model(t))._data, dtype=np.float32)
            lq = np.asarray((quant_model(t))._data, dtype=np.float32)
            if lf.shape != lq.shape:
                raise ValueError(
                    f"logit shapes differ: {lf.shape} vs {lq.shape}")
            max_err = max(max_err, float(np.max(np.abs(lf - lq))))
            gf = np.argmax(lf, axis=-1)
            gq = np.argmax(lq, axis=-1)
            diverged += int(np.sum(gf != gq))
            total += int(gf.size)
    for m, was_training in modes:
        if was_training:
            m.train()
    return {
        "logit_max_abs_err": max_err,
        "greedy_divergence_rate": float(diverged) / max(total, 1),
        "positions": total,
    }
