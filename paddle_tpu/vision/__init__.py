"""paddle_tpu.vision — models, transforms, datasets."""
from . import models  # noqa: F401


def __getattr__(name):
    import importlib

    if name in ("transforms", "datasets", "ops", "detection"):
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)
