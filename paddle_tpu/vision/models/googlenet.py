"""GoogLeNet (Inception v1). Parity:
/root/reference/python/paddle/vision/models/googlenet.py — returns
(out, out1, out2) aux logits in train mode like the reference."""
from __future__ import annotations

from ... import nn
from ...ops import manipulation as manip

__all__ = ["GoogLeNet", "googlenet"]


class Inception(nn.Layer):
    def __init__(self, in_c, c1, c2_1, c2_3, c3_1, c3_5, c4):
        super().__init__()
        self.branch1 = nn.Sequential(nn.Conv2D(in_c, c1, 1), nn.ReLU())
        self.branch2 = nn.Sequential(
            nn.Conv2D(in_c, c2_1, 1), nn.ReLU(),
            nn.Conv2D(c2_1, c2_3, 3, padding=1), nn.ReLU())
        self.branch3 = nn.Sequential(
            nn.Conv2D(in_c, c3_1, 1), nn.ReLU(),
            nn.Conv2D(c3_1, c3_5, 5, padding=2), nn.ReLU())
        self.branch4 = nn.Sequential(
            nn.MaxPool2D(kernel_size=3, stride=1, padding=1),
            nn.Conv2D(in_c, c4, 1), nn.ReLU())

    def forward(self, x):
        return manip.concat([self.branch1(x), self.branch2(x),
                             self.branch3(x), self.branch4(x)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2, padding=1),
        )
        self.ince3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.ince3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.maxpool3 = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.ince4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.ince4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.ince4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.ince4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.ince4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.maxpool4 = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.ince5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.ince5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            # aux classifiers (train-mode extra heads, parity with reference)
            self.aux1 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), nn.Flatten(),
                nn.Linear(512 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))
            self.aux2 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), nn.Flatten(),
                nn.Linear(528 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.stem(x)
        x = self.maxpool3(self.ince3b(self.ince3a(x)))
        x = self.ince4a(x)
        a1 = x
        x = self.ince4d(self.ince4c(self.ince4b(x)))
        a2 = x
        x = self.maxpool4(self.ince4e(x))
        x = self.ince5b(self.ince5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.dropout(x)
            x = manip.flatten(x, 1)
            out = self.fc(x)
            if self.training:
                return out, self.aux1(a1), self.aux2(a2)
            return out
        return x


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
