"""ShuffleNetV2. Parity: /root/reference/python/paddle/vision/models/shufflenetv2.py."""
from __future__ import annotations

from ... import nn
from ...ops import manipulation as manip

__all__ = [
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0",
]

_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512],
    0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}


def channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = manip.reshape(x, [b, groups, c // groups, h, w])
    x = manip.transpose(x, [0, 2, 1, 3, 4])
    return manip.reshape(x, [b, c, h, w])


def _conv_bn(in_c, out_c, k, stride=1, padding=0, groups=1, act=True):
    layers = [nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                        groups=groups, bias_attr=False), nn.BatchNorm2D(out_c)]
    if act:
        layers.append(nn.ReLU())
    return nn.Sequential(*layers)


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn(in_c // 2, branch_c, 1),
                _conv_bn(branch_c, branch_c, 3, stride, 1, groups=branch_c, act=False),
                _conv_bn(branch_c, branch_c, 1),
            )
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                _conv_bn(in_c, in_c, 3, stride, 1, groups=in_c, act=False),
                _conv_bn(in_c, branch_c, 1),
            )
            self.branch2 = nn.Sequential(
                _conv_bn(in_c, branch_c, 1),
                _conv_bn(branch_c, branch_c, 3, stride, 1, groups=branch_c, act=False),
                _conv_bn(branch_c, branch_c, 1),
            )

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = manip.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = manip.concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_out = _STAGE_OUT[scale]
        stage_repeats = [4, 8, 4]
        self.conv1 = _conv_bn(3, stage_out[0], 3, stride=2, padding=1)
        self.max_pool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        blocks = []
        in_c = stage_out[0]
        for stage_i, repeats in enumerate(stage_repeats):
            out_c = stage_out[stage_i + 1]
            for i in range(repeats):
                blocks.append(InvertedResidual(in_c, out_c, stride=2 if i == 0 else 1))
                in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = _conv_bn(in_c, stage_out[-1], 1)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(stage_out[-1], num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        x = self.blocks(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = manip.flatten(x, 1)
            x = self.fc(x)
        return x


def _make(scale):
    def f(pretrained=False, **kwargs):
        return ShuffleNetV2(scale=scale, **kwargs)
    return f


shufflenet_v2_x0_25 = _make(0.25)
shufflenet_v2_x0_33 = _make(0.33)
shufflenet_v2_x0_5 = _make(0.5)
shufflenet_v2_x1_0 = _make(1.0)
shufflenet_v2_x1_5 = _make(1.5)
shufflenet_v2_x2_0 = _make(2.0)
