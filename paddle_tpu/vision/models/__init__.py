"""Vision model zoo. Parity: python/paddle/vision/models/ in the reference
(lenet, alexnet, vgg, resnet, mobilenet v1/v2, inception — added over rounds)."""
from .lenet import LeNet  # noqa: F401


def __getattr__(name):
    import importlib

    _mods = {
        "resnet18": "resnet", "resnet34": "resnet", "resnet50": "resnet",
        "resnet101": "resnet", "resnet152": "resnet", "ResNet": "resnet",
        "wide_resnet50_2": "resnet", "wide_resnet101_2": "resnet",
        "VGG": "vgg", "vgg11": "vgg", "vgg13": "vgg", "vgg16": "vgg", "vgg19": "vgg",
        "AlexNet": "alexnet", "alexnet": "alexnet",
        "MobileNetV1": "mobilenetv1", "mobilenet_v1": "mobilenetv1",
        "MobileNetV2": "mobilenetv2", "mobilenet_v2": "mobilenetv2",
        "GoogLeNet": "googlenet", "googlenet": "googlenet",
        "InceptionV3": "inceptionv3", "inception_v3": "inceptionv3",
        "SqueezeNet": "squeezenet", "squeezenet1_0": "squeezenet", "squeezenet1_1": "squeezenet",
        "DenseNet": "densenet", "densenet121": "densenet", "densenet161": "densenet",
        "densenet169": "densenet", "densenet201": "densenet", "densenet264": "densenet",
        "ResNeXt": "resnext", "resnext50_32x4d": "resnext", "resnext50_64x4d": "resnext",
        "resnext101_32x4d": "resnext", "resnext101_64x4d": "resnext",
        "resnext152_32x4d": "resnext", "resnext152_64x4d": "resnext",
        "ShuffleNetV2": "shufflenetv2", "shufflenet_v2_x0_25": "shufflenetv2",
        "shufflenet_v2_x0_33": "shufflenetv2", "shufflenet_v2_x0_5": "shufflenetv2",
        "shufflenet_v2_x1_0": "shufflenetv2", "shufflenet_v2_x1_5": "shufflenetv2",
        "shufflenet_v2_x2_0": "shufflenetv2",
    }
    if name in _mods:
        mod = importlib.import_module(f".{_mods[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(name)
