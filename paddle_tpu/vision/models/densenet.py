"""DenseNet. Parity: /root/reference/python/paddle/vision/models/densenet.py."""
from __future__ import annotations

from ... import nn
from ...ops import manipulation as manip

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFGS = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class _DenseLayer(nn.Layer):
    def __init__(self, num_input_features, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(num_input_features)
        self.relu1 = nn.ReLU()
        self.conv1 = nn.Conv2D(num_input_features, bn_size * growth_rate, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.relu2 = nn.ReLU()
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                               bias_attr=False)
        self.drop_rate = drop_rate
        if drop_rate:
            self.dropout = nn.Dropout(drop_rate)

    def forward(self, x):
        out = self.conv1(self.relu1(self.norm1(x)))
        out = self.conv2(self.relu2(self.norm2(out)))
        if self.drop_rate:
            out = self.dropout(out)
        return manip.concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, num_input_features, num_output_features):
        super().__init__()
        self.norm = nn.BatchNorm2D(num_input_features)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(num_input_features, num_output_features, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(kernel_size=2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        num_init_features, growth_rate, block_config = _CFGS[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [
            nn.Conv2D(3, num_init_features, kernel_size=7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(num_init_features),
            nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2, padding=1),
        ]
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            for j in range(num_layers):
                feats.append(_DenseLayer(num_features + j * growth_rate, growth_rate,
                                         bn_size, dropout))
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                feats.append(_Transition(num_features, num_features // 2))
                num_features //= 2
        feats.extend([nn.BatchNorm2D(num_features), nn.ReLU()])
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(num_features, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = manip.flatten(x, 1)
            x = self.classifier(x)
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(264, **kwargs)
