"""ResNeXt. Parity: /root/reference/python/paddle/vision/models/resnext.py —
expressed via the grouped-convolution Bottleneck of resnet.py (same math,
one implementation; the reference duplicates the block code)."""
from __future__ import annotations

from .resnet import BottleneckBlock, ResNet

__all__ = [
    "ResNeXt", "resnext50_32x4d", "resnext50_64x4d", "resnext101_32x4d",
    "resnext101_64x4d", "resnext152_32x4d", "resnext152_64x4d",
]


class ResNeXt(ResNet):
    def __init__(self, depth=50, cardinality=32, base_width=4, num_classes=1000,
                 with_pool=True):
        layer_cfg = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        super().__init__(BottleneckBlock, None, width=base_width, groups=cardinality,
                         num_classes=num_classes, with_pool=with_pool,
                         layers=layer_cfg[depth])


def resnext50_32x4d(pretrained=False, **kwargs):
    return ResNeXt(50, 32, 4, **kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    return ResNeXt(50, 64, 4, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    return ResNeXt(101, 32, 4, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return ResNeXt(101, 64, 4, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    return ResNeXt(152, 32, 4, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    return ResNeXt(152, 64, 4, **kwargs)
