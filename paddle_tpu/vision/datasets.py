"""paddle.vision.datasets parity — file-format loaders (no network egress).

Parity: /root/reference/python/paddle/vision/datasets/{mnist,cifar}.py. The
reference auto-downloads; this environment has no egress, so datasets load
from a user-supplied local path (same file formats: idx-ubyte for MNIST,
python-pickle batches for CIFAR) and raise a clear error otherwise.
``FakeData`` provides deterministic synthetic samples for pipelines/tests.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Callable, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx image magic {magic}"
        return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx label magic {magic}"
        return np.frombuffer(f.read(), np.uint8)


class MNIST(Dataset):
    """MNIST from local idx-ubyte files (image_path/label_path), parity with
    the reference's MNIST(mode=...) surface."""

    NAME = "mnist"

    def __init__(self, image_path: Optional[str] = None, label_path: Optional[str] = None,
                 mode: str = "train", transform: Optional[Callable] = None,
                 download: bool = True, backend: str = "cv2"):
        self.mode = mode.lower()
        self.transform = transform
        if (image_path is None) != (label_path is None):
            raise ValueError("pass BOTH image_path and label_path, or neither")
        if image_path is None:
            base = os.environ.get("PADDLE_TPU_DATA_HOME", "")
            stem = "train" if self.mode == "train" else "t10k"
            cand_i = os.path.join(base, self.NAME, f"{stem}-images-idx3-ubyte.gz")
            cand_l = os.path.join(base, self.NAME, f"{stem}-labels-idx1-ubyte.gz")
            if base and os.path.exists(cand_i) and os.path.exists(cand_l):
                image_path, label_path = cand_i, cand_l
            else:
                raise RuntimeError(
                    f"{type(self).__name__}: no network egress in this build — "
                    "pass image_path/label_path to local idx-ubyte files or set "
                    "PADDLE_TPU_DATA_HOME with both image and label files "
                    "present (use FakeData for synthetic samples)")
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)

    def __getitem__(self, idx):
        img = self.images[idx]
        lbl = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(lbl, np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class _CifarBase(Dataset):
    MODE_FLAG_MAP = {}

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = True,
                 backend: str = "cv2"):
        self.mode = mode.lower()
        self.transform = transform
        if data_file is None:
            raise RuntimeError(
                f"{type(self).__name__}: no network egress in this build — "
                "pass data_file pointing at the local CIFAR python pickle dir "
                "(use FakeData for synthetic samples)")
        self.data = []
        files = self._files(data_file)
        for fp in files:
            with open(fp, "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            imgs = batch[b"data"].reshape(-1, 3, 32, 32)
            labels = batch.get(self._label_key, batch.get(b"labels"))
            for img, lbl in zip(imgs, labels):
                self.data.append((img, int(lbl)))

    def __getitem__(self, idx):
        img, lbl = self.data[idx]
        img = np.transpose(img, (1, 2, 0))  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(lbl, np.int64)

    def __len__(self):
        return len(self.data)


class Cifar10(_CifarBase):
    _label_key = b"labels"

    def _files(self, root):
        if os.path.isfile(root):
            return [root]
        if self.mode == "train":
            return [os.path.join(root, f"data_batch_{i}") for i in range(1, 6)]
        return [os.path.join(root, "test_batch")]


class Cifar100(_CifarBase):
    _label_key = b"fine_labels"

    def _files(self, root):
        if os.path.isfile(root):
            return [root]
        return [os.path.join(root, "train" if self.mode == "train" else "test")]


class FakeData(Dataset):
    """Deterministic synthetic dataset for pipeline tests/benchmarks."""

    def __init__(self, size=100, image_shape=(3, 224, 224), num_classes=10,
                 transform: Optional[Callable] = None, seed: int = 0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        img = rng.standard_normal(self.image_shape).astype(np.float32)
        lbl = np.asarray(rng.integers(0, self.num_classes), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return self.size


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def _pil_loader(path):
    from PIL import Image

    with open(path, "rb") as f:
        return Image.open(f).convert("RGB")


class DatasetFolder(Dataset):
    """Class-per-subdirectory image dataset (parity:
    python/paddle/vision/datasets/folder.py:65 DatasetFolder): classes are
    the sorted subdirectory names of ``root``; samples are (image, class
    index)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _pil_loader
        extensions = extensions or (IMG_EXTENSIONS
                                    if is_valid_file is None else None)
        classes = sorted(d.name for d in os.scandir(root) if d.is_dir())
        if not classes:
            raise RuntimeError(f"no class folders found in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        if is_valid_file is None:
            def is_valid_file(p):  # noqa: A001
                return p.lower().endswith(tuple(extensions))
        samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir, followlinks=True)):
                for fname in sorted(files):
                    p = os.path.join(dirpath, fname)
                    if is_valid_file(p):
                        samples.append((p, self.class_to_idx[c]))
        if not samples:
            raise RuntimeError(f"found 0 files in subfolders of {root}")
        self.samples = samples

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Unlabeled image collection (parity: folder.py:222 ImageFolder):
    every image under ``root`` (recursively), samples are [image]."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _pil_loader
        extensions = extensions or (IMG_EXTENSIONS
                                    if is_valid_file is None else None)
        if is_valid_file is None:
            def is_valid_file(p):  # noqa: A001
                return p.lower().endswith(tuple(extensions))
        samples = []
        for dirpath, _, files in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(files):
                p = os.path.join(dirpath, fname)
                if is_valid_file(p):
                    samples.append(p)
        if not samples:
            raise RuntimeError(f"found 0 files in {root}")
        self.samples = samples

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Oxford 102 Flowers (parity: vision/datasets/flowers.py:43): images
    from the ``102flowers`` archive/dir, labels + train/valid/test splits
    from the ``imagelabels.mat`` / ``setid.mat`` files. No auto-download
    (zero-egress build): pass local ``data_file``/``label_file``/
    ``setid_file``."""

    # the reference deliberately SWAPS the official splits (flowers.py:37:
    # "test data is more than train data. So we exchange the train data and
    # test data") — keep that behavior for parity
    _FLAGS = {"train": "tstid", "valid": "valid", "test": "trnid"}

    def __init__(self, data_file, label_file, setid_file, mode="train",
                 transform=None, backend="cv2"):
        import tarfile

        import scipy.io as scio

        assert mode in ("train", "valid", "test")
        self.transform = transform
        self.backend = backend
        if os.path.isdir(data_file):
            self.data_path = data_file
        else:
            stem = (data_file[:-len(".tgz")] if data_file.endswith(".tgz")
                    else data_file)
            self.data_path = stem + "/"
            # crash-safe extraction: a half-finished extraction must not
            # satisfy the exists() check forever. A per-pid tmp dir plus an
            # exclusive rename also makes concurrent constructors (launcher
            # ranks) safe: whoever renames first wins, the rest discard.
            if not os.path.isdir(os.path.join(self.data_path, "jpg")):
                import shutil

                tmp = f"{stem}.extracting.{os.getpid()}"
                os.makedirs(tmp)
                try:
                    with tarfile.open(data_file) as t:
                        t.extractall(tmp)
                    dst = self.data_path.rstrip("/")
                    try:
                        os.replace(tmp, dst)
                    except OSError:
                        # another process completed first; use its copy
                        if not os.path.isdir(os.path.join(dst, "jpg")):
                            raise
                finally:
                    if os.path.isdir(tmp):
                        shutil.rmtree(tmp, ignore_errors=True)
        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[self._FLAGS[mode]][0]

    def __getitem__(self, idx):
        from PIL import Image

        index = int(self.indexes[idx])
        label = np.array([self.labels[index - 1]], np.int64)
        path = os.path.join(self.data_path, "jpg", "image_%05d.jpg" % index)
        img = Image.open(path)
        if self.backend != "pil":
            img = np.array(img)
        if self.transform is not None:
            img = self.transform(img)
        if self.backend == "pil":
            return img, label
        return np.asarray(img, np.float32), label

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs (parity:
    vision/datasets/voc2012.py:40): (image, class-index mask) read from the
    VOCtrainval tar (or an extracted VOCdevkit dir), split per
    ImageSets/Segmentation/{trainval,train,val}.txt. No auto-download."""

    _SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    _DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    _LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
    _MODE_FLAG = {"train": "trainval", "test": "train", "valid": "val"}

    def __init__(self, data_file, mode="train", transform=None):
        import tarfile

        assert mode in ("train", "valid", "test")
        self.transform = transform
        self._tar = None
        self._tar_pid = None
        self._data_file = data_file
        if os.path.isdir(data_file):
            self._root = data_file
            read = self._read_fs
        else:
            read = self._read_tar
        self._read = read
        names = read(self._SET_FILE.format(self._MODE_FLAG[mode])).decode()
        self.ids = [ln.strip() for ln in names.splitlines() if ln.strip()]

    def _read_fs(self, rel):
        with open(os.path.join(self._root, rel), "rb") as f:
            return f.read()

    def _read_tar(self, rel):
        import tarfile

        # per-pid handle: forked DataLoader workers must not share one file
        # offset (concurrent reads would interleave seeks → corrupt bytes)
        pid = os.getpid()
        if self._tar is None or self._tar_pid != pid:
            self._tar = tarfile.open(self._data_file)
            self._tar_pid = pid
        return self._tar.extractfile(rel).read()

    def __getitem__(self, idx):
        import io

        from PIL import Image

        name = self.ids[idx]
        img = Image.open(io.BytesIO(self._read(self._DATA_FILE.format(name))))
        lbl = Image.open(io.BytesIO(self._read(self._LABEL_FILE.format(name))))
        img = np.asarray(img.convert("RGB"), np.float32)
        lbl = np.asarray(lbl, np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self.ids)
