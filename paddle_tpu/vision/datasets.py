"""paddle.vision.datasets parity — file-format loaders (no network egress).

Parity: /root/reference/python/paddle/vision/datasets/{mnist,cifar}.py. The
reference auto-downloads; this environment has no egress, so datasets load
from a user-supplied local path (same file formats: idx-ubyte for MNIST,
python-pickle batches for CIFAR) and raise a clear error otherwise.
``FakeData`` provides deterministic synthetic samples for pipelines/tests.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Callable, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx image magic {magic}"
        return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx label magic {magic}"
        return np.frombuffer(f.read(), np.uint8)


class MNIST(Dataset):
    """MNIST from local idx-ubyte files (image_path/label_path), parity with
    the reference's MNIST(mode=...) surface."""

    NAME = "mnist"

    def __init__(self, image_path: Optional[str] = None, label_path: Optional[str] = None,
                 mode: str = "train", transform: Optional[Callable] = None,
                 download: bool = True, backend: str = "cv2"):
        self.mode = mode.lower()
        self.transform = transform
        if (image_path is None) != (label_path is None):
            raise ValueError("pass BOTH image_path and label_path, or neither")
        if image_path is None:
            base = os.environ.get("PADDLE_TPU_DATA_HOME", "")
            stem = "train" if self.mode == "train" else "t10k"
            cand_i = os.path.join(base, self.NAME, f"{stem}-images-idx3-ubyte.gz")
            cand_l = os.path.join(base, self.NAME, f"{stem}-labels-idx1-ubyte.gz")
            if base and os.path.exists(cand_i) and os.path.exists(cand_l):
                image_path, label_path = cand_i, cand_l
            else:
                raise RuntimeError(
                    f"{type(self).__name__}: no network egress in this build — "
                    "pass image_path/label_path to local idx-ubyte files or set "
                    "PADDLE_TPU_DATA_HOME with both image and label files "
                    "present (use FakeData for synthetic samples)")
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)

    def __getitem__(self, idx):
        img = self.images[idx]
        lbl = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(lbl, np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class _CifarBase(Dataset):
    MODE_FLAG_MAP = {}

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = True,
                 backend: str = "cv2"):
        self.mode = mode.lower()
        self.transform = transform
        if data_file is None:
            raise RuntimeError(
                f"{type(self).__name__}: no network egress in this build — "
                "pass data_file pointing at the local CIFAR python pickle dir "
                "(use FakeData for synthetic samples)")
        self.data = []
        files = self._files(data_file)
        for fp in files:
            with open(fp, "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            imgs = batch[b"data"].reshape(-1, 3, 32, 32)
            labels = batch.get(self._label_key, batch.get(b"labels"))
            for img, lbl in zip(imgs, labels):
                self.data.append((img, int(lbl)))

    def __getitem__(self, idx):
        img, lbl = self.data[idx]
        img = np.transpose(img, (1, 2, 0))  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(lbl, np.int64)

    def __len__(self):
        return len(self.data)


class Cifar10(_CifarBase):
    _label_key = b"labels"

    def _files(self, root):
        if os.path.isfile(root):
            return [root]
        if self.mode == "train":
            return [os.path.join(root, f"data_batch_{i}") for i in range(1, 6)]
        return [os.path.join(root, "test_batch")]


class Cifar100(_CifarBase):
    _label_key = b"fine_labels"

    def _files(self, root):
        if os.path.isfile(root):
            return [root]
        return [os.path.join(root, "train" if self.mode == "train" else "test")]


class FakeData(Dataset):
    """Deterministic synthetic dataset for pipeline tests/benchmarks."""

    def __init__(self, size=100, image_shape=(3, 224, 224), num_classes=10,
                 transform: Optional[Callable] = None, seed: int = 0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        img = rng.standard_normal(self.image_shape).astype(np.float32)
        lbl = np.asarray(rng.integers(0, self.num_classes), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return self.size
