"""paddle_tpu.vision.ops — detection / region operators.

Parity: python/paddle/vision/ops.py in the reference (yolo_loss:42,
yolo_box:252, deform_conv2d:423, DeformConv2D:626, read_file:819,
decode_jpeg:864, psroi_pool:911, roi_pool:1022, roi_align:1145), backed there
by CUDA kernels under paddle/fluid/operators/detection/ (yolov3_loss_op.h,
yolo_box_op.h, roi_align_op.*, roi_pool_op.*, psroi_pool_op.*,
deformable_conv_op.*).

TPU-native redesign: every op is a static-shape vectorized XLA program —
region pooling uses separable bin masks instead of per-box dynamic loops,
RoIAlign/deform-conv sample with batched bilinear gathers, and YOLO loss is a
fully-vectorized (N, B) x (S, H, W) broadcast instead of the reference's
quadruple host loop. One deliberate deviation: `roi_align` with
``sampling_ratio=-1`` uses a fixed 2x2 sampling grid per bin (the reference
derives the count from each RoI's runtime size, which is a dynamic shape XLA
cannot tile; 2 is its value for RoIs smaller than twice the output size).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..nn.layer import Layer
from ..nn import initializer as init_mod
from ..ops._primitive import primitive
from ..tensor import Tensor

__all__ = [
    "yolo_loss",
    "yolo_box",
    "deform_conv2d",
    "DeformConv2D",
    "read_file",
    "decode_jpeg",
    "psroi_pool",
    "PSRoIPool",
    "roi_pool",
    "RoIPool",
    "roi_align",
    "RoIAlign",
    "nms",
    "affine_grid",
    "temporal_shift",
    "correlation",
    "bilateral_slice",
    "prroi_pool",
]


def _pair(v):
    if isinstance(v, (int, np.integer)):
        return (int(v), int(v))
    return tuple(int(i) for i in v)


def _box_batch_ids(boxes_num, total):
    """Per-box batch index from per-image box counts (static total)."""
    n = boxes_num.shape[0]
    return jnp.repeat(jnp.arange(n, dtype=jnp.int32), boxes_num,
                      total_repeat_length=total)


def _bilinear_sample(fmap, ys, xs):
    """Sample (C, H, W) at float coords; zero outside [-1, H] per the
    reference bilinear_interpolate (roi_align_op.cu) border rule."""
    h, w = fmap.shape[-2], fmap.shape[-1]
    valid = (ys > -1.0) & (ys < h) & (xs > -1.0) & (xs < w)
    y = jnp.clip(ys, 0.0, h - 1.0)
    x = jnp.clip(xs, 0.0, w - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    ly, lx = y - y0, x - x0
    hy, hx = 1.0 - ly, 1.0 - lx
    v00 = fmap[:, y0, x0]
    v01 = fmap[:, y0, x1]
    v10 = fmap[:, y1, x0]
    v11 = fmap[:, y1, x1]
    out = hy * hx * v00 + hy * lx * v01 + ly * hx * v10 + ly * lx * v11
    return jnp.where(valid, out, 0.0)


# ---------------------------------------------------------------------------
# RoIAlign
# ---------------------------------------------------------------------------

def _roi_align_raw(x, boxes, batch_ids, output_size, spatial_scale,
                   sampling_ratio, aligned):
    ph, pw = output_size
    s = sampling_ratio if sampling_ratio > 0 else 2

    def one_box(bid, box):
        offset = 0.5 if aligned else 0.0
        x1 = box[0] * spatial_scale - offset
        y1 = box[1] * spatial_scale - offset
        x2 = box[2] * spatial_scale - offset
        y2 = box[3] * spatial_scale - offset
        roi_w = x2 - x1
        roi_h = y2 - y1
        if not aligned:
            roi_w = jnp.maximum(roi_w, 1.0)
            roi_h = jnp.maximum(roi_h, 1.0)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        # sample grid: (ph, s) x (pw, s)
        iy = (jnp.arange(s) + 0.5) / s  # fractional offsets within a bin
        gy = y1 + (jnp.arange(ph)[:, None] + iy[None, :]) * bin_h  # (ph, s)
        gx = x1 + (jnp.arange(pw)[:, None] + iy[None, :]) * bin_w  # (pw, s)
        ys = jnp.broadcast_to(gy[:, None, :, None], (ph, pw, s, s))
        xs = jnp.broadcast_to(gx[None, :, None, :], (ph, pw, s, s))
        vals = _bilinear_sample(x[bid], ys, xs)  # (C, ph, pw, s, s)
        return vals.mean(axis=(-1, -2))

    return jax.vmap(one_box)(batch_ids, boxes)  # (num_boxes, C, ph, pw)


@primitive
def _roi_align_op(x, boxes, batch_ids, output_size, spatial_scale,
                  sampling_ratio, aligned):
    return _roi_align_raw(x, boxes, batch_ids, output_size, spatial_scale,
                          sampling_ratio, aligned)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (Mask R-CNN). boxes: (num_boxes, 4) [x1,y1,x2,y2];
    boxes_num: (N,) boxes per image. Returns (num_boxes, C, ph, pw)."""
    output_size = _pair(output_size)
    bn = boxes_num._data if isinstance(boxes_num, Tensor) else jnp.asarray(boxes_num)
    total = boxes.shape[0]
    batch_ids = _box_batch_ids(bn, total)
    return _roi_align_op(x, boxes, batch_ids, output_size, float(spatial_scale),
                         int(sampling_ratio), bool(aligned))


class RoIAlign(Layer):
    """Parity: reference vision/ops.py:1255."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


# ---------------------------------------------------------------------------
# RoIPool / PSRoIPool — separable bin masks (h-mask x w-mask) keep the
# pooling static-shaped; the reference uses per-box dynamic windows.
# ---------------------------------------------------------------------------

def _bin_masks(start, size, pooled, extent):
    """(pooled, extent) membership masks for integer bins [floor(p*size/pooled
    + start), ceil((p+1)*size/pooled + start)), clamped to [0, extent)."""
    p = jnp.arange(pooled, dtype=jnp.float32)
    lo = jnp.floor(p * size / pooled + start)
    hi = jnp.ceil((p + 1.0) * size / pooled + start)
    lo = jnp.clip(lo, 0, extent)
    hi = jnp.clip(hi, 0, extent)
    pos = jnp.arange(extent, dtype=jnp.float32)
    return (pos[None, :] >= lo[:, None]) & (pos[None, :] < hi[:, None])


def _roi_pool_raw(x, boxes, batch_ids, output_size, spatial_scale):
    ph, pw = output_size
    H, W = x.shape[-2], x.shape[-1]

    def one_box(bid, box):
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
        roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)
        mh = _bin_masks(y1, roi_h, ph, H)  # (ph, H)
        mw = _bin_masks(x1, roi_w, pw, W)  # (pw, W)
        fm = x[bid]  # (C, H, W)
        neg = jnp.asarray(-3.4e38, dtype=fm.dtype)
        t = jnp.where(mh[None, :, :, None], fm[:, None, :, :], neg).max(axis=2)  # (C, ph, W)
        out = jnp.where(mw[None, None, :, :], t[:, :, None, :], neg).max(axis=3)  # (C, ph, pw)
        # empty bins -> 0 (reference: is_empty ? 0 : max)
        empty = (~mh.any(1))[:, None] | (~mw.any(1))[None, :]
        return jnp.where(empty[None], 0.0, out)

    return jax.vmap(one_box)(batch_ids, boxes)


@primitive
def _roi_pool_op(x, boxes, batch_ids, output_size, spatial_scale):
    return _roi_pool_raw(x, boxes, batch_ids, output_size, spatial_scale)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (Fast R-CNN): max-pool each RoI into a fixed (ph, pw) grid."""
    output_size = _pair(output_size)
    bn = boxes_num._data if isinstance(boxes_num, Tensor) else jnp.asarray(boxes_num)
    batch_ids = _box_batch_ids(bn, boxes.shape[0])
    return _roi_pool_op(x, boxes, batch_ids, output_size, float(spatial_scale))


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size, self._spatial_scale)


def _psroi_pool_raw(x, boxes, batch_ids, output_size, spatial_scale):
    ph, pw = output_size
    C, H, W = x.shape[-3], x.shape[-2], x.shape[-1]
    c_out = C // (ph * pw)
    # input channel for output (c, i, j) is (c*ph + i)*pw + j
    chan = (jnp.arange(c_out)[:, None, None] * ph
            + jnp.arange(ph)[None, :, None]) * pw + jnp.arange(pw)[None, None, :]

    def one_box(bid, box):
        x1 = box[0] * spatial_scale
        y1 = box[1] * spatial_scale
        x2 = box[2] * spatial_scale
        y2 = box[3] * spatial_scale
        roi_h = jnp.maximum(y2 - y1, 0.1)
        roi_w = jnp.maximum(x2 - x1, 0.1)
        mh = _bin_masks(y1, roi_h, ph, H).astype(x.dtype)  # (ph, H)
        mw = _bin_masks(x1, roi_w, pw, W).astype(x.dtype)  # (pw, W)
        fm = x[bid]  # (C, H, W)
        sums = jnp.einsum("chw,ih,jw->cij", fm, mh, mw)  # (C, ph, pw)
        counts = mh.sum(1)[:, None] * mw.sum(1)[None, :]  # (ph, pw)
        avg = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), 0.0)
        # out[c, i, j] = avg[(c*ph + i)*pw + j, i, j]
        return avg[chan,
                   jnp.arange(ph)[None, :, None],
                   jnp.arange(pw)[None, None, :]]

    return jax.vmap(one_box)(batch_ids, boxes)


@primitive
def _psroi_pool_op(x, boxes, batch_ids, output_size, spatial_scale):
    return _psroi_pool_raw(x, boxes, batch_ids, output_size, spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI average pooling (R-FCN). Input channels must be
    divisible by ph*pw; output has C/(ph*pw) channels."""
    output_size = _pair(output_size)
    ph, pw = output_size
    if x.shape[1] % (ph * pw) != 0:
        raise ValueError("the channel of input tensor x should be divisible by "
                         "the product of output_size")
    bn = boxes_num._data if isinstance(boxes_num, Tensor) else jnp.asarray(boxes_num)
    batch_ids = _box_batch_ids(bn, boxes.shape[0])
    return _psroi_pool_op(x, boxes, batch_ids, output_size, float(spatial_scale))


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size, self._spatial_scale)


# ---------------------------------------------------------------------------
# Deformable convolution (v1 when mask is None, v2 otherwise)
# ---------------------------------------------------------------------------

def _deform_conv2d_raw(x, offset, weight, bias, stride, padding, dilation,
                       deformable_groups, groups, mask):
    n, cin, H, W = x.shape
    cout, cin_g, kh, kw = weight.shape
    sh, sw = stride
    phh, pww = padding
    dh, dw = dilation
    hout, wout = offset.shape[-2], offset.shape[-1]
    K = kh * kw
    dg = deformable_groups

    # base sampling positions: p0 + pk
    oy = jnp.arange(hout) * sh - phh
    ox = jnp.arange(wout) * sw - pww
    ky = jnp.repeat(jnp.arange(kh), kw) * dh  # (K,)
    kx = jnp.tile(jnp.arange(kw), kh) * dw

    # offset layout: (n, dg*K*2, hout, wout), per kernel point (y, x) pairs
    off = offset.reshape(n, dg, K, 2, hout, wout)
    ys = (oy[None, None, None, :, None] + ky[None, None, :, None, None]
          + off[:, :, :, 0])  # (n, dg, K, hout, wout)
    xs = (ox[None, None, None, None, :] + kx[None, None, :, None, None]
          + off[:, :, :, 1])

    cpg = cin // dg  # channels per deformable group

    def sample_img(fmap, ys_i, xs_i):
        # fmap (cin, H, W) grouped into dg blocks; ys_i (dg, K, hout, wout)
        def per_group(fm_g, y_g, x_g):
            return _bilinear_sample(fm_g, y_g, x_g)  # (cpg, K, hout, wout)
        return jax.vmap(per_group)(fmap.reshape(dg, cpg, H, W), ys_i, xs_i)

    sampled = jax.vmap(sample_img)(x, ys, xs)  # (n, dg, cpg, K, hout, wout)
    if mask is not None:
        m = mask.reshape(n, dg, 1, K, hout, wout)
        sampled = sampled * m
    sampled = sampled.reshape(n, cin, K, hout, wout)

    # grouped conv as einsum over (cin/groups, K)
    cin_per_g = cin // groups
    cout_per_g = cout // groups
    sg = sampled.reshape(n, groups, cin_per_g, K, hout, wout)
    wg = weight.reshape(groups, cout_per_g, cin_g, K)
    out = jnp.einsum("ngckhw,gock->ngohw", sg, wg)
    out = out.reshape(n, cout, hout, wout)
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out


@primitive
def _deform_conv2d_op(x, offset, weight, bias, stride, padding, dilation,
                      deformable_groups, groups, mask):
    return _deform_conv2d_raw(x, offset, weight, bias, stride, padding,
                              dilation, deformable_groups, groups, mask)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1 (mask=None) / v2. offset: (N, 2*dg*kh*kw,
    Hout, Wout); mask: (N, dg*kh*kw, Hout, Wout)."""
    return _deform_conv2d_op(x, offset, weight, bias, _pair(stride),
                             _pair(padding), _pair(dilation),
                             int(deformable_groups), int(groups), mask)


class DeformConv2D(Layer):
    """Parity: reference vision/ops.py:626."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self._deformable_groups = deformable_groups
        self._groups = groups
        self._stride = _pair(stride)
        self._padding = _pair(padding)
        self._dilation = _pair(dilation)
        kh, kw = _pair(kernel_size)
        fan_in = (in_channels // groups) * kh * kw
        bound = float(np.sqrt(1.0 / fan_in))
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr,
            default_initializer=init_mod.Uniform(-bound, bound),
        )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self._stride,
                             self._padding, self._dilation,
                             self._deformable_groups, self._groups, mask)


# ---------------------------------------------------------------------------
# YOLO
# ---------------------------------------------------------------------------

def _yolo_box_raw(x, img_size, anchors, class_num, conf_thresh,
                  downsample_ratio, clip_bbox, scale_x_y, iou_aware,
                  iou_aware_factor):
    n, _, h, w = x.shape
    an_num = len(anchors) // 2
    aw = jnp.asarray(anchors[0::2], dtype=x.dtype)
    ah = jnp.asarray(anchors[1::2], dtype=x.dtype)
    bias = -0.5 * (scale_x_y - 1.0)
    in_h = downsample_ratio * h
    in_w = downsample_ratio * w

    if iou_aware:
        iou_logit = x[:, :an_num]  # (n, S, h, w)
        body = x[:, an_num:].reshape(n, an_num, 5 + class_num, h, w)
    else:
        body = x.reshape(n, an_num, 5 + class_num, h, w)

    tx, ty, tw, th = body[:, :, 0], body[:, :, 1], body[:, :, 2], body[:, :, 3]
    conf = jax.nn.sigmoid(body[:, :, 4])
    if iou_aware:
        iou = jax.nn.sigmoid(iou_logit)
        conf = conf ** (1.0 - iou_aware_factor) * iou ** iou_aware_factor

    img_h = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    img_w = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    gx = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(h, dtype=x.dtype)[None, None, :, None]

    bx = (gx + jax.nn.sigmoid(tx) * scale_x_y + bias) * img_w / w
    by = (gy + jax.nn.sigmoid(ty) * scale_x_y + bias) * img_h / h
    bw = jnp.exp(tw) * aw[None, :, None, None] * img_w / in_w
    bh = jnp.exp(th) * ah[None, :, None, None] * img_h / in_h

    x1, y1 = bx - bw / 2, by - bh / 2
    x2, y2 = bx + bw / 2, by + bh / 2
    if clip_bbox:
        x1 = jnp.maximum(x1, 0.0)
        y1 = jnp.maximum(y1, 0.0)
        x2 = jnp.minimum(x2, img_w - 1.0)
        y2 = jnp.minimum(y2, img_h - 1.0)

    keep = conf >= conf_thresh
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
    scores = (conf[..., None]
              * jax.nn.sigmoid(jnp.moveaxis(body[:, :, 5:], 2, -1))
              * keep[..., None])
    # flatten (S, h, w) -> box_num in the reference's (anchor, h, w) order
    boxes = boxes.reshape(n, an_num * h * w, 4)
    scores = scores.reshape(n, an_num * h * w, class_num)
    return boxes, scores


@primitive
def _yolo_box_op(x, img_size, anchors, class_num, conf_thresh,
                 downsample_ratio, clip_bbox, scale_x_y, iou_aware,
                 iou_aware_factor):
    return _yolo_box_raw(x, img_size, anchors, class_num, conf_thresh,
                         downsample_ratio, clip_bbox, scale_x_y, iou_aware,
                         iou_aware_factor)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head output into boxes + per-class scores
    (reference yolo_box_op.h)."""
    return _yolo_box_op(x, img_size, tuple(anchors), int(class_num),
                        float(conf_thresh), int(downsample_ratio),
                        bool(clip_bbox), float(scale_x_y), bool(iou_aware),
                        float(iou_aware_factor))


def _iou_cwh(x1, y1, w1, h1, x2, y2, w2, h2):
    """IoU of center-size boxes (broadcasting)."""
    l = jnp.maximum(x1 - w1 / 2, x2 - w2 / 2)
    r = jnp.minimum(x1 + w1 / 2, x2 + w2 / 2)
    t = jnp.maximum(y1 - h1 / 2, y2 - h2 / 2)
    b = jnp.minimum(y1 + h1 / 2, y2 + h2 / 2)
    iw = jnp.maximum(r - l, 0.0)
    ih = jnp.maximum(b - t, 0.0)
    inter = iw * ih
    union = w1 * h1 + w2 * h2 - inter
    return inter / jnp.maximum(union, 1e-10)


def _sce(logit, label):
    """SigmoidCrossEntropy as in yolov3_loss_op.h:
    max(x,0) - x*z + log(1+exp(-|x|))."""
    return jnp.maximum(logit, 0.0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))


def _yolo_loss_raw(x, gt_box, gt_label, gt_score, anchors, anchor_mask,
                   class_num, ignore_thresh, downsample_ratio,
                   use_label_smooth, scale_x_y):
    n, _, h, w = x.shape
    b = gt_box.shape[1]
    an_num = len(anchors) // 2
    mask_num = len(anchor_mask)
    input_size = downsample_ratio * h
    bias = -0.5 * (scale_x_y - 1.0)
    aw_all = jnp.asarray(anchors[0::2], dtype=x.dtype)
    ah_all = jnp.asarray(anchors[1::2], dtype=x.dtype)
    amask = jnp.asarray(anchor_mask, dtype=jnp.int32)
    aw = aw_all[amask]
    ah = ah_all[amask]

    if use_label_smooth and class_num > 1:
        smooth = min(1.0 / class_num, 1.0 / 40)
        label_pos, label_neg = 1.0 - smooth, smooth
    else:
        label_pos, label_neg = 1.0, 0.0

    body = x.reshape(n, mask_num, 5 + class_num, h, w)
    px, py = body[:, :, 0], body[:, :, 1]
    pw, phh = body[:, :, 2], body[:, :, 3]
    pobj = body[:, :, 4]
    pcls = body[:, :, 5:]  # (n, S, C, h, w)

    gx, gy = gt_box[..., 0], gt_box[..., 1]  # (n, b) normalized center
    gw, gh = gt_box[..., 2], gt_box[..., 3]
    gt_valid = (gw > 0) & (gh > 0)  # GtValid: boxes with non-positive wh skipped

    # ---- ignore pass: every prediction's best IoU vs all gts -------------
    grid_x = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    bx = (grid_x + jax.nn.sigmoid(px) * scale_x_y + bias) / w  # (n,S,h,w)
    by = (grid_y + jax.nn.sigmoid(py) * scale_x_y + bias) / h
    bw = jnp.exp(pw) * aw[None, :, None, None] / input_size
    bh = jnp.exp(phh) * ah[None, :, None, None] / input_size
    iou = _iou_cwh(
        bx[:, :, :, :, None], by[:, :, :, :, None],
        bw[:, :, :, :, None], bh[:, :, :, :, None],
        gx[:, None, None, None, :], gy[:, None, None, None, :],
        gw[:, None, None, None, :], gh[:, None, None, None, :],
    )  # (n, S, h, w, b)
    iou = jnp.where(gt_valid[:, None, None, None, :], iou, 0.0)
    best_iou = iou.max(axis=-1)
    ignored = best_iou > ignore_thresh  # (n, S, h, w)

    # ---- positive pass: each gt matches its best global anchor -----------
    an_iou = _iou_cwh(
        0.0, 0.0, gw[..., None], gh[..., None],
        0.0, 0.0, (aw_all / input_size)[None, None, :],
        (ah_all / input_size)[None, None, :],
    )  # (n, b, an_num)
    best_n = jnp.argmax(an_iou, axis=-1)  # (n, b)
    # mask index of best anchor, -1 if not in anchor_mask
    in_mask = best_n[..., None] == amask[None, None, :]  # (n, b, mask_num)
    mask_idx = jnp.where(in_mask.any(-1), jnp.argmax(in_mask, -1), -1)
    positive = gt_valid & (mask_idx >= 0)  # (n, b)

    gi = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)
    midx = jnp.clip(mask_idx, 0, mask_num - 1)
    bidx = jnp.arange(n)[:, None]

    # per-gt predicted entries at (mask_idx, gj, gi)
    sel = lambda t: t[bidx, midx, gj, gi]  # noqa: E731  (n, b)
    tx_t = gx * w - gi
    ty_t = gy * h - gj
    aw_best = aw_all[best_n]
    ah_best = ah_all[best_n]
    tw_t = jnp.log(jnp.maximum(gw * input_size / aw_best, 1e-9))
    th_t = jnp.log(jnp.maximum(gh * input_size / ah_best, 1e-9))
    score = gt_score if gt_score is not None else jnp.ones_like(gx)
    box_scale = (2.0 - gw * gh) * score
    loc = (_sce(sel(px), tx_t) + _sce(sel(py), ty_t)
           + jnp.abs(sel(pw) - tw_t) + jnp.abs(sel(phh) - th_t)) * box_scale
    loss_loc = jnp.where(positive, loc, 0.0).sum(axis=1)

    labels = jnp.clip(gt_label, 0, class_num - 1)
    cls_target = jnp.where(
        jax.nn.one_hot(labels, class_num, dtype=x.dtype) > 0, label_pos, label_neg
    )  # (n, b, C)
    pcls_sel = pcls[bidx, midx, :, gj, gi]  # (n, b, C)
    cls = _sce(pcls_sel, cls_target).sum(-1) * score
    loss_cls = jnp.where(positive, cls, 0.0).sum(axis=1)

    # ---- objectness target map ------------------------------------------
    # scatter positives: obj target = score at (mask_idx, gj, gi); later gt
    # wins on collision (reference writes sequentially)
    base = jnp.where(ignored, -1.0, 0.0).reshape(n, mask_num * h * w)
    pos_flat = midx * (h * w) + gj * w + gi  # (n, b)
    cells = jnp.arange(mask_num * h * w)
    match = positive[:, :, None] & (pos_flat[:, :, None] == cells[None, None, :])
    has_pos = match.any(axis=1)  # (n, cells)
    # last matching gt wins on collision (reference writes sequentially)
    t_star = jnp.argmax(
        jnp.where(match, jnp.arange(b)[None, :, None], -1), axis=1)
    val = jnp.take_along_axis(score, t_star.reshape(n, -1), axis=1)
    obj_t = jnp.where(has_pos, val, base).reshape(n, mask_num, h, w)

    pos_obj = obj_t > 1e-5
    neg_obj = (obj_t > -0.5) & ~pos_obj
    loss_obj = (jnp.where(pos_obj, _sce(pobj, 1.0) * obj_t, 0.0)
                + jnp.where(neg_obj, _sce(pobj, 0.0), 0.0)).sum(axis=(1, 2, 3))

    return loss_loc + loss_cls + loss_obj


@primitive
def _yolo_loss_op(x, gt_box, gt_label, gt_score, anchors, anchor_mask,
                  class_num, ignore_thresh, downsample_ratio,
                  use_label_smooth, scale_x_y):
    return _yolo_loss_raw(x, gt_box, gt_label, gt_score, anchors, anchor_mask,
                          class_num, ignore_thresh, downsample_ratio,
                          use_label_smooth, scale_x_y)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference yolov3_loss_op.h). x: (N, S*(5+C), H, W);
    gt_box: (N, B, 4) normalized [cx, cy, w, h]; gt_label: (N, B) int.
    Returns per-sample loss (N,)."""
    return _yolo_loss_op(x, gt_box, gt_label, gt_score, tuple(anchors),
                         tuple(anchor_mask), int(class_num),
                         float(ignore_thresh), int(downsample_ratio),
                         bool(use_label_smooth), float(scale_x_y))


# ---------------------------------------------------------------------------
# NMS (greedy hard-nms; catalog ops multiclass_nms/matrix_nms rely on it)
# ---------------------------------------------------------------------------

def _nms_keep_mask(boxes, scores, iou_threshold):
    m = boxes.shape[0]
    order = jnp.argsort(-scores)
    sorted_boxes = boxes[order]
    x1, y1, x2, y2 = (sorted_boxes[:, i] for i in range(4))
    area = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0.0) * jnp.maximum(iy2 - iy1, 0.0)
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-10)

    def body(i, keep):
        # suppress j>i overlapping an already-kept i
        sup = keep[i] & (iou[i] > iou_threshold) & (jnp.arange(m) > i)
        return keep & ~sup

    keep = jax.lax.fori_loop(0, m, body, jnp.ones(m, dtype=bool))
    return order, keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Returns kept box indices sorted by descending score.
    Host-synced output size (eager-only op, like the reference's dynamic-shape
    multiclass_nms, detection/multiclass_nms_op.cc)."""
    bd = boxes._data if isinstance(boxes, Tensor) else jnp.asarray(boxes)
    if scores is None:
        sd = jnp.arange(bd.shape[0], 0, -1, dtype=bd.dtype)
    else:
        sd = scores._data if isinstance(scores, Tensor) else jnp.asarray(scores)
    if category_idxs is not None:
        cd = (category_idxs._data if isinstance(category_idxs, Tensor)
              else jnp.asarray(category_idxs))
        # offset boxes per category so cross-category pairs never overlap
        # (span-sized stride handles negative coordinates)
        offs = (cd.astype(bd.dtype) * (bd.max() - bd.min() + 1.0))[:, None]
        bd = bd + offs
    order, keep = _nms_keep_mask(bd, sd, iou_threshold)
    kept = np.asarray(order)[np.asarray(keep)]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept.astype(np.int64)))


# ---------------------------------------------------------------------------
# image IO (host ops; reference read_file_op.cc / decode_jpeg_op.cu use
# nvjpeg — on TPU decode stays on host)
# ---------------------------------------------------------------------------

def read_file(filename, name=None):
    """Read a file's raw bytes as a uint8 1-D Tensor."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte Tensor to CHW uint8 (host-side PIL)."""
    import io

    from PIL import Image

    raw = bytes(np.asarray(x._data if isinstance(x, Tensor) else x, dtype=np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


# ---------------------------------------------------------------------------
# vision misc tail (VERDICT r4 #4): affine_grid, temporal_shift, correlation,
# bilateral_slice
# ---------------------------------------------------------------------------

@primitive
def _affine_grid_op(theta, hw, align_corners):
    h, w = hw
    n = theta.shape[0]

    def lin(count):
        # affine_grid_op.h Linspace: align_corners=True spans [-1, 1]
        # inclusive; False shrinks by (count-1)/count (half-pixel centers)
        start, end = -1.0, 1.0
        if align_corners:
            step = (end - start) / (count - 1)
            s = start
        else:
            step = (end - start) / count
            s = start * (count - 1) / count
        return s + jnp.arange(count, dtype=theta.dtype) * step

    xs = lin(w)  # [W]
    ys = lin(h)  # [H]
    ones = jnp.ones((h, w), theta.dtype)
    base = jnp.stack([jnp.broadcast_to(xs[None, :], (h, w)),
                      jnp.broadcast_to(ys[:, None], (h, w)), ones],
                     axis=-1)  # [H, W, 3]
    # output = base @ theta^T per batch
    return jnp.einsum("hwk,nck->nhwc", base, theta)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2D affine sampling grid (reference: operators/affine_grid_op.h
    AffineGridOpKernel; python nn.functional.affine_grid). theta [N, 2, 3],
    out_shape (N, C, H, W) → grid [N, H, W, 2] of (x, y) in [-1, 1],
    differentiable w.r.t. theta."""
    shp = [int(s) for s in (out_shape.tolist() if hasattr(out_shape, "tolist")
                            else out_shape)]
    h, w = shp[2], shp[3]
    return _affine_grid_op(theta, (h, w), bool(align_corners))


@primitive
def _temporal_shift_op(x, seg_num, shift_ratio, data_format):
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    nt, c, h, w = x.shape
    n = nt // seg_num
    xr = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    # slide channels [0,c1) back one step (zero-pad at t=0), [c1,c2) forward
    # one step (zero at t=T-1), remainder identity (temporal_shift_op.h)
    back = jnp.pad(xr[:, :-1, :c1], ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
    fwd = jnp.pad(xr[:, 1:, c1:c2], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
    out = jnp.concatenate([back, fwd, xr[:, :, c2:]], axis=2)
    out = out.reshape(nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM temporal shift (reference: operators/temporal_shift_op.h;
    python nn.functional.temporal_shift): x [N*T, C, H, W] viewed as T-frame
    segments; the first c*ratio channels look one frame back, the next
    c*ratio one frame ahead."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"temporal_shift: bad data_format {data_format}")
    return _temporal_shift_op(x, int(seg_num), float(shift_ratio), data_format)


@primitive
def _correlation_op(x1, x2, pad_size, kernel_size, max_displacement,
                    stride1, stride2):
    n, c, h, w = x1.shape
    krad = (kernel_size - 1) // 2
    drad = max_displacement // stride2
    border = krad + max_displacement
    ph, pw = h + 2 * pad_size, w + 2 * pad_size
    out_h = -(-(ph - 2 * border) // stride1)  # ceil div
    out_w = -(-(pw - 2 * border) // stride1)

    pad = ((0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size))
    a = jnp.pad(x1, pad)
    b = jnp.pad(x2, pad)
    nelems = float(kernel_size * kernel_size * c)

    # zero-filled static shifts (no wrap-around): extra margin covers the
    # largest combined displacement+kernel tap, so every slice is in-bounds
    # and out-of-map taps read zeros
    marg = drad * stride2 + krad
    bm = jnp.pad(b, ((0, 0), (0, 0), (marg, marg), (marg, marg)))

    def shifted_b(dy, dx):
        return lax.dynamic_slice(
            bm, (0, 0, marg + dy, marg + dx), b.shape)

    outs = []
    for tj in range(-drad, drad + 1):
        for ti in range(-drad, drad + 1):
            # x2 displaced by (tj, ti)*stride2 relative to x1
            prod = (a * shifted_b(tj * stride2, ti * stride2)).sum(axis=1)
            # kernel window sum around each center (zero-filled taps)
            pm = jnp.pad(prod, ((0, 0), (krad, krad), (krad, krad)))
            ksum = jnp.zeros_like(prod)
            for j in range(-krad, krad + 1):
                for i in range(-krad, krad + 1):
                    ksum = ksum + lax.dynamic_slice(
                        pm, (0, krad + j, krad + i), prod.shape)
            # centers: h1 = hout*stride1 + max_displacement
            hh = max_displacement + stride1 * jnp.arange(out_h)
            ww = max_displacement + stride1 * jnp.arange(out_w)
            outs.append(ksum[:, hh][:, :, ww] / nelems)
    return jnp.stack(outs, axis=1)  # [N, D*D, out_h, out_w]


def correlation(x, y, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply=1, name=None):
    """FlowNet correlation volume (reference: operators/correlation_op.cu
    correlation_forward): output [N, D*D, Hout, Wout] with
    D = 2*(max_displacement//stride2)+1; each channel d=(tj,ti) is the
    channel-mean dot product of a kernel_size^2 window of x with the window
    of y displaced by (tj, ti)*stride2. Valid centers start at
    max_displacement in the padded map (border_radius = kernel_rad +
    max_displacement); displaced/kernel taps beyond the padded map read
    zeros (explicit zero-filled shifts — the reference CUDA kernel reads
    out of bounds there for kernel_size > 2*pad_size+1 configs)."""
    if int(kernel_size) % 2 != 1:
        raise ValueError("correlation: kernel_size must be odd")
    return _correlation_op(x, y, int(pad_size), int(kernel_size),
                           int(max_displacement), int(stride1), int(stride2))


@primitive
def _bilateral_slice_op(x, guide, grid, has_offset):
    n, ci, h, w = x.shape
    gn, gc, gd, gh, gw = grid.shape
    coeff_stride = ci + 1 if has_offset else ci
    co = gc // coeff_stride

    # sample positions (bilateral_slice_op.cu forward): half-pixel centers
    # scaled to grid resolution; z from the guide map
    gx = (jnp.arange(w, dtype=x.dtype) + 0.5) * gw / w          # [W]
    gy = (jnp.arange(h, dtype=x.dtype) + 0.5) * gh / h          # [H]
    gz = guide * gd                                             # [N, H, W]

    fx = jnp.floor(gx - 0.5)
    fy = jnp.floor(gy - 0.5)
    fz = jnp.floor(gz - 0.5)

    def tent(d):
        return jnp.maximum(1.0 - jnp.abs(d), 0.0)

    # accumulate the 8 trilinear corners; corner indices clamp to the grid.
    # Per-pixel flat gather into the [gd*gh*gw] cell axis — never
    # materializes a depth-expanded [N, gc, gd, H, W] intermediate
    grid_flat = grid.reshape(n, gc, gd * gh * gw)
    coeff = jnp.zeros((n, gc, h, w), x.dtype)
    for dx in range(2):
        xx = fx + dx
        x_ = jnp.clip(xx, 0, gw - 1).astype(jnp.int32)          # [W]
        wx = tent(xx + 0.5 - gx)                                # [W]
        for dy in range(2):
            yy = fy + dy
            y_ = jnp.clip(yy, 0, gh - 1).astype(jnp.int32)      # [H]
            wy = tent(yy + 0.5 - gy)                            # [H]
            for dz in range(2):
                zz = fz + dz                                    # [N, H, W]
                z_ = jnp.clip(zz, 0, gd - 1).astype(jnp.int32)
                wz = tent(zz + 0.5 - gz)                        # [N, H, W]
                lin = (z_ * gh + y_[None, :, None]) * gw + x_[None, None, :]
                samp = jnp.take_along_axis(
                    grid_flat, lin.reshape(n, 1, h * w), axis=2
                ).reshape(n, gc, h, w)
                coeff = coeff + samp * (wx[None, None, None, :]
                                        * wy[None, None, :, None]
                                        * wz[:, None, :, :])
    coeff = coeff.reshape(n, co, coeff_stride, h, w)
    out = (coeff[:, :, :ci] * x[:, None]).sum(axis=2)
    if has_offset:
        out = out + coeff[:, :, ci]
    return out


def bilateral_slice(x, guide, grid, has_offset=False, name=None):
    """HDRNet bilateral-grid slicing (reference:
    operators/bilateral_slice_op.cu BilateralSliceCudaForwardKernel;
    python fluid.contrib.layers.bilateral_slice): per output pixel,
    trilinearly sample an affine color transform from the bilateral grid at
    (x/w*gw, y/h*gh, guide*gd) and apply it to the input channels.
    x [N, Ci, H, W], guide [N, H, W], grid [N, gc, gd, gh, gw] →
    [N, Co, H, W] with Co = gc/(Ci+1) if has_offset else gc/Ci."""
    return _bilateral_slice_op(x, guide, grid, bool(has_offset))


def _tent_integral(lo, hi, centers):
    """∫_{lo}^{hi} max(0, 1-|t-c|) dt per center c, in closed form: the
    tent CDF g(u) = 0 (u<=-1), (u+1)^2/2 (-1..0), 1-(1-u)^2/2 (0..1),
    1 (u>=1), evaluated as g(hi-c) - g(lo-c)."""
    def g(u):
        u = jnp.clip(u, -1.0, 1.0)
        return jnp.where(u <= 0.0, 0.5 * (u + 1.0) ** 2,
                         1.0 - 0.5 * (1.0 - u) ** 2)

    return g(hi[..., None] - centers) - g(lo[..., None] - centers)


@primitive
def _prroi_pool_op(x, boxes, batch_ids, output_size, spatial_scale):
    ph_, pw_ = output_size
    n, c, h, w = x.shape

    def one(roi, bid):
        sw = roi[0] * spatial_scale
        sh = roi[1] * spatial_scale
        ew = roi[2] * spatial_scale
        eh = roi[3] * spatial_scale
        rw = jnp.maximum(ew - sw, 0.0)
        rh = jnp.maximum(eh - sh, 0.0)
        bh = rh / ph_
        bw = rw / pw_
        win = jnp.maximum(bh * bw, 0.0)
        y0 = sh + jnp.arange(ph_) * bh          # [ph]
        y1 = y0 + bh
        x0 = sw + jnp.arange(pw_) * bw          # [pw]
        x1 = x0 + bw
        # separable exact integral of the bilinear interpolant: per-bin
        # weight of grid column i is the tent integral over [x0, x1]
        wy = _tent_integral(y0, y1, jnp.arange(h, dtype=x.dtype))  # [ph, H]
        wx = _tent_integral(x0, x1, jnp.arange(w, dtype=x.dtype))  # [pw, W]
        img = x[bid]                                               # [C, H, W]
        acc = jnp.einsum("chw,ph,qw->cpq", img, wy, wx)
        return jnp.where(win > 0, acc / jnp.maximum(win, 1e-30), 0.0)

    return jax.vmap(one)(boxes, batch_ids)


def prroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Precise RoI pooling (prroi_pool_op.h PrRoIPoolingMatCalculation —
    the PreciseRoIPooling paper's exact integral of the bilinearly
    interpolated feature map over each bin, divided by the bin area; fully
    differentiable, no sampling-point quantization).

    TPU-native redesign: the reference decomposes the integral per unit
    cell; here the bilinear basis separates into per-axis tent functions,
    so each bin's value is an exact [C,H,W] x [ph,H] x [pw,W] contraction
    of closed-form tent integrals — one einsum per RoI on the MXU.
    Coordinates outside the map integrate zeros (reference
    PrRoIPoolingGetData)."""
    output_size = _pair(output_size)
    bn = boxes_num._data if isinstance(boxes_num, Tensor) else jnp.asarray(boxes_num)
    total = boxes.shape[0]
    batch_ids = _box_batch_ids(bn, total)
    return _prroi_pool_op(x, boxes, batch_ids, output_size,
                          float(spatial_scale))
