"""paddle.vision.transforms parity — host-side numpy preprocessing.

Parity: /root/reference/python/paddle/vision/transforms/transforms.py +
functional.py. TPU-native stance: transforms run on HOST numpy inside the
DataLoader workers (the device should only see final batched arrays — no
per-sample device traffic), mirroring the reference's CPU-side pipeline.

Array convention: HWC uint8/float numpy in, unless noted; ``ToTensor``
produces CHW float32 scaled to [0, 1].
"""
from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

__all__ = [
    "Compose", "BaseTransform", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
    "Normalize", "Transpose", "Pad", "RandomRotation", "Grayscale",
    "BrightnessTransform", "ContrastTransform", "SaturationTransform",
    "HueTransform", "ColorJitter",
    # functional
    "to_tensor", "resize", "center_crop", "crop", "hflip", "vflip",
    "normalize", "pad", "rotate", "to_grayscale", "adjust_brightness",
    "adjust_contrast", "adjust_hue",
]


# ---------------------------------------------------------------- functional
def _as_float(img):
    return img.astype(np.float32)


def resize(img, size, interpolation="bilinear"):
    """size: int (short side) or (h, w)."""
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return img
    # bilinear resize via jax-free numpy (host path): index-based sampling
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    if interpolation == "nearest":
        out = img[np.round(ys).astype(int)][:, np.round(xs).astype(int)]
        return out
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    if img.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    f = _as_float(img)
    top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
    bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(img.dtype) if np.issubdtype(img.dtype, np.integer) else out


def crop(img, top, left, height, width):
    return img[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(img, top, left, th, tw)


def hflip(img):
    return img[:, ::-1]


def vflip(img):
    return img[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4  # left, top, right, bottom
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    pad_width = [(t, b), (l, r)] + [(0, 0)] * (img.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(img, pad_width, mode=mode, **kw)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    img = _as_float(img)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def to_tensor(img, data_format="CHW"):
    """HWC uint8 [0,255] → CHW float32 [0,1] numpy array."""
    f = _as_float(img)
    if np.issubdtype(img.dtype, np.integer):
        f = f / 255.0
    if img.ndim == 2:
        f = f[:, :, None]
    if data_format == "CHW":
        f = np.transpose(f, (2, 0, 1))
    return f


def to_grayscale(img, num_output_channels=1):
    f = _as_float(img)
    gray = f[..., 0] * 0.299 + f[..., 1] * 0.587 + f[..., 2] * 0.114
    out = np.stack([gray] * num_output_channels, axis=-1)
    return out.astype(img.dtype) if np.issubdtype(img.dtype, np.integer) else out


def adjust_brightness(img, factor):
    f = _as_float(img) * factor
    if np.issubdtype(img.dtype, np.integer):
        return np.clip(f, 0, 255).astype(img.dtype)
    return f


def adjust_contrast(img, factor):
    f = _as_float(img)
    mean = f.mean()
    out = (f - mean) * factor + mean
    if np.issubdtype(img.dtype, np.integer):
        return np.clip(out, 0, 255).astype(img.dtype)
    return out


# ------------------------------------------------------------------ classes
class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = pad(img, self.padding, self.fill, self.padding_mode)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (max(tw - w, 0), max(th - h, 0)), self.fill, self.padding_mode)
            h, w = img.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return crop(img, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * random.uniform(*self.scale)
            aspect = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                return resize(crop(img, top, left, ch, cw), self.size, self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        m, s = self.mean, self.std
        c = img.shape[0] if self.data_format == "CHW" else img.shape[-1]
        if len(m) != c:
            m = [m[0]] * c
            s = [s[0]] * c
        return normalize(img, m, s, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2 and max(self.order) > 1:
            img = img[:, :, None]
        return np.transpose(img, self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


def rotate(img, angle, fill=0):
    """Rotate by ``angle`` degrees about the center (nearest-neighbor
    resampling on host numpy; out-of-bounds pixels take ``fill``)."""
    h, w = img.shape[:2]
    theta = np.deg2rad(angle)
    cos, sin = np.cos(theta), np.sin(theta)
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    ys, xs = np.mgrid[0:h, 0:w]
    # inverse-map output pixels to source coordinates
    sx = cos * (xs - cx) + sin * (ys - cy) + cx
    sy = -sin * (xs - cx) + cos * (ys - cy) + cy
    sxi = np.round(sx).astype(int)
    syi = np.round(sy).astype(int)
    inside = (sxi >= 0) & (sxi < w) & (syi >= 0) & (syi < h)
    out = np.full_like(img, fill)
    out[inside] = img[syi[inside], sxi[inside]]
    return out


class RandomRotation(BaseTransform):
    def __init__(self, degrees, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_brightness(img, random.uniform(max(0, 1 - self.value), 1 + self.value))


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_contrast(img, random.uniform(max(0, 1 - self.value), 1 + self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        gray = to_grayscale(img, img.shape[-1] if img.ndim == 3 else 1)
        alpha = random.uniform(max(0, 1 - self.value), 1 + self.value)
        out = _as_float(img) * alpha + _as_float(gray) * (1 - alpha)
        if np.issubdtype(img.dtype, np.integer):
            return np.clip(out, 0, 255).astype(img.dtype)
        return out


def adjust_hue(img, factor):
    """Shift hue by ``factor`` (in [-0.5, 0.5] turns) via RGB→HSV→RGB."""
    was_int = np.issubdtype(img.dtype, np.integer)
    f = _as_float(img) / (255.0 if was_int else 1.0)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    maxc = np.max(f, axis=-1)
    minc = np.min(f, axis=-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    h = np.where(maxc == r, (g - b) / dz % 6,
                 np.where(maxc == g, (b - r) / dz + 2, (r - g) / dz + 4)) / 6.0
    h = np.where(delta == 0, 0.0, h)
    h = (h + factor) % 1.0
    # HSV → RGB
    i = np.floor(h * 6).astype(int)
    frac = h * 6 - i
    p = v * (1 - s)
    q = v * (1 - s * frac)
    t = v * (1 - s * (1 - frac))
    i = i % 6
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1)
    if was_int:
        return np.clip(out * 255.0, 0, 255).astype(img.dtype)
    return out.astype(np.float32)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        ts = list(self.transforms)
        random.shuffle(ts)
        for t in ts:
            img = t(img)
        return img
