"""paddle_tpu.vision.detection — the detection op family.

Parity: paddle/fluid/operators/detection/ (34 op files) — prior_box_op.h,
density_prior_box_op.h, anchor_generator_op.h, box_coder_op.h,
iou_similarity_op.h, box_clip_op.h, bipartite_match_op.cc,
multiclass_nms_op.cc (NMSFast/MultiClassNMS/MultiClassOutput),
matrix_nms_op.cc (NMSMatrix decay), generate_proposals_op.cc /
generate_proposals_v2_op.cc (+ bbox_util.h BoxCoder/FilterBoxes),
distribute_fpn_proposals_op.h, collect_fpn_proposals_op.h,
sigmoid_focal_loss_op.cc, target_assign_op.h, polygon_box_transform_op.cc,
box_decoder_and_assign_op.h, mine_hard_examples_op.cc.

TPU-native redesign: every op is a static-shape XLA program. Ops whose
reference output is dynamically sized (NMS families, proposals, FPN
distribute) follow the framework's LoD redesign — fixed-capacity padded
arrays plus a valid-count (``rois_num``); padding rows carry label -1 and
zero boxes. Greedy/sequential reference loops (NMS, bipartite match) become
``lax.fori_loop`` programs over precomputed pairwise matrices so they jit.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..ops._primitive import primitive
from ..tensor import Tensor

__all__ = [
    "prior_box",
    "density_prior_box",
    "anchor_generator",
    "box_coder",
    "iou_similarity",
    "box_clip",
    "bipartite_match",
    "target_assign",
    "sigmoid_focal_loss",
    "multiclass_nms",
    "multiclass_nms2",
    "multiclass_nms3",
    "matrix_nms",
    "generate_proposals",
    "generate_proposals_v2",
    "retinanet_detection_output",
    "rpn_target_assign",
    "distribute_fpn_proposals",
    "collect_fpn_proposals",
    "polygon_box_transform",
    "box_decoder_and_assign",
    "mine_hard_examples",
    "locality_aware_nms",
    "generate_proposal_labels",
    "roi_perspective_transform",
    "generate_mask_labels",
    "deformable_psroi_pooling",
    "retinanet_target_assign",
]

_BBOX_CLIP = math.log(1000.0 / 16.0)  # bbox_util.h kBBoxClipDefault


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _expand_aspect_ratios(aspect_ratios, flip):
    """prior_box_op.h ExpandAspectRatios: dedup, prepend 1, optionally add
    reciprocals."""
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


# ---------------------------------------------------------------------------
# prior / anchor generators
# ---------------------------------------------------------------------------

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),  # noqa: A002
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (prior_box_op.h PriorBoxOpKernel). Returns
    (boxes [H, W, P, 4] in normalized x1y1x2y2, variances [H, W, P, 4])."""
    x = _arr(input)
    img = _arr(image)
    fh, fw = int(x.shape[2]), int(x.shape[3])
    ih, iw = float(img.shape[2]), float(img.shape[3])
    step_w = float(steps[0]) or iw / fw
    step_h = float(steps[1]) or ih / fh
    ars = _expand_aspect_ratios(list(aspect_ratios), flip)
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = [float(s) for s in np.atleast_1d(max_sizes)] if max_sizes else []

    # per-cell (half-)extents for each prior, in input pixels
    ws, hs = [], []
    for si, mn in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            ws.append(mn / 2.0), hs.append(mn / 2.0)
            if max_sizes:
                mx = math.sqrt(mn * max_sizes[si])
                ws.append(mx / 2.0), hs.append(mx / 2.0)
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                ws.append(mn * math.sqrt(ar) / 2.0)
                hs.append(mn / math.sqrt(ar) / 2.0)
        else:
            for ar in ars:
                ws.append(mn * math.sqrt(ar) / 2.0)
                hs.append(mn / math.sqrt(ar) / 2.0)
            if max_sizes:
                mx = math.sqrt(mn * max_sizes[si])
                ws.append(mx / 2.0), hs.append(mx / 2.0)
    half_w = jnp.asarray(ws, jnp.float32)  # [P]
    half_h = jnp.asarray(hs, jnp.float32)

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w  # [W]
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h  # [H]
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, half_w.shape[0]))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, half_w.shape[0]))
    boxes = jnp.stack([
        (cxg - half_w) / iw, (cyg - half_h) / ih,
        (cxg + half_w) / iw, (cyg + half_h) / ih,
    ], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    return Tensor(boxes), Tensor(var)


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,  # noqa: A002
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    """Density prior boxes (density_prior_box_op.h): per cell, each
    (density, fixed_size) pair tiles density x density shifted centers with
    every fixed_ratio."""
    x = _arr(input)
    img = _arr(image)
    fh, fw = int(x.shape[2]), int(x.shape[3])
    ih, iw = float(img.shape[2]), float(img.shape[3])
    step_w = float(steps[0]) or iw / fw
    step_h = float(steps[1]) or ih / fh

    # enumerate per-cell prior offsets/extents (host loop — static config)
    offs_x, offs_y, half_w, half_h = [], [], [], []
    for size, density in zip(fixed_sizes, densities):
        density = int(density)
        shift = step_w / density
        for ar in fixed_ratios:
            bw = float(size) * math.sqrt(ar) / 2.0
            bh = float(size) / math.sqrt(ar) / 2.0
            for di in range(density):
                for dj in range(density):
                    offs_x.append(-step_w / 2.0 + shift / 2.0 + dj * shift)
                    offs_y.append(-step_h / 2.0 + shift / 2.0 + di * shift)
                    half_w.append(bw)
                    half_h.append(bh)
    ox = jnp.asarray(offs_x, jnp.float32)
    oy = jnp.asarray(offs_y, jnp.float32)
    hw = jnp.asarray(half_w, jnp.float32)
    hh = jnp.asarray(half_h, jnp.float32)
    p = ox.shape[0]
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    cxg = cx[None, :, None] + ox[None, None, :]
    cyg = cy[:, None, None] + oy[None, None, :]
    cxg = jnp.broadcast_to(cxg, (fh, fw, p))
    cyg = jnp.broadcast_to(cyg, (fh, fw, p))
    boxes = jnp.stack([
        (cxg - hw) / iw, (cyg - hh) / ih,
        (cxg + hw) / iw, (cyg + hh) / ih,
    ], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    if flatten_to_2d:
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return Tensor(boxes), Tensor(var)


def anchor_generator(input, anchor_sizes, aspect_ratios, variances, stride,  # noqa: A002
                     offset=0.5, name=None):
    """RPN anchors (anchor_generator_op.h): for each cell, one anchor per
    (aspect_ratio, anchor_size); corners use the pixel (-1) convention."""
    x = _arr(input)
    fh, fw = int(x.shape[2]), int(x.shape[3])
    sw, sh = float(stride[0]), float(stride[1])
    widths, heights = [], []
    for ar in aspect_ratios:
        for size in anchor_sizes:
            area = sw * sh
            base_w = round(math.sqrt(area / ar))
            base_h = round(base_w * ar)
            widths.append(size / sw * base_w)
            heights.append(size / sh * base_h)
    aw = jnp.asarray(widths, jnp.float32)
    ah = jnp.asarray(heights, jnp.float32)
    xc = jnp.arange(fw, dtype=jnp.float32) * sw + offset * (sw - 1)
    yc = jnp.arange(fh, dtype=jnp.float32) * sh + offset * (sh - 1)
    xg = jnp.broadcast_to(xc[None, :, None], (fh, fw, aw.shape[0]))
    yg = jnp.broadcast_to(yc[:, None, None], (fh, fw, aw.shape[0]))
    anchors = jnp.stack([
        xg - 0.5 * (aw - 1), yg - 0.5 * (ah - 1),
        xg + 0.5 * (aw - 1), yg + 0.5 * (ah - 1),
    ], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), anchors.shape)
    return Tensor(anchors), Tensor(var)


# ---------------------------------------------------------------------------
# box geometry
# ---------------------------------------------------------------------------

def _box_wh(box, normalized):
    off = 0.0 if normalized else 1.0
    w = box[..., 2] - box[..., 0] + off
    h = box[..., 3] - box[..., 1] + off
    return w, h


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, variance=None, name=None):
    """Encode/decode center-size box deltas (box_coder_op.h)."""
    pb = _arr(prior_box).astype(jnp.float32)
    tb = _arr(target_box).astype(jnp.float32)
    pbv = None if prior_box_var is None else _arr(prior_box_var).astype(jnp.float32)
    var_attr = (jnp.asarray(variance, jnp.float32)
                if variance else None)

    pw, ph = _box_wh(pb, box_normalized)
    pcx = pb[..., 0] + pw / 2
    pcy = pb[..., 1] + ph / 2

    @primitive
    def _encode(tb, pb_stats):
        pcx, pcy, pw, ph = pb_stats  # each [M]
        tw, th = _box_wh(tb, box_normalized)  # [N]
        tcx = (tb[..., 2] + tb[..., 0]) / 2
        tcy = (tb[..., 3] + tb[..., 1]) / 2
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        dh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)  # [N, M, 4]
        if pbv is not None:
            out = out / pbv[None, :, :]
        elif var_attr is not None:
            out = out / var_attr
        return out

    @primitive
    def _decode(tb, pb_stats):
        pcx, pcy, pw, ph = pb_stats
        # broadcast prior stats along the non-prior axis
        if axis == 0:
            sh = (1, -1)
        else:
            sh = (-1, 1)
        pcx, pcy = pcx.reshape(sh), pcy.reshape(sh)
        pw, ph = pw.reshape(sh), ph.reshape(sh)
        if pbv is not None:
            v = pbv.reshape(sh + (4,))
        elif var_attr is not None:
            v = var_attr.reshape((1, 1, 4))
        else:
            v = jnp.ones((1, 1, 4), jnp.float32)
        cx = v[..., 0] * tb[..., 0] * pw + pcx
        cy = v[..., 1] * tb[..., 1] * ph + pcy
        w = jnp.exp(v[..., 2] * tb[..., 2]) * pw
        h = jnp.exp(v[..., 3] * tb[..., 3]) * ph
        off = 0.0 if box_normalized else 1.0
        return jnp.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], axis=-1)

    if code_type == "encode_center_size":
        return _encode(tb, (pcx, pcy, pw, ph))
    if code_type == "decode_center_size":
        return _decode(tb, (pcx, pcy, pw, ph))
    raise ValueError(f"unknown code_type {code_type!r}")


def _pairwise_iou(a, b, normalized, eps=1e-10):
    """IoU matrix [N, M] (iou_similarity_op.h IOUSimilarity)."""
    off = 0.0 if normalized else 1.0
    area_a = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    area_b = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(ix2 - ix1 + off, 0.0)
    ih = jnp.maximum(iy2 - iy1 + off, 0.0)
    inter = iw * ih
    return inter / (area_a[:, None] + area_b[None, :] - inter + eps)


def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU [N, M] (iou_similarity_op)."""

    @primitive
    def _iou(x, y):
        return _pairwise_iou(x.astype(jnp.float32), y.astype(jnp.float32),
                             box_normalized)

    return _iou(_arr(x), _arr(y))


def box_clip(input, im_info, name=None):  # noqa: A002
    """Clip boxes into [0, im - 1] (box_clip_op.h: im_info rows are
    (height, width, scale); boxes clipped to the scaled image extent)."""

    @primitive
    def _clip(boxes, im_info):
        im = im_info.astype(jnp.float32)
        h = im[..., 0] / im[..., 2] - 1.0
        w = im[..., 1] / im[..., 2] - 1.0
        if boxes.ndim == 3:  # [N, M, 4]
            w = w[:, None]
            h = h[:, None]
        x1 = jnp.clip(boxes[..., 0], 0.0, w)
        y1 = jnp.clip(boxes[..., 1], 0.0, h)
        x2 = jnp.clip(boxes[..., 2], 0.0, w)
        y2 = jnp.clip(boxes[..., 3], 0.0, h)
        return jnp.stack([x1, y1, x2, y2], axis=-1).astype(boxes.dtype)

    return _clip(_arr(input), _arr(im_info))


# ---------------------------------------------------------------------------
# matching / assignment
# ---------------------------------------------------------------------------

def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=None,
                    name=None):
    """Greedy maximum bipartite matching (bipartite_match_op.cc): repeatedly
    take the globally largest remaining distance, match that (row, col) pair
    and retire both. ``per_prediction`` then argmax-fills unmatched columns
    whose best row distance >= dist_threshold. Returns
    (col_to_row_match_indices [1, M] int32, col_to_row_match_dist [1, M])."""

    @primitive(nondiff=True)
    def _match(dist):
        dist = dist.astype(jnp.float32)
        r, c = dist.shape
        eps = 1e-6

        def body(_, carry):
            match, mdist, row_free = carry
            masked = jnp.where(row_free[:, None] & (match < 0)[None, :]
                               & (dist > eps), dist, -1.0)
            flat = jnp.argmax(masked)
            i, j = flat // c, flat % c
            ok = masked[i, j] > 0
            match = jnp.where(ok, match.at[j].set(i.astype(jnp.int32)), match)
            mdist = jnp.where(ok, mdist.at[j].set(dist[i, j]), mdist)
            row_free = jnp.where(ok, row_free.at[i].set(False), row_free)
            return match, mdist, row_free

        match = jnp.full((c,), -1, jnp.int32)
        mdist = jnp.zeros((c,), jnp.float32)
        row_free = jnp.ones((r,), bool)
        match, mdist, _ = lax.fori_loop(0, min(r, c), body,
                                        (match, mdist, row_free))
        if match_type == "per_prediction":
            thr = float(dist_threshold if dist_threshold is not None else 0.5)
            best = jnp.max(dist, axis=0)
            argbest = jnp.argmax(dist, axis=0).astype(jnp.int32)
            fill = (match < 0) & (best >= thr) & (best > eps)
            match = jnp.where(fill, argbest, match)
            mdist = jnp.where(fill, best, mdist)
        return match[None, :], mdist[None, :]

    return _match(_arr(dist_matrix))


def target_assign(input, matched_indices, negative_indices=None,  # noqa: A002
                  negative_lengths=None, mismatch_value=0, name=None):
    """Gather targets by match indices; unmatched (-1) slots get
    mismatch_value with weight 0 (target_assign_op.h). ``negative_indices``
    (flat per-image prior ids + ``negative_lengths`` counts, the LoD
    redesign) marks hard-negative slots: they keep mismatch_value but get
    weight 1 (NegTargetAssignFunctor). input: [M, K] rows indexed by
    matched row id, matched_indices: [N, P]."""
    neg_rows = neg_cols = None
    if negative_indices is not None:
        ni = np.asarray(_arr(negative_indices)).astype(np.int64).reshape(-1)
        if negative_lengths is None:
            nl = np.asarray([ni.shape[0]], np.int64)
        else:
            nl = np.asarray(_arr(negative_lengths)).astype(np.int64).reshape(-1)
        neg_rows = np.repeat(np.arange(len(nl)), nl)
        neg_cols = ni

    @primitive
    def _assign(x, idx):
        safe = jnp.maximum(idx, 0)
        out = jnp.take(x, safe, axis=0)  # [N, P, K]
        miss = (idx < 0)[..., None]
        out = jnp.where(miss, jnp.asarray(mismatch_value, x.dtype), out)
        w = jnp.where(miss[..., 0], 0.0, 1.0)
        if neg_rows is not None:
            w = w.at[jnp.asarray(neg_rows), jnp.asarray(neg_cols)].set(1.0)
        return out, w

    return _assign(_arr(input), _arr(matched_indices))


def sigmoid_focal_loss(x, label, normalizer=None, alpha=0.25, gamma=2.0,
                       name=None):
    """Focal loss on per-class logits (sigmoid_focal_loss_op.cc): label is
    the 1-based foreground class id (0 = background); class c's target is
    1 when label == c + 1."""

    @primitive
    def _loss(x, label, fg_num):
        xf = x.astype(jnp.float32)
        c = x.shape[1]
        tgt = (label.astype(jnp.int32)
               == jnp.arange(1, c + 1, dtype=jnp.int32)[None, :])
        tgt = tgt.astype(jnp.float32)
        p = jax.nn.sigmoid(xf)
        ce = (tgt * jax.nn.softplus(-xf) + (1 - tgt) * jax.nn.softplus(xf))
        w = tgt * alpha * (1 - p) ** gamma + (1 - tgt) * (1 - alpha) * p ** gamma
        loss = w * ce
        if fg_num is not None:
            loss = loss / jnp.maximum(fg_num.astype(jnp.float32), 1.0)
        return loss

    fg = None if normalizer is None else _arr(normalizer)
    return _loss(_arr(x), _arr(label), fg)


# ---------------------------------------------------------------------------
# NMS family — fixed-capacity padded outputs + rois_num
# ---------------------------------------------------------------------------

def _greedy_nms_mask(boxes, scores, valid, nms_threshold, nms_eta, normalized):
    """Sequential NMSFast (multiclass_nms_op.cc:140) as a fori_loop over the
    score-sorted candidate list: keep candidate i iff its IoU with every
    already-kept candidate <= the (eta-adaptive) threshold. Returns
    (order, keep-mask-over-order)."""
    m = boxes.shape[0]
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
    sb = boxes[order]
    sv = valid[order]
    iou = _pairwise_iou(sb, sb, normalized)
    idx = jnp.arange(m)

    def body(i, carry):
        keep, thr = carry
        sup = jnp.any(keep & (iou[i] > thr) & (idx < i))
        ki = sv[i] & ~sup
        keep = keep.at[i].set(ki)
        thr = jnp.where(ki & (nms_eta < 1.0) & (thr > 0.5), thr * nms_eta, thr)
        return keep, thr

    keep, _ = lax.fori_loop(
        0, m, body, (jnp.zeros((m,), bool), jnp.float32(nms_threshold)))
    return order, keep


def _keep_topk_output(keep_cm, scores_cm, gather_boxes, keep_top_k,
                      background_label):
    """Shared multiclass-output tail (multiclass_nms_op.cc
    MultiClassOutput): keep_top_k over all classes, rows ordered ascending
    class then score-descending, padding rows label -1 / zeros.

    keep_cm/scores_cm [C, M]; ``gather_boxes(flat_idx)`` returns the [K, 4]
    candidate boxes for flat indices cls*M + box (class-shared callers
    gather from their [M, 4] array via idx % M without materializing a
    [C*M, 4] copy). Returns (out [K, 6], box_id [K] (index % M, -1
    padding), valid count)."""
    c, m = keep_cm.shape
    if 0 <= background_label < c:
        keep_cm = keep_cm.at[background_label].set(False)
    flat_scores = jnp.where(keep_cm, scores_cm, -jnp.inf).reshape(-1)
    k = keep_top_k if keep_top_k > -1 else c * m
    k = min(k, c * m)
    top_scores, top_idx = lax.top_k(flat_scores, k)
    sel_valid = top_scores > -jnp.inf
    cls_id = (top_idx // m).astype(jnp.float32)
    box_id = top_idx % m
    sel_boxes = gather_boxes(top_idx)
    # reference row order: ascending class label, score-descending within a
    # class (MultiClassOutput iterates the class-indexed map)
    order2 = jnp.lexsort((-top_scores, jnp.where(sel_valid, cls_id, jnp.inf)))
    top_scores = top_scores[order2]
    sel_valid = sel_valid[order2]
    cls_id = cls_id[order2]
    box_id = box_id[order2]
    sel_boxes = sel_boxes[order2]
    out = jnp.concatenate([
        jnp.where(sel_valid, cls_id, -1.0)[:, None],
        jnp.where(sel_valid, top_scores, 0.0)[:, None],
        jnp.where(sel_valid[:, None], sel_boxes, 0.0),
    ], axis=1)
    index = jnp.where(sel_valid, box_id, -1)
    return out, index, jnp.sum(sel_valid.astype(jnp.int32))


def _multiclass_nms_single(bboxes, scores, score_threshold, nms_top_k,
                           keep_top_k, nms_threshold, normalized, nms_eta,
                           background_label):
    """One image: bboxes [M, 4], scores [C, M] → (out [K, 6], index [K],
    count). Padding rows: label -1, zeros."""
    c, m = scores.shape
    top = min(nms_top_k, m) if nms_top_k > -1 else m

    def per_class(cls_scores):
        valid = cls_scores > score_threshold
        if top < m:
            kth = -jnp.sort(-jnp.where(valid, cls_scores, -jnp.inf))[top - 1]
            valid = valid & (cls_scores >= kth)
        order, keep = _greedy_nms_mask(bboxes, cls_scores, valid,
                                       nms_threshold, nms_eta, normalized)
        mask = jnp.zeros((m,), bool).at[order].set(keep)
        return mask

    keep_cm = jax.vmap(per_class)(scores)  # [C, M]
    return _keep_topk_output(
        keep_cm, scores, lambda idx: jnp.take(bboxes, idx % m, axis=0),
        keep_top_k, background_label)


def multiclass_nms3(bboxes, scores, rois_num=None, score_threshold=0.05,
                    nms_top_k=400, keep_top_k=200, nms_threshold=0.3,
                    normalized=True, nms_eta=1.0, background_label=0,
                    return_index=False, name=None):
    """Batched multiclass NMS (multiclass_nms_op.cc MultiClassNMS3).
    bboxes [N, M, 4], scores [N, C, M]. Returns (out [N*K, 6],
    index [N*K] into the flattened [N*M] boxes, nms_rois_num [N])."""
    bb = _arr(bboxes).astype(jnp.float32)
    sc = _arr(scores).astype(jnp.float32)

    @primitive(nondiff=True)
    def _nms(bb, sc):
        n, m = bb.shape[0], bb.shape[1]

        def one(b, s):
            return _multiclass_nms_single(
                b, s, score_threshold, nms_top_k, keep_top_k, nms_threshold,
                normalized, nms_eta, background_label)

        out, index, cnt = jax.vmap(one)(bb, sc)  # [N,K,6], [N,K], [N]
        base = (jnp.arange(n, dtype=index.dtype) * m)[:, None]
        index = jnp.where(index >= 0, index + base, -1)
        k = out.shape[1]
        return out.reshape(n * k, 6), index.reshape(n * k), cnt

    out, index, cnt = _nms(bb, sc)
    if return_index:
        return out, index, cnt
    return out, cnt


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=200, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """v1: padded detections + per-image counts (≙ LoD output)."""
    out, cnt = multiclass_nms3(
        bboxes, scores, score_threshold=score_threshold, nms_top_k=nms_top_k,
        keep_top_k=keep_top_k, nms_threshold=nms_threshold,
        normalized=normalized, nms_eta=nms_eta,
        background_label=background_label)
    return out, cnt


def multiclass_nms2(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                    keep_top_k=200, nms_threshold=0.3, normalized=True,
                    nms_eta=1.0, background_label=0, return_index=True,
                    name=None):
    """v2: adds the kept-box index output."""
    return multiclass_nms3(
        bboxes, scores, score_threshold=score_threshold, nms_top_k=nms_top_k,
        keep_top_k=keep_top_k, nms_threshold=nms_threshold,
        normalized=normalized, nms_eta=nms_eta,
        background_label=background_label, return_index=return_index)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix (soft) NMS (matrix_nms_op.cc NMSMatrix): per class, sort by
    score, decay each score by min_j f(iou_ij, max_iou_j); keep decayed
    scores > post_threshold, then global top keep_top_k."""
    bb = _arr(bboxes).astype(jnp.float32)
    sc = _arr(scores).astype(jnp.float32)

    @primitive(nondiff=True)
    def _mnms(bb, sc):
        n, m = bb.shape[0], bb.shape[1]
        c = sc.shape[1]
        pre = min(nms_top_k, m) if nms_top_k > -1 else m

        def per_class(boxes, cls_scores):
            valid = cls_scores > score_threshold
            s_sorted, order = lax.top_k(jnp.where(valid, cls_scores, -jnp.inf),
                                        pre)
            sv = s_sorted > -jnp.inf
            sb = jnp.take(boxes, order, axis=0)
            iou = _pairwise_iou(sb, sb, normalized)
            idx = jnp.arange(pre)
            lower = (idx[:, None] > idx[None, :]) & sv[None, :] & sv[:, None]
            iou_l = jnp.where(lower, iou, 0.0)
            iou_max = jnp.max(iou_l, axis=1)  # max_{j<i} iou[i, j]
            if use_gaussian:
                decay = jnp.exp((iou_max[None, :] ** 2 - iou_l ** 2)
                                * gaussian_sigma)
            else:
                decay = (1.0 - iou_l) / (1.0 - iou_max[None, :] + 1e-10)
            decay = jnp.where(lower, decay, 1.0)
            min_decay = jnp.min(decay, axis=1)
            ds = jnp.where(sv, min_decay * s_sorted, -jnp.inf)
            ds = jnp.where(ds > post_threshold, ds, -jnp.inf)
            return ds, order

        def one(b, s):
            ds, order = jax.vmap(lambda cs: per_class(b, cs))(s)  # [C, pre]
            if 0 <= background_label < c:
                ds = ds.at[background_label].set(-jnp.inf)
            k = min(keep_top_k if keep_top_k > -1 else c * pre, c * pre)
            top_s, top_i = lax.top_k(ds.reshape(-1), k)
            ok = top_s > -jnp.inf
            cls_id = (top_i // pre).astype(jnp.float32)
            box_id = jnp.take(order.reshape(-1), top_i)
            sel = jnp.take(b, box_id, axis=0)
            # reference row order: class-ascending, score-desc within class
            o2 = jnp.lexsort((-top_s, jnp.where(ok, cls_id, jnp.inf)))
            top_s, ok, cls_id = top_s[o2], ok[o2], cls_id[o2]
            box_id, sel = box_id[o2], sel[o2]
            out = jnp.concatenate([
                jnp.where(ok, cls_id, -1.0)[:, None],
                jnp.where(ok, top_s, 0.0)[:, None],
                jnp.where(ok[:, None], sel, 0.0),
            ], axis=1)
            return out, jnp.where(ok, box_id, -1), jnp.sum(ok.astype(jnp.int32))

        out, index, cnt = jax.vmap(one)(bb, sc)
        base = (jnp.arange(n, dtype=index.dtype) * m)[:, None]
        index = jnp.where(index >= 0, index + base, -1)
        k = out.shape[1]
        return out.reshape(n * k, 6), index.reshape(n * k), cnt

    out, index, cnt = _mnms(bb, sc)
    res = [out]
    if return_index:
        res.append(index)
    if return_rois_num:
        res.append(cnt)
    return tuple(res) if len(res) > 1 else res[0]


# ---------------------------------------------------------------------------
# RPN proposals
# ---------------------------------------------------------------------------

def _decode_anchor_deltas(anchors, deltas, variances, pixel_offset):
    """bbox_util.h BoxCoder: anchors+deltas → corner proposals."""
    off = 1.0 if pixel_offset else 0.0
    aw = anchors[:, 2] - anchors[:, 0] + off
    ah = anchors[:, 3] - anchors[:, 1] + off
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    if variances is not None:
        dx, dy, dw, dh = (variances[:, i] * deltas[:, i] for i in range(4))
    else:
        dx, dy, dw, dh = (deltas[:, i] for i in range(4))
    cx = dx * aw + acx
    cy = dy * ah + acy
    w = jnp.exp(jnp.minimum(dw, _BBOX_CLIP)) * aw
    h = jnp.exp(jnp.minimum(dh, _BBOX_CLIP)) * ah
    return jnp.stack([cx - w / 2, cy - h / 2,
                      cx + w / 2 - off, cy + h / 2 - off], axis=-1)


def generate_proposals_v2(scores, bbox_deltas, img_size, anchors, variances,
                          pre_nms_top_n=6000, post_nms_top_n=1000,
                          nms_thresh=0.5, min_size=0.1, eta=1.0,
                          pixel_offset=True, return_rois_num=True, name=None):
    """RPN proposal generation (generate_proposals_v2_op.cc ProposalForOneImage):
    top pre_nms scores → decode deltas on anchors → clip to image → drop
    boxes smaller than min_size → NMS → top post_nms. scores [N, A, H, W],
    bbox_deltas [N, 4A, H, W], img_size [N, 2] (h, w), anchors [H, W, A, 4].
    Returns (rois [N*post, 4] padded, roi_scores [N*post], rois_num [N])."""
    sc = _arr(scores).astype(jnp.float32)
    bd = _arr(bbox_deltas).astype(jnp.float32)
    ims = _arr(img_size).astype(jnp.float32)
    an = _arr(anchors).astype(jnp.float32).reshape(-1, 4)
    va = _arr(variances).astype(jnp.float32).reshape(-1, 4)

    @primitive(nondiff=True)
    def _gen(sc, bd, ims):
        n, a, h, w = sc.shape
        total = h * w * a
        pre = min(pre_nms_top_n, total)
        post = min(post_nms_top_n, pre)
        # layout: NCHW → (H, W, A) flatten, matching the anchor grid order
        sc_f = jnp.transpose(sc, (0, 2, 3, 1)).reshape(n, total)
        bd_f = jnp.transpose(bd.reshape(n, a, 4, h, w),
                             (0, 3, 4, 1, 2)).reshape(n, total, 4)

        def one(s, d, im):
            top_s, top_i = lax.top_k(s, pre)
            d_sel = jnp.take(d, top_i, axis=0)
            a_sel = jnp.take(an, top_i, axis=0)
            v_sel = jnp.take(va, top_i, axis=0)
            props = _decode_anchor_deltas(a_sel, d_sel, v_sel, pixel_offset)
            # clip to image (bbox_util.h ClipTiledBoxes)
            off = 1.0 if pixel_offset else 0.0
            hi = jnp.stack([im[1] - off, im[0] - off,
                            im[1] - off, im[0] - off])
            props = jnp.clip(props, 0.0, hi)
            # FilterBoxes: both sides >= min_size; centers inside the image
            ms = max(float(min_size), 1.0)
            ws = props[:, 2] - props[:, 0] + off
            hs = props[:, 3] - props[:, 1] + off
            keep = (ws >= ms) & (hs >= ms)
            if pixel_offset:
                cx = props[:, 0] + ws / 2
                cy = props[:, 1] + hs / 2
                keep = keep & (cx <= im[1]) & (cy <= im[0])
            order, kmask = _greedy_nms_mask(props, top_s, keep, nms_thresh,
                                            eta, True)
            # top post_nms in score order = first `post` kept rows of `order`
            rank = jnp.cumsum(kmask.astype(jnp.int32)) - 1
            slot = jnp.where(kmask, rank, post)
            rois = jnp.zeros((post + 1, 4), jnp.float32)
            rscore = jnp.zeros((post + 1,), jnp.float32)
            rois = rois.at[slot].set(jnp.take(props, order, axis=0))[:post]
            rscore = rscore.at[slot].set(jnp.take(top_s, order))[:post]
            cnt = jnp.minimum(jnp.sum(kmask.astype(jnp.int32)), post)
            return rois, rscore, cnt

        rois, rscores, cnt = jax.vmap(one)(sc_f, bd_f, ims)
        return rois.reshape(n * post, 4), rscores.reshape(n * post), cnt

    rois, rscores, cnt = _gen(sc, bd, ims)
    if return_rois_num:
        return rois, rscores, cnt
    return rois, rscores


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, return_rois_num=True, name=None):
    """v1 (generate_proposals_op.cc): im_info rows (h, w, scale); otherwise
    the v2 pipeline with pixel_offset=True."""
    im = _arr(im_info).astype(jnp.float32)
    return generate_proposals_v2(
        scores, bbox_deltas, im[:, :2], anchors, variances,
        pre_nms_top_n=pre_nms_top_n, post_nms_top_n=post_nms_top_n,
        nms_thresh=nms_thresh, min_size=min_size, eta=eta, pixel_offset=True,
        return_rois_num=return_rois_num, name=name)


# ---------------------------------------------------------------------------
# FPN routing
# ---------------------------------------------------------------------------

def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=True, rois_num=None,
                             name=None):
    """Route RoIs to FPN levels by scale (distribute_fpn_proposals_op.h):
    level = floor(log2(sqrt(area)/refer_scale + 1e-6)) + refer_level,
    clamped. ``fpn_rois`` must be PACKED valid rows (no padding — slice a
    padded generate_proposals output by its counts first); ``rois_num``
    gives the per-image counts of that packed layout. Returns
    (multi_rois: per-level [R, 4] padded arrays, restore_ind [R, 1],
    per-level counts [L]) — with ``rois_num``, counts is replaced by
    rois_num_per_level [L, N] (the reference's MultiLevelRoIsNum)."""
    rois = _arr(fpn_rois).astype(jnp.float32)
    img_of = None
    if rois_num is not None:
        rn = np.asarray(_arr(rois_num)).astype(np.int64).reshape(-1)
        if int(rn.sum()) != int(rois.shape[0]):
            raise ValueError(
                f"rois_num sums to {int(rn.sum())} but fpn_rois has "
                f"{int(rois.shape[0])} rows — pass packed valid rows "
                "(slice padded proposals by their counts)")
        img_of = np.repeat(np.arange(len(rn)), rn)

    @primitive(nondiff=True)
    def _route(rois):
        r = rois.shape[0]
        off = 1.0 if pixel_offset else 0.0
        ws = rois[:, 2] - rois[:, 0] + off
        hs = rois[:, 3] - rois[:, 1] + off
        scale = jnp.sqrt(jnp.maximum(ws * hs, 0.0))
        lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        num_level = max_level - min_level + 1
        # stable sort by level keeps in-level input order (reference order)
        order = jnp.argsort(lvl, stable=True)
        sorted_rois = jnp.take(rois, order, axis=0)
        sorted_lvl = jnp.take(lvl, order)
        counts = jnp.sum(lvl[None, :] == (jnp.arange(num_level)[:, None]
                                          + min_level), axis=1)
        starts = jnp.concatenate([jnp.zeros((1,), counts.dtype),
                                  jnp.cumsum(counts)[:-1]])
        # per-level padded arrays: level rows land at [0, count)
        outs = []
        for li in range(num_level):
            in_lvl = sorted_lvl == (li + min_level)
            pos = jnp.cumsum(in_lvl.astype(jnp.int32)) - 1
            slot = jnp.where(in_lvl, pos, r)
            buf = jnp.zeros((r + 1, 4), jnp.float32)
            outs.append(buf.at[slot].set(sorted_rois)[:r])
        restore = jnp.zeros((r,), jnp.int32).at[order].set(
            jnp.arange(r, dtype=jnp.int32))
        if img_of is not None:
            # per-level per-image counts (MultiLevelRoIsNum)
            n_img = int(img_of.max()) + 1 if img_of.size else 0
            in_lvl = lvl[None, :] == (jnp.arange(num_level)[:, None]
                                      + min_level)  # [L, R]
            in_img = (jnp.asarray(img_of)[None, :]
                      == jnp.arange(n_img)[:, None])  # [N, R]
            per = jnp.einsum("lr,nr->ln", in_lvl.astype(jnp.int32),
                             in_img.astype(jnp.int32))
            return tuple(outs) + (restore[:, None], per)
        return tuple(outs) + (restore[:, None], counts)

    res = _route(rois)
    multi_rois, restore_ind, counts = list(res[:-2]), res[-2], res[-1]
    return multi_rois, restore_ind, counts


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None, name=None):
    """Merge per-level proposals by global score top-k
    (collect_fpn_proposals_op.h). Inputs are the per-level padded arrays +
    counts; returns (rois [post, 4], counts kept)."""
    rois = jnp.concatenate([_arr(r) for r in multi_rois], axis=0)
    scores = jnp.concatenate(
        [_arr(s).reshape(-1) for s in multi_scores], axis=0)
    if rois_num_per_level is not None:
        # accept [L] totals or the [L, N] per-image counts that
        # distribute_fpn_proposals emits — a level's valid-row count is the
        # sum over images either way
        counts = _arr(rois_num_per_level)
        if counts.ndim > 1:
            counts = counts.sum(axis=tuple(range(1, counts.ndim)))
        counts = counts.reshape(-1)
        if int(counts.shape[0]) != len(multi_rois):
            raise ValueError(
                f"rois_num_per_level has {int(counts.shape[0])} levels but "
                f"{len(multi_rois)} level arrays were passed")
        sizes = [int(_arr(r).shape[0]) for r in multi_rois]
        valids = []
        for li, sz in enumerate(sizes):
            valids.append(jnp.arange(sz) < counts[li])
        valid = jnp.concatenate(valids)
        scores = jnp.where(valid, scores, -jnp.inf)

    @primitive(nondiff=True)
    def _collect(rois, scores):
        k = min(post_nms_top_n, rois.shape[0])
        top_s, top_i = lax.top_k(scores, k)
        ok = top_s > -jnp.inf
        sel = jnp.where(ok[:, None], jnp.take(rois, top_i, axis=0), 0.0)
        return sel, jnp.sum(ok.astype(jnp.int32))

    return _collect(rois, scores)


# ---------------------------------------------------------------------------
# misc detection ops
# ---------------------------------------------------------------------------

def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               nms_threshold=0.3, keep_top_k=100,
                               nms_eta=1.0, name=None):
    """RetinaNet decode + NMS head (retinanet_detection_output_op.cc):
    per FPN level, threshold the [cells*A, C] sigmoid scores and keep the
    top nms_top_k candidates; decode their deltas on the level's anchors
    (variance-free corner decode); pool levels; per-class greedy NMS; keep
    the global top keep_top_k. bboxes: list of [N, M_l, 4] deltas; scores:
    list of [N, M_l, C]; anchors: list of [M_l, 4]. Returns
    (out [N*keep, 6], rois_num [N])."""
    bb = [_arr(b).astype(jnp.float32) for b in bboxes]
    sc = [_arr(s).astype(jnp.float32) for s in scores]
    an = [_arr(a).astype(jnp.float32).reshape(-1, 4) for a in anchors]
    im = _arr(im_info).astype(jnp.float32)

    @primitive(nondiff=True)
    def _rdo(im, *flat):
        nlev = len(an)
        bbs, scs = flat[:nlev], flat[nlev:]
        n = bbs[0].shape[0]
        c = scs[0].shape[-1]

        def one(args):
            per_level_deltas, per_level_scores, imi = args
            cand_boxes, cand_scores, cand_cls = [], [], []
            for li in range(nlev):
                s = per_level_scores[li]  # [M_l, C]
                m_l = s.shape[0]
                top = min(nms_top_k, m_l * c)
                flat_s = jnp.where(s > score_threshold, s, -jnp.inf).reshape(-1)
                ts, ti = lax.top_k(flat_s, top)
                box_id = ti // c
                cls_id = ti % c
                d = jnp.take(per_level_deltas[li], box_id, axis=0)
                a = jnp.take(an[li], box_id, axis=0)
                # +1 pixel convention (retinanet_detection_output_op.h:
                # anchor w = x2-x1+1, corners cx±w/2∓1); boxes map back to
                # ORIGINAL-image coords via im_scale before clipping
                props = _decode_anchor_deltas(a, d, None, True)
                props = props / imi[2]
                hi = jnp.stack([imi[1], imi[0], imi[1], imi[0]]) / imi[2] - 1
                props = jnp.clip(props, 0.0, hi)
                cand_boxes.append(props)
                cand_scores.append(ts)
                cand_cls.append(cls_id)
            boxes = jnp.concatenate(cand_boxes, axis=0)
            scores_all = jnp.concatenate(cand_scores, axis=0)
            cls_all = jnp.concatenate(cand_cls, axis=0)

            def per_class(cl):
                valid = (scores_all > -jnp.inf) & (cls_all == cl)
                # normalized=False: +1 pixel-convention IoU (JaccardOverlap
                # normalized=false in the reference kernel)
                order, keep = _greedy_nms_mask(boxes, scores_all, valid,
                                               nms_threshold, nms_eta, False)
                mask = jnp.zeros((boxes.shape[0],), bool).at[order].set(keep)
                return mask

            keep_cm = jax.vmap(per_class)(jnp.arange(c))  # [C, M]
            kept = jnp.any(keep_cm, axis=0)
            final_s = jnp.where(kept, scores_all, -jnp.inf)
            k = min(keep_top_k, final_s.shape[0])
            ts, ti = lax.top_k(final_s, k)
            ok = ts > -jnp.inf
            sel_cls = jnp.take(cls_all, ti).astype(jnp.float32)
            sel_box = jnp.take(boxes, ti, axis=0)
            o2 = jnp.lexsort((-ts, jnp.where(ok, sel_cls, jnp.inf)))
            ts, ok, sel_cls, sel_box = ts[o2], ok[o2], sel_cls[o2], sel_box[o2]
            out = jnp.concatenate([
                jnp.where(ok, sel_cls, -1.0)[:, None],
                jnp.where(ok, ts, 0.0)[:, None],
                jnp.where(ok[:, None], sel_box, 0.0),
            ], axis=1)
            return out, jnp.sum(ok.astype(jnp.int32))

        outs, cnts = [], []
        for b in range(n):
            o, cn = one(([x[b] for x in bbs], [x[b] for x in scs], im[b]))
            outs.append(o)
            cnts.append(cn)
        return jnp.concatenate(outs, axis=0), jnp.stack(cnts)

    return _rdo(im, *bb, *sc)


def rpn_target_assign(anchors, gt_boxes, im_info, gt_counts=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True, name=None):
    """RPN training targets (rpn_target_assign_op.cc ScoreAssign +
    SampleFgBgGt): per image, anchors inside the image (straddle filter)
    are labeled fg when they are a gt's argmax anchor or IoU >=
    positive_overlap, bg when max-IoU < negative_overlap; fg is
    reservoir-subsampled to fg_fraction*batch and bg to the remainder.
    Host op (CPU-only in the reference too) on the framework PRNG.

    Deviation noted for parity readers: the reference's Detectron
    "fake fg" bookkeeping (its own code comments it as a bug) is replaced
    by the standard degenerate-case handling — images with no fg anchor
    contribute one zero-inside-weight placeholder so downstream shapes
    stay non-empty.

    Returns per-image lists of dicts with loc_index, score_index,
    tgt_label, tgt_bbox (encoded deltas), bbox_inside_weight arrays."""
    from ..random import split_key

    an = np.asarray(_arr(anchors), np.float64).reshape(-1, 4)
    gtb = np.asarray(_arr(gt_boxes), np.float64).reshape(-1, 4)
    im = np.asarray(_arr(im_info), np.float64).reshape(-1, 3)
    if gt_counts is None:
        gcs = np.asarray([len(gtb)], np.int64)
    else:
        gcs = np.asarray(_arr(gt_counts), np.int64).reshape(-1)
    rng = np.random.default_rng(
        np.asarray(jax.random.key_data(split_key())).ravel()[-1])
    out = []
    g_off = 0
    for n in range(len(gcs)):
        gt = gtb[g_off: g_off + int(gcs[n])]
        g_off += int(gcs[n])
        h, w = im[n, 0], im[n, 1]
        if rpn_straddle_thresh >= 0:
            keep = np.where(
                (an[:, 0] >= -rpn_straddle_thresh)
                & (an[:, 1] >= -rpn_straddle_thresh)
                & (an[:, 2] < w + rpn_straddle_thresh)
                & (an[:, 3] < h + rpn_straddle_thresh))[0]
        else:
            keep = np.arange(len(an))
        a = an[keep]
        if len(a) == 0:  # every anchor straddles: nothing to assign
            out.append({
                "loc_index": np.zeros(0, np.int64),
                "score_index": np.zeros(0, np.int64),
                "tgt_label": np.zeros(0, np.int32),
                "tgt_bbox": np.zeros((0, 4), np.float32),
                "bbox_inside_weight": np.zeros((0, 4), np.float32),
            })
            continue
        if len(gt):
            a2g_max, a2g_arg, is_best = _match_anchors_np(a, gt)
        else:
            a2g_max = np.zeros(len(a))
            a2g_arg = np.zeros(len(a), int)
            is_best = np.zeros(len(a), bool)
        fg_mask = is_best | (a2g_max >= rpn_positive_overlap)
        fg_inds = np.where(fg_mask)[0]
        n_fg = int(rpn_fg_fraction * rpn_batch_size_per_im)
        if len(fg_inds) > n_fg:  # cap applies in both sampling modes
            fg_inds = (rng.choice(fg_inds, n_fg, replace=False)
                       if use_random else fg_inds[:n_fg])
        bg_inds = np.where((a2g_max < rpn_negative_overlap) & ~fg_mask)[0]
        n_bg = rpn_batch_size_per_im - len(fg_inds)
        if len(bg_inds) > n_bg:
            bg_inds = (rng.choice(bg_inds, n_bg, replace=False)
                       if use_random else bg_inds[:n_bg])
        inside_w = np.ones((len(fg_inds), 4), np.float32)
        if len(fg_inds) == 0 and len(bg_inds) > 0:
            # degenerate image: borrow one bg anchor as a zero-loss-weight
            # fg placeholder (and REMOVE it from bg so score_index stays
            # duplicate-free and within the batch budget)
            fg_inds = bg_inds[:1]
            bg_inds = bg_inds[1:]
            inside_w = np.zeros((1, 4), np.float32)
        # encoded regression targets for the fg anchors
        if len(gt) and len(fg_inds):
            tgt_bbox = _encode_deltas_np(a[fg_inds], gt[a2g_arg[fg_inds]])
        else:
            tgt_bbox = np.zeros((len(fg_inds), 4), np.float32)
        score_index = np.concatenate([fg_inds, bg_inds]).astype(np.int64)
        labels = np.concatenate([
            np.ones(len(fg_inds), np.int32) * (0 if inside_w.sum() == 0
                                               else 1),
            np.zeros(len(bg_inds), np.int32)])
        out.append({
            "loc_index": keep[fg_inds].astype(np.int64),
            "score_index": keep[score_index],
            "tgt_label": labels,
            "tgt_bbox": tgt_bbox,
            "bbox_inside_weight": inside_w,
        })
    return out


def polygon_box_transform(input, name=None):  # noqa: A002
    """EAST-style offset maps → absolute quad coordinates
    (polygon_box_transform_op.cc: out = 4*index - in per coordinate plane,
    where index is the pixel column for even channels, row for odd)."""

    @primitive
    def _pbt(x):
        n, c, h, w = x.shape
        col = jnp.broadcast_to(jnp.arange(w, dtype=x.dtype), (h, w))
        row = jnp.broadcast_to(jnp.arange(h, dtype=x.dtype)[:, None], (h, w))
        is_x = (jnp.arange(c) % 2 == 0)[None, :, None, None]
        idx = jnp.where(is_x, col[None, None], row[None, None])
        return 4.0 * idx - x

    return _pbt(_arr(input))


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=4.135, name=None):
    """Decode per-class deltas then pick each box's best non-background
    class (box_decoder_and_assign_op.h). target_box [M, 4*C],
    box_score [M, C]. Returns (decoded [M, 4*C], assigned [M, 4])."""

    @primitive(nondiff=True)
    def _bda(pb, pbv, tb, sc):
        m, c4 = tb.shape
        c = c4 // 4
        pw = pb[:, 2] - pb[:, 0] + 1.0
        ph = pb[:, 3] - pb[:, 1] + 1.0
        pcx = pb[:, 0] + 0.5 * pw
        pcy = pb[:, 1] + 0.5 * ph
        d = tb.reshape(m, c, 4) * pbv[:, None, :]
        cx = d[..., 0] * pw[:, None] + pcx[:, None]
        cy = d[..., 1] * ph[:, None] + pcy[:, None]
        w = jnp.exp(jnp.minimum(d[..., 2], box_clip)) * pw[:, None]
        h = jnp.exp(jnp.minimum(d[..., 3], box_clip)) * ph[:, None]
        dec = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - 1, cy + h / 2 - 1], axis=-1)
        best = jnp.argmax(sc[:, 1:], axis=1) + 1  # skip background class 0
        assigned = jnp.take_along_axis(
            dec, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
        return dec.reshape(m, c4), assigned

    return _bda(_arr(prior_box).astype(jnp.float32),
                _arr(prior_box_var).astype(jnp.float32),
                _arr(target_box).astype(jnp.float32),
                _arr(box_score).astype(jnp.float32))


def mine_hard_examples(cls_loss, match_indices, neg_pos_ratio=3.0,
                       neg_dist_threshold=0.5, loc_loss=None,
                       mining_type="max_negative", sample_size=None,
                       name=None):
    """Hard negative mining (mine_hard_examples_op.cc max_negative mode):
    per image, rank unmatched priors by loss and keep the top
    neg_pos_ratio * num_pos as negatives. Returns (neg_mask [N, P] bool,
    neg_count [N])."""
    if mining_type != "max_negative":
        raise NotImplementedError(
            f"mining_type {mining_type!r} is not implemented (only "
            "'max_negative'; 'hard_example' needs sample_size sampling)")
    if sample_size is not None:
        raise NotImplementedError(
            "sample_size belongs to mining_type='hard_example', which is "
            "not implemented")

    @primitive(nondiff=True)
    def _mine(loss, match):
        neg = match < 0
        n_pos = jnp.sum((match >= 0).astype(jnp.int32), axis=1)
        n_neg = (n_pos.astype(jnp.float32) * neg_pos_ratio).astype(jnp.int32)
        n_neg = jnp.minimum(n_neg, jnp.sum(neg.astype(jnp.int32), axis=1))
        masked = jnp.where(neg, loss, -jnp.inf)
        order = jnp.argsort(-masked, axis=1)
        rank = jnp.zeros_like(order).at[
            jnp.arange(order.shape[0])[:, None], order
        ].set(jnp.broadcast_to(jnp.arange(order.shape[1]), order.shape))
        sel = neg & (rank < n_neg[:, None])
        return sel, n_neg

    total = _arr(cls_loss)
    if loc_loss is not None:
        total = total + _arr(loc_loss)
    return _mine(total.astype(jnp.float32), _arr(match_indices))


def _locality_merge(boxes, scores, nms_threshold, normalized):
    """The locality-aware pre-pass (locality_aware_nms_op.cc
    GetMaxScoreIndexWithLocalityAware): walk boxes in input order keeping a
    running head; an incoming box whose IoU with the head exceeds the
    threshold is score-weighted-merged INTO the head (head score += its
    score), otherwise the head is finalised and the incoming box becomes the
    new head. Returns (merged boxes, merged scores, finalised mask)."""
    m = boxes.shape[0]

    def body(i, carry):
        bx, sc, fin, head = carry
        i32 = jnp.asarray(i, head.dtype)

        def with_head(carry):
            bx, sc, fin, head = carry
            hb = lax.dynamic_index_in_dim(bx, head, keepdims=False)
            hs = lax.dynamic_index_in_dim(sc, head, keepdims=False)
            ov = _pairwise_iou(bx[i][None], hb[None], normalized)[0, 0]

            def merge(_):
                num = bx[i] * sc[i] + hb * hs
                merged = num / (sc[i] + hs)
                return (bx.at[head].set(merged), sc.at[head].add(sc[i]),
                        fin, head)

            def finalize(_):
                return bx, sc, fin.at[head].set(True), i32

            return lax.cond(ov > nms_threshold, merge, finalize, None)

        def no_head(carry):
            bx, sc, fin, _ = carry
            return bx, sc, fin, i32

        return lax.cond(head >= 0, with_head, no_head, (bx, sc, fin, head))

    boxes, scores, fin, head = lax.fori_loop(
        0, m, body, (boxes, scores, jnp.zeros((m,), bool), jnp.int32(-1)))
    fin = lax.cond(head >= 0, lambda f: f.at[head].set(True), lambda f: f, fin)
    return boxes, scores, fin


def locality_aware_nms(bboxes, scores, score_threshold=0.0, nms_top_k=-1,
                       keep_top_k=-1, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """Locality-aware NMS (detection/locality_aware_nms_op.cc — the EAST
    text-detection postprocess): a sequential score-weighted merge of
    neighbouring boxes followed by standard greedy NMS and multiclass
    keep_top_k output.

    bboxes [N, M, 4], scores [N, C, M] → (out [N*K, 6] rows
    [label, score, x1, y1, x2, y2] with -1/zero padding, counts [N]).
    Only the 4-coordinate rectangle layout is supported; the reference's
    8..32-point polygon layouts (PolyIoU over gpc polygon clipping) are out
    of scope v1 and raise. Note the reference kernel mutates the shared box
    buffer across the class loop (bbox_slice = *bboxes); typical usage is
    single-class, and this redesign runs each class on the pristine boxes.
    """
    bb = _arr(bboxes).astype(jnp.float32)
    sc = _arr(scores).astype(jnp.float32)
    if bb.shape[-1] != 4:
        raise NotImplementedError(
            "locality_aware_nms: polygon layouts (last dim "
            f"{bb.shape[-1]}) need gpc polygon clipping — out of scope v1; "
            "only [x1,y1,x2,y2] boxes are supported")

    @primitive(nondiff=True)
    def _nms(bb, sc):
        n, m = bb.shape[0], bb.shape[1]
        c = sc.shape[1]
        top = min(nms_top_k, m) if nms_top_k > -1 else m

        def one(b, s):
            def per_class(cls_scores):
                mb, ms, fin = _locality_merge(b, cls_scores, nms_threshold,
                                              normalized)
                valid = fin & (ms > score_threshold)
                if top < m:
                    kth = -jnp.sort(-jnp.where(valid, ms, -jnp.inf))[top - 1]
                    valid = valid & (ms >= kth)
                order, keep = _greedy_nms_mask(mb, ms, valid, nms_threshold,
                                               nms_eta, normalized)
                mask = jnp.zeros((m,), bool).at[order].set(keep)
                return mask, mb, ms

            keep_cm, mb_cm, ms_cm = jax.vmap(per_class)(s)  # [C,M],[C,M,4],[C,M]
            mb_flat = mb_cm.reshape(c * m, 4)  # per-class MERGED boxes
            out, _idx, cnt = _keep_topk_output(
                keep_cm, ms_cm, lambda idx: jnp.take(mb_flat, idx, axis=0),
                keep_top_k, background_label)
            return out, cnt

        out, cnt = jax.vmap(one)(bb, sc)
        return out.reshape(-1, 6), cnt

    return _nms(bb, sc)


def _match_anchors_np(anchors, gt):
    """Anchor↔gt matching stats shared by the target-assign family
    (rpn_target_assign_op.cc ScoreAssign): per-anchor max/argmax IoU plus
    the is-some-gt's-best-anchor mask (1e-5 tie tolerance)."""
    iou = np.asarray(_pairwise_iou(
        jnp.asarray(anchors, jnp.float32), jnp.asarray(gt, jnp.float32),
        False))
    a_max = iou.max(axis=1)
    a_arg = iou.argmax(axis=1)
    g_max = iou.max(axis=0)
    is_best = np.zeros(len(anchors), bool)
    for j in range(len(gt)):
        if g_max[j] > 0:  # a gt overlapping nothing marks no anchor
            is_best |= np.abs(iou[:, j] - g_max[j]) < 1e-5
    return a_max, a_arg, is_best


def _encode_deltas_np(anchors, gts):
    """(+1)-width center/size deltas (bbox_util.h BoxToDelta, unweighted) —
    the rpn/retinanet regression-target encoding."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    gw = gts[:, 2] - gts[:, 0] + 1.0
    gh = gts[:, 3] - gts[:, 1] + 1.0
    gcx = gts[:, 0] + 0.5 * gw
    gcy = gts[:, 1] + 0.5 * gh
    return np.stack([
        (gcx - acx) / aw, (gcy - acy) / ah,
        np.log(gw / aw), np.log(gh / ah)], axis=1).astype(np.float32)


def _box_to_delta(ex, gt, weights, normalized=False):
    """bbox_util.h BoxToDelta: encode gt relative to ex boxes."""
    off = 0.0 if normalized else 1.0
    ew = ex[:, 2] - ex[:, 0] + off
    eh = ex[:, 3] - ex[:, 1] + off
    ecx = ex[:, 0] + 0.5 * ew
    ecy = ex[:, 1] + 0.5 * eh
    gw = gt[:, 2] - gt[:, 0] + off
    gh = gt[:, 3] - gt[:, 1] + off
    gcx = gt[:, 0] + 0.5 * gw
    gcy = gt[:, 1] + 0.5 * gh
    d = np.stack([(gcx - ecx) / ew, (gcy - ecy) / eh,
                  np.log(gw / ew), np.log(gh / eh)], axis=1)
    return (d / np.asarray(weights)[None, :]).astype(np.float32)


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, rois_counts=None, gt_counts=None,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.5, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False,
                             max_overlap=None, name=None):
    """Fast R-CNN training targets
    (detection/generate_proposal_labels_op.cc SampleRoisForOneImage +
    SampleFgBgGt): per image, proposals (plus the gt boxes themselves) are
    matched to gt by IoU; fg rois (max IoU >= fg_thresh) are subsampled to
    fg_fraction*batch, bg rois ([bg_thresh_lo, bg_thresh_hi)) fill the rest;
    labels come from gt_classes, regression targets are BoxToDelta deltas
    expanded into per-class slots. Host op on the framework PRNG (the
    reference kernel is CPU-only too).

    Dense redesign of the LoD interface: flat arrays + per-image counts
    (rois_counts / gt_counts). Returns per-image list of dicts with rois,
    labels_int32, bbox_targets [P, 4*class_nums], bbox_inside_weights,
    bbox_outside_weights, max_overlap_with_gt.
    """
    from ..random import split_key

    rois_all = np.asarray(_arr(rpn_rois), np.float64).reshape(-1, 4)
    gtc_all = np.asarray(_arr(gt_classes), np.int64).reshape(-1)
    crowd_all = np.asarray(_arr(is_crowd), np.int64).reshape(-1)
    gtb_all = np.asarray(_arr(gt_boxes), np.float64).reshape(-1, 4)
    im = np.asarray(_arr(im_info), np.float64).reshape(-1, 3)
    n_im = im.shape[0]
    if rois_counts is None:
        rcs = np.asarray([len(rois_all)], np.int64)
    else:
        rcs = np.asarray(_arr(rois_counts), np.int64).reshape(-1)
    if gt_counts is None:
        gcs = np.asarray([len(gtb_all)], np.int64)
    else:
        gcs = np.asarray(_arr(gt_counts), np.int64).reshape(-1)
    mo_all = (np.asarray(_arr(max_overlap), np.float64).reshape(-1)
              if max_overlap is not None else None)
    rng = np.random.default_rng(
        np.asarray(jax.random.key_data(split_key())).ravel()[-1])
    weights = [float(wv) for wv in bbox_reg_weights]

    out = []
    r_off = g_off = 0
    for b in range(n_im):
        rois = rois_all[r_off: r_off + int(rcs[b])].copy()
        mo = (mo_all[r_off: r_off + int(rcs[b])]
              if mo_all is not None else None)
        r_off += int(rcs[b])
        gtb = gtb_all[g_off: g_off + int(gcs[b])]
        gtc = gtc_all[g_off: g_off + int(gcs[b])]
        crowd = crowd_all[g_off: g_off + int(gcs[b])]
        g_off += int(gcs[b])
        im_scale = im[b, 2]
        rois = rois / im_scale

        if is_cascade_rcnn and mo is not None:
            # FilterRoIs: keep proposals whose previous-stage max_overlap
            # < fg_thresh is REMOVED — cascade keeps the confident ones
            keep = np.where(mo >= fg_thresh)[0]
            rois = rois[keep] if len(keep) else np.zeros((1, 4))

        boxes = np.concatenate([gtb, rois], axis=0)
        n_box = len(boxes)
        if len(gtb):
            iou = np.asarray(_pairwise_iou(
                jnp.asarray(boxes, jnp.float32),
                jnp.asarray(gtb, jnp.float32), False))
        else:
            iou = np.zeros((n_box, 0))
        max_ov = iou.max(axis=1) if iou.shape[1] else np.zeros(n_box)
        arg_ov = iou.argmax(axis=1) if iou.shape[1] else np.zeros(n_box, int)
        # a crowd gt row never becomes fg (SampleFgBgGt crowd_data check)
        for j in range(len(crowd)):
            if crowd[j]:
                max_ov[j] = -1.0

        fg_mask = max_ov >= fg_thresh
        fg_inds = np.where(fg_mask)[0]
        bg_inds = np.where((max_ov >= bg_thresh_lo)
                           & (max_ov < bg_thresh_hi))[0]
        if not is_cascade_rcnn:
            n_fg = min(int(batch_size_per_im * fg_fraction), len(fg_inds))
            if use_random and len(fg_inds) > n_fg:
                fg_inds = rng.permutation(fg_inds)
            fg_inds = fg_inds[:n_fg]
            n_bg = min(batch_size_per_im - len(fg_inds), len(bg_inds))
            if use_random and len(bg_inds) > n_bg:
                bg_inds = rng.permutation(bg_inds)
            bg_inds = bg_inds[:n_bg]

        sel = np.concatenate([fg_inds, bg_inds]).astype(int)
        sampled_boxes = boxes[sel]
        labels = np.concatenate([
            gtc[arg_ov[fg_inds]] if len(gtb) else np.zeros(len(fg_inds), int),
            np.zeros(len(bg_inds), np.int64)]).astype(np.int32)
        sampled_max_ov = max_ov[sel].astype(np.float32)

        # deltas for fg rows only
        n_fg_s = len(fg_inds)
        deltas = np.zeros((len(sel), 4), np.float32)
        if n_fg_s and len(gtb):
            deltas[:n_fg_s] = _box_to_delta(
                sampled_boxes[:n_fg_s], gtb[arg_ov[fg_inds]], weights)

        width = 4 * class_nums
        tgt = np.zeros((len(sel), width), np.float32)
        inw = np.zeros((len(sel), width), np.float32)
        for i in range(len(sel)):
            lbl = int(labels[i])
            if lbl > 0:
                if is_cls_agnostic:
                    lbl = 1
                tgt[i, 4 * lbl: 4 * lbl + 4] = deltas[i]
                inw[i, 4 * lbl: 4 * lbl + 4] = 1.0
        outw = inw.copy()

        out.append({
            "rois": (sampled_boxes * im_scale).astype(np.float32),
            "labels_int32": labels,
            "bbox_targets": tgt,
            "bbox_inside_weights": inw,
            "bbox_outside_weights": outw,
            "max_overlap_with_gt": sampled_max_ov,
        })
    return out


def roi_perspective_transform(x, rois, transformed_height, transformed_width,
                              spatial_scale=1.0, rois_num=None, name=None):
    """Perspective-warp quadrilateral RoIs to a fixed grid
    (detection/roi_perspective_transform_op.cc — the OCR text-rectify op):
    each RoI is 4 (x, y) points; a 3x3 perspective matrix maps output grid
    coords to source coords, sampled bilinearly; points outside the quad or
    the image are zeroed and masked.

    x [N, C, H, W]; rois [R, 8]; rois_num [N] (≙ LoD) maps RoIs to images.
    Returns (out [R, C, th, tw], mask [R, 1, th, tw] int32,
    transform_matrix [R, 9]). Tolerant comparisons (1e-4) follow the
    reference's GT_E/LT_E/GT helpers."""
    th, tw = int(transformed_height), int(transformed_width)
    ss = float(spatial_scale)
    xv = _arr(x).astype(jnp.float32)
    rv = _arr(rois).astype(jnp.float32)
    total = rv.shape[0]
    if rois_num is None:
        batch_ids = jnp.zeros((total,), jnp.int32)
    else:
        from .ops import _box_batch_ids

        batch_ids = _box_batch_ids(_arr(rois_num), total)

    # differentiable w.r.t. x through the bilinear sample (the reference op
    # registers an X-grad kernel); mask/matrix ride as aux outputs
    @primitive(aux=2)
    def _rpt(xv, rv, batch_ids):
        n, c, h, w = xv.shape
        eps = 1e-4

        def one(roi, bid):
            rx = roi[0::2] * ss  # [4]
            ry = roi[1::2] * ss
            x0, x1, x2, x3 = rx[0], rx[1], rx[2], rx[3]
            y0, y1, y2, y3 = ry[0], ry[1], ry[2], ry[3]
            len1 = jnp.sqrt((x0 - x1) ** 2 + (y0 - y1) ** 2)
            len2 = jnp.sqrt((x1 - x2) ** 2 + (y1 - y2) ** 2)
            len3 = jnp.sqrt((x2 - x3) ** 2 + (y2 - y3) ** 2)
            len4 = jnp.sqrt((x3 - x0) ** 2 + (y3 - y0) ** 2)
            est_h = (len2 + len4) / 2.0
            est_w = (len1 + len3) / 2.0
            nh = jnp.float32(max(2, th))
            nw = jnp.round(est_w * (nh - 1) / jnp.maximum(est_h, 1e-8)) + 1
            nw = jnp.clip(nw, 2.0, float(tw))

            dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
            dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
            den = dx1 * dy2 - dx2 * dy1 + 1e-5
            m6 = (dx3 * dy2 - dx2 * dy3) / den / (nw - 1)
            m7 = (dx1 * dy3 - dx3 * dy1) / den / (nh - 1)
            m8 = jnp.float32(1.0)
            m3 = (y1 - y0 + m6 * (nw - 1) * y1) / (nw - 1)
            m4 = (y3 - y0 + m7 * (nh - 1) * y3) / (nh - 1)
            m5 = y0
            m0 = (x1 - x0 + m6 * (nw - 1) * x1) / (nw - 1)
            m1 = (x3 - x0 + m7 * (nh - 1) * x3) / (nh - 1)
            m2 = x0
            matrix = jnp.stack([m0, m1, m2, m3, m4, m5, m6, m7, m8])

            ow = jnp.arange(tw, dtype=jnp.float32)[None, :]  # [1, tw]
            oh = jnp.arange(th, dtype=jnp.float32)[:, None]  # [th, 1]
            u = m0 * ow + m1 * oh + m2
            v = m3 * ow + m4 * oh + m5
            ww = m6 * ow + m7 * oh + m8
            in_w = u / ww  # [th, tw]
            in_h = v / ww

            # in_quad (crossing test with the reference's edge tolerance)
            on_edge = jnp.zeros(in_w.shape, bool)
            n_cross = jnp.zeros(in_w.shape, jnp.int32)
            for i in range(4):
                xs, ys = rx[i], ry[i]
                xe, ye = rx[(i + 1) % 4], ry[(i + 1) % 4]
                horiz = jnp.abs(ys - ye) < eps
                # horizontal edge: on it iff y matches and x within span
                on_h = (horiz & (jnp.abs(in_h - ys) < eps)
                        & (jnp.abs(in_h - ye) < eps)
                        & (in_w >= jnp.minimum(xs, xe) - eps)
                        & (in_w <= jnp.maximum(xs, xe) + eps))
                ix = (in_h - ys) * (xe - xs) / jnp.where(horiz, 1.0, ye - ys) + xs
                on_s = (~horiz & (jnp.abs(ix - in_w) < eps)
                        & (in_h >= jnp.minimum(ys, ye) - eps)
                        & (in_h <= jnp.maximum(ys, ye) + eps))
                on_edge = on_edge | on_h | on_s
                in_span = (~horiz
                           & ~(in_h <= jnp.minimum(ys, ye) + eps)
                           & ~(in_h - jnp.maximum(ys, ye) > eps))
                n_cross = n_cross + jnp.where(
                    in_span & (ix - in_w > eps), 1, 0)
            inside = on_edge | (n_cross % 2 == 1)

            in_img = (~(in_w <= -0.5 + eps) & ~(in_w >= w - 0.5 - eps)
                      & ~(in_h <= -0.5 + eps) & ~(in_h >= h - 0.5 - eps))
            mask = inside & in_img

            # bilinear sample (clamped to edges like the reference)
            swc = jnp.clip(in_w, 0.0, float(w - 1))
            shc = jnp.clip(in_h, 0.0, float(h - 1))
            wf = jnp.floor(swc)
            hf = jnp.floor(shc)
            wf = jnp.minimum(wf, float(w - 1))
            hf = jnp.minimum(hf, float(h - 1))
            wc_ = jnp.minimum(wf + 1, float(w - 1))
            hc_ = jnp.minimum(hf + 1, float(h - 1))
            fw = swc - wf
            fh = shc - hf
            img = xv[bid]  # [C, H, W]
            wf_i = wf.astype(jnp.int32); hc_i = hc_.astype(jnp.int32)
            wc_i = wc_.astype(jnp.int32); hf_i = hf.astype(jnp.int32)
            v1 = img[:, hf_i, wf_i]
            v2 = img[:, hc_i, wf_i]
            v3 = img[:, hc_i, wc_i]
            v4 = img[:, hf_i, wc_i]
            val = (v1 * (1 - fw) * (1 - fh) + v2 * (1 - fw) * fh
                   + v3 * fw * fh + v4 * fw * (1 - fh))
            out = jnp.where(mask[None], val, 0.0)
            return out, mask[None].astype(jnp.int32), matrix

        return jax.vmap(one)(rv, batch_ids)

    out, mask, tm = _rpt(xv, rv, batch_ids)
    return out, mask, tm


def _poly_fill_mask(polys, box, resolution):
    """Rasterize polygons (image coords) into a box-relative
    resolution x resolution binary mask. Even-odd (crossing-parity) fill
    sampled at pixel centers — the documented redesign of the reference's
    COCO 5x-upsampled boundary rasterization (mask_util.cc Poly2Mask):
    identical interiors, sub-pixel differences possible only on boundary
    pixels."""
    res = int(resolution)
    x0, y0, x1, y1 = box
    w = max(x1 - x0, 1e-6)
    h = max(y1 - y0, 1e-6)
    xs = (np.arange(res) + 0.5) * w / res + x0
    ys = (np.arange(res) + 0.5) * h / res + y0
    gx, gy = np.meshgrid(xs, ys)  # [res, res]
    mask = np.zeros((res, res), bool)
    for poly in polys:
        px = np.asarray(poly[0::2], np.float64)
        py = np.asarray(poly[1::2], np.float64)
        n = len(px)
        inside = np.zeros((res, res), bool)
        j = n - 1
        for i in range(n):
            cond = (py[i] > gy) != (py[j] > gy)
            with np.errstate(divide="ignore", invalid="ignore"):
                xcross = (px[j] - px[i]) * (gy - py[i]) / (py[j] - py[i]) + px[i]
            inside ^= cond & (gx < xcross)
            j = i
        mask |= inside
    return mask.astype(np.uint8)


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, gt_counts=None, rois_counts=None,
                         poly_lengths=None, num_classes=81, resolution=14,
                         name=None):
    """Mask R-CNN mask targets (detection/generate_mask_labels_op.cc
    SampleMaskForOneImage + ExpandMaskTarget): for each fg roi, pick the
    gt polygon set whose bounding box overlaps it most, rasterize the
    polygons into a roi-relative resolution^2 binary mask, and expand it
    into the roi's class slot (all other slots -1).

    Dense redesign of the 3-level segms LoD: ``gt_segms`` is a list (per
    gt) of lists of flat [x0,y0,x1,y1,...] polygons; counts map gts/rois
    to images. Returns per-image dicts with mask_rois, roi_has_mask_int32,
    mask_int32 [fg, num_classes*resolution^2]."""
    im = np.asarray(_arr(im_info), np.float64).reshape(-1, 3)
    gtc_all = np.asarray(_arr(gt_classes), np.int64).reshape(-1)
    crowd_all = np.asarray(_arr(is_crowd), np.int64).reshape(-1)
    rois_all = np.asarray(_arr(rois), np.float64).reshape(-1, 4)
    lab_all = np.asarray(_arr(labels_int32), np.int64).reshape(-1)
    n_im = im.shape[0]
    gcs = (np.asarray(_arr(gt_counts), np.int64).reshape(-1)
           if gt_counts is not None else np.asarray([len(gtc_all)]))
    rcs = (np.asarray(_arr(rois_counts), np.int64).reshape(-1)
           if rois_counts is not None else np.asarray([len(rois_all)]))
    res = int(resolution)
    m_sq = res * res

    out = []
    g_off = r_off = 0
    for b in range(n_im):
        gtc = gtc_all[g_off: g_off + int(gcs[b])]
        crowd = crowd_all[g_off: g_off + int(gcs[b])]
        segms = gt_segms[g_off: g_off + int(gcs[b])]
        g_off += int(gcs[b])
        rb = rois_all[r_off: r_off + int(rcs[b])]
        lab = lab_all[r_off: r_off + int(rcs[b])]
        r_off += int(rcs[b])
        im_scale = im[b, 2]

        # gts with a real class and not crowd contribute mask polys
        keep_gt = [i for i in range(len(gtc)) if gtc[i] > 0 and not crowd[i]]
        gt_polys = [segms[i] for i in keep_gt]
        # Poly2Boxes: bbox of the union of each gt's polygons
        pboxes = np.zeros((len(gt_polys), 4), np.float64)
        for i, polys in enumerate(gt_polys):
            ax = np.concatenate([np.asarray(p[0::2]) for p in polys])
            ay = np.concatenate([np.asarray(p[1::2]) for p in polys])
            pboxes[i] = [ax.min(), ay.min(), ax.max(), ay.max()]

        fg_inds = np.where(lab > 0)[0]
        if len(fg_inds) and len(gt_polys):
            rois_fg = rb[fg_inds] / im_scale
            iou = np.asarray(_pairwise_iou(
                jnp.asarray(rois_fg, jnp.float32),
                jnp.asarray(pboxes, jnp.float32), False))
            best = iou.argmax(axis=1)
            masks = np.zeros((len(fg_inds), m_sq), np.uint8)
            for i in range(len(fg_inds)):
                masks[i] = _poly_fill_mask(
                    gt_polys[best[i]], rois_fg[i], res).reshape(-1)
            cls_lab = lab[fg_inds]
            expand = np.full((len(fg_inds), num_classes * m_sq), -1, np.int32)
            for i, cl in enumerate(cls_lab):
                if cl > 0:
                    expand[i, m_sq * cl: m_sq * (cl + 1)] = masks[i]
            out.append({
                "mask_rois": (rois_fg * im_scale).astype(np.float32),
                "roi_has_mask_int32": fg_inds.astype(np.int32),
                "mask_int32": expand,
            })
        else:
            # degenerate: one bg roi with an all -1 target
            out.append({
                "mask_rois": rb[:1].astype(np.float32),
                "roi_has_mask_int32": np.zeros(1, np.int32),
                "mask_int32": np.full((1, num_classes * m_sq), -1, np.int32),
            })
    return out


def deformable_psroi_pooling(x, rois, trans=None, rois_num=None,
                             no_trans=False, spatial_scale=1.0,
                             output_dim=None, group_size=(1, 1),
                             pooled_height=1, pooled_width=1,
                             part_size=None, sample_per_part=1,
                             trans_std=0.1, name=None):
    """Deformable position-sensitive RoI pooling
    (deformable_psroi_pooling_op.cu DeformablePSROIPoolForwardKernel — the
    Deformable ConvNets R-FCN head): each output bin averages
    sample_per_part^2 bilinear samples from its position-sensitive channel
    group, with a learned per-part (x, y) offset from ``trans`` shifting
    the bin window. Samples outside the image are dropped from the mean.

    x [N, C, H, W] with C = output_dim*group_h*group_w; rois [R, 4] in
    image coords; trans [R, 2*num_classes, part_h, part_w]; rois_num [N]
    maps rois to images. Returns (out [R, output_dim, ph, pw],
    top_count [R, output_dim, ph, pw]). Differentiable w.r.t. x and trans.
    """
    xv = _arr(x).astype(jnp.float32)
    rv = _arr(rois).astype(jnp.float32)
    gh, gw = (int(group_size[0]), int(group_size[1]))
    ph_, pw_ = int(pooled_height), int(pooled_width)
    if output_dim is None:
        output_dim = xv.shape[1] // (gh * gw)
    od = int(output_dim)
    sp = int(sample_per_part)
    ss = float(spatial_scale)
    tstd = float(trans_std)
    if part_size is None:
        part_size = (ph_, pw_)
    part_h, part_w = int(part_size[0]), int(part_size[1])
    total = rv.shape[0]
    if rois_num is None:
        batch_ids = jnp.zeros((total,), jnp.int32)
    else:
        from .ops import _box_batch_ids

        batch_ids = _box_batch_ids(_arr(rois_num), total)
    if no_trans or trans is None:
        tv = jnp.zeros((total, 2, part_h, part_w), jnp.float32)
        num_classes = 1
        use_trans = False
    else:
        tv = _arr(trans).astype(jnp.float32)
        num_classes = tv.shape[1] // 2
        use_trans = True
    cec = max(od // num_classes, 1)

    @primitive
    def _dpsroi(xv, rv, tv, batch_ids):
        n, c, h, w = xv.shape

        def one(roi, tr, bid):
            rsw = jnp.round(roi[0]) * ss - 0.5
            rsh = jnp.round(roi[1]) * ss - 0.5
            rew = (jnp.round(roi[2]) + 1.0) * ss - 0.5
            reh = (jnp.round(roi[3]) + 1.0) * ss - 0.5
            rw = jnp.maximum(rew - rsw, 0.1)
            rh = jnp.maximum(reh - rsh, 0.1)
            bh = rh / ph_
            bw = rw / pw_
            sbh = bh / sp
            sbw = bw / sp

            ctop = jnp.arange(od)[:, None, None]              # [od,1,1]
            phg = jnp.arange(ph_)[None, :, None]              # [1,ph,1]
            pwg = jnp.arange(pw_)[None, None, :]              # [1,1,pw]
            part_hi = jnp.floor(phg.astype(jnp.float32) / ph_ * part_h
                                ).astype(jnp.int32)
            part_wi = jnp.floor(pwg.astype(jnp.float32) / pw_ * part_w
                                ).astype(jnp.int32)
            cls_id = ctop // cec
            if use_trans:
                tx = tr[2 * cls_id, part_hi, part_wi] * tstd   # [od,ph,pw]
                ty = tr[2 * cls_id + 1, part_hi, part_wi] * tstd
            else:
                tx = jnp.zeros((od, ph_, pw_), jnp.float32)
                ty = jnp.zeros((od, ph_, pw_), jnp.float32)

            wstart = pwg * bw + rsw + tx * rw                  # [od,ph,pw]
            hstart = phg * bh + rsh + ty * rh
            gwi = jnp.clip((pwg * gw) // pw_, 0, gw - 1)
            ghi = jnp.clip((phg * gh) // ph_, 0, gh - 1)
            chan = (ctop * gh + ghi) * gw + gwi                # [od,ph,pw]
            chan = jnp.broadcast_to(chan, (od, ph_, pw_))

            ihs = jnp.arange(sp)[:, None]                      # [sp,1]
            iws = jnp.arange(sp)[None, :]                      # [1,sp]
            sw = wstart[..., None, None] + iws * sbw           # [od,ph,pw,sp,sp]
            sh = hstart[..., None, None] + ihs * sbh
            ok = ((sw >= -0.5) & (sw <= w - 0.5)
                  & (sh >= -0.5) & (sh <= h - 0.5))
            swc = jnp.clip(sw, 0.0, float(w - 1))
            shc = jnp.clip(sh, 0.0, float(h - 1))
            wf = jnp.floor(swc); hf = jnp.floor(shc)
            wc_ = jnp.minimum(wf + 1, w - 1).astype(jnp.int32)
            hc_ = jnp.minimum(hf + 1, h - 1).astype(jnp.int32)
            wf_i = wf.astype(jnp.int32); hf_i = hf.astype(jnp.int32)
            fw = swc - wf; fh = shc - hf
            img = xv[bid]                                      # [C,H,W]
            cb = jnp.broadcast_to(chan[..., None, None],
                                  sw.shape)                    # [od,ph,pw,sp,sp]
            v1 = img[cb, hf_i, wf_i]
            v2 = img[cb, hc_, wf_i]
            v3 = img[cb, hc_, wc_]
            v4 = img[cb, hf_i, wc_]
            val = (v1 * (1 - fw) * (1 - fh) + v2 * (1 - fw) * fh
                   + v3 * fw * fh + v4 * fw * (1 - fh))
            val = jnp.where(ok, val, 0.0)
            cnt = jnp.sum(ok, axis=(-1, -2)).astype(jnp.float32)
            s = jnp.sum(val, axis=(-1, -2))
            out = jnp.where(cnt > 0, s / jnp.maximum(cnt, 1.0), 0.0)
            return out, cnt

        return jax.vmap(one)(rv, tv, batch_ids)

    out, cnt = _dpsroi(xv, rv, tv, batch_ids)
    return out, cnt


def retinanet_target_assign(anchors, gt_boxes, gt_labels, is_crowd, im_info,
                            gt_counts=None, positive_overlap=0.5,
                            negative_overlap=0.4, name=None):
    """RetinaNet training targets (rpn_target_assign_op.cc
    RetinanetTargetAssignKernel): the rpn assignment WITHOUT subsampling —
    every anchor whose max IoU >= positive_overlap (or that is some gt's
    best anchor) is fg with the gt's CLASS label, every anchor below
    negative_overlap is bg (label 0), the rest are ignored; crowd gts are
    filtered out before matching. Outputs per image add the fg count
    (focal loss normalizer). Host op like the rpn sibling."""
    an = np.asarray(_arr(anchors), np.float64).reshape(-1, 4)
    gtb_all = np.asarray(_arr(gt_boxes), np.float64).reshape(-1, 4)
    gtl_all = np.asarray(_arr(gt_labels), np.int64).reshape(-1)
    crowd_all = np.asarray(_arr(is_crowd), np.int64).reshape(-1)
    # im_info accepted for op-signature parity; the retinanet kernel does
    # no straddle filtering (unlike the rpn sibling)
    if gt_counts is None:
        gcs = np.asarray([len(gtb_all)], np.int64)
    else:
        gcs = np.asarray(_arr(gt_counts), np.int64).reshape(-1)

    out = []
    g_off = 0
    for b in range(len(gcs)):
        gtb = gtb_all[g_off: g_off + int(gcs[b])]
        gtl = gtl_all[g_off: g_off + int(gcs[b])]
        crowd = crowd_all[g_off: g_off + int(gcs[b])]
        g_off += int(gcs[b])
        keep_gt = ~crowd.astype(bool)
        gtb, gtl = gtb[keep_gt], gtl[keep_gt]

        if len(gtb):
            a_max, a_arg, is_best = _match_anchors_np(an, gtb)
            fg_mask = is_best | (a_max >= positive_overlap)
        else:
            a_max = np.zeros(len(an))
            a_arg = np.zeros(len(an), int)
            fg_mask = np.zeros(len(an), bool)
        fg_inds = np.where(fg_mask)[0]
        bg_inds = np.where((a_max < negative_overlap) & ~fg_mask)[0]

        if len(gtb) and len(fg_inds):
            tgt_bbox = _encode_deltas_np(an[fg_inds], gtb[a_arg[fg_inds]])
            labels = gtl[a_arg[fg_inds]].astype(np.int32)
        else:
            tgt_bbox = np.zeros((len(fg_inds), 4), np.float32)
            labels = np.zeros(len(fg_inds), np.int32)

        out.append({
            "loc_index": fg_inds.astype(np.int64),
            "score_index": np.concatenate([fg_inds, bg_inds]).astype(np.int64),
            "tgt_bbox": tgt_bbox,
            "tgt_label": np.concatenate(
                [labels, np.zeros(len(bg_inds), np.int32)]),
            "bbox_inside_weight": np.ones((len(fg_inds), 4), np.float32),
            "fg_num": np.int32(len(fg_inds) + 1),  # reference: fg + 1
        })
    return out
