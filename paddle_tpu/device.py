"""Device / Place model.

Parity: /root/reference/paddle/fluid/platform/place.h:37 (CPUPlace, CUDAPlace,
XPUPlace, NPUPlace, CUDAPinnedPlace) and python/paddle/device/__init__.py
(set_device / get_device). TPU-native redesign: a Place is a selector over
``jax.devices()``; there is no DeviceContext/stream model — XLA owns streams
and scheduling, so the reference's DeviceContextPool collapses into this file.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = [
    "Place",
    "CPUPlace",
    "TPUPlace",
    "CUDAPlace",
    "CUDAPinnedPlace",
    "set_device",
    "get_device",
    "get_default_place",
    "device_count",
    "is_compiled_with_tpu",
    "is_compiled_with_cuda",
    "is_compiled_with_xpu",
    "is_compiled_with_npu",
    "XPUPlace",
    "NPUPlace",
]


class Place:
    """Base class for device selectors."""

    device_type: str = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    # --- jax bridge -------------------------------------------------------
    def jax_device(self):
        """Resolve this place to a concrete jax.Device."""
        platform = "cpu" if self.device_type == "cpu" else None
        if platform is not None:
            devs = jax.devices("cpu")
        else:
            devs = jax.local_devices()
        if self.device_id >= len(devs):
            raise ValueError(
                f"{self!r}: device id out of range ({len(devs)} local devices)"
            )
        return devs[self.device_id]

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_tpu_place(self):
        return self.device_type == "tpu"

    # the reference API spells these gpu; accelerator == tpu here
    def is_gpu_place(self):
        return self.device_type == "tpu"


class CPUPlace(Place):
    device_type = "cpu"


class TPUPlace(Place):
    device_type = "tpu"


# Compatibility aliases so reference-style user code ports unchanged: on this
# framework the accelerator is the TPU chip.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace
NPUPlace = TPUPlace


class CUDAPinnedPlace(CPUPlace):
    """Host memory place. TPU transfers stage through host RAM managed by
    PJRT; a distinct pinned pool is unnecessary (reference:
    paddle/fluid/memory/allocation/pinned_allocator.cc)."""


_current_device: Optional[str] = None


def _accelerator_available() -> bool:
    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except RuntimeError:
        return False


def set_device(device: str):
    """Set the global default place. Accepts 'cpu', 'tpu', 'tpu:0', and the
    reference spellings 'gpu'/'gpu:0' (mapped to tpu)."""
    global _current_device
    device = device.lower().replace("gpu", "tpu").replace("xpu", "tpu").replace("npu", "tpu")
    if not (device == "cpu" or device.startswith("tpu")):
        raise ValueError(f"Unsupported device {device!r}")
    _current_device = device
    return get_default_place()


def get_device() -> str:
    if _current_device is not None:
        return _current_device
    return "tpu:0" if _accelerator_available() else "cpu"


def get_default_place() -> Place:
    dev = get_device()
    if dev == "cpu":
        return CPUPlace(0)
    idx = int(dev.split(":")[1]) if ":" in dev else 0
    return TPUPlace(idx)


def device_count() -> int:
    return len(jax.local_devices())


def is_compiled_with_tpu() -> bool:
    return _accelerator_available()


def is_compiled_with_cuda() -> bool:
    # honest answer: this framework never targets CUDA
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def _place_from(place) -> Place:
    if place is None:
        return get_default_place()
    if isinstance(place, Place):
        return place
    if isinstance(place, str):
        saved = _current_device
        try:
            p = set_device(place)
        finally:
            globals()["_current_device"] = saved
        return p
    raise TypeError(f"Expected Place or str, got {type(place)}")


def get_cudnn_version():
    """Parity: paddle.device.get_cudnn_version — no cuDNN on TPU (None,
    matching the reference's CPU-only answer)."""
    return None
