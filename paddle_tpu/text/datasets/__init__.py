"""Text datasets (parity: python/paddle/text/datasets/ — Conll05st, Imdb,
Imikolov, Movielens, UCIHousing, WMT14, WMT16).

This build runs with zero network egress, so datasets load from a local
``data_file`` (the same archive formats the reference downloads) or, for
quick experiments and tests, generate a deterministic synthetic sample with
``mode='synthetic'``-compatible behavior when no file is given.
"""
from __future__ import annotations

import gzip
import os
import tarfile
from typing import Optional

import numpy as np

from ...io.dataset import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]


class _FileBackedDataset(Dataset):
    """Shared plumbing: explicit data_file, else deterministic synthetic."""

    _synthetic_size = 64

    def __init__(self, data_file: Optional[str] = None, mode: str = "train"):
        assert mode in ("train", "test", "dev"), f"bad mode {mode}"
        self.mode = mode
        self.data_file = data_file
        if data_file is not None and not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{type(self).__name__}: data_file {data_file!r} not found; "
                "downloads are disabled in this environment — place the "
                "reference archive locally and pass data_file="
            )
        self._load()

    def _load(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        return self.samples[idx]


class UCIHousing(_FileBackedDataset):
    """Boston housing regression (parity: text/datasets/uci_housing.py).
    File format: whitespace-separated floats, 14 columns."""

    FEATURE_DIM = 13

    def _load(self):
        if self.data_file:
            raw = np.loadtxt(self.data_file)
        else:
            rng = np.random.RandomState(42)
            x = rng.rand(self._synthetic_size, self.FEATURE_DIM)
            w = np.linspace(-2, 2, self.FEATURE_DIM)
            y = x @ w + 0.1 * rng.randn(self._synthetic_size)
            raw = np.concatenate([x, y[:, None]], axis=1)
        # reference normalizes features by train-split statistics
        feats = raw[:, :-1].astype("float32")
        feats = (feats - feats.mean(0)) / np.maximum(feats.std(0), 1e-6)
        labels = raw[:, -1:].astype("float32")
        split = int(0.8 * len(raw))
        sl = slice(0, split) if self.mode == "train" else slice(split, None)
        self.samples = [(feats[i], labels[i]) for i in range(*sl.indices(len(raw)))]


class Imdb(_FileBackedDataset):
    """IMDB sentiment (parity: text/datasets/imdb.py). data_file: aclImdb
    tar.gz; synthetic: token-id sequences with sign-of-sum labels."""

    def __init__(self, data_file=None, mode="train", cutoff: int = 150):
        self.cutoff = cutoff
        super().__init__(data_file, mode)

    def _load(self):
        if self.data_file:
            self.samples, self.word_idx = self._parse_tar()
            return
        rng = np.random.RandomState(7)
        vocab = 200
        self.word_idx = {f"w{i}": i for i in range(vocab)}
        self.samples = []
        for _ in range(self._synthetic_size):
            n = rng.randint(5, 40)
            seq = rng.randint(0, vocab, size=n).astype("int64")
            label = np.int64(int(seq.mean() > vocab / 2))
            self.samples.append((seq, label))

    def _parse_tar(self):
        pat = f"aclImdb/{self.mode}"
        word_freq = {}
        docs = []
        with tarfile.open(self.data_file) as tf:
            for member in tf.getmembers():
                if not member.name.startswith(pat) or not member.name.endswith(".txt"):
                    continue
                if "/pos/" not in member.name and "/neg/" not in member.name:
                    continue
                text = tf.extractfile(member).read().decode("utf-8", "ignore")
                toks = [t.strip().lower() for t in text.split()]
                docs.append((toks, 1 if "/pos/" in member.name else 0))
                for t in toks:
                    word_freq[t] = word_freq.get(t, 0) + 1
        words = sorted(
            (w for w, c in word_freq.items() if c >= self.cutoff),
            key=lambda w: -word_freq[w],
        )
        word_idx = {w: i for i, w in enumerate(words)}
        unk = len(word_idx)
        samples = [
            (np.array([word_idx.get(t, unk) for t in toks], "int64"), np.int64(y))
            for toks, y in docs
        ]
        return samples, word_idx


class Imikolov(_FileBackedDataset):
    """PTB-style n-gram LM dataset (parity: text/datasets/imikolov.py)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5, mode="train"):
        self.data_type = data_type
        self.window_size = window_size
        super().__init__(data_file, mode)

    def _load(self):
        if self.data_file:
            opener = gzip.open if self.data_file.endswith(".gz") else open
            with opener(self.data_file, "rt") as f:
                lines = [l.split() for l in f]
            vocab = {}
            for l in lines:
                for w in l:
                    vocab[w] = vocab.get(w, 0) + 1
            self.word_idx = {w: i for i, w in enumerate(sorted(vocab))}
            ids = [[self.word_idx[w] for w in l] for l in lines]
        else:
            rng = np.random.RandomState(3)
            self.word_idx = {f"w{i}": i for i in range(50)}
            ids = [rng.randint(0, 50, size=rng.randint(6, 20)).tolist()
                   for _ in range(self._synthetic_size)]
        self.samples = []
        k = self.window_size
        for sent in ids:
            for i in range(len(sent) - k + 1):
                ctx = np.array(sent[i:i + k - 1], "int64")
                tgt = np.int64(sent[i + k - 1])
                self.samples.append((ctx, tgt))


class Movielens(_FileBackedDataset):
    """MovieLens rating prediction (parity: text/datasets/movielens.py).
    Synthetic: (user_id, movie_id, rating) triples."""

    def _load(self):
        rng = np.random.RandomState(11)
        if self.data_file:
            raise NotImplementedError(
                "Movielens archive parsing is not implemented; pass no "
                "data_file for the synthetic sample"
            )
        self.samples = [
            (np.int64(rng.randint(0, 100)), np.int64(rng.randint(0, 500)),
             np.float32(rng.randint(1, 6)))
            for _ in range(self._synthetic_size)
        ]


class _ParallelCorpus(_FileBackedDataset):
    """Shared WMT-style source/target id sequences."""

    src_vocab = 30
    tgt_vocab = 30

    def _load(self):
        if self.data_file:
            raise NotImplementedError(
                f"{type(self).__name__} archive parsing is not implemented; "
                "pass no data_file for the synthetic sample"
            )
        rng = np.random.RandomState(5)
        self.samples = []
        for _ in range(self._synthetic_size):
            n = rng.randint(4, 16)
            src = rng.randint(2, self.src_vocab, size=n).astype("int64")
            tgt = np.concatenate([[0], (src[::-1] % self.tgt_vocab)]).astype("int64")
            self.samples.append((src, tgt[:-1], tgt[1:]))


class WMT14(_ParallelCorpus):
    pass


class WMT16(_ParallelCorpus):
    pass


class Conll05st(_FileBackedDataset):
    """SRL tagging dataset (parity: text/datasets/conll05.py). Synthetic:
    token/predicate/label triples for a small tag set."""

    num_labels = 9

    def _load(self):
        if self.data_file:
            raise NotImplementedError(
                "Conll05st archive parsing is not implemented; pass no "
                "data_file for the synthetic sample"
            )
        rng = np.random.RandomState(13)
        self.samples = []
        for _ in range(self._synthetic_size):
            n = rng.randint(5, 25)
            words = rng.randint(0, 100, size=n).astype("int64")
            pred = np.int64(rng.randint(0, n))
            labels = rng.randint(0, self.num_labels, size=n).astype("int64")
            self.samples.append((words, pred, labels))
