"""Text datasets (parity: python/paddle/text/datasets/ — Conll05st, Imdb,
Imikolov, Movielens, UCIHousing, WMT14, WMT16).

This build runs with zero network egress, so datasets load from a local
``data_file`` (the same archive formats the reference downloads) or, for
quick experiments and tests, generate a deterministic synthetic sample with
``mode='synthetic'``-compatible behavior when no file is given.
"""
from __future__ import annotations

import gzip
import os
import tarfile
from typing import Optional

import numpy as np

from ...io.dataset import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]


class _FileBackedDataset(Dataset):
    """Shared plumbing: explicit data_file, else deterministic synthetic."""

    _synthetic_size = 64

    def __init__(self, data_file: Optional[str] = None, mode: str = "train"):
        assert mode in ("train", "test", "dev"), f"bad mode {mode}"
        self.mode = mode
        self.data_file = data_file
        if data_file is not None and not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{type(self).__name__}: data_file {data_file!r} not found; "
                "downloads are disabled in this environment — place the "
                "reference archive locally and pass data_file="
            )
        self._load()

    def _load(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        return self.samples[idx]


class UCIHousing(_FileBackedDataset):
    """Boston housing regression (parity: text/datasets/uci_housing.py).
    File format: whitespace-separated floats, 14 columns."""

    FEATURE_DIM = 13

    def _load(self):
        if self.data_file:
            raw = np.loadtxt(self.data_file)
        else:
            rng = np.random.RandomState(42)
            x = rng.rand(self._synthetic_size, self.FEATURE_DIM)
            w = np.linspace(-2, 2, self.FEATURE_DIM)
            y = x @ w + 0.1 * rng.randn(self._synthetic_size)
            raw = np.concatenate([x, y[:, None]], axis=1)
        # reference normalizes features by train-split statistics
        feats = raw[:, :-1].astype("float32")
        feats = (feats - feats.mean(0)) / np.maximum(feats.std(0), 1e-6)
        labels = raw[:, -1:].astype("float32")
        split = int(0.8 * len(raw))
        sl = slice(0, split) if self.mode == "train" else slice(split, None)
        self.samples = [(feats[i], labels[i]) for i in range(*sl.indices(len(raw)))]


class Imdb(_FileBackedDataset):
    """IMDB sentiment (parity: text/datasets/imdb.py). data_file: aclImdb
    tar.gz; synthetic: token-id sequences with sign-of-sum labels."""

    def __init__(self, data_file=None, mode="train", cutoff: int = 150):
        self.cutoff = cutoff
        super().__init__(data_file, mode)

    def _load(self):
        if self.data_file:
            self.samples, self.word_idx = self._parse_tar()
            return
        rng = np.random.RandomState(7)
        vocab = 200
        self.word_idx = {f"w{i}": i for i in range(vocab)}
        self.samples = []
        for _ in range(self._synthetic_size):
            n = rng.randint(5, 40)
            seq = rng.randint(0, vocab, size=n).astype("int64")
            label = np.int64(int(seq.mean() > vocab / 2))
            self.samples.append((seq, label))

    def _parse_tar(self):
        pat = f"aclImdb/{self.mode}"
        word_freq = {}
        docs = []
        with tarfile.open(self.data_file) as tf:
            for member in tf.getmembers():
                if not member.name.startswith(pat) or not member.name.endswith(".txt"):
                    continue
                if "/pos/" not in member.name and "/neg/" not in member.name:
                    continue
                text = tf.extractfile(member).read().decode("utf-8", "ignore")
                toks = [t.strip().lower() for t in text.split()]
                docs.append((toks, 1 if "/pos/" in member.name else 0))
                for t in toks:
                    word_freq[t] = word_freq.get(t, 0) + 1
        words = sorted(
            (w for w, c in word_freq.items() if c >= self.cutoff),
            key=lambda w: -word_freq[w],
        )
        word_idx = {w: i for i, w in enumerate(words)}
        unk = len(word_idx)
        samples = [
            (np.array([word_idx.get(t, unk) for t in toks], "int64"), np.int64(y))
            for toks, y in docs
        ]
        return samples, word_idx


class Imikolov(_FileBackedDataset):
    """PTB-style n-gram LM dataset (parity: text/datasets/imikolov.py)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5, mode="train"):
        self.data_type = data_type
        self.window_size = window_size
        super().__init__(data_file, mode)

    def _load(self):
        if self.data_file:
            opener = gzip.open if self.data_file.endswith(".gz") else open
            with opener(self.data_file, "rt") as f:
                lines = [l.split() for l in f]
            vocab = {}
            for l in lines:
                for w in l:
                    vocab[w] = vocab.get(w, 0) + 1
            self.word_idx = {w: i for i, w in enumerate(sorted(vocab))}
            ids = [[self.word_idx[w] for w in l] for l in lines]
        else:
            rng = np.random.RandomState(3)
            self.word_idx = {f"w{i}": i for i in range(50)}
            ids = [rng.randint(0, 50, size=rng.randint(6, 20)).tolist()
                   for _ in range(self._synthetic_size)]
        self.samples = []
        k = self.window_size
        for sent in ids:
            for i in range(len(sent) - k + 1):
                ctx = np.array(sent[i:i + k - 1], "int64")
                tgt = np.int64(sent[i + k - 1])
                self.samples.append((ctx, tgt))


class Movielens(_FileBackedDataset):
    """MovieLens rating prediction (parity: text/datasets/movielens.py,
    which parses the ml-1m archive: ``ratings.dat`` / ``users.dat`` /
    ``movies.dat`` with ``::``-separated fields). ``data_file``: the ml-1m
    zip (or a directory with the .dat files). Samples mirror the reference:
    (user_id, gender_id, age_id, job_id, movie_id, rating)."""

    AGES = [1, 18, 25, 35, 45, 50, 56]

    def _load(self):
        if self.data_file:
            self.samples = self._parse_ml1m()
            return
        rng = np.random.RandomState(11)
        self.samples = [
            (np.int64(rng.randint(0, 100)), np.int64(rng.randint(0, 2)),
             np.int64(rng.randint(0, 7)), np.int64(rng.randint(0, 21)),
             np.int64(rng.randint(0, 500)), np.float32(rng.randint(1, 6)))
            for _ in range(self._synthetic_size)
        ]

    def _read_member(self, name):
        import io
        import zipfile

        if os.path.isdir(self.data_file):
            with open(os.path.join(self.data_file, name), "rb") as f:
                return io.TextIOWrapper(io.BytesIO(f.read()),
                                        encoding="latin-1").readlines()
        with zipfile.ZipFile(self.data_file) as z:
            cand = [n for n in z.namelist() if n.endswith(name)]
            if not cand:
                raise FileNotFoundError(f"{name} not in {self.data_file}")
            return io.TextIOWrapper(io.BytesIO(z.read(cand[0])),
                                    encoding="latin-1").readlines()

    def _parse_ml1m(self):
        age_idx = {a: i for i, a in enumerate(self.AGES)}
        users = {}
        for line in self._read_member("users.dat"):
            parts = line.strip().split("::")
            if len(parts) < 4:
                continue
            uid, gender, age, job = parts[0], parts[1], int(parts[2]), int(parts[3])
            users[uid] = (np.int64(0 if gender == "M" else 1),
                          np.int64(age_idx.get(age, 0)), np.int64(job))
        samples = []
        for line in self._read_member("ratings.dat"):
            parts = line.strip().split("::")
            if len(parts) < 3 or parts[0] not in users:
                continue
            g, a, j = users[parts[0]]
            samples.append((np.int64(parts[0]), g, a, j,
                            np.int64(parts[1]), np.float32(parts[2])))
        return samples


class _ParallelCorpus(_FileBackedDataset):
    """Shared WMT-style parallel corpus (parity: text/datasets/wmt14.py /
    wmt16.py — tarballs of parallel ``<split>.src`` / ``<split>.trg`` token
    files). ``data_file``: a tar(.gz) holding ``{mode}.src``/``{mode}.trg``
    (or the reference's ``train/train.fr-en.{fr,en}``-style pairs — any two
    same-stem members with distinct suffixes). Samples are
    (src_ids, trg_ids[:-1], trg_ids[1:]) with <s>=0 </s>=1 <unk>=2."""

    src_vocab = 30
    tgt_vocab = 30
    BOS, EOS, UNK = 0, 1, 2

    def _load(self):
        if self.data_file:
            self.samples = self._parse_tar()
            return
        rng = np.random.RandomState(5)
        self.samples = []
        for _ in range(self._synthetic_size):
            n = rng.randint(4, 16)
            src = rng.randint(2, self.src_vocab, size=n).astype("int64")
            tgt = np.concatenate([[0], (src[::-1] % self.tgt_vocab)]).astype("int64")
            self.samples.append((src, tgt[:-1], tgt[1:]))

    def _build_vocab(self, lines):
        freq = {}
        for line in lines:
            for w in line.split():
                freq[w] = freq.get(w, 0) + 1
        idx = {"<s>": self.BOS, "<e>": self.EOS, "<unk>": self.UNK}
        for w in sorted(freq, key=lambda w: (-freq[w], w)):
            idx.setdefault(w, len(idx))
        return idx

    def _parse_tar(self):
        pairs = {}
        with tarfile.open(self.data_file) as tf:
            for m in tf.getmembers():
                if not m.isfile():
                    continue
                base = os.path.basename(m.name)
                stem, _, suffix = base.rpartition(".")
                if self.mode not in base or not suffix:
                    continue
                pairs.setdefault(stem, {})[suffix] = [
                    l.decode("utf-8", "ignore").strip()
                    for l in tf.extractfile(m).read().splitlines()
                ]
        two = next((v for v in pairs.values() if len(v) >= 2), None)
        if two is None:
            raise ValueError(
                f"no parallel '{self.mode}' member pair in {self.data_file}")
        suffixes = sorted(two)
        src_lines, trg_lines = two[suffixes[0]], two[suffixes[1]]
        self.src_idx = self._build_vocab(src_lines)
        self.trg_idx = self._build_vocab(trg_lines)
        samples = []
        for s, t in zip(src_lines, trg_lines):
            if not s or not t:
                continue
            src = np.array([self.src_idx.get(w, self.UNK) for w in s.split()],
                           "int64")
            trg = np.array(
                [self.BOS] + [self.trg_idx.get(w, self.UNK) for w in t.split()]
                + [self.EOS], "int64")
            samples.append((src, trg[:-1], trg[1:]))
        return samples


class WMT14(_ParallelCorpus):
    pass


class WMT16(_ParallelCorpus):
    pass


class Conll05st(_FileBackedDataset):
    """SRL tagging dataset (parity: text/datasets/conll05.py). Synthetic:
    token/predicate/label triples for a small tag set."""

    num_labels = 9

    def _load(self):
        if self.data_file:
            self.samples = self._parse()
            return
        rng = np.random.RandomState(13)
        self.samples = []
        for _ in range(self._synthetic_size):
            n = rng.randint(5, 25)
            words = rng.randint(0, 100, size=n).astype("int64")
            pred = np.int64(rng.randint(0, n))
            labels = rng.randint(0, self.num_labels, size=n).astype("int64")
            self.samples.append((words, pred, labels))

    def _parse(self):
        """CoNLL column format (word / predicate-marker / SRL tag per line,
        blank line between sentences), optionally gzipped — the reference's
        words/props file pair flattened into one file per split."""
        opener = gzip.open if self.data_file.endswith(".gz") else open
        with opener(self.data_file, "rt") as f:
            lines = [l.rstrip("\n") for l in f]
        word_idx, label_idx = {}, {}
        sents, cur = [], []
        for line in lines + [""]:
            if not line.strip():
                if cur:
                    sents.append(cur)
                cur = []
                continue
            cols = line.split()
            cur.append((cols[0].lower(), cols[1] if len(cols) > 1 else "-",
                        cols[2] if len(cols) > 2 else "O"))
        samples = []
        for sent in sents:
            for w, _, t in sent:
                word_idx.setdefault(w, len(word_idx))
                label_idx.setdefault(t, len(label_idx))
            words = np.array([word_idx[w] for (w, _, _) in sent], "int64")
            marks = [i for i, (_, m, _) in enumerate(sent) if m != "-"]
            pred = np.int64(marks[0] if marks else 0)
            labels = np.array([label_idx[t] for (_, _, t) in sent], "int64")
            samples.append((words, pred, labels))
        self.word_idx, self.label_idx = word_idx, label_idx
        return samples
