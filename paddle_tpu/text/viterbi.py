"""Viterbi decoding for linear-chain CRF tagging.

Parity: viterbi_decode op (reference
/root/reference/paddle/fluid/operators/... viterbi-family; crf_decoding
operators/crf_decoding_op.h) — max-sum dynamic program over a transition
matrix with optional start/stop augmentation via include_bos_eos_tag.

TPU-native: the DP recurrence is a ``jax.lax.scan`` over time (compiles to a
single fused loop on device; no per-step host dispatch), batched over
sequences, with length masking instead of LoD.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops._primitive import primitive, unwrap, wrap

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi_raw(potentials, transition, lengths, include_bos_eos_tag=True):
    """potentials: (B, T, N) emission scores; transition: (N, N);
    lengths: (B,) int. Returns (scores (B,), paths (B, T) int64)."""
    B, T, N = potentials.shape
    trans = transition
    if include_bos_eos_tag:
        # reference convention: tag N-2 = BOS, N-1 = EOS
        start_mask = transition[N - 2]
        stop_vec = transition[:, N - 1]
    else:
        start_mask = jnp.zeros((N,), potentials.dtype)
        stop_vec = jnp.zeros((N,), potentials.dtype)

    alpha0 = potentials[:, 0, :] + (start_mask if include_bos_eos_tag else 0.0)

    def step(carry, t):
        alpha, _ = carry
        emit = potentials[:, t, :]                       # (B, N)
        scores = alpha[:, :, None] + trans[None, :, :] + emit[:, None, :]
        best_prev = jnp.argmax(scores, axis=1)           # (B, N)
        new_alpha = jnp.max(scores, axis=1)              # (B, N)
        active = (t < lengths)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        return (new_alpha, None), jnp.where(active, best_prev, -1)

    (alpha, _), backptrs = jax.lax.scan(
        step, (alpha0, None), jnp.arange(1, T)
    )  # backptrs: (T-1, B, N)

    final = alpha + (stop_vec[None, :] if include_bos_eos_tag else 0.0)
    scores = jnp.max(final, axis=-1)
    last_tag = jnp.argmax(final, axis=-1)                # (B,)

    def backtrack(carry, bp_t):
        # walk backwards: bp_t is (B, N) pointers for step t
        tag, t_idx, _ = carry
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        new_tag = jnp.where(prev >= 0, prev, tag)
        return (new_tag, t_idx - 1, None), tag

    (first_tag, _, _), rev_tags = jax.lax.scan(
        backtrack, (last_tag, T - 2, None), backptrs, reverse=True
    )  # rev_tags: (T-1, B) tags for positions 1..T-1
    paths = jnp.concatenate([first_tag[None, :], rev_tags], axis=0)  # (T, B)
    paths = jnp.transpose(paths).astype(jnp.int64)        # (B, T)
    # positions past each sequence's length: repeat last valid tag -> mask to 0
    pos = jnp.arange(T)[None, :]
    paths = jnp.where(pos < lengths[:, None], paths, 0)
    return scores, paths


@primitive(nondiff=True)
def _viterbi_op(potentials, transition, lengths, include_bos_eos_tag):
    return _viterbi_raw(potentials, transition, lengths, include_bos_eos_tag)


def viterbi_decode(potentials, transition_params, lengths, include_bos_eos_tag=True):
    """Returns (scores, paths) — best-path scores and tag sequences."""
    return _viterbi_op(potentials, transition_params, lengths, include_bos_eos_tag)


class ViterbiDecoder:
    """Layer-style wrapper holding the transition matrix."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(
            potentials, self.transitions, lengths, self.include_bos_eos_tag
        )
