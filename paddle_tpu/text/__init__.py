"""paddle_tpu.text — text datasets and sequence decoding.

Parity: python/paddle/text (reference text/__init__.py exposes datasets;
viterbi_decode op is operators/viterbi_decode_op.* with
paddle.text.ViterbiDecoder in later versions).
"""
from . import datasets  # noqa: F401
from .datasets import (  # noqa: F401
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
    UCIHousing,
    WMT14,
    WMT16,
)
from .tokenizer import BasicTokenizer, BertTokenizer, WordpieceTokenizer  # noqa: F401
from .viterbi import ViterbiDecoder, viterbi_decode  # noqa: F401

__all__ = [
    "datasets",
    "Conll05st",
    "Imdb",
    "Imikolov",
    "Movielens",
    "UCIHousing",
    "WMT14",
    "WMT16",
    "BasicTokenizer",
    "BertTokenizer",
    "WordpieceTokenizer",
    "ViterbiDecoder",
    "viterbi_decode",
]
