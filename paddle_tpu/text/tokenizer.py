"""BERT-style tokenization: basic + wordpiece + a batch-encoding front end.

Parity: the reference's ``faster_tokenizer`` C++ op
(/root/reference/paddle/fluid/operators/string/faster_tokenizer_op.cc wraps
BertTokenizer: BasicTokenizer whitespace/punct/CJK/accent handling +
WordpieceTokenizer greedy longest-match with '##' continuation) — here a
host-side tokenizer whose output feeds device arrays; tokenization is I/O
preprocessing and stays on the host in the TPU design (the device never
sees strings).
"""
from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

__all__ = ["BasicTokenizer", "WordpieceTokenizer", "BertTokenizer"]


def _is_whitespace(ch):
    return ch in " \t\n\r" or unicodedata.category(ch) == "Zs"


def _is_control(ch):
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch):
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp):
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


class BasicTokenizer:
    """Whitespace/punct/CJK split with optional lowercasing+accent strip
    (reference BasicTokenizer semantics)."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        out_chars = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            if _is_cjk(cp):
                out_chars.extend([" ", ch, " "])
            elif _is_whitespace(ch):
                out_chars.append(" ")
            else:
                out_chars.append(ch)
        tokens = []
        for tok in "".join(out_chars).split():
            if self.do_lower_case:
                tok = tok.lower()
                tok = "".join(c for c in unicodedata.normalize("NFD", tok)
                              if unicodedata.category(c) != "Mn")
            cur = []
            for ch in tok:
                if _is_punctuation(ch):
                    if cur:
                        tokens.append("".join(cur))
                        cur = []
                    tokens.append(ch)
                else:
                    cur.append(ch)
            if cur:
                tokens.append("".join(cur))
        return tokens


class WordpieceTokenizer:
    """Greedy longest-match-first subword split with '##' continuations."""

    def __init__(self, vocab: Dict[str, int], unk_token: str = "[UNK]",
                 max_input_chars_per_word: int = 100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars = max_input_chars_per_word

    def tokenize(self, token: str) -> List[str]:
        if len(token) > self.max_chars:
            return [self.unk_token]
        out = []
        start = 0
        while start < len(token):
            end = len(token)
            cur = None
            while start < end:
                piece = token[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = piece
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            out.append(cur)
            start = end
        return out


class BertTokenizer:
    """vocab-file-driven end-to-end tokenizer + batch encoder (parity:
    faster_tokenizer op output contract: input_ids + token_type_ids with
    [CLS]/[SEP], truncation and padding)."""

    def __init__(self, vocab: Union[str, Dict[str, int], Sequence[str]],
                 do_lower_case: bool = True, unk_token: str = "[UNK]",
                 cls_token: str = "[CLS]", sep_token: str = "[SEP]",
                 pad_token: str = "[PAD]"):
        if isinstance(vocab, str):
            with open(vocab, encoding="utf-8") as f:
                words = [l.rstrip("\n") for l in f]
            self.vocab = {w: i for i, w in enumerate(words)}
        elif isinstance(vocab, dict):
            self.vocab = dict(vocab)
        else:
            self.vocab = {w: i for i, w in enumerate(vocab)}
        self.inv_vocab = {i: w for w, i in self.vocab.items()}
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(self.vocab, unk_token)
        self.unk_token, self.cls_token = unk_token, cls_token
        self.sep_token, self.pad_token = sep_token, pad_token

    def tokenize(self, text: str) -> List[str]:
        out = []
        for tok in self.basic.tokenize(text):
            out.extend(self.wordpiece.tokenize(tok))
        return out

    def convert_tokens_to_ids(self, tokens: Sequence[str]) -> List[int]:
        unk = self.vocab.get(self.unk_token, 0)
        return [self.vocab.get(t, unk) for t in tokens]

    def convert_ids_to_tokens(self, ids: Sequence[int]) -> List[str]:
        return [self.inv_vocab.get(int(i), self.unk_token) for i in ids]

    def __call__(self, text: Union[str, Sequence[str]],
                 text_pair: Optional[Union[str, Sequence[str]]] = None,
                 max_seq_len: Optional[int] = None,
                 pad_to_max_seq_len: bool = False):
        """Batch encode → {'input_ids', 'token_type_ids'} int64 arrays
        (lists when unpadded; the faster_tokenizer op contract)."""
        single = isinstance(text, str)
        texts = [text] if single else list(text)
        pairs = ([text_pair] if isinstance(text_pair, str)
                 else list(text_pair) if text_pair is not None
                 else [None] * len(texts))
        cls_id = self.vocab.get(self.cls_token, 0)
        sep_id = self.vocab.get(self.sep_token, 0)
        pad_id = self.vocab.get(self.pad_token, 0)
        all_ids, all_types = [], []
        for t, p in zip(texts, pairs):
            ids_a = self.convert_tokens_to_ids(self.tokenize(t))
            ids_b = self.convert_tokens_to_ids(self.tokenize(p)) if p else []
            if max_seq_len:
                budget = max_seq_len - 2 - (1 if ids_b else 0)
                if ids_b:
                    # longest-first truncation
                    while len(ids_a) + len(ids_b) > budget:
                        (ids_a if len(ids_a) >= len(ids_b) else ids_b).pop()
                else:
                    ids_a = ids_a[:budget]
            ids = [cls_id] + ids_a + [sep_id]
            types = [0] * len(ids)
            if ids_b:
                ids += ids_b + [sep_id]
                types += [1] * (len(ids_b) + 1)
            if max_seq_len and pad_to_max_seq_len:
                ids += [pad_id] * (max_seq_len - len(ids))
                types += [0] * (max_seq_len - len(types))
            all_ids.append(ids)
            all_types.append(types)
        if max_seq_len and pad_to_max_seq_len:
            out = {"input_ids": np.asarray(all_ids, "int64"),
                   "token_type_ids": np.asarray(all_types, "int64")}
        else:
            out = {"input_ids": all_ids, "token_type_ids": all_types}
        if single:
            return {k: v[0] for k, v in out.items()}
        return out
