"""Default dtype (parity: paddle.set_default_dtype/get_default_dtype)."""
from __future__ import annotations

from ..dtype import convert_dtype

_default_dtype = "float32"


def set_default_dtype(d):
    global _default_dtype
    name = convert_dtype(d)
    if name not in ("float16", "bfloat16", "float32", "float64"):
        raise TypeError(f"set_default_dtype only accepts float types, got {d}")
    _default_dtype = name


def get_default_dtype() -> str:
    return _default_dtype
