"""Global int64 stats registry.

Parity role: ``platform::Monitor`` / ``STAT_ADD``/``STAT_INT64`` counters
(reference: paddle/fluid/platform/monitor.h) — a process-wide named-counter
table used for lightweight observability (e.g. STAT_GPU_MEM). The TPU build
keeps the same shape and seeds it with host/device memory and step counters
that the DataLoader, trainer and profiler update.
"""
from __future__ import annotations

import threading
from typing import Dict

__all__ = ["stat_add", "stat_set", "stat_get", "stat_reset", "all_stats"]

_lock = threading.Lock()
_stats: Dict[str, int] = {}


def stat_add(name: str, value: int = 1) -> int:
    with _lock:
        _stats[name] = _stats.get(name, 0) + int(value)
        return _stats[name]


def stat_set(name: str, value: int) -> None:
    with _lock:
        _stats[name] = int(value)


def stat_get(name: str) -> int:
    with _lock:
        return _stats.get(name, 0)


def stat_reset(name: str = None) -> None:
    with _lock:
        if name is None:
            _stats.clear()
        else:
            _stats.pop(name, None)


def all_stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats)
