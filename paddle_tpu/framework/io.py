"""paddle.save / paddle.load — pickled state dicts.

Parity: /root/reference/python/paddle/framework/io.py:553 (save), :769 (load)
— pickled nested dicts of tensors (Layer.state_dict / Optimizer.state_dict),
>4GB protocol, path conventions (.pdparams / .pdopt by convention only).

TPU-native: tensors serialize as numpy arrays (device-independent); loading
device-puts lazily on first use (jax default device).
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..tensor import Tensor

__all__ = ["save", "load"]

_PROTOCOL = 4


class _TensorPayload:
    """Pickle surrogate: stores the numpy value + tensor metadata."""

    def __init__(self, array: np.ndarray, stop_gradient: bool, name):
        self.array = array
        self.stop_gradient = stop_gradient
        self.name = name


def _pack(obj: Any):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data), obj.stop_gradient, obj.name)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj: Any, return_numpy: bool):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        t = Tensor(obj.array, stop_gradient=obj.stop_gradient, name=obj.name)
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = _PROTOCOL, **configs):
    if isinstance(path, str):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump(_pack(obj), f, protocol=protocol)
    else:  # file-like
        pickle.dump(_pack(obj), path, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs):
    if isinstance(path, str):
        with open(path, "rb") as f:
            obj = pickle.load(f)
    else:
        obj = pickle.load(path)
    return _unpack(obj, return_numpy)
