"""paddle_tpu.framework — save/load, defaults, misc framework surface."""
from .io import load, save  # noqa: F401
from .dtype_default import get_default_dtype, set_default_dtype  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from . import monitor  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import CheckpointManager, load_checkpoint, save_checkpoint  # noqa: F401
