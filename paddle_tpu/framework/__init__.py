"""paddle_tpu.framework — save/load, defaults, misc framework surface."""
from .io import load, save  # noqa: F401
from .dtype_default import get_default_dtype, set_default_dtype  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from . import monitor  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import CheckpointManager, load_checkpoint, save_checkpoint  # noqa: F401


def disable_signal_handler():
    """No-op on TPU (parity: fluid.framework.disable_signal_handler — the
    reference unhooks its C++ fault handlers; jax installs none we own)."""


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Configure numpy print options used for Tensor reprs (parity:
    paddle.set_printoptions)."""
    import numpy as np

    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)
