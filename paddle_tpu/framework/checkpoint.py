"""Sharded training checkpoints with atomic snapshots and reshard-on-load.

Parity: the reference checkpoint stack — ``paddle.save/load`` pickled state
(/root/reference/python/paddle/framework/io.py:553,769), static
``save/load_persistables`` (fluid/io.py:1847), fleet ``save_persistables``
(fleet/base/fleet_base.py:1234 region) and the auto-checkpoint snapshot layer
(incubate/checkpoint/checkpoint_saver.py).

TPU-native redesign: state is a pytree of jax arrays that may be sharded over
a ``jax.sharding.Mesh``. Each array is saved with its PartitionSpec so a later
load can re-place it on the *current* mesh — topology changes between save and
load (the reference's reshard.py concern) reduce to a fresh ``device_put``.
Snapshots are written to a temp dir then atomically renamed (crash-safe), old
snapshots pruned, and saving can run on a background thread (async save like
the reference's async checkpoint saver).
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..tensor import Tensor

__all__ = ["CheckpointManager", "save_checkpoint", "load_checkpoint"]

_META = "meta.json"
_ARRAYS = "arrays.npz"
_PYTREE = "pytree.json"


def _py_default(obj):
    """JSON fallback for numpy scalars in pyvals. Arbitrary objects are
    rejected on purpose: the pytree blob is plain JSON so loading an
    untrusted checkpoint can never execute code (arrays already load via
    np.load(allow_pickle=False))."""
    if isinstance(obj, np.generic):
        return obj.item()
    raise TypeError(
        f"checkpoint python values must be JSON-serializable, got "
        f"{type(obj).__name__}; convert it before saving")


def _spec_of(arr) -> Optional[list]:
    sh = getattr(arr, "sharding", None)
    spec = getattr(sh, "spec", None)
    if spec is None:
        return None
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append(list(entry))
        else:
            out.append(entry)
    return out


def _flatten_state(state):
    """Flatten a pytree into path->leaf, unwrapping Tensors."""
    flat = {}

    def walk(prefix, obj):
        if isinstance(obj, Tensor):
            flat[prefix] = obj._data
        elif isinstance(obj, (jax.Array, np.ndarray)):
            flat[prefix] = obj
        elif isinstance(obj, dict):
            for k in sorted(obj, key=str):
                walk(f"{prefix}/{k}", obj[k])
        elif isinstance(obj, (list, tuple)):
            for i, v in enumerate(obj):
                walk(f"{prefix}/{i}", v)
        else:
            flat[prefix] = ("__py__", obj)
    walk("", state)
    return flat


class CheckpointManager:
    """Step-keyed snapshot directory: ``<dir>/step_<N>/``.

    ``state`` may be any nesting of dict/list/tuple holding Tensors, jax/numpy
    arrays, and JSON-serializable python values (steps, RNG seeds, dataloader
    cursors); the structure blob is plain JSON so loading a checkpoint never
    executes code.
    """

    def __init__(self, directory: str, keep_max: int = 3, async_save: bool = False):
        self.directory = directory
        self.keep_max = keep_max
        self.async_save = async_save
        self._thread: Optional[threading.Thread] = None
        os.makedirs(directory, exist_ok=True)

    # -- save -----------------------------------------------------------
    def save(self, step: int, state: Any, metadata: Optional[Dict] = None):
        flat = _flatten_state(state)
        # materialize on host NOW (so async write sees a consistent snapshot)
        arrays = {}
        pyvals = {}
        specs = {}
        prng_keys = []
        for path, leaf in flat.items():
            if isinstance(leaf, tuple) and len(leaf) == 2 and leaf[0] == "__py__":
                pyvals[path] = leaf[1]
                continue
            spec = _spec_of(leaf)
            if spec is not None:
                specs[path] = spec
            if isinstance(leaf, jax.Array) and jax.numpy.issubdtype(
                leaf.dtype, jax.dtypes.prng_key
            ):
                arrays[path] = np.asarray(jax.random.key_data(leaf))
                prng_keys.append(path)
            else:
                arrays[path] = np.asarray(leaf)
        treedef = _TreeSpec.from_state(state)
        # serialize the structure blob NOW, on the caller's thread: a
        # non-JSON value must raise here, not vanish inside the async writer
        tree_blob = json.dumps({"treedef": treedef.to_json(),
                                "pyvals": pyvals}, default=_py_default)
        meta_blob = json.dumps({"step": step, "specs": specs,
                                "prng_keys": prng_keys,
                                "metadata": metadata or {}},
                               default=_py_default)

        if self.async_save:
            self.wait()
            self._thread = threading.Thread(
                target=self._write,
                args=(step, arrays, tree_blob, meta_blob),
                daemon=True,
            )
            self._thread.start()
        else:
            self._write(step, arrays, tree_blob, meta_blob)

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _write(self, step, arrays, tree_blob, meta_blob):
        final = os.path.join(self.directory, f"step_{step}")
        tmp = tempfile.mkdtemp(prefix=f".tmp_step_{step}_", dir=self.directory)
        try:
            with open(os.path.join(tmp, _ARRAYS), "wb") as f:
                np.savez(f, **{k.replace("/", "|"): v for k, v in arrays.items()})
            with open(os.path.join(tmp, _PYTREE), "w") as f:
                f.write(tree_blob)
            with open(os.path.join(tmp, _META), "w") as f:
                f.write(meta_blob)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)  # atomic publish
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._prune()

    def _prune(self):
        steps = self.all_steps()
        for s in steps[: -self.keep_max] if self.keep_max else []:
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"),
                          ignore_errors=True)

    # -- load -----------------------------------------------------------
    def all_steps(self):
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_"):
                try:
                    out.append(int(name[len("step_"):]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def load(self, step: Optional[int] = None, mesh=None):
        """Rebuild the state pytree; sharded arrays are re-placed on ``mesh``
        (default: the current global mesh) per their saved PartitionSpec —
        the spec is validated against the mesh so a topology change reshards
        instead of failing."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        d = os.path.join(self.directory, f"step_{step}")
        with open(os.path.join(d, _META)) as f:
            meta = json.load(f)
        tree_path = os.path.join(d, _PYTREE)
        if not os.path.exists(tree_path) and os.path.exists(
                os.path.join(d, "pytree.pkl")):
            raise RuntimeError(
                f"{d} holds a legacy pickle-format checkpoint; the pickle "
                "format was dropped (loading untrusted pickles can execute "
                "code). Re-save it with the current version, or load the "
                "arrays directly from arrays.npz.")
        with open(tree_path) as f:
            raw = json.load(f)
        tree = {"treedef": _TreeSpec.from_json(raw["treedef"]),
                "pyvals": raw["pyvals"]}
        data = np.load(os.path.join(d, _ARRAYS), allow_pickle=False)

        if mesh is None:
            from ..distributed.env import get_mesh

            mesh = get_mesh()

        prng_keys = set(meta.get("prng_keys", ()))
        arrays = {}
        for key in data.files:
            path = key.replace("|", "/")
            arr = data[key]
            if path in prng_keys:
                arrays[path] = jax.random.wrap_key_data(jax.numpy.asarray(arr))
                continue
            spec = meta["specs"].get(path)
            if spec is not None and mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                from ..distributed.spmd import sanitize_spec

                entries = [tuple(e) if isinstance(e, list) else e for e in spec]
                ps = sanitize_spec(PartitionSpec(*entries), mesh)
                arrays[path] = jax.device_put(arr, NamedSharding(mesh, ps))
            else:
                arrays[path] = jax.numpy.asarray(arr)
        return tree["treedef"].unflatten(arrays, tree["pyvals"]), meta["metadata"]


class _TreeSpec:
    """JSON-safe structure record mirroring _flatten_state's traversal."""

    def __init__(self, kind, keys=None, children=None):
        self.kind = kind          # 'leaf' | 'py' | 'dict' | 'list' | 'tuple' | 'tensor'
        self.keys = keys
        self.children = children

    def to_json(self):
        out = {"kind": self.kind}
        if self.keys is not None:
            out["keys"] = self.keys
        if self.children is not None:
            out["children"] = [c.to_json() for c in self.children]
        return out

    @classmethod
    def from_json(cls, d):
        children = d.get("children")
        return cls(d["kind"], keys=d.get("keys"),
                   children=[cls.from_json(c) for c in children]
                   if children is not None else None)

    @classmethod
    def from_state(cls, obj):
        if isinstance(obj, Tensor):
            return cls("tensor")
        if isinstance(obj, (jax.Array, np.ndarray)):
            return cls("leaf")
        if isinstance(obj, dict):
            keys = sorted(obj, key=str)
            for k in keys:
                # keys must round-trip through JSON unchanged; tuples etc.
                # would save fine but make the snapshot unloadable
                if not isinstance(k, (str, int, float, bool)):
                    raise TypeError(
                        f"checkpoint dict keys must be str/int/float/bool, "
                        f"got {type(k).__name__}: {k!r}")
            return cls("dict", keys=keys,
                       children=[cls.from_state(obj[k]) for k in keys])
        if isinstance(obj, (list, tuple)):
            return cls("list" if isinstance(obj, list) else "tuple",
                       children=[cls.from_state(v) for v in obj])
        return cls("py")

    def unflatten(self, arrays, pyvals, prefix=""):
        if self.kind == "tensor":
            return Tensor(arrays[prefix])
        if self.kind == "leaf":
            return arrays[prefix]
        if self.kind == "py":
            return pyvals[prefix]
        if self.kind == "dict":
            return {
                k: c.unflatten(arrays, pyvals, f"{prefix}/{k}")
                for k, c in zip(self.keys, self.children)
            }
        vals = [
            c.unflatten(arrays, pyvals, f"{prefix}/{i}")
            for i, c in enumerate(self.children)
        ]
        return vals if self.kind == "list" else tuple(vals)


def save_checkpoint(directory: str, step: int, model=None, optimizer=None,
                    extra: Optional[Dict] = None, keep_max: int = 3,
                    async_save: bool = False):
    """One-call training snapshot: model + optimizer state_dicts + extras
    (parity: fleet.save_persistables + .pdopt side files)."""
    state = {"extra": extra or {}}
    if model is not None:
        state["model"] = dict(model.state_dict())
    if optimizer is not None:
        state["optimizer"] = dict(optimizer.state_dict())
    from ..random import get_rng_state

    state["rng"] = get_rng_state()
    mgr = CheckpointManager(directory, keep_max=keep_max, async_save=async_save)
    mgr.save(step, state)
    mgr.wait()
    return mgr


def load_checkpoint(directory: str, model=None, optimizer=None, step=None, mesh=None):
    """Restore a save_checkpoint snapshot; returns (step, extra)."""
    mgr = CheckpointManager(directory)
    step = step if step is not None else mgr.latest_step()
    if step is None:
        return None, None
    state, _meta = mgr.load(step, mesh=mesh)
    if model is not None and "model" in state:
        model.set_state_dict(state["model"])
    if optimizer is not None and "optimizer" in state:
        optimizer.set_state_dict(state["optimizer"])
    if "rng" in state:
        from ..random import set_rng_state

        try:
            set_rng_state(state["rng"])
        except Exception:
            pass
    return step, state.get("extra", {})
