"""Sharded training checkpoints with atomic snapshots and reshard-on-load.

Parity: the reference checkpoint stack — ``paddle.save/load`` pickled state
(/root/reference/python/paddle/framework/io.py:553,769), static
``save/load_persistables`` (fluid/io.py:1847), fleet ``save_persistables``
(fleet/base/fleet_base.py:1234 region) and the auto-checkpoint snapshot layer
(incubate/checkpoint/checkpoint_saver.py).

TPU-native redesign: state is a pytree of jax arrays that may be sharded over
a ``jax.sharding.Mesh``. Each array is saved with its PartitionSpec so a later
load can re-place it on the *current* mesh — topology changes between save and
load (the reference's reshard.py concern) reduce to a fresh ``device_put``.
Snapshots are written to a temp dir then atomically renamed (crash-safe), old
snapshots pruned, and saving can run on a background thread (async save like
the reference's async checkpoint saver).

Fault tolerance (the resilience layer's storage contract): every array is
stamped with a crc32 checksum in ``meta.json`` at save time; ``load``
re-hashes on read and raises :class:`CheckpointCorruptionError` on mismatch
or on unreadable files, and a ``load()`` without an explicit step falls back
to the newest INTACT snapshot with a warning instead of crashing. Async
writer threads are joined before a new save, on ``wait()``, and at
interpreter exit, so a snapshot is never half-renamed.
"""
from __future__ import annotations

import atexit
import json
import os
import shutil
import tempfile
import threading
import time
import warnings
import weakref
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..tensor import Tensor

__all__ = ["CheckpointManager", "CheckpointCorruptionError",
           "CheckpointReshardError", "build_train_state", "save_checkpoint",
           "load_checkpoint", "reshard_train_state", "shard_bounds",
           "shard_slice", "unshard", "durable_write_bytes"]

_META = "meta.json"
_ARRAYS = "arrays.npz"
_PYTREE = "pytree.json"


def _inject_fire(point: str, **labels):
    """resilience/inject.py hook (lazy import: framework must not pull the
    resilience package in at module-import time)."""
    from ..resilience.inject import fire

    return fire(point, **labels)

# async-writer managers alive in this process: one interpreter-exit hook
# joins them all so a daemon writer thread is never killed mid-write
_LIVE_MANAGERS: "weakref.WeakSet[CheckpointManager]" = weakref.WeakSet()


@atexit.register
def _join_live_managers():
    for mgr in list(_LIVE_MANAGERS):
        try:
            mgr.wait()
        except Exception:
            pass


class CheckpointCorruptionError(RuntimeError):
    """A snapshot failed its integrity check (checksum mismatch, truncated
    or unreadable file). ``load(step=None)`` treats this as "try the next
    older snapshot"; an explicit-step load propagates it."""


class CheckpointReshardError(RuntimeError):
    """The snapshot is INTACT but its sharded layout cannot be mapped onto
    the requested topology (e.g. a dim sharded over dp=3 loaded at dp=2
    with an indivisible extent). Deliberately NOT a corruption error: the
    newest-intact fallback must not walk past it — every older snapshot
    shares the same layout, so retrying older steps only hides the real
    problem."""


def _crc32(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def durable_write_bytes(path: str, data: bytes):
    """THE crash-safe publish protocol for a single file, factored from the
    snapshot writer so the replicated checkpoint data plane
    (:mod:`~paddle_tpu.resilience.durability`) shares one write path:
    write to a dot-temp sibling, flush + fsync, atomically rename onto
    ``path``, then fsync the parent directory so the rename itself is
    durable. A crash at any point leaves either the old file or the new
    one — never a torn published file."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(prefix=f".tmp_{os.path.basename(path)}_",
                               dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    CheckpointManager._fsync_dir(d)


def _check_reshardable(path: str, shape, spec, mesh):
    """Pre-validate a saved PartitionSpec against the CURRENT mesh so a
    topology change that cannot host the array raises
    :class:`CheckpointReshardError` (the snapshot is fine!) instead of an
    opaque XLA sharding failure deep inside device_put."""
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        n = 1
        for ax in axes:
            n *= int(mesh.shape.get(ax, 1))
        if n > 1 and int(shape[dim]) % n:
            raise CheckpointReshardError(
                f"{path}: dim {dim} (extent {shape[dim]}) is sharded over "
                f"mesh axes {tuple(axes)} (total {n} parts) but the extent "
                f"is not divisible on the current mesh "
                f"{dict(mesh.shape)} — the snapshot is intact; pick a "
                f"topology whose axis sizes divide the array")


def _py_default(obj):
    """JSON fallback for numpy scalars in pyvals. Arbitrary objects are
    rejected on purpose: the pytree blob is plain JSON so loading an
    untrusted checkpoint can never execute code (arrays already load via
    np.load(allow_pickle=False))."""
    if isinstance(obj, np.generic):
        return obj.item()
    raise TypeError(
        f"checkpoint python values must be JSON-serializable, got "
        f"{type(obj).__name__}; convert it before saving")


def _spec_of(arr) -> Optional[list]:
    sh = getattr(arr, "sharding", None)
    spec = getattr(sh, "spec", None)
    if spec is None:
        return None
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append(list(entry))
        else:
            out.append(entry)
    return out


def _flatten_state(state):
    """Flatten a pytree into path->leaf, unwrapping Tensors."""
    flat = {}

    def walk(prefix, obj):
        if isinstance(obj, Tensor):
            flat[prefix] = obj._data
        elif isinstance(obj, (jax.Array, np.ndarray)):
            flat[prefix] = obj
        elif isinstance(obj, dict):
            for k in sorted(obj, key=str):
                walk(f"{prefix}/{k}", obj[k])
        elif isinstance(obj, (list, tuple)):
            for i, v in enumerate(obj):
                walk(f"{prefix}/{i}", v)
        else:
            flat[prefix] = ("__py__", obj)
    walk("", state)
    return flat


def shard_bounds(extent: int, world: int) -> List[Tuple[int, int]]:
    """Deterministic 1-D partition of ``extent`` rows over ``world`` ranks:
    the first ``extent % world`` ranks get one extra row (numpy's
    ``array_split`` convention). Shared by the elastic trainer's ZeRO-style
    slot sharding and :func:`reshard_train_state`, so the rank that WRITES
    a shard and the rank that RELOADS it after a topology change always
    agree on the cut points."""
    if world < 1:
        raise ValueError("world must be >= 1")
    base, extra = divmod(int(extent), int(world))
    bounds, start = [], 0
    for r in range(world):
        n = base + (1 if r < extra else 0)
        bounds.append((start, start + n))
        start += n
    return bounds


def shard_slice(arr: np.ndarray, world: int, rank: int,
                axis: int = 0) -> np.ndarray:
    """This rank's partition of a GLOBAL array along ``axis``."""
    lo, hi = shard_bounds(arr.shape[axis], world)[rank]
    idx = [slice(None)] * arr.ndim
    idx[axis] = slice(lo, hi)
    return arr[tuple(idx)]


def unshard(parts: List[np.ndarray], axis: int = 0) -> np.ndarray:
    """Reassemble rank-ordered partitions into the global array."""
    return np.concatenate([np.asarray(p) for p in parts], axis=axis)


def reshard_train_state(state: Any, layout: Dict[str, Dict], world: int,
                        rank: int) -> Any:
    """Slice a GLOBAL train-state pytree into ``rank``'s shard for a
    ``world``-rank data-parallel topology.

    ``layout`` is the snapshot's sharding metadata (``meta.json``'s
    ``layout`` field, written via ``CheckpointManager.save(layout=...)``):
    ``{path: {"axis": dim, "world": N_at_save, "even": bool}}``. Arrays at
    listed paths are global in the snapshot (the saving rank gathered its
    peers' shards first); everything else (replicated params, step
    counters) passes through untouched. ``world`` may differ from the
    save-time world — that is the point: a snapshot saved at dp=N loads at
    dp=N±k by re-cutting the same global arrays.

    ``even=True`` records a layout whose consumer requires equal shards
    (the jax-mesh contract — XLA rejects uneven partitions); an extent the
    new world cannot divide raises :class:`CheckpointReshardError`."""
    if not (0 <= int(rank) < int(world)):
        raise ValueError(f"rank {rank} outside world {world}")
    layout = layout or {}

    def transform(prefix, obj):
        if isinstance(obj, Tensor):
            return Tensor(transform(prefix, obj._data))
        if isinstance(obj, dict):
            return {k: transform(f"{prefix}/{k}", v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            vals = [transform(f"{prefix}/{i}", v) for i, v in enumerate(obj)]
            return vals if isinstance(obj, list) else tuple(vals)
        entry = layout.get(prefix)
        if entry is None or not isinstance(obj, (np.ndarray, jax.Array)):
            return obj
        if "axis" not in entry:
            # a mesh-spec layout (ParallelTrainer.state_layout()'s
            # {"axes", "mesh"} schema) is resharded in-process by the
            # trainer's restore_state — cutting it here as an axis-0 dp
            # shard would silently corrupt model-parallel params
            raise CheckpointReshardError(
                f"{prefix}: layout entry keys {sorted(entry)} are not the "
                f"dp-shard schema {{'axis', 'world', 'even'}}; mesh-spec "
                f"layouts must go through the trainer's restore_state")
        arr = np.asarray(obj)
        axis = int(entry["axis"])
        if entry.get("even") and arr.shape[axis] % int(world):
            raise CheckpointReshardError(
                f"{prefix}: dim {axis} (extent {arr.shape[axis]}) cannot be "
                f"evenly resharded over world={world} (saved at "
                f"world={entry.get('world')})")
        return shard_slice(arr, int(world), int(rank), axis=axis)

    return transform("", state)


class CheckpointManager:
    """Step-keyed snapshot directory: ``<dir>/step_<N>/``.

    ``state`` may be any nesting of dict/list/tuple holding Tensors, jax/numpy
    arrays, and JSON-serializable python values (steps, RNG seeds, dataloader
    cursors); the structure blob is plain JSON so loading a checkpoint never
    executes code.
    """

    def __init__(self, directory: str, keep_max: int = 3, async_save: bool = False):
        self.directory = directory
        self.keep_max = keep_max
        self.async_save = async_save
        # in-flight async writer; guarded-by: self._lock
        self._thread: Optional[threading.Thread] = None
        # RLock, not Lock: a SIGTERM handler (resilience.PreemptionGuard)
        # runs on the main thread and may re-enter save()/wait() while the
        # interrupted frame is already inside them — a plain lock would
        # self-deadlock exactly when the emergency save matters most.
        # It intentionally holds across the in-flight writer join: that
        # serialization is the torn-snapshot guarantee.
        self._lock = threading.RLock()  # hostrace: blocking-ok
        self.last_loaded_step: Optional[int] = None
        self.last_loaded_meta: Optional[Dict] = None
        os.makedirs(directory, exist_ok=True)
        self._sweep_stale_tmp()
        _LIVE_MANAGERS.add(self)

    def _sweep_stale_tmp(self, max_age_s: float = 3600.0):
        """Remove temp dirs abandoned by a writer that died mid-save (the
        atomic-rename protocol means they were never published, so deleting
        them can never lose a snapshot). Age-gated: a sibling process may
        legitimately be mid-write in the same directory."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        # det-ok: stale-tmp GC compares against file mtimes, which are
        # wall-clock by nature; published snapshots are never touched
        now = time.time()
        for name in names:
            if not name.startswith(".tmp_step_"):
                continue
            p = os.path.join(self.directory, name)
            try:
                if now - os.path.getmtime(p) > max_age_s:
                    shutil.rmtree(p, ignore_errors=True)
            except OSError:
                pass

    # -- save -----------------------------------------------------------
    def save(self, step: int, state: Any, metadata: Optional[Dict] = None,
             sync: bool = False, layout: Optional[Dict] = None):
        """Snapshot ``state`` under ``step``. ``sync=True`` forces the write
        onto the caller's thread even for an async manager (the emergency
        preemption path must not race process teardown).

        ``layout`` records per-array data-parallel sharding metadata
        (``{path: {"axis": a, "world": N, "even": bool}}``) for snapshots
        whose arrays were gathered to GLOBAL from sharded ranks — the
        contract :func:`reshard_train_state` consumes to reload the
        snapshot at a different world size."""
        from ..observability import trace as _obs

        with _obs.span("train.checkpoint_save", step=int(step),
                       sync=bool(sync)):
            return self._save_impl(step, state, metadata=metadata,
                                   sync=sync, layout=layout)

    def _save_impl(self, step: int, state: Any,
                   metadata: Optional[Dict] = None, sync: bool = False,
                   layout: Optional[Dict] = None):
        flat = _flatten_state(state)
        # materialize on host NOW (so async write sees a consistent snapshot)
        arrays = {}
        pyvals = {}
        specs = {}
        prng_keys = []
        for path, leaf in flat.items():
            if isinstance(leaf, tuple) and len(leaf) == 2 and leaf[0] == "__py__":
                pyvals[path] = leaf[1]
                continue
            spec = _spec_of(leaf)
            if spec is not None:
                specs[path] = spec
            if isinstance(leaf, jax.Array) and jax.numpy.issubdtype(
                leaf.dtype, jax.dtypes.prng_key
            ):
                arrays[path] = np.asarray(jax.random.key_data(leaf))
                prng_keys.append(path)
            else:
                arrays[path] = np.asarray(leaf)
        treedef = _TreeSpec.from_state(state)
        # serialize the structure blob NOW, on the caller's thread: a
        # non-JSON value must raise here, not vanish inside the async writer
        tree_blob = json.dumps({"treedef": treedef.to_json(),
                                "pyvals": pyvals}, default=_py_default)
        checksums = {path: _crc32(arr) for path, arr in arrays.items()}
        # topology metadata: every array's GLOBAL shape, the save-time mesh
        # axis sizes, and (for gathered-from-ranks snapshots) the explicit
        # dp layout — enough for a later load to resolve dp=N±k resharding
        # instead of assuming the world it was saved under
        mesh_axes: Dict[str, int] = {}
        try:
            from ..distributed.env import get_mesh

            mesh = get_mesh()
            if mesh is not None:
                mesh_axes = {str(k): int(v) for k, v in mesh.shape.items()}
        except Exception:
            pass
        meta_blob = json.dumps({"step": step, "specs": specs,
                                "prng_keys": prng_keys,
                                "checksums": checksums,
                                "tree_crc": zlib.crc32(tree_blob.encode()),
                                "shapes": {p: list(a.shape)
                                           for p, a in arrays.items()},
                                "mesh_axes": mesh_axes,
                                "layout": layout or {},
                                "metadata": metadata or {}},
                               default=_py_default)

        with self._lock:
            # a second save() while a prior write is in flight joins the
            # previous thread FIRST — two writers racing the same step dir
            # (or the prune) could otherwise publish a torn snapshot
            self._join_locked()
            if self.async_save and not sync:
                t = threading.Thread(
                    target=self._write,
                    args=(step, arrays, tree_blob, meta_blob),
                    daemon=True,
                )
                # start BEFORE publishing: a signal handler re-entering
                # wait() on this thread must never join an unstarted thread
                t.start()
                self._thread = t
            else:
                self._write(step, arrays, tree_blob, meta_blob)

    def wait(self):
        """Join any in-flight async write (public: call before reading the
        snapshot back, handing off the directory, or exiting)."""
        with self._lock:
            self._join_locked()

    # hostrace: requires(self._lock)
    def _join_locked(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _write(self, step, arrays, tree_blob, meta_blob):
        final = os.path.join(self.directory, f"step_{step}")
        tmp = tempfile.mkdtemp(prefix=f".tmp_step_{step}_", dir=self.directory)

        def _durable(name, data, mode):
            # write-to-temp + flush + fsync: an os-crash between the data
            # write and the dir rename must never publish a step dir whose
            # files are still in the page cache — that torn state would
            # carry a stale-but-CRC-consistent meta.json next to truncated
            # arrays, defeating the newest-intact fallback
            with open(os.path.join(tmp, name), mode) as f:
                if callable(data):
                    data(f)
                else:
                    f.write(data)
                f.flush()
                os.fsync(f.fileno())

        try:
            _durable(_ARRAYS, lambda f: np.savez(
                f, **{k.replace("/", "|"): v for k, v in arrays.items()}),
                "wb")
            _durable(_PYTREE, tree_blob, "w")
            _durable(_META, meta_blob, "w")
            self._fsync_dir(tmp)
            # injection seam (resilience/inject.py): the checkpoint
            # writer's two classic torn states, made deterministic —
            # crash_after_temp dies here (temp durable, never published;
            # a REAL crash runs no cleanup, so the temp dir stays for the
            # stale sweep), torn truncates the published arrays so the
            # CRC fallback path replays without killing a process
            fault = _inject_fire("checkpoint.write", step=int(step))
            if fault is not None and fault.kind == "crash_after_temp":
                raise fault.build_exception()
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)  # atomic publish
            # make the rename itself durable: the parent dir entry must hit
            # disk before save() reports success (preemption follows fast)
            self._fsync_dir(self.directory)
        except BaseException as e:
            from ..resilience.inject import InjectedCrash

            # a simulated crash must leave the temp dir exactly as a real
            # one would — cleanup code does not run in a dead process
            if not isinstance(e, InjectedCrash):
                shutil.rmtree(tmp, ignore_errors=True)
            raise
        if fault is not None and fault.kind == "torn":
            arr_path = os.path.join(final, _ARRAYS)
            size = os.path.getsize(arr_path)
            with open(arr_path, "r+b") as f:
                f.truncate(max(size // 2, 1))
        self._prune()

    @staticmethod
    def _fsync_dir(path):
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return  # platform without O_RDONLY dir opens: best effort
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)

    def _prune(self):
        """Evict snapshots past ``keep_max`` — but NEVER the newest intact
        one. keep_max counts by step number, so a torn newest publish (a
        crash or an injected ``torn`` fault lands a corrupt step dir ABOVE
        the intact ones) would otherwise rotate every intact snapshot out
        while the only retained dirs are garbage: with keep_max=1, save(1)
        then a torn save(2) must leave step_1 on disk or the newest-intact
        fallback has nothing to fall back to."""
        if not self.keep_max:
            return
        steps = self.all_steps()
        doomed = steps[: -self.keep_max]
        if not doomed:
            return
        if not any(self._intact_light(s) for s in steps[-self.keep_max:]):
            # every retained snapshot is damaged — spare the newest intact
            # one from the doomed range (the fallback loader's lifeline)
            for s in reversed(doomed):
                if self._intact_light(s):
                    doomed = [d for d in doomed if d != s]
                    break
        for s in doomed:
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"),
                          ignore_errors=True)

    def _intact_light(self, step: int) -> bool:
        """Cheap structural intactness probe used by the prune guard:
        meta.json parses, the tree blob matches its CRC, and arrays.npz
        opens as a zip whose member set matches the stamped checksums.
        Deliberately does NOT hash array payloads (that full verify is
        load()'s and the durability scrubber's job) — it only needs to
        catch the torn-publish shapes (truncated/missing files)."""
        d = os.path.join(self.directory, f"step_{step}")
        try:
            with open(os.path.join(d, _META)) as f:
                meta = json.load(f)
            with open(os.path.join(d, _PYTREE)) as f:
                tree_blob = f.read()
            if (meta.get("tree_crc") is not None
                    and zlib.crc32(tree_blob.encode()) != meta["tree_crc"]):
                return False
            data = np.load(os.path.join(d, _ARRAYS), allow_pickle=False)
            checksums = meta.get("checksums")
            if checksums is not None:
                have = {k.replace("|", "/") for k in data.files}
                if have != set(checksums):
                    return False
            return True
        except Exception:
            return False

    # -- load -----------------------------------------------------------
    def all_steps(self):
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_"):
                try:
                    out.append(int(name[len("step_"):]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def load(self, step: Optional[int] = None, mesh=None, verify: bool = True):
        """Rebuild the state pytree; sharded arrays are re-placed on ``mesh``
        (default: the current global mesh) per their saved PartitionSpec —
        the spec is validated against the mesh so a topology change reshards
        instead of failing.

        Integrity: with ``verify`` (default) every array is re-hashed
        against the crc32 stamped at save time. An explicit ``step`` raises
        :class:`CheckpointCorruptionError` on damage; ``step=None`` walks
        newest → oldest and returns the first INTACT snapshot, warning about
        each corrupt one it skips (a preemption mid-write must cost at most
        one snapshot, never the job)."""
        if step is not None:
            return self._load_step(step, mesh, verify)
        steps = self.all_steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        last_err: Optional[Exception] = None
        corrupt_steps: List[int] = []
        for s in reversed(steps):
            try:
                out = self._load_step(s, mesh, verify)
            except (CheckpointCorruptionError, OSError, ValueError,
                    KeyError) as e:
                warnings.warn(
                    f"checkpoint step_{s} in {self.directory} is corrupt "
                    f"({type(e).__name__}: {e}); falling back to the "
                    f"previous snapshot", RuntimeWarning)
                corrupt_steps.append(s)
                last_err = e
                continue
            if corrupt_steps:
                # a corrupt-snapshot fallback is an OPERATIONAL EVENT, not
                # just a warning string: count it next to the serving/
                # elastic series and freeze a flight dump naming the steps
                # skipped and the step actually served
                self._record_corruption_fallback(corrupt_steps, s, last_err)
            return out
        raise CheckpointCorruptionError(
            f"no intact checkpoint in {self.directory} "
            f"(tried steps {steps}): {last_err}")

    def _record_corruption_fallback(self, corrupt_steps: List[int],
                                    loaded_step: int,
                                    err: Optional[Exception]):
        """First-class observability for the newest-intact fallback:
        ``ckpt_corruption_fallbacks_total`` counts every snapshot skipped,
        and one flight dump per load episode records which steps were
        corrupt and which step was loaded instead. Exception-contained —
        the fallback load must win even if telemetry fails."""
        try:
            from ..observability.flight import flight_recorder
            from ..observability.metrics import default_registry

            default_registry().counter(
                "ckpt_corruption_fallbacks_total",
                "corrupt snapshots skipped by the newest-intact fallback",
                ("directory",)).inc(len(corrupt_steps),
                                    directory=self.directory)
            flight_recorder().dump(
                "ckpt_corruption_fallback",
                extra={"directory": self.directory,
                       "corrupt_steps": list(corrupt_steps),
                       "loaded_step": int(loaded_step),
                       "error": f"{type(err).__name__}: {err}"
                       if err is not None else None})
        except Exception:
            pass

    def _load_step(self, step: int, mesh=None, verify: bool = True):
        d = os.path.join(self.directory, f"step_{step}")
        try:
            with open(os.path.join(d, _META)) as f:
                meta = json.load(f)
        except FileNotFoundError:
            raise
        except (OSError, ValueError) as e:
            raise CheckpointCorruptionError(
                f"{d}/{_META} unreadable: {e}") from e
        tree_path = os.path.join(d, _PYTREE)
        if not os.path.exists(tree_path) and os.path.exists(
                os.path.join(d, "pytree.pkl")):
            raise RuntimeError(
                f"{d} holds a legacy pickle-format checkpoint; the pickle "
                "format was dropped (loading untrusted pickles can execute "
                "code). Re-save it with the current version, or load the "
                "arrays directly from arrays.npz.")
        checksums = meta.get("checksums")  # absent on pre-resilience saves
        try:
            with open(tree_path) as f:
                tree_blob = f.read()
            raw = json.loads(tree_blob)
        except FileNotFoundError as e:
            raise CheckpointCorruptionError(f"{tree_path} missing") from e
        except (OSError, ValueError) as e:
            raise CheckpointCorruptionError(
                f"{tree_path} unreadable: {e}") from e
        if (verify and meta.get("tree_crc") is not None
                and zlib.crc32(tree_blob.encode()) != meta["tree_crc"]):
            raise CheckpointCorruptionError(
                f"{tree_path} checksum mismatch (truncated or bit-rotted)")
        tree = {"treedef": _TreeSpec.from_json(raw["treedef"]),
                "pyvals": raw["pyvals"]}
        try:
            data = np.load(os.path.join(d, _ARRAYS), allow_pickle=False)
        except FileNotFoundError as e:
            raise CheckpointCorruptionError(f"{d}/{_ARRAYS} missing") from e
        except Exception as e:  # zipfile.BadZipFile, OSError, ValueError...
            raise CheckpointCorruptionError(
                f"{d}/{_ARRAYS} unreadable: {e}") from e

        if verify and checksums is not None:
            have = {k.replace("|", "/") for k in data.files}
            if have != set(checksums):
                raise CheckpointCorruptionError(
                    f"{d}/{_ARRAYS} array set does not match meta.json "
                    f"(missing: {sorted(set(checksums) - have)[:4]}, "
                    f"extra: {sorted(have - set(checksums))[:4]})")

        if mesh is None:
            from ..distributed.env import get_mesh

            mesh = get_mesh()

        prng_keys = set(meta.get("prng_keys", ()))
        arrays = {}
        for key in data.files:
            path = key.replace("|", "/")
            try:
                arr = data[key]
            except Exception as e:  # truncated member: zip/zlib/EOF errors
                raise CheckpointCorruptionError(
                    f"{d}/{_ARRAYS}[{key}] unreadable: {e}") from e
            if verify and checksums is not None:
                want = checksums.get(path)
                if want is None or _crc32(arr) != want:
                    raise CheckpointCorruptionError(
                        f"{d}/{_ARRAYS}[{key}] checksum mismatch")
            if path in prng_keys:
                arrays[path] = jax.random.wrap_key_data(jax.numpy.asarray(arr))
                continue
            spec = meta["specs"].get(path)
            if spec is not None and mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                from ..distributed.spmd import sanitize_spec

                entries = [tuple(e) if isinstance(e, list) else e for e in spec]
                ps = sanitize_spec(PartitionSpec(*entries), mesh)
                _check_reshardable(path, arr.shape, ps, mesh)
                arrays[path] = jax.device_put(arr, NamedSharding(mesh, ps))
            else:
                arrays[path] = jax.numpy.asarray(arr)
        self.last_loaded_step = step
        self.last_loaded_meta = meta
        return tree["treedef"].unflatten(arrays, tree["pyvals"]), meta["metadata"]


class _TreeSpec:
    """JSON-safe structure record mirroring _flatten_state's traversal."""

    def __init__(self, kind, keys=None, children=None):
        self.kind = kind          # 'leaf' | 'py' | 'dict' | 'list' | 'tuple' | 'tensor'
        self.keys = keys
        self.children = children

    def to_json(self):
        out = {"kind": self.kind}
        if self.keys is not None:
            out["keys"] = self.keys
        if self.children is not None:
            out["children"] = [c.to_json() for c in self.children]
        return out

    @classmethod
    def from_json(cls, d):
        children = d.get("children")
        return cls(d["kind"], keys=d.get("keys"),
                   children=[cls.from_json(c) for c in children]
                   if children is not None else None)

    @classmethod
    def from_state(cls, obj):
        if isinstance(obj, Tensor):
            return cls("tensor")
        if isinstance(obj, (jax.Array, np.ndarray)):
            return cls("leaf")
        if isinstance(obj, dict):
            keys = sorted(obj, key=str)
            for k in keys:
                # keys must round-trip through JSON unchanged; tuples etc.
                # would save fine but make the snapshot unloadable
                if not isinstance(k, (str, int, float, bool)):
                    raise TypeError(
                        f"checkpoint dict keys must be str/int/float/bool, "
                        f"got {type(k).__name__}: {k!r}")
            return cls("dict", keys=keys,
                       children=[cls.from_state(obj[k]) for k in keys])
        if isinstance(obj, (list, tuple)):
            return cls("list" if isinstance(obj, list) else "tuple",
                       children=[cls.from_state(v) for v in obj])
        return cls("py")

    def unflatten(self, arrays, pyvals, prefix=""):
        if self.kind == "tensor":
            return Tensor(arrays[prefix])
        if self.kind == "leaf":
            return arrays[prefix]
        if self.kind == "py":
            return pyvals[prefix]
        if self.kind == "dict":
            return {
                k: c.unflatten(arrays, pyvals, f"{prefix}/{k}")
                for k, c in zip(self.keys, self.children)
            }
        vals = [
            c.unflatten(arrays, pyvals, f"{prefix}/{i}")
            for i, c in enumerate(self.children)
        ]
        return vals if self.kind == "list" else tuple(vals)


def build_train_state(model=None, optimizer=None, scaler=None,
                      extra: Optional[Dict] = None) -> Dict[str, Any]:
    """THE resume-critical state schema — model + optimizer state_dicts,
    GradScaler state, RNG state, extras. Single assembly point shared by
    :func:`save_checkpoint` (periodic snapshots) and
    ``resilience.capture_train_state`` (emergency preemption snapshots), so
    the two kinds of snapshot can never silently diverge."""
    state: Dict[str, Any] = {"extra": extra or {}}
    if model is not None:
        state["model"] = dict(model.state_dict())
    if optimizer is not None:
        state["optimizer"] = dict(optimizer.state_dict())
    if scaler is not None:
        state["scaler"] = scaler.state_dict()
    from ..random import get_rng_state

    state["rng"] = get_rng_state()
    return state


def save_checkpoint(directory: str, step: int, model=None, optimizer=None,
                    extra: Optional[Dict] = None, keep_max: int = 3,
                    async_save: bool = False, scaler=None):
    """One-call training snapshot: model + optimizer state_dicts + GradScaler
    state + extras (parity: fleet.save_persistables + .pdopt side files).
    Persisting the scaler means resume reproduces the exact loss scale and
    good/bad-step counters instead of restarting the dynamic-scale machine."""
    state = build_train_state(model=model, optimizer=optimizer, scaler=scaler,
                              extra=extra)
    mgr = CheckpointManager(directory, keep_max=keep_max, async_save=async_save)
    mgr.save(step, state)
    mgr.wait()
    return mgr


def load_checkpoint(directory: str, model=None, optimizer=None, step=None,
                    mesh=None, scaler=None):
    """Restore a save_checkpoint snapshot; returns (step, extra). With
    ``step=None`` the newest INTACT snapshot wins (corrupt ones are skipped
    with a warning — see CheckpointManager.load)."""
    mgr = CheckpointManager(directory)
    if step is None and mgr.latest_step() is None:
        return None, None
    state, _meta = mgr.load(step, mesh=mesh)
    if step is None:
        step = mgr.last_loaded_step  # may differ from latest_step() if the
        # newest snapshot was corrupt and the loader fell back
    if model is not None and "model" in state:
        model.set_state_dict(state["model"])
    if optimizer is not None and "optimizer" in state:
        optimizer.set_state_dict(state["optimizer"])
    if scaler is not None and "scaler" in state:
        scaler.load_state_dict(state["scaler"])
    if "rng" in state:
        from ..random import set_rng_state

        try:
            set_rng_state(state["rng"])
        except Exception:
            pass
    return step, state.get("extra", {})
