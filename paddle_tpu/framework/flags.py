"""Global flag registry — ``paddle.set_flags`` / ``paddle.get_flags``.

Parity role: the reference exports C++ gflags to Python through
``global_value_getter_setter.cc`` and auto-parses ``FLAGS_*`` environment
variables at init (reference: paddle/fluid/platform/flags.cc — 43 exported
flags; paddle/fluid/framework/init.cc InitGflags). The TPU build keeps the
same surface: a typed registry with env override at import, plus hooks so a
flag flip can reconfigure the runtime (e.g. ``FLAGS_check_nan_inf`` toggles
jax debug_nans).

Flags whose reference semantics are CUDA-specific (memory fractions, cudnn
switches) are kept as accepted-but-documented no-ops so reference scripts run
unchanged; TPU-meaningful flags actually steer behavior.
"""
from __future__ import annotations

import builtins
import os
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

__all__ = ["set_flags", "get_flags", "register_flag", "flag"]

_lock = threading.RLock()


class _Flag:
    __slots__ = ("name", "value", "default", "type", "help", "on_change")

    def __init__(self, name, default, type_, help_, on_change=None):
        self.name = name
        self.default = default
        self.value = default
        self.type = type_
        self.help = help_
        self.on_change = on_change


_REGISTRY: Dict[str, _Flag] = {}


def _coerce(f: _Flag, value: Any):
    if f.type is bool:
        if isinstance(value, str):
            return value.strip().lower() in ("1", "true", "yes", "on")
        return bool(value)
    return f.type(value)


def register_flag(name: str, default: Any, help: str = "", type: Optional[type] = None,  # noqa: A002
                  on_change: Optional[Callable[[Any], None]] = None) -> None:
    """Register a flag. Env var of the same name overrides the default
    immediately (parity: init.cc InitGflags env parsing)."""
    with _lock:
        t = type if type is not None else builtins.type(default)
        f = _Flag(name, default, t, help, on_change)
        _REGISTRY[name] = f
        env = os.environ.get(name)
        if env is not None:
            f.value = _coerce(f, env)
            if f.on_change:
                f.on_change(f.value)


def flag(name: str) -> Any:
    """Fast internal read of one flag value."""
    f = _REGISTRY.get(name)
    return None if f is None else f.value


def set_flags(flags: Dict[str, Any]) -> None:
    """Parity: ``paddle.set_flags`` (fluid/framework.py)."""
    with _lock:
        for name, value in flags.items():
            f = _REGISTRY.get(name)
            if f is None:
                raise ValueError(f"unknown flag {name!r}; known: {sorted(_REGISTRY)}")
            f.value = _coerce(f, value)
            if f.on_change:
                f.on_change(f.value)


def get_flags(flags: Union[str, Iterable[str], None] = None) -> Dict[str, Any]:
    """Parity: ``paddle.get_flags``. None returns every flag."""
    with _lock:
        if flags is None:
            names: List[str] = sorted(_REGISTRY)
        elif isinstance(flags, str):
            names = [flags]
        else:
            names = list(flags)
        out = {}
        for name in names:
            f = _REGISTRY.get(name)
            if f is None:
                raise ValueError(f"unknown flag {name!r}")
            out[name] = f.value
        return out


def _on_check_nan_inf(value: bool) -> None:
    # TPU-native: jax debug_nans re-runs the offending computation un-jitted
    # and raises at the op that produced the NaN — the same developer
    # experience as the reference's per-op output scan
    # (details/nan_inf_utils_detail.cc hooked at operator.cc:1199).
    try:
        import jax

        jax.config.update("jax_debug_nans", bool(value))
    except Exception:
        pass


def _on_deterministic(value: bool) -> None:
    # Parity: FLAGS_cudnn_deterministic (platform/flags.cc:143). XLA:TPU is
    # deterministic for a fixed program + seed; this flag additionally pins
    # the XLA latency-hiding scheduler's reduction order.
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "")


# ---------------------------------------------------------------------------
# registry — names follow the reference where a counterpart exists
# (platform/flags.cc) so reference scripts using paddle.set_flags port as-is.
# ---------------------------------------------------------------------------
register_flag("FLAGS_check_nan_inf", False,
              "scan op outputs for NaN/Inf (jax debug_nans)", on_change=_on_check_nan_inf)
register_flag("FLAGS_benchmark", False,
              "force per-step device sync (block_until_ready) for timing")
register_flag("FLAGS_cudnn_deterministic", False,
              "deterministic kernels; TPU/XLA is deterministic by construction",
              on_change=_on_deterministic)
register_flag("FLAGS_use_pallas_attention", True,
              "route nn attention through the Pallas flash kernel on TPU")
register_flag("FLAGS_use_pallas_softmax_ce", False,
              "route the softmax-cross-entropy loss head (both mp and "
              "non-mp branches) through the fused Pallas kernel")
register_flag("FLAGS_eager_layer_jit", "true", type=str,
              help="transparently jit-cache per-Layer forwards in dygraph "
                   "mode: true (TPU only) | force (any backend) | false")
register_flag("FLAGS_allocator_strategy", "auto_growth",
              "host pinned-pool strategy: auto_growth | naive_best_fit")
register_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92,
              "accepted for script parity; TPU HBM is managed by PJRT")
register_flag("FLAGS_eager_delete_tensor_gb", 0.0,
              "accepted for script parity; XLA buffer liveness handles GC")
register_flag("FLAGS_max_inplace_grad_add", 0,
              "accepted for script parity; XLA fuses accumulation")
register_flag("FLAGS_enable_unused_var_check", False,
              "warn on layer params that received no gradient")
register_flag("FLAGS_profile_host", False,
              "record host-side RecordEvent spans even outside profiler range")
register_flag("FLAGS_selected_tpus", "",
              "comma list of visible TPU chip ids (parity: FLAGS_selected_gpus)")
register_flag("FLAGS_stop_check_timeout", 300,
              "elastic: seconds to wait for straggler before restart", type=int)
register_flag("FLAGS_gpt_qkv_assume_legacy", False,
              "treat untagged GPT state dicts as legacy [3, nh, hd] column-"
              "layout qkv and permute to head-major on load")
