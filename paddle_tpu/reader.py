"""paddle_tpu.reader — legacy reader decorators.

Parity: python/paddle/reader/decorator.py in the reference (map_readers,
shuffle, chain, compose, buffered, firstn, cache, xmap_readers) — generator
combinators predating paddle.io.DataLoader, kept so legacy pipelines port.
The buffered/xmap variants use host threads (the TPU-side prefetch lives in
paddle_tpu.io.DataLoader).
"""
from __future__ import annotations

import queue
import threading

import numpy as np

__all__ = ["map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "cache", "xmap_readers"]


def map_readers(func, *readers):
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    def shuffled():
        rng = np.random.default_rng()
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    def chained():
        for r in readers:
            yield from r()

    return chained


class ComposeNotAligned(ValueError):
    """Raised when composed readers yield different sample counts
    (reference reader/decorator.py ComposeNotAligned)."""


def compose(*readers, check_alignment=True):
    def composed():
        end = object()
        iters = [r() for r in readers]
        while True:
            items = [next(it, end) for it in iters]
            done = [it is end for it in items]
            if all(done):
                return
            if any(done):
                if check_alignment:
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned (different lengths)")
                return
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)

    return composed


def buffered(reader, size):
    """Prefetch up to `size` samples on a background thread."""
    end = object()

    def buffered_reader():
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for s in reader():
                    q.put(s)
            finally:
                q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is end:
                break
            yield s

    return buffered_reader


def firstn(reader, n):
    def limited():
        for i, s in enumerate(reader()):
            if i >= n:
                break
            yield s

    return limited


def cache(reader):
    all_data = []
    filled = [False]

    def cached():
        if filled[0]:
            yield from all_data
            return
        for s in reader():
            all_data.append(s)
            yield s
        filled[0] = True

    return cached


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads."""
    end = object()

    def xreader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            for i, s in enumerate(reader()):
                in_q.put((i, s))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    break
                i, s = item
                out_q.put((i, mapper(s)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return xreader
