"""Stateful RNG over jax's functional PRNG.

Parity surface: ``paddle.seed`` (python/paddle/fluid/framework.py generator
seeding), ``paddle/fluid/pybind/generator_py.cc``, and the tensor-parallel RNG
state tracker (/root/reference/python/paddle/distributed/fleet/meta_parallel/
parallel_layers/random.py — get_rng_state_tracker) used to keep dropout masks
identical or distinct across TP ranks.

TPU-native design: one global Generator holds a jax PRNG key; every random op
splits off a fresh subkey (functional under the hood, stateful at the API).
Inside jit-traced code the split is traced, so randomness stays reproducible
and compile-cache friendly.
"""
from __future__ import annotations

import contextlib
from typing import Dict

import jax

__all__ = [
    "seed",
    "Generator",
    "default_generator",
    "get_rng_state",
    "set_rng_state",
    "split_key",
    "RNGStatesTracker",
    "get_rng_state_tracker",
]


class Generator:
    """Stateful wrapper over a jax PRNG key chain."""

    def __init__(self, seed_: int = 0):
        self._seed = int(seed_)
        self._key = jax.random.key(self._seed)

    def manual_seed(self, seed_: int):
        self._seed = int(seed_)
        self._key = jax.random.key(self._seed)
        return self

    def initial_seed(self) -> int:
        return self._seed

    def split(self):
        """Return a fresh subkey; advances internal state."""
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        return self._key

    def set_state(self, key):
        self._key = key


default_generator = Generator(0)


def seed(value: int) -> Generator:
    """Seed the global generator (parity: paddle.seed)."""
    default_generator.manual_seed(value)
    get_rng_state_tracker()._reseed_base(value)
    return default_generator


def split_key():
    """Get a fresh PRNG subkey from the global generator."""
    return default_generator.split()


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state):
    default_generator.set_state(state)


class RNGStatesTracker:
    """Named RNG streams for tensor-parallel determinism.

    Parity: meta_parallel/parallel_layers/random.py RNGStatesTracker — dropout
    inside a TP region must draw from a per-rank stream ('local_seed') while
    non-TP dropout draws from the shared stream ('global_seed').
    """

    MODEL_PARALLEL_RNG = "model_parallel_rng"

    def __init__(self):
        self._states: Dict[str, Generator] = {}

    def reset(self):
        self._states.clear()

    def add(self, name: str, seed_: int):
        if name in self._states:
            raise ValueError(f"rng state {name} already exists")
        self._states[name] = Generator(seed_)

    def _reseed_base(self, base_seed: int):
        # re-derive any registered streams deterministically from the new seed
        for i, name in enumerate(sorted(self._states)):
            self._states[name] = Generator(base_seed + 1000 + i)

    @contextlib.contextmanager
    def rng_state(self, name: str = MODEL_PARALLEL_RNG):
        """Temporarily make the named stream the global default stream."""
        if name not in self._states:
            raise ValueError(f"rng state {name} was not added")
        global default_generator
        prev = default_generator
        default_generator = self._states[name]
        try:
            yield
        finally:
            default_generator = prev

    def get_states_tracker(self):
        return {k: g.get_state() for k, g in self._states.items()}

    def set_states_tracker(self, states):
        for k, s in states.items():
            self._states.setdefault(k, Generator(0)).set_state(s)


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker
