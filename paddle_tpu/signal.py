"""paddle_tpu.signal — short-time Fourier transform and framing ops.

Parity: python/paddle/tensor/signal.py in the reference (frame:34,
overlap_add:155, stft:238, istft — backed by the ``frame`` / ``overlap_add``
operators, paddle/fluid/operators/frame_op.cc, overlap_add_op.cc, and
spectral ops).

TPU-native redesign: ``frame`` is a gather with a precomputed (frame_length,
n_frames) index grid and ``overlap_add`` is its transpose — a scatter-add via
``Array.at[].add`` — both static-shaped so XLA vectorizes them; the reference's
dedicated CUDA kernels have no equivalent. stft/istft compose frame/overlap_add
with the fft module and fold the window and NOLA normalization into the same
XLA program.
"""
from __future__ import annotations

import jax.numpy as jnp

from .ops._primitive import primitive
from .tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_raw(x, frame_length, hop_length, axis):
    if axis not in (0, -1):
        raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")
    seq_len = x.shape[-1] if axis == -1 else x.shape[0]
    if frame_length > seq_len:
        raise ValueError(
            f"Attribute frame_length should be less equal than sequence length, "
            f"but got ({frame_length}) > ({seq_len})."
        )
    n_frames = 1 + (seq_len - frame_length) // hop_length
    idx = (
        jnp.arange(frame_length)[:, None]
        + jnp.arange(n_frames)[None, :] * hop_length
    )  # (frame_length, n_frames)
    if axis == -1:
        return x[..., idx]
    # axis == 0: (seq, ...) -> (n_frames, frame_length, ...)
    return x[idx.T]


@primitive
def _frame_op(x, frame_length, hop_length, axis):
    return _frame_raw(x, frame_length, hop_length, axis)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice a signal into (possibly overlapping) frames.

    axis=-1: (..., seq_len) -> (..., frame_length, num_frames)
    axis=0:  (seq_len, ...) -> (num_frames, frame_length, ...)
    """
    if hop_length < 1:
        raise ValueError(f"Unexpected hop_length: {hop_length}. It should be an positive integer.")
    return _frame_op(x, frame_length, hop_length, axis)


def _overlap_add_raw(x, hop_length, axis):
    if axis not in (0, -1):
        raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")
    if axis == -1:
        frame_length, n_frames = x.shape[-2], x.shape[-1]
        seq_len = (n_frames - 1) * hop_length + frame_length
        idx = (
            jnp.arange(frame_length)[:, None]
            + jnp.arange(n_frames)[None, :] * hop_length
        )
        out = jnp.zeros(x.shape[:-2] + (seq_len,), dtype=x.dtype)
        return out.at[..., idx].add(x)
    # axis == 0: (n_frames, frame_length, ...) -> (seq_len, ...)
    moved = jnp.moveaxis(x, (0, 1), (-1, -2))
    out = _overlap_add_raw(moved, hop_length, -1)
    return jnp.moveaxis(out, -1, 0)


@primitive
def _overlap_add_op(x, hop_length, axis):
    return _overlap_add_raw(x, hop_length, axis)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Reconstruct a signal from framed slices by summing overlaps."""
    if hop_length < 1:
        raise ValueError(f"Unexpected hop_length: {hop_length}. It should be an positive integer.")
    return _overlap_add_op(x, hop_length, axis)


def _pad_center(w, size):
    lpad = (size - w.shape[-1]) // 2
    return jnp.pad(w, [(lpad, size - w.shape[-1] - lpad)])


@primitive
def _stft_op(x, window, n_fft, hop_length, center, pad_mode, normalized, onesided):
    if center:
        pad = n_fft // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)], mode=pad_mode)
    frames = _frame_raw(x, n_fft, hop_length, -1)  # (..., n_fft, num_frames)
    frames = frames * window[:, None]
    norm = "ortho" if normalized else "backward"
    if onesided:
        return jnp.fft.rfft(frames, axis=-2, norm=norm)
    return jnp.fft.fft(frames, axis=-2, norm=norm)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (reference tensor/signal.py:238).

    x: (T,) or (N, T) real (complex allowed with onesided=False).
    Returns (..., n_fft//2+1 if onesided else n_fft, num_frames).
    """
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if xd.ndim not in (1, 2):
        raise ValueError(f"x should be a 1D or 2D real tensor, but got rank {xd.ndim}")
    if hop_length is None:
        hop_length = n_fft // 4
    if win_length is None:
        win_length = n_fft
    if not center and n_fft > xd.shape[-1]:
        raise ValueError("n_fft should be in [0, seq_length] when center is False")
    if window is not None:
        wd = window._data if isinstance(window, Tensor) else jnp.asarray(window)
        if wd.shape[-1] != win_length:
            raise ValueError(f"window length must equal win_length {win_length}")
    else:
        wd = jnp.ones(win_length, dtype=xd.real.dtype if jnp.iscomplexobj(xd) else xd.dtype)
    wd = _pad_center(wd, n_fft)
    if jnp.iscomplexobj(xd) and onesided:
        raise ValueError("onesided should be False when input or window is a complex Tensor")
    return _stft_op(Tensor(xd) if not isinstance(x, Tensor) else x, wd, n_fft,
                    hop_length, center, pad_mode, normalized, onesided)


@primitive
def _istft_op(x, window, n_fft, hop_length, win_length, center, normalized,
              onesided, length, return_complex):
    norm = "ortho" if normalized else "backward"
    if onesided:
        frames = jnp.fft.irfft(x, n=n_fft, axis=-2, norm=norm)
    else:
        frames = jnp.fft.ifft(x, axis=-2, norm=norm)
        if not return_complex:
            frames = frames.real
    # apply synthesis window and overlap-add (..., n_fft, num_frames) -> (..., T)
    frames = frames * window[:, None]
    y = _overlap_add_raw(frames, hop_length, -1)
    # NOLA normalization: overlap-added squared window envelope
    n_frames = x.shape[-1]
    env = _overlap_add_raw(
        jnp.tile((window * window)[:, None], (1, n_frames)), hop_length, -1
    )
    y = y / jnp.where(env > 1e-11, env, 1.0)
    if center:
        pad = n_fft // 2
        y = y[..., pad: y.shape[-1] - pad]
    if length is not None:
        if y.shape[-1] >= length:
            y = y[..., :length]
        else:
            y = jnp.pad(y, [(0, 0)] * (y.ndim - 1) + [(0, length - y.shape[-1])])
    return y


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse short-time Fourier transform."""
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if xd.ndim not in (2, 3):
        raise ValueError(f"x should be a 2D or 3D complex tensor, but got rank {xd.ndim}")
    if onesided and return_complex:
        raise ValueError(
            "onesided output from a real signal cannot be complex: pass "
            "onesided=False with return_complex=True")
    if hop_length is None:
        hop_length = n_fft // 4
    if win_length is None:
        win_length = n_fft
    n_bins = xd.shape[-2]
    expected = n_fft // 2 + 1 if onesided else n_fft
    if n_bins != expected:
        raise ValueError(f"Input x has {n_bins} frequency bins, expected {expected}")
    if window is not None:
        wd = window._data if isinstance(window, Tensor) else jnp.asarray(window)
        if wd.shape[-1] != win_length:
            raise ValueError(f"window length must equal win_length {win_length}")
    else:
        wd = jnp.ones(win_length, dtype=jnp.float32)
    wd = _pad_center(wd, n_fft)
    return _istft_op(x if isinstance(x, Tensor) else Tensor(xd), wd, n_fft,
                     hop_length, win_length, center, normalized, onesided,
                     length, return_complex)
