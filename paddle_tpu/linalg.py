"""paddle_tpu.linalg — linear-algebra namespace (parity:
python/paddle/linalg.py re-exporting tensor.linalg)."""
from .ops.linalg import (  # noqa: F401
    bmm,
    cholesky,
    cholesky_solve,
    cond,
    cov,
    det,
    eig,
    eigh,
    eigvals,
    eigvalsh,
    inverse,
    lstsq,
    matmul,
    matrix_power,
    matrix_rank,
    multi_dot,
    norm,
    pinv,
    qr,
    slogdet,
    solve,
    svd,
    triangular_solve,
)

__all__ = [
    "cholesky", "cholesky_solve", "cond", "cov", "det", "eig", "eigh",
    "eigvals", "eigvalsh", "inverse", "lstsq", "matmul", "matrix_power",
    "matrix_rank", "multi_dot", "norm", "pinv", "qr", "slogdet", "solve",
    "svd", "triangular_solve", "bmm",
]
