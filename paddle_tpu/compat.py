"""paddle_tpu.compat — py2/3-era string helpers kept for API parity.

Parity: python/paddle/compat.py in the reference (to_text/to_bytes over
str/bytes and nested containers, plus rounding helpers).
"""
from __future__ import annotations

import math

__all__ = ["long_type", "to_text", "to_bytes", "round", "floor_division",
           "get_exception_message"]

long_type = int


def _convert(obj, conv):
    if obj is None:
        return obj
    if isinstance(obj, (list, set, tuple)):
        return type(obj)(_convert(o, conv) for o in obj)
    if isinstance(obj, dict):
        return {conv_key(k, conv): _convert(v, conv) for k, v in obj.items()}
    return conv(obj)


def conv_key(k, conv):
    return conv(k) if isinstance(k, (str, bytes)) else k


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes → str (recursively through containers)."""
    def conv(o):
        return o.decode(encoding) if isinstance(o, bytes) else o

    return _convert(obj, conv)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """str → bytes (recursively through containers)."""
    def conv(o):
        return o.encode(encoding) if isinstance(o, str) else o

    return _convert(obj, conv)


def round(x, d=0):  # noqa: A001
    """Python-2-style half-away-from-zero rounding."""
    p = 10 ** d
    if x > 0:
        return float(math.floor(x * p + 0.5)) / p
    if x < 0:
        return float(math.ceil(x * p - 0.5)) / p
    return 0.0


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
