"""Shape / layout / gather-scatter manipulation ops.

Parity: python/paddle/tensor/manipulation.py and the reference operators
reshape2, transpose2, concat, split, gather(_nd), scatter(_nd_add), slice,
strided_slice, expand_v2, tile, unique, where_index
(/root/reference/paddle/fluid/operators/). Dynamic-shape outputs
(masked_select, nonzero, unique) are eager-only on TPU — under jit they must
be expressed with masks; both facts documented per-op.
"""
from __future__ import annotations

import builtins
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..dtype import to_jax_dtype
from ..tensor import Tensor
from ._primitive import primitive, unwrap, wrap


def _ints(seq):
    if isinstance(seq, Tensor):
        return tuple(int(v) for v in seq.numpy())
    if isinstance(seq, (int, np.integer)):
        return (int(seq),)
    return tuple(int(unwrap(v)) for v in seq)


# ---------------------------------------------------------------------------
# shape
# ---------------------------------------------------------------------------


@primitive
def _reshape(x, shape):
    return jnp.reshape(x, shape)


def reshape(x, shape):
    return _reshape(x, _ints(shape))


def reshape_(x, shape):
    from ._primitive import inplace_guard

    inplace_guard(x, "reshape_")
    x._set_data(jnp.reshape(x._data, _ints(shape)))
    return x


@primitive
def _flatten(x, start, stop):
    shp = x.shape
    stop = stop if stop >= 0 else len(shp) + stop
    new = shp[:start] + (-1,) + shp[stop + 1 :]
    return jnp.reshape(x, new)


def flatten(x, start_axis=0, stop_axis=-1):
    return _flatten(x, start_axis, stop_axis)


@primitive
def _transpose(x, perm):
    return jnp.transpose(x, perm)


def transpose(x, perm):
    return _transpose(x, _ints(perm))


@primitive
def _squeeze(x, axis):
    if axis is None:
        return jnp.squeeze(x)
    axis = tuple(a for a in axis if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axis) if axis else x


def squeeze(x, axis=None):
    if axis is not None:
        axis = _ints(axis if isinstance(axis, (list, tuple)) else [axis])
        axis = tuple(a if a >= 0 else a + unwrap(x).ndim for a in axis)
    return _squeeze(x, axis)


@primitive
def _unsqueeze(x, axis):
    for a in sorted(axis):
        x = jnp.expand_dims(x, a)
    return x


def unsqueeze(x, axis):
    axis = _ints(axis if isinstance(axis, (list, tuple, Tensor)) else [axis])
    return _unsqueeze(x, axis)


@primitive
def _expand(x, shape):
    shape = tuple(
        x.shape[i - (len(shape) - x.ndim)] if s == -1 and i >= len(shape) - x.ndim else s
        for i, s in enumerate(shape)
    )
    return jnp.broadcast_to(x, shape)


def expand(x, shape):
    return _expand(x, _ints(shape))


def expand_as(x, y):
    return _expand(x, tuple(unwrap(y).shape))


def broadcast_to(x, shape):
    return _expand(x, _ints(shape))


def broadcast_tensors(inputs):
    arrs = jnp.broadcast_arrays(*[unwrap(i) for i in inputs])
    return [wrap(a) for a in arrs]


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@primitive
def _tile(x, reps):
    return jnp.tile(x, reps)


def tile(x, repeat_times):
    return _tile(x, _ints(repeat_times))


@primitive
def _roll(x, shifts, axis):
    return jnp.roll(x, shifts, axis)


def roll(x, shifts, axis=None):
    return _roll(x, shifts, axis)


@primitive
def _flip(x, axis):
    return jnp.flip(x, axis)


def flip(x, axis):
    return _flip(x, _ints(axis if isinstance(axis, (list, tuple)) else [axis]))


def rot90(x, k=1, axes=(0, 1)):
    return wrap(jnp.rot90(unwrap(x), k=k, axes=tuple(axes)))


@primitive
def _moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


def moveaxis(x, source, destination):
    return _moveaxis(x, _ints(source), _ints(destination))


def swapaxes(x, axis0, axis1):
    perm = list(range(unwrap(x).ndim))
    perm[axis0], perm[axis1] = perm[axis1], perm[axis0]
    return transpose(x, perm)


transpose_ = swapaxes


@primitive
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@primitive
def as_complex(x):
    return x[..., 0] + 1j * x[..., 1]


# ---------------------------------------------------------------------------
# join / split
# ---------------------------------------------------------------------------


@primitive
def _concat(xs, axis):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0):
    axis = int(unwrap(axis))
    return _concat(list(x), axis)


@primitive
def _stack(xs, axis):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0):
    return _stack(list(x), axis)


@primitive
def _split_sections(x, indices, axis):
    return tuple(jnp.split(x, indices, axis=axis))


def split(x, num_or_sections, axis=0):
    axis = int(unwrap(axis))
    n = unwrap(x).shape[axis]
    if isinstance(num_or_sections, int):
        idx = [n // num_or_sections * i for i in range(1, num_or_sections)]
    else:
        sections = list(num_or_sections)
        total_known = builtins.sum(s for s in sections if s != -1)
        sections = [n - total_known if s == -1 else s for s in sections]
        idx = list(np.cumsum(sections)[:-1])
    out = _split_sections(x, idx, axis)
    return list(out) if isinstance(out, tuple) else [out]


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


def unbind(x, axis=0):
    n = unwrap(x).shape[axis]
    parts = split(x, n, axis)
    return [squeeze(p, [axis]) for p in parts]


unstack = unbind


# ---------------------------------------------------------------------------
# indexing / gather / scatter
# ---------------------------------------------------------------------------


@primitive
def _getitem_diff(x, idx):
    return x[idx]


def _getitem(x, idx):
    raw_idx = idx if isinstance(idx, tuple) else (idx,)
    has_bool = builtins.any(
        (isinstance(i, Tensor) and i.dtype == "bool")
        or (isinstance(i, (jnp.ndarray, np.ndarray)) and i.dtype == np.bool_)
        for i in raw_idx
    )
    idx2 = tuple(unwrap(i) for i in raw_idx)
    if len(idx2) == 1:
        idx2 = idx2[0]
    if has_bool:
        # dynamic output shape: eager-only, no grad
        return wrap(unwrap(x)[idx2])
    return _getitem_diff(x, idx2)


@primitive
def _gather(x, index, axis):
    return jnp.take(x, index, axis=axis)


def gather(x, index, axis=0):
    index = unwrap(index)
    if index.ndim == 2 and index.shape[1] == 1:
        index = index[:, 0]
    return _gather(x, wrap(index), int(unwrap(axis)))


@primitive
def _gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def gather_nd(x, index):
    return _gather_nd(x, index)


@primitive
def _scatter(x, index, updates, overwrite):
    if index.ndim == 2 and index.shape[1] == 1:
        index = index[:, 0]
    if overwrite:
        return x.at[index].set(updates)
    # paddle scatter(overwrite=False): zero the rows then add (sum duplicates)
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter(x, index, updates, overwrite=True):
    return _scatter(x, index, updates, overwrite)


@primitive
def _scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd_add(x, index, updates):
    return _scatter_nd_add(x, index, updates)


def scatter_nd(index, updates, shape):
    from .creation import zeros

    zero = zeros(shape, dtype=unwrap(updates).dtype)
    return _scatter_nd_add(zero, index, updates)


@primitive
def _index_select(x, index, axis):
    return jnp.take(x, index, axis=axis)


def index_select(x, index, axis=0):
    return _index_select(x, wrap(jnp.reshape(unwrap(index), (-1,))), axis)


@primitive
def _index_sample(x, index):
    rows = jnp.arange(x.shape[0])[:, None]
    return x[rows, index]


def index_sample(x, index):
    return _index_sample(x, index)


@primitive
def _take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


def take_along_axis(x, indices, axis):
    return _take_along_axis(x, indices, axis)


@primitive
def _put_along_axis(x, indices, values, axis, reduce):
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)
    dims = [jnp.arange(s) for s in x.shape]
    grids = jnp.meshgrid(*dims, indexing="ij")
    grids[axis] = jnp.broadcast_to(indices, grids[axis].shape)
    idx = tuple(grids)
    if reduce == "add":
        return x.at[idx].add(jnp.broadcast_to(values, x.shape))
    if reduce == "multiply" or reduce == "mul":
        return x.at[idx].multiply(jnp.broadcast_to(values, x.shape))
    raise ValueError(f"unknown reduce {reduce}")


def put_along_axis(x, indices, values, axis, reduce="assign"):
    return _put_along_axis(x, indices, unwrap(values), axis, reduce)


@primitive
def _repeat_interleave(x, repeats, axis):
    return jnp.repeat(x, repeats, axis=axis)


def repeat_interleave(x, repeats, axis=None):
    if axis is None:
        x = reshape(x, [-1])
        axis = 0
    return _repeat_interleave(x, unwrap(repeats), axis)


def masked_select(x, mask, size=None, fill_value=0):
    """Dynamic-shape op, two modes (reference masked_select_op):
    - ``size=None``: eager-only (host-visible output shape).
    - ``size=N``: jit-capable static form — the first N selected elements,
      padded with ``fill_value`` (the TPU-native paradigm; same convention
      as jnp.nonzero's size argument)."""
    if size is None:
        return wrap(unwrap(x)[unwrap(mask)])

    @primitive(name="masked_select")
    def _ms(x, mask):
        flat = x.reshape(-1)
        m = jnp.broadcast_to(mask, x.shape).reshape(-1)
        (idx,) = jnp.nonzero(m, size=size, fill_value=flat.shape[0])
        padded = jnp.concatenate(
            [flat, jnp.full((1,), fill_value, flat.dtype)])
        return jnp.take(padded, idx)

    return _ms(x, mask)


@primitive
def _where(cond, x, y):
    return jnp.where(cond, x, y)


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return _where(condition, x, y)


def nonzero(x, as_tuple=False, size=None, fill_value=-1):
    """Dynamic-shape op; ``size=N`` gives the jit-capable static form
    (first N coordinates, rows padded with ``fill_value``)."""
    if size is None:
        arrs = jnp.nonzero(unwrap(x))
    else:
        @primitive(nondiff=True, name="nonzero")
        def _nz(x):
            return jnp.nonzero(x, size=size, fill_value=fill_value)

        res = _nz(x)
        arrs = [unwrap(a) for a in (res if isinstance(res, tuple) else (res,))]
    if as_tuple:
        return tuple(wrap(a[:, None]) for a in arrs)
    return wrap(jnp.stack([unwrap(a) for a in arrs], axis=1))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, size=None, fill_value=None):
    """Dynamic-shape op; ``size=N`` gives the jit-capable static form
    (jnp.unique size/fill_value convention: sorted uniques padded to N)."""
    if size is None:
        res = jnp.unique(
            unwrap(x),
            return_index=return_index,
            return_inverse=return_inverse,
            return_counts=return_counts,
            axis=axis,
        )
    else:
        @primitive(nondiff=True, name="unique")
        def _uq(x):
            return jnp.unique(x, return_index=return_index,
                              return_inverse=return_inverse,
                              return_counts=return_counts, axis=axis,
                              size=size, fill_value=fill_value)

        res = _uq(x)
    if isinstance(res, tuple):
        return tuple(wrap(r) for r in res)
    return wrap(res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    arr = np.asarray(unwrap(x))
    vals = []
    counts = []
    inverse = np.zeros(arr.size, dtype=np.int64)
    flat = arr.reshape(-1) if axis is None else arr
    prev = None
    for i, v in enumerate(flat.tolist()):
        if prev is None or v != prev:
            vals.append(v)
            counts.append(1)
        else:
            counts[-1] += 1
        inverse[i] = len(vals) - 1
        prev = v
    out = [wrap(jnp.asarray(np.asarray(vals, dtype=arr.dtype)))]
    if return_inverse:
        out.append(wrap(jnp.asarray(inverse)))
    if return_counts:
        out.append(wrap(jnp.asarray(np.asarray(counts, dtype=np.int64))))
    return tuple(out) if len(out) > 1 else out[0]


# ---------------------------------------------------------------------------
# slice family
# ---------------------------------------------------------------------------


@primitive
def _slice(x, axes, starts, ends):
    idx = [builtins.slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = builtins.slice(s, e)
    return x[tuple(idx)]


def slice(x, axes, starts, ends):  # noqa: A001
    return _slice(x, _ints(axes), _ints(starts), _ints(ends))


@primitive
def _strided_slice(x, axes, starts, ends, strides):
    idx = [builtins.slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = builtins.slice(s, e, st)
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides):
    return _strided_slice(x, _ints(axes), _ints(starts), _ints(ends), _ints(strides))


@primitive
def _pad_nd(x, pad, mode, value):
    return jnp.pad(x, pad, mode=mode, constant_values=value) if mode == "constant" else jnp.pad(x, pad, mode=mode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):  # noqa: A002
    """paddle.nn.functional.pad semantics: `pad` is [l,r] pairs from the last
    dim backwards when len(pad) < 2*ndim (conv-style), else full spec."""
    x_arr = unwrap(x)
    nd = x_arr.ndim
    pad = _ints(pad)
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    if len(pad) == 2 * nd:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # conv-style: applies to spatial dims per data_format
        npairs = len(pad) // 2
        width = [(0, 0)] * nd
        if data_format.endswith("C") and nd >= 3:  # NHWC/NLC/NDHWC
            spatial = list(range(1, 1 + npairs))
        else:  # NCHW-style
            spatial = list(range(nd - npairs, nd))
        for k, d in enumerate(spatial):
            width[d] = (pad[2 * k], pad[2 * k + 1])
    return _pad_nd(x, tuple(width), jmode, value)


# ---------------------------------------------------------------------------
# cast / dtype
# ---------------------------------------------------------------------------


@primitive
def _cast_f(x, dt):
    return x.astype(dt)


def cast(x, dtype):
    jdt = to_jax_dtype(dtype)
    if jnp.issubdtype(jdt, jnp.inexact):
        return _cast_f(x, jdt)
    return wrap(unwrap(x).astype(jdt))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    """Parity: shard_index op (used by parallel vocab partitioning)."""
    arr = unwrap(input)
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    in_shard = (arr >= lo) & (arr < lo + shard_size)
    return wrap(jnp.where(in_shard, arr - lo, ignore_value))


def index_add(x, index, axis, value, name=None):
    """Add `value` rows into x at `index` along `axis` (parity: index_add op;
    duplicate indices accumulate)."""

    @primitive
    def _ia(x, index, value):
        moved = jnp.moveaxis(x, axis, 0)
        vmoved = jnp.moveaxis(value, axis, 0)
        out = moved.at[index].add(vmoved)
        return jnp.moveaxis(out, 0, axis)

    return _ia(x, index, value)


def index_put(x, indices, value, accumulate=False, name=None):
    """Put values at coordinates given by a tuple of index tensors
    (parity: index_put op)."""

    @primitive
    def _ip(x, value, *indices):
        if accumulate:
            return x.at[tuple(indices)].add(value)
        return x.at[tuple(indices)].set(value)

    idx = tuple(indices) if isinstance(indices, (tuple, list)) else (indices,)
    return _ip(x, value, *idx)


def reverse(x, axis, name=None):
    """Alias of flip with paddle's legacy name (reverse op)."""
    return flip(x, axis)


def crop(x, shape=None, offsets=None, name=None):
    """Crop a sub-tensor: take `shape` elements starting at `offsets`
    (parity: crop_tensor op, reference operators/crop_tensor_op.cc).
    shape entries of -1 keep the remainder; offsets default to zeros."""
    nd = len(x.shape)
    if shape is None:
        shape = list(x.shape)
    shape = [int(s) for s in (unwrap(shape) if not isinstance(shape, (list, tuple)) else shape)]
    if offsets is None:
        offsets = [0] * nd
    offsets = [int(o) for o in (unwrap(offsets) if not isinstance(offsets, (list, tuple)) else offsets)]
    full = x.shape
    ends = [o + (s if s != -1 else full[i] - o) for i, (o, s) in enumerate(zip(offsets, shape))]
    for i, (o, e) in enumerate(zip(offsets, ends)):
        if o < 0 or e > full[i]:
            raise ValueError(
                f"crop out of bounds on dim {i}: offset {o} + size {e - o} "
                f"exceeds input extent {full[i]}")

    @primitive
    def _crop(x):
        idx = tuple(jnp.s_[o:e] for o, e in zip(offsets, ends))
        return x[idx]

    return _crop(x)


def squeeze_(x, axis=None):
    from ._primitive import inplace_guard

    inplace_guard(x, "squeeze_")
    arr = x._data
    out = jnp.squeeze(arr, axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis)
    x._set_data(out)
    return x


def unsqueeze_(x, axis):
    from ._primitive import inplace_guard

    inplace_guard(x, "unsqueeze_")
    arr = x._data
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    out = jnp.expand_dims(arr, tuple(axes))
    x._set_data(out)
    return x


def scatter_(x, index, updates, overwrite=True):
    from ._primitive import inplace_guard

    inplace_guard(x, "scatter_")
    out = scatter(x, index, updates, overwrite=overwrite)
    x._set_data(out._data if hasattr(out, "_data") else out)
    return x


def tolist(x):
    """Nested python list of the tensor's values (parity: paddle.tolist)."""
    import numpy as _np

    return _np.asarray(unwrap(x)).tolist()


def shape(x, name=None):
    """Runtime shape as an int32 tensor (parity: shape op)."""
    import numpy as _np

    return wrap(jnp.asarray(_np.array(list(unwrap(x).shape), dtype=_np.int32)))


def rank(x, name=None):
    """Tensor rank as a 0-D int32 tensor (parity: rank op)."""
    import numpy as _np

    return wrap(jnp.asarray(_np.int32(len(unwrap(x).shape))))


@primitive
def _index_fill(x, index, axis, value):
    idx = [builtins.slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(value)


def index_fill(x, index, axis, value, name=None):
    """index_fill op: rows at ``index`` along ``axis`` set to ``value``."""
    return _index_fill(x, unwrap(index), int(axis), value)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.tril_indices(row, k=offset, m=col)
    return wrap(jnp.asarray(np.stack([r, c]).astype(np.int64)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(row, k=offset, m=col)
    return wrap(jnp.asarray(np.stack([r, c]).astype(np.int64)))


def view(x, shape_or_dtype, name=None):
    """paddle.view: zero-copy reshape/dtype reinterpret (XLA owns layout; a
    reshape/bitcast is already copy-free under jit)."""
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    from ..dtype import to_jax_dtype

    @primitive(name="view_dtype")
    def _bitcast(x):
        dt = jnp.dtype(to_jax_dtype(shape_or_dtype))
        src_size = jnp.dtype(x.dtype).itemsize
        if dt.itemsize > src_size:
            # widening: group the last dim by the width ratio, bitcast
            # removes the group axis -> (..., last // ratio)
            ratio = dt.itemsize // src_size
            if x.shape[-1] % ratio:
                raise ValueError(
                    f"cannot view last dim {x.shape[-1]} as {dt} "
                    f"(needs a multiple of {ratio})")
            grouped = x.reshape(x.shape[:-1] + (x.shape[-1] // ratio, ratio))
            return jax.lax.bitcast_convert_type(grouped, dt)
        out = jax.lax.bitcast_convert_type(x, dt)
        if out.ndim == x.ndim + 1:
            # narrower dtype: fold the per-element axis into the last dim
            out = out.reshape(out.shape[:-2] + (-1,))
        return out

    return _bitcast(x)


def view_as(x, other, name=None):
    return reshape(x, tuple(unwrap(other).shape))
