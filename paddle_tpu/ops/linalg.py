"""Linear algebra ops.

Parity: python/paddle/tensor/linalg.py and the reference's matmul_v2
(/root/reference/paddle/fluid/operators/matmul_v2_op.cc:354-380), bmm, mv,
svd/eig/cholesky/solve family. On TPU every matmul lowers to the MXU; the
reference's Blas wrapper (operators/math/blas.h) has no equivalent because
XLA owns GEMM selection.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._primitive import primitive, unwrap, wrap

__all__ = [
    "matmul",
    "bmm",
    "dot",
    "mv",
    "t",
    "norm",
    "dist",
    "cholesky",
    "inverse",
    "det",
    "slogdet",
    "svd",
    "qr",
    "eig",
    "eigh",
    "eigvals",
    "eigvalsh",
    "solve",
    "triangular_solve",
    "cholesky_solve",
    "lstsq",
    "matrix_power",
    "matrix_rank",
    "pinv",
    "multi_dot",
    "cross",
    "histogram",
    "bincount",
    "einsum",
    "cov",
    "cond",
    "corrcoef",
    "lu",
]


@primitive
def _matmul(x, y, transpose_x, transpose_y):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):  # noqa: ARG001
    return _matmul(x, y, transpose_x, transpose_y)


@primitive
def bmm(x, y):
    return jnp.einsum("bij,bjk->bik", x, y)


@primitive
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@primitive
def mv(x, vec):
    return jnp.matmul(x, vec)


def t(x):
    xa = unwrap(x)
    if xa.ndim < 2:
        from .creation import assign

        return assign(x)
    from .manipulation import transpose

    return transpose(x, [1, 0])


@primitive
def _p_norm(x, p, axis, keepdim):
    if p == "fro" or p == 2:
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == "nuc":
        s = jnp.linalg.svd(x, compute_uv=False)
        return jnp.sum(s, axis=-1)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    if p == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdim)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p)


def norm(x, p="fro", axis=None, keepdim=False):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    elif axis is not None:
        axis = int(axis)
    return _p_norm(x, p, axis, keepdim)


def p_norm(x, p=2, axis=None, keepdim=False):
    return norm(x, p, axis, keepdim)


@primitive
def dist(x, y, p=2):
    d = x - y
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)


@primitive
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@primitive
def inverse(x):
    return jnp.linalg.inv(x)


@primitive
def det(x):
    return jnp.linalg.det(x)


def slogdet(x):
    sign, logdet = jnp.linalg.slogdet(unwrap(x))
    return wrap(jnp.stack([sign, logdet]))


def svd(x, full_matrices=False):
    u, s, vh = jnp.linalg.svd(unwrap(x), full_matrices=full_matrices)
    return wrap(u), wrap(s), wrap(jnp.swapaxes(vh, -1, -2))


def qr(x, mode="reduced"):
    q, r = jnp.linalg.qr(unwrap(x), mode=mode)
    return wrap(q), wrap(r)


def eig(x):
    # jnp.linalg.eig is CPU-only; route through host
    import numpy as np

    w, v = np.linalg.eig(np.asarray(unwrap(x)))
    return wrap(jnp.asarray(w)), wrap(jnp.asarray(v))


def eigh(x, UPLO="L"):
    w, v = jnp.linalg.eigh(unwrap(x), UPLO=UPLO)
    return wrap(w), wrap(v)


def eigvals(x):
    import numpy as np

    return wrap(jnp.asarray(np.linalg.eigvals(np.asarray(unwrap(x)))))


def eigvalsh(x, UPLO="L"):
    return wrap(jnp.linalg.eigvalsh(unwrap(x), UPLO=UPLO))


@primitive
def solve(x, y):
    return jnp.linalg.solve(x, y)


@primitive
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
    )


@primitive
def cholesky_solve(x, y, upper=False):
    # solve A z = x where A = L L^T given Cholesky factor y
    L = jnp.swapaxes(y, -1, -2) if upper else y
    z = jax.scipy.linalg.solve_triangular(L, x, lower=True)
    return jax.scipy.linalg.solve_triangular(jnp.swapaxes(L, -1, -2), z, lower=False)


def lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(unwrap(x), unwrap(y), rcond=rcond)
    return wrap(sol), wrap(res), wrap(rank), wrap(sv)


@primitive
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_rank(x, tol=None, hermitian=False):
    return wrap(jnp.linalg.matrix_rank(unwrap(x), rtol=tol))


@primitive
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@primitive
def _multi_dot(xs):
    out = xs[0]
    for m in xs[1:]:
        out = out @ m
    return out


def multi_dot(x):
    return _multi_dot(list(x))


@primitive
def cross(x, y, axis=9):
    axis = -1 if axis == 9 else axis
    return jnp.cross(x, y, axis=axis)


def histogram(input, bins=100, min=0, max=0):  # noqa: A002
    arr = unwrap(input)
    if min == 0 and max == 0:
        lo, hi = float(jnp.min(arr)), float(jnp.max(arr))
    else:
        lo, hi = float(min), float(max)
    hist, _ = jnp.histogram(arr, bins=bins, range=(lo, hi))
    return wrap(hist.astype(jnp.int64))


def bincount(x, weights=None, minlength=0):
    return wrap(jnp.bincount(unwrap(x), weights=unwrap(weights), minlength=minlength))


@primitive
def _einsum(equation, operands):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands):
    return _einsum(equation, list(operands))


@primitive
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fweights, aweights=aweights)


@primitive
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


def lu(x, pivot=True):
    lu_, piv = jax.scipy.linalg.lu_factor(unwrap(x))
    return wrap(lu_), wrap(piv.astype(jnp.int32) + 1)  # paddle pivots are 1-based


def cond(x, p=None, name=None):
    """Condition number (parity: paddle.linalg.cond). p in {None/'fro',
    'nuc', 1, -1, 2, -2, inf, -inf}; None means 2-norm like numpy."""

    @primitive
    def _cond(x):
        return jnp.linalg.cond(x, p=p)

    return _cond(x)
