"""Tensor-array ops (reference: python/paddle/tensor/array.py over the
write_to_array / read_from_array / lod_array_length framework ops,
operators/controlflow — SURVEY App. A control-flow family).

TPU-native redesign: a LoDTensorArray is a plain Python list of Tensors at
trace time (static program = unrolled writes/reads). Concrete indices
index the list exactly like the reference's dynamic executor; a TRACED
index raises the teachable XLA error — dynamic array growth has no
static-shape analog (use lax.scan-carried buffers for fixed-capacity
dynamic indexing)."""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor

__all__ = ["create_array", "array_write", "array_read", "array_length"]


def _idx(i):
    v = i._data if isinstance(i, Tensor) else i
    try:
        return int(np.asarray(v).reshape(()))
    except Exception as e:  # jax tracer
        raise TypeError(
            "tensor-array indices must be concrete under XLA (the reference "
            "executes write_to_array dynamically; here the program is "
            "traced once) — use python ints or eager tensors") from e


def create_array(dtype=None, initialized_list=None, name=None):
    """New tensor array, optionally seeded from a list."""
    arr = []
    if initialized_list is not None:
        for v in initialized_list:
            arr.append(v if isinstance(v, Tensor) else Tensor(v))
    return arr


def array_write(x, i, array=None, name=None):
    """Write ``x`` at position ``i`` (extends the array when i == len)."""
    if array is None:
        array = []
    i = _idx(i)
    if i > len(array):
        raise IndexError(
            f"array_write index {i} beyond array length {len(array)}")
    x = x if isinstance(x, Tensor) else Tensor(x)
    if i == len(array):
        array.append(x)
    else:
        array[i] = x
    return array


def array_read(array, i, name=None):
    return array[_idx(i)]


def array_length(array, name=None):
    import jax.numpy as jnp

    return Tensor(jnp.asarray(len(array), jnp.int64))
