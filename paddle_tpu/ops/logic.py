"""Comparison / logical / bitwise ops.

Parity: python/paddle/tensor/logic.py and the reference's compare ops
(/root/reference/paddle/fluid/operators/controlflow/compare_op.cc,
logical_op.cc, bitwise ops). All nondifferentiable.
"""
from __future__ import annotations

import jax.numpy as jnp

from ._primitive import unwrap, wrap

__all__ = [
    "equal",
    "not_equal",
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "equal_all",
    "allclose",
    "isclose",
    "logical_and",
    "logical_or",
    "logical_not",
    "logical_xor",
    "bitwise_and",
    "bitwise_or",
    "bitwise_not",
    "bitwise_xor",
    "is_empty",
]


def _cmp(jfn):
    from ._primitive import primitive

    @primitive(nondiff=True, name=jfn.__name__)
    def fn(x, y=None, name=None):  # noqa: ARG001
        return jfn(jnp.asarray(x), jnp.asarray(y))

    return fn


equal = _cmp(jnp.equal)
not_equal = _cmp(jnp.not_equal)
less_than = _cmp(jnp.less)
less_equal = _cmp(jnp.less_equal)
greater_than = _cmp(jnp.greater)
greater_equal = _cmp(jnp.greater_equal)
logical_and = _cmp(jnp.logical_and)
logical_or = _cmp(jnp.logical_or)
logical_xor = _cmp(jnp.logical_xor)
bitwise_and = _cmp(jnp.bitwise_and)
bitwise_or = _cmp(jnp.bitwise_or)
bitwise_xor = _cmp(jnp.bitwise_xor)


def logical_not(x, name=None):  # noqa: ARG001
    return wrap(jnp.logical_not(unwrap(x)))


def bitwise_not(x, name=None):  # noqa: ARG001
    return wrap(jnp.bitwise_not(unwrap(x)))


def equal_all(x, y):
    return wrap(jnp.array_equal(unwrap(x), unwrap(y)))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return wrap(jnp.allclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol, equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return wrap(jnp.isclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol, equal_nan=equal_nan))


def is_empty(x):
    return wrap(jnp.asarray(unwrap(x).size == 0))
