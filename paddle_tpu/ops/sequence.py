"""Sequence ops — the reference's LoD-tensor family re-designed for dense
batches with explicit lengths.

Parity: paddle/fluid/operators/sequence_ops/ (sequence_pad, sequence_unpad,
sequence_expand, sequence_reverse, sequence_softmax, sequence_slice...) and
python/paddle/fluid/layers/sequence_lod.py. The reference threads raggedness
through LoD metadata on one flat tensor; TPU-native code wants static shapes,
so here a ragged batch is (flat_data, lengths) in and padded (batch, max_len,
...) out — the masks are XLA-friendly and jit-stable. sequence_mask lives in
nn.functional (the keystone helper these build on).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._primitive import primitive, unwrap, wrap

__all__ = [
    "sequence_pad",
    "sequence_unpad",
    "sequence_expand",
    "sequence_reverse",
    "sequence_softmax",
]


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Pack a flat ragged batch into a padded dense one (sequence_pad op).

    x: (sum(lengths), ...) flat rows; length: (B,) per-sequence row counts.
    Returns (padded (B, maxlen, ...), lengths)."""
    if length is None:
        raise ValueError("sequence_pad needs `length` (the LoD replacement)")
    lens = np.asarray(unwrap(length)).astype(np.int64)
    B = len(lens)
    ml = int(maxlen) if maxlen is not None else int(lens.max()) if B else 0
    if B and ml < int(lens.max()):
        raise ValueError(
            f"maxlen ({ml}) must cover the longest sequence ({int(lens.max())}) "
            "(reference sequence_pad enforces padded_length >= max length)")
    starts = np.concatenate([[0], np.cumsum(lens)[:-1]])

    @primitive
    def _pad(x, pad_value):
        # gather row indices per (b, t); OOB slots point at row 0 and are
        # overwritten by pad_value
        idx = starts[:, None] + np.arange(ml)[None, :]
        valid = np.arange(ml)[None, :] < lens[:, None]
        idx = np.where(valid, np.clip(idx, 0, max(x.shape[0] - 1, 0)), 0)
        out = x[jnp.asarray(idx)]
        mask = jnp.asarray(valid).reshape((B, ml) + (1,) * (x.ndim - 1))
        return jnp.where(mask, out, jnp.asarray(pad_value, x.dtype))

    from ..tensor import Tensor as _T

    out_lens = length if isinstance(length, _T) else wrap(jnp.asarray(lens))
    return _pad(x, unwrap(pad_value)), out_lens


def sequence_unpad(x, length, name=None):
    """Inverse of sequence_pad: drop padding back to flat rows
    (sequence_unpad op). Dynamic output rows — eager-only, like the
    reference's LoD output; differentiable (concrete slice bounds inside
    the taped closure)."""
    lens = np.asarray(unwrap(length)).astype(np.int64)

    @primitive
    def _unpad(x):
        rows = [x[b, : int(n)] for b, n in enumerate(lens)]
        return jnp.concatenate(rows, axis=0) if rows else x[:0, 0]

    return _unpad(x)


def sequence_expand(x, y_lengths, ref_level=0, name=None, x_lengths=None):
    """sequence_expand op in the dense+lengths redesign.

    Two forms (reference sequence_expand_op.cc semantics):
    - row form (no ``x_lengths``): x row i repeats ``y_lengths[i]`` times —
      the rank-0/LoD-level-1 case.
    - nested form (``x_lengths`` given): x's flat rows are partitioned into
      sequences by ``x_lengths``; SEQUENCE i (its whole row block) repeats
      ``y_lengths[i]`` times — the reference's 2-level-LoD expansion where
      ``ref_level`` indexes y's outer level (the dense redesign carries
      that level's counts directly in ``y_lengths``).
    """
    if ref_level not in (0, -1):
        raise NotImplementedError(
            "ref_level beyond the outer level: pass that level's counts as "
            "y_lengths directly (dense+lengths redesign)")
    lens = np.asarray(unwrap(y_lengths)).astype(np.int64)
    if x_lengths is None:
        if len(lens) != unwrap(x).shape[0]:
            raise ValueError(
                f"y_lengths has {len(lens)} entries but x has "
                f"{unwrap(x).shape[0]} rows; each row needs a repeat count")
        idx = np.repeat(np.arange(len(lens)), lens)
    else:
        xl = np.asarray(unwrap(x_lengths)).astype(np.int64)
        if len(lens) != len(xl):
            raise ValueError(
                f"y_lengths has {len(lens)} entries but x_lengths defines "
                f"{len(xl)} sequences")
        offs = np.concatenate([[0], np.cumsum(xl)])
        if offs[-1] != unwrap(x).shape[0]:
            raise ValueError(
                f"x_lengths sums to {offs[-1]} but x has "
                f"{unwrap(x).shape[0]} rows")
        parts = [np.tile(np.arange(offs[i], offs[i + 1]), int(r))
                 for i, r in enumerate(lens)]
        idx = (np.concatenate(parts) if parts
               else np.zeros((0,), np.int64)).astype(np.int64)

    @primitive
    def _exp(x):
        return x[jnp.asarray(idx)]

    return _exp(x)


def sequence_reverse(x, length=None, name=None):
    """Reverse each sequence's valid prefix, keeping padding in place
    (sequence_reverse op). x: (B, T, ...); length optional (full reverse
    when omitted)."""

    @primitive
    def _rev(x, lens):
        T = x.shape[1]
        pos = jnp.arange(T)[None, :]
        if lens is None:
            idx = T - 1 - pos
            idx = jnp.broadcast_to(idx, x.shape[:2])
        else:
            ln = lens.astype(jnp.int32)[:, None]
            valid = pos < ln
            idx = jnp.where(valid, ln - 1 - pos, pos)
        return jnp.take_along_axis(
            x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32),
            axis=1)

    return _rev(x, None if length is None else unwrap(length))


def sequence_softmax(x, length=None, name=None):
    """Softmax over each sequence's valid prefix (sequence_softmax op).
    x: (B, T); padding gets probability 0."""

    @primitive
    def _sm(x, lens):
        if lens is None:
            return jax.nn.softmax(x, axis=-1)
        pos = jnp.arange(x.shape[1])[None, :]
        valid = pos < lens.astype(jnp.int32)[:, None]
        masked = jnp.where(valid, x, jnp.asarray(-1e9, x.dtype))
        sm = jax.nn.softmax(masked, axis=-1)
        return jnp.where(valid, sm, 0.0)

    return _sm(x, None if length is None else unwrap(length))
