"""Sequence ops — the reference's LoD-tensor family re-designed for dense
batches with explicit lengths.

Parity: paddle/fluid/operators/sequence_ops/ (sequence_pad, sequence_unpad,
sequence_expand, sequence_reverse, sequence_softmax, sequence_slice...) and
python/paddle/fluid/layers/sequence_lod.py. The reference threads raggedness
through LoD metadata on one flat tensor; TPU-native code wants static shapes,
so here a ragged batch is (flat_data, lengths) in and padded (batch, max_len,
...) out — the masks are XLA-friendly and jit-stable. sequence_mask lives in
nn.functional (the keystone helper these build on).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._primitive import primitive, unwrap, wrap

__all__ = [
    "sequence_pad",
    "sequence_unpad",
    "sequence_expand",
    "sequence_expand_as",
    "sequence_reverse",
    "sequence_softmax",
    "sequence_concat",
    "sequence_pool",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_conv",
    "sequence_enumerate",
    "sequence_erase",
    "sequence_reshape",
    "sequence_scatter",
    "sequence_slice",
    "row_conv",
    "im2sequence",
    "sequence_topk_avg_pooling",
    "match_matrix_tensor",
]


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Pack a flat ragged batch into a padded dense one (sequence_pad op).

    x: (sum(lengths), ...) flat rows; length: (B,) per-sequence row counts.
    Returns (padded (B, maxlen, ...), lengths)."""
    if length is None:
        raise ValueError("sequence_pad needs `length` (the LoD replacement)")
    lens = np.asarray(unwrap(length)).astype(np.int64)
    B = len(lens)
    ml = int(maxlen) if maxlen is not None else int(lens.max()) if B else 0
    if B and ml < int(lens.max()):
        raise ValueError(
            f"maxlen ({ml}) must cover the longest sequence ({int(lens.max())}) "
            "(reference sequence_pad enforces padded_length >= max length)")
    starts = np.concatenate([[0], np.cumsum(lens)[:-1]])

    @primitive
    def _pad(x, pad_value):
        # gather row indices per (b, t); OOB slots point at row 0 and are
        # overwritten by pad_value
        idx = starts[:, None] + np.arange(ml)[None, :]
        valid = np.arange(ml)[None, :] < lens[:, None]
        idx = np.where(valid, np.clip(idx, 0, max(x.shape[0] - 1, 0)), 0)
        out = x[jnp.asarray(idx)]
        mask = jnp.asarray(valid).reshape((B, ml) + (1,) * (x.ndim - 1))
        return jnp.where(mask, out, jnp.asarray(pad_value, x.dtype))

    from ..tensor import Tensor as _T

    out_lens = length if isinstance(length, _T) else wrap(jnp.asarray(lens))
    return _pad(x, unwrap(pad_value)), out_lens


def sequence_unpad(x, length, name=None):
    """Inverse of sequence_pad: drop padding back to flat rows
    (sequence_unpad op). Dynamic output rows — eager-only, like the
    reference's LoD output; differentiable (concrete slice bounds inside
    the taped closure)."""
    lens = np.asarray(unwrap(length)).astype(np.int64)

    @primitive
    def _unpad(x):
        rows = [x[b, : int(n)] for b, n in enumerate(lens)]
        return jnp.concatenate(rows, axis=0) if rows else x[:0, 0]

    return _unpad(x)


def sequence_expand(x, y_lengths, ref_level=0, name=None, x_lengths=None):
    """sequence_expand op in the dense+lengths redesign.

    Two forms (reference sequence_expand_op.cc semantics):
    - row form (no ``x_lengths``): x row i repeats ``y_lengths[i]`` times —
      the rank-0/LoD-level-1 case.
    - nested form (``x_lengths`` given): x's flat rows are partitioned into
      sequences by ``x_lengths``; SEQUENCE i (its whole row block) repeats
      ``y_lengths[i]`` times — the reference's 2-level-LoD expansion where
      ``ref_level`` indexes y's outer level (the dense redesign carries
      that level's counts directly in ``y_lengths``).
    """
    if ref_level not in (0, -1):
        raise NotImplementedError(
            "ref_level beyond the outer level: pass that level's counts as "
            "y_lengths directly (dense+lengths redesign)")
    lens = np.asarray(unwrap(y_lengths)).astype(np.int64)
    if x_lengths is None:
        if len(lens) != unwrap(x).shape[0]:
            raise ValueError(
                f"y_lengths has {len(lens)} entries but x has "
                f"{unwrap(x).shape[0]} rows; each row needs a repeat count")
        idx = np.repeat(np.arange(len(lens)), lens)
    else:
        xl = np.asarray(unwrap(x_lengths)).astype(np.int64)
        if len(lens) != len(xl):
            raise ValueError(
                f"y_lengths has {len(lens)} entries but x_lengths defines "
                f"{len(xl)} sequences")
        offs = np.concatenate([[0], np.cumsum(xl)])
        if offs[-1] != unwrap(x).shape[0]:
            raise ValueError(
                f"x_lengths sums to {offs[-1]} but x has "
                f"{unwrap(x).shape[0]} rows")
        parts = [np.tile(np.arange(offs[i], offs[i + 1]), int(r))
                 for i, r in enumerate(lens)]
        idx = (np.concatenate(parts) if parts
               else np.zeros((0,), np.int64)).astype(np.int64)

    @primitive
    def _exp(x):
        return x[jnp.asarray(idx)]

    return _exp(x)


def sequence_reverse(x, length=None, name=None):
    """Reverse each sequence's valid prefix, keeping padding in place
    (sequence_reverse op). x: (B, T, ...); length optional (full reverse
    when omitted)."""

    @primitive
    def _rev(x, lens):
        T = x.shape[1]
        pos = jnp.arange(T)[None, :]
        if lens is None:
            idx = T - 1 - pos
            idx = jnp.broadcast_to(idx, x.shape[:2])
        else:
            ln = lens.astype(jnp.int32)[:, None]
            valid = pos < ln
            idx = jnp.where(valid, ln - 1 - pos, pos)
        return jnp.take_along_axis(
            x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32),
            axis=1)

    return _rev(x, None if length is None else unwrap(length))


def sequence_softmax(x, length=None, name=None):
    """Softmax over each sequence's valid prefix (sequence_softmax op).
    x: (B, T); padding gets probability 0."""

    @primitive
    def _sm(x, lens):
        if lens is None:
            return jax.nn.softmax(x, axis=-1)
        pos = jnp.arange(x.shape[1])[None, :]
        valid = pos < lens.astype(jnp.int32)[:, None]
        masked = jnp.where(valid, x, jnp.asarray(-1e9, x.dtype))
        sm = jax.nn.softmax(masked, axis=-1)
        return jnp.where(valid, sm, 0.0)

    return _sm(x, None if length is None else unwrap(length))


def sequence_expand_as(x, y_lengths, name=None):
    """sequence_expand_as op: row i of x repeats to the length of y's
    sequence i (sequence_expand_as_op.cc — x's own LoD is ignored)."""
    return sequence_expand(x, y_lengths, ref_level=0)


def sequence_concat(inputs, lengths, name=None):
    """Per-sequence concat of ragged batches (sequence_concat_op.cc): output
    sequence b = input0's seq b ++ input1's seq b ++ ... Inputs are flat
    (sum_i, ...) arrays with per-input lengths [B]. Returns
    (flat out, out_lengths)."""
    lens = [np.asarray(unwrap(ln)).astype(np.int64) for ln in lengths]
    B = len(lens[0])
    starts = [np.concatenate([[0], np.cumsum(ln)[:-1]]) for ln in lens]
    # row indices into the concatenation of all inputs — one gather, not
    # per-row slices
    input_offs = np.concatenate(
        [[0], np.cumsum([unwrap(x).shape[0] for x in inputs])[:-1]])
    gather = []
    for b in range(B):
        for k in range(len(lens)):
            s = int(input_offs[k] + starts[k][b])
            gather.append(np.arange(s, s + int(lens[k][b])))
    idx = (np.concatenate(gather) if gather else np.zeros((0,), np.int64))
    out_lens = np.stack([ln for ln in lens]).sum(axis=0)

    @primitive
    def _cat(*xs):
        return jnp.take(jnp.concatenate(list(xs), axis=0),
                        jnp.asarray(idx), axis=0)

    return _cat(*inputs), wrap(jnp.asarray(out_lens))


def sequence_pool(x, pool_type, length=None, pad_value=0.0, name=None):
    """Pool each sequence's valid prefix to one row (sequence_pool op,
    math/sequence_pooling.cc SequencePoolFunctor). x: (B, T, ...) padded;
    pool_type in SUM/AVERAGE/SQRT/MAX/LAST/FIRST. Empty sequences yield
    ``pad_value``."""
    ptype = pool_type.upper()
    if ptype not in ("SUM", "AVERAGE", "SQRT", "MAX", "LAST", "FIRST"):
        raise ValueError(f"unsupported pool_type {pool_type!r}")

    @primitive
    def _pool(x, lens):
        T = x.shape[1]
        if lens is None:
            ln = jnp.full((x.shape[0],), T, jnp.int32)
        else:
            ln = lens.astype(jnp.int32)
        pos = jnp.arange(T)[None, :]
        valid = (pos < ln[:, None]).reshape(
            (x.shape[0], T) + (1,) * (x.ndim - 2))
        lnf = ln.astype(x.dtype).reshape((-1,) + (1,) * (x.ndim - 2))
        if ptype == "MAX":
            neg = jnp.asarray(jnp.finfo(x.dtype).min
                              if jnp.issubdtype(x.dtype, jnp.floating)
                              else jnp.iinfo(x.dtype).min, x.dtype)
            out = jnp.max(jnp.where(valid, x, neg), axis=1)
        elif ptype == "FIRST":
            out = x[:, 0]
        elif ptype == "LAST":
            idx = jnp.maximum(ln - 1, 0)
            out = jnp.take_along_axis(
                x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1)[:, 0]
        else:
            s = jnp.sum(jnp.where(valid, x, 0), axis=1)
            if ptype == "AVERAGE":
                out = s / jnp.maximum(lnf, 1)
            elif ptype == "SQRT":
                out = s / jnp.sqrt(jnp.maximum(lnf, 1))
            else:
                out = s
        empty = (ln == 0).reshape((-1,) + (1,) * (x.ndim - 2))
        return jnp.where(empty, jnp.asarray(pad_value, x.dtype), out)

    return _pool(x, None if length is None else unwrap(length))


def sequence_first_step(x, length=None, name=None):
    return sequence_pool(x, "FIRST", length=length)


def sequence_last_step(x, length=None, name=None):
    return sequence_pool(x, "LAST", length=length)


def sequence_conv(x, weight, length=None, context_length=3, context_start=None,
                  bias=None, name=None):
    """Context-window projection (sequence_conv_op): each timestep gathers
    rows [t+start, t+start+context_length) of ITS OWN sequence (zeros
    outside), flattens to context_length*D and multiplies the filter
    [context_length*D, out]. x: (B, T, D) padded."""
    if context_start is None:
        context_start = -(context_length // 2)  # reference python default

    @primitive
    def _conv(x, w, b, lens):
        B, T, D = x.shape
        if lens is None:
            ln = jnp.full((B,), T, jnp.int32)
        else:
            ln = lens.astype(jnp.int32)
        pos = jnp.arange(T)
        cols = []
        for j in range(context_length):
            src = pos + context_start + j
            ok = (src >= 0) & (src < ln[:, None])
            g = jnp.take(x, jnp.clip(src, 0, T - 1), axis=1)
            cols.append(jnp.where(ok[..., None], g, 0.0))
        ctx = jnp.concatenate(cols, axis=-1)  # (B, T, ctx*D)
        out = jnp.einsum("btk,ko->bto", ctx, w)
        if b is not None:
            out = out + b
        # zero rows beyond each sequence's length
        valid = (pos[None, :] < ln[:, None])[..., None]
        return jnp.where(valid, out, 0.0)

    return _conv(x, weight, bias, None if length is None else unwrap(length))


def sequence_enumerate(x, win_size, pad_value=0, length=None, name=None):
    """Rolling windows per sequence (sequence_enumerate_op): out[t] =
    [x[t], ..., x[t+win-1]] with positions past the sequence end set to
    pad_value. x: (B, T) int ids (dense form of the flat LoD input)."""

    @primitive(nondiff=True)
    def _enum(x, lens):
        B, T = x.shape
        if lens is None:
            ln = jnp.full((B,), T, jnp.int32)
        else:
            ln = lens.astype(jnp.int32)
        pos = jnp.arange(T)
        outs = []
        for j in range(win_size):
            src = pos + j
            ok = src < ln[:, None]
            g = jnp.take(x, jnp.clip(src, 0, T - 1), axis=1)
            outs.append(jnp.where(ok, g, jnp.asarray(pad_value, x.dtype)))
        out = jnp.stack(outs, axis=-1)  # (B, T, win)
        valid = pos[None, :] < ln[:, None]
        return jnp.where(valid[..., None], out,
                         jnp.asarray(pad_value, x.dtype))

    return _enum(x, None if length is None else unwrap(length))


def sequence_erase(x, tokens, length=None, name=None):
    """Remove listed tokens from each sequence (sequence_erase_op). Dynamic
    per-sequence lengths — eager host op, like the reference's LoD output.
    x: (B, T) ids; returns (out (B, T) padded with 0, new_lengths)."""
    xs = np.asarray(unwrap(x))
    B, T = xs.shape
    if length is None:
        lens = np.full((B,), T, np.int64)
    else:
        lens = np.asarray(unwrap(length)).astype(np.int64)
    drop = set(int(t) for t in tokens)
    out = np.zeros_like(xs)
    new_lens = np.zeros((B,), np.int64)
    for b in range(B):
        kept = [v for v in xs[b, : int(lens[b])] if int(v) not in drop]
        out[b, : len(kept)] = kept
        new_lens[b] = len(kept)
    return wrap(jnp.asarray(out)), wrap(jnp.asarray(new_lens))


def sequence_reshape(x, new_dim, length=None, name=None):
    """Re-chunk each sequence's payload to ``new_dim`` columns
    (sequence_reshape_op): sequence b's len[b]*D values become
    len[b]*D/new_dim rows. x: flat (total, D) + lengths. Returns
    (flat (total*D/new_dim, new_dim), new_lengths)."""
    xs = unwrap(x)
    D = xs.shape[1]
    if length is None:
        lens = np.asarray([xs.shape[0]], np.int64)
    else:
        lens = np.asarray(unwrap(length)).astype(np.int64)
    if (lens * D % new_dim).any():
        raise ValueError("each sequence's payload must divide new_dim "
                         "(sequence_reshape_op InferShape)")

    @primitive
    def _rs(x):
        return x.reshape(-1, new_dim)

    return _rs(x), wrap(jnp.asarray(lens * D // new_dim))


def sequence_scatter(x, index, updates, index_lengths=None, name=None):
    """Scatter-add per-sequence updates into rows of x
    (sequence_scatter_op): for sequence b, x[b, index[j]] += updates[j].
    x: (B, D); index/updates: flat (sum_lens,) [+ (.., ) payload] with
    per-sequence counts ``index_lengths``."""
    idx = np.asarray(unwrap(index)).astype(np.int64)
    if index_lengths is None:
        lens = np.asarray([idx.shape[0]], np.int64)
    else:
        lens = np.asarray(unwrap(index_lengths)).astype(np.int64)
    rows = np.repeat(np.arange(len(lens)), lens)

    @primitive
    def _scatter(x, updates):
        return jnp.asarray(x).at[jnp.asarray(rows), jnp.asarray(idx)].add(
            jnp.asarray(updates).astype(x.dtype))

    return _scatter(x, updates)


def sequence_slice(x, offset, length, seq_lengths=None, name=None):
    """Per-sequence slice (sequence_slice_op): sequence b keeps rows
    [offset[b], offset[b]+length[b]). x: (B, T, ...) padded. Returns
    (out (B, max(length), ...) padded with 0, new lengths)."""
    offs = np.asarray(unwrap(offset)).astype(np.int64).reshape(-1)
    lns = np.asarray(unwrap(length)).astype(np.int64).reshape(-1)
    ml = int(lns.max()) if lns.size else 0

    @primitive
    def _slice(x):
        pos = jnp.arange(ml)[None, :]
        src = jnp.asarray(offs)[:, None] + pos
        ok = pos < jnp.asarray(lns)[:, None]
        g = jnp.take_along_axis(
            x, jnp.clip(src, 0, x.shape[1] - 1).reshape(
                (x.shape[0], ml) + (1,) * (x.ndim - 2)), axis=1)
        return jnp.where(ok.reshape((x.shape[0], ml) + (1,) * (x.ndim - 2)),
                         g, 0)

    return _slice(x), wrap(jnp.asarray(lns))


def row_conv(x, weight, length=None, name=None):
    """Lookahead row convolution (row_conv_op, DeepSpeech2): out[t] =
    sum_j w[j] * x[t+j] over the future context window, within-sequence.
    x: (B, T, D); weight: (context, D)."""

    @primitive
    def _rc(x, w, lens):
        B, T, D = x.shape
        if lens is None:
            ln = jnp.full((B,), T, jnp.int32)
        else:
            ln = lens.astype(jnp.int32)
        pos = jnp.arange(T)
        out = jnp.zeros_like(x)
        for j in range(w.shape[0]):
            src = pos + j
            ok = src < ln[:, None]
            g = jnp.take(x, jnp.clip(src, 0, T - 1), axis=1)
            out = out + jnp.where(ok[..., None], g, 0.0) * w[j]
        valid = (pos[None, :] < ln[:, None])[..., None]
        return jnp.where(valid, out, 0.0)

    return _rc(x, weight, None if length is None else unwrap(length))


def im2sequence(x, filter_size, stride=1, padding=0, name=None):
    """Image patches → sequence rows (im2sequence_op): NCHW input becomes
    (N*out_h*out_w, kh*kw*C) rows in raster order."""
    kh, kw = ((filter_size, filter_size)
              if isinstance(filter_size, int) else tuple(filter_size))
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    if isinstance(padding, int):
        pad = (padding, padding, padding, padding)
    else:
        pad = tuple(padding)
        if len(pad) == 2:
            pad = pad + pad

    @primitive
    def _im2seq(x):
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), (sh, sw),
            padding=((pad[0], pad[2]), (pad[1], pad[3])))
        # patches: (N, C*kh*kw, oh, ow) with channel-major feature order
        n, f, oh, ow = patches.shape
        out = jnp.transpose(patches, (0, 2, 3, 1)).reshape(n * oh * ow, f)
        return out

    return _im2seq(x)


def sequence_topk_avg_pooling(x, row_lengths, col_lengths, topks, channel_num,
                              name=None):
    """Top-k average pooling over match-matrix columns
    (sequence_ops/sequence_topk_avg_pooling_op.h): for every (batch, channel,
    row), average the top-k column scores for each k in ``topks``.

    Dense+lengths redesign of the LoD op: x is the padded match matrix
    [B, channel_num, Rmax, Cmax] (the reference's flat per-batch
    channel-major rows ≙ x[b, c, r]); row_lengths/col_lengths [B] give the
    valid extent. Returns [B, Rmax, channel_num * len(topks)] with the
    reference's row-major (row, channel, k) layout; padding rows are zero.
    When a row has fewer than k valid columns the reference's prefix-sum
    carry (sum over the valid ones, still divided by k) is reproduced.
    """
    topks = [int(k) for k in topks]
    if any(k <= 0 for k in topks):
        raise ValueError("sequence_topk_avg_pooling: topks must be positive")
    max_k = max(topks)

    @primitive
    def _topk_avg(x, rl, cl):
        b, c, rmax, cmax = x.shape
        col_ok = jnp.arange(cmax)[None, :] < cl[:, None]          # [B, Cmax]
        neg = jnp.asarray(-jnp.inf, x.dtype)
        masked = jnp.where(col_ok[:, None, None, :], x, neg)
        # top max_k column values per (b, c, r), descending
        kk = min(max_k, cmax)
        vals = jax.lax.top_k(masked, kk)[0]                        # [B,C,R,kk]
        if kk < max_k:
            vals = jnp.pad(vals, ((0, 0),) * 3 + ((0, max_k - kk),),
                           constant_values=-jnp.inf)
        take = jnp.arange(max_k)[None, :] < cl[:, None]            # [B, max_k]
        contrib = jnp.where(take[:, None, None, :], vals, 0.0)
        contrib = jnp.where(jnp.isfinite(contrib), contrib, 0.0)
        prefix = jnp.cumsum(contrib, axis=-1)                      # [B,C,R,max_k]
        outs = [prefix[..., k - 1] / k for k in topks]             # each [B,C,R]
        out = jnp.stack(outs, axis=-1)                             # [B,C,R,K]
        out = jnp.transpose(out, (0, 2, 1, 3))                     # [B,R,C,K]
        row_ok = jnp.arange(rmax)[None, :] < rl[:, None]
        out = jnp.where(row_ok[:, :, None, None], out, 0.0)
        return out.reshape(b, rmax, c * len(topks))

    return _topk_avg(x, unwrap(row_lengths), unwrap(col_lengths))


def match_matrix_tensor(x, y, w, x_lengths, y_lengths, dim_t=None, name=None):
    """Semantic-matching tensor layer (match_matrix_tensor_op.cc): for each
    batch pair of sequences, out[b, t, i, j] = x_i^T @ W[:, t, :] @ y_j.

    Dense+lengths redesign: x [B, Lmax, D], y [B, Rmax, D] padded,
    w [D, dim_t, D], lengths [B]. Returns (out [B, dim_t, Lmax, Rmax] with
    zero padding, tmp [B, Lmax, dim_t, D] — the reference's Tmp = x @ W
    intermediate). Differentiable; the reference's LoD output layout
    (dim_t*len_l*len_r rows per batch) is recovered by slicing valid
    extents."""
    w_dim_t = int(unwrap(w).shape[1])
    if dim_t is not None and int(dim_t) != w_dim_t:
        raise ValueError(
            f"match_matrix_tensor: dim_t ({dim_t}) != W.shape[1] ({w_dim_t})")

    @primitive
    def _mmt(x, y, w, xl, yl):
        b, lmax, d = x.shape
        rmax = y.shape[1]
        tmp = jnp.einsum("bld,dte->blte", x, w)          # [B, L, T, D]
        out = jnp.einsum("blte,bre->btlr", tmp, y)       # [B, T, L, R]
        lok = jnp.arange(lmax)[None, :] < xl[:, None]
        rok = jnp.arange(rmax)[None, :] < yl[:, None]
        mask = lok[:, None, :, None] & rok[:, None, None, :]
        return jnp.where(mask, out, 0.0), tmp

    return _mmt(x, y, w, unwrap(x_lengths), unwrap(y_lengths))
