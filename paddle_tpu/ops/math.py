"""Elementwise + reduction math ops.

Parity: the reference's elementwise/, reduce_ops/, activation and scalar math
operators (/root/reference/paddle/fluid/operators/elementwise/,
reduce_ops/reduce_op.cu.h, activation_op.cc) and the python surface
python/paddle/tensor/math.py. Broadcasting, dtype promotion and fusion are
XLA's job here — the reference's hand-written broadcast fast paths
(elementwise_op_function.h) have no equivalent because the compiler owns them.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..dtype import to_jax_dtype
from ..tensor import Tensor
from ._primitive import primitive, unwrap, wrap

# ---------------------------------------------------------------------------
# unary elementwise
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs,
    "acos": jnp.arccos,
    "asin": jnp.arcsin,
    "atan": jnp.arctan,
    "acosh": jnp.arccosh,
    "asinh": jnp.arcsinh,
    "atanh": jnp.arctanh,
    "ceil": jnp.ceil,
    "cos": jnp.cos,
    "cosh": jnp.cosh,
    "digamma": jax.scipy.special.digamma,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "floor": jnp.floor,
    "i0": lambda x: jax.scipy.special.i0(x),
    "lgamma": jax.scipy.special.gammaln,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "neg": jnp.negative,
    "reciprocal": jnp.reciprocal,
    "rsqrt": jax.lax.rsqrt,
    "sign": jnp.sign,
    "sin": jnp.sin,
    "sinh": jnp.sinh,
    "sqrt": jnp.sqrt,
    "square": jnp.square,
    "tan": jnp.tan,
    "tanh": jnp.tanh,
    "trunc": jnp.trunc,
    "angle": jnp.angle,
    "conj": jnp.conj,
    "real": jnp.real,
    "imag": jnp.imag,
    "deg2rad": jnp.deg2rad,
    "rad2deg": jnp.rad2deg,
    "sigmoid": jax.nn.sigmoid,
}

_g = globals()
for _name, _fn in _UNARY.items():
    _g[_name] = primitive(_fn, name=_name)


@primitive
def round(x):  # noqa: A001
    return jnp.round(x)


@primitive
def frac(x):
    return x - jnp.trunc(x)


@primitive
def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@primitive
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


# ---------------------------------------------------------------------------
# binary elementwise
# ---------------------------------------------------------------------------


def _binop(jfn, name):
    def fn(x, y, name=None):  # noqa: ARG001 - paddle passes name kwarg
        return _prim(x, y)

    _prim = primitive(lambda x, y: jfn(jnp.asarray(unwrap(x)), jnp.asarray(unwrap(y))), name=name)
    fn.__name__ = name
    fn.raw = jfn
    return fn


add = _binop(jnp.add, "add")
subtract = _binop(jnp.subtract, "subtract")
multiply = _binop(jnp.multiply, "multiply")
divide = _binop(jnp.true_divide, "divide")
floor_divide = _binop(jnp.floor_divide, "floor_divide")
remainder = _binop(jnp.remainder, "remainder")
mod = remainder
floor_mod = remainder
pow = _binop(jnp.power, "pow")  # noqa: A001
maximum = _binop(jnp.maximum, "maximum")
minimum = _binop(jnp.minimum, "minimum")
fmax = _binop(jnp.fmax, "fmax")
fmin = _binop(jnp.fmin, "fmin")
atan2 = _binop(jnp.arctan2, "atan2")
heaviside = _binop(jnp.heaviside, "heaviside")
kron = _binop(jnp.kron, "kron")
gcd = _binop(jnp.gcd, "gcd")
lcm = _binop(jnp.lcm, "lcm")
logaddexp = _binop(jnp.logaddexp, "logaddexp")
hypot = _binop(jnp.hypot, "hypot")
copysign = _binop(jnp.copysign, "copysign")
nextafter = _binop(jnp.nextafter, "nextafter")
ldexp = _binop(lambda x, y: jnp.ldexp(x, y.astype(jnp.int32)), "ldexp")


@primitive
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    scale = jnp.asarray(scale, x.dtype) if not hasattr(scale, "dtype") else scale.astype(x.dtype)
    if bias_after_scale:
        out = x * scale + jnp.asarray(bias, x.dtype)
    else:
        out = (x + jnp.asarray(bias, x.dtype)) * scale
    if act == "relu":
        out = jax.nn.relu(out)
    elif act == "tanh":
        out = jnp.tanh(out)
    return out


@primitive
def clip(x, min=None, max=None):  # noqa: A002
    return jnp.clip(x, unwrap(min), unwrap(max))


@primitive
def lerp(x, y, weight):
    return x + unwrap(weight) * (y - x)


@primitive
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@primitive
def multiplex(inputs, index):
    stacked = jnp.stack(inputs, axis=0)  # [n, batch, ...]
    idx = jnp.reshape(index, (-1,))
    return stacked[idx, jnp.arange(stacked.shape[1])]


def increment(x, value=1.0):
    x._set_data(x._data + jnp.asarray(value, x._data.dtype))
    return x


def assign(x, output=None):
    from .creation import assign as _assign

    out = _assign(x)
    if output is not None:
        output._set_data(out._data)
        return output
    return out


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@primitive
def _sum(x, axis, keepdim, dtype):
    return jnp.sum(x, axis=axis, keepdims=keepdim, dtype=dtype)


def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    return _sum(x, _axis(axis), keepdim, to_jax_dtype(dtype) if dtype else None)


@primitive
def _mean(x, axis, keepdim):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def mean(x, axis=None, keepdim=False):
    return _mean(x, _axis(axis), keepdim)


@primitive
def _prod(x, axis, keepdim, dtype):
    return jnp.prod(x, axis=axis, keepdims=keepdim, dtype=dtype)


def prod(x, axis=None, keepdim=False, dtype=None):
    return _prod(x, _axis(axis), keepdim, to_jax_dtype(dtype) if dtype else None)


@primitive
def _max(x, axis, keepdim):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim=False):  # noqa: A001
    return _max(x, _axis(axis), keepdim)


@primitive
def _min(x, axis, keepdim):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False):  # noqa: A001
    return _min(x, _axis(axis), keepdim)


amax = max
amin = min


@primitive
def _logsumexp(x, axis, keepdim):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False):
    return _logsumexp(x, _axis(axis), keepdim)


@primitive
def _std(x, axis, unbiased, keepdim):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False):
    return _std(x, _axis(axis), unbiased, keepdim)


@primitive
def _var(x, axis, unbiased, keepdim):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False):
    return _var(x, _axis(axis), unbiased, keepdim)


@primitive
def _median(x, axis, keepdim):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def median(x, axis=None, keepdim=False):
    return _median(x, _axis(axis), keepdim)


@primitive
def _quantile(x, q, axis, keepdim):
    return jnp.quantile(x, q, axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False):
    return _quantile(x, q, _axis(axis), keepdim)


@primitive
def _nanmean(x, axis, keepdim):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False):
    return _nanmean(x, _axis(axis), keepdim)


@primitive
def _nansum(x, axis, keepdim):
    return jnp.nansum(x, axis=axis, keepdims=keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False):  # noqa: ARG001
    return _nansum(x, _axis(axis), keepdim)


def all(x, axis=None, keepdim=False):  # noqa: A001
    return wrap(jnp.all(unwrap(x), axis=_axis(axis), keepdims=keepdim))


def any(x, axis=None, keepdim=False):  # noqa: A001
    return wrap(jnp.any(unwrap(x), axis=_axis(axis), keepdims=keepdim))


def count_nonzero(x, axis=None, keepdim=False):
    return wrap(jnp.count_nonzero(unwrap(x), axis=_axis(axis), keepdims=keepdim))


def numel(x):
    return wrap(jnp.asarray(unwrap(x).size, jnp.int64))


# ---------------------------------------------------------------------------
# cumulative / running
# ---------------------------------------------------------------------------


@primitive
def _cumsum(x, axis):
    return jnp.cumsum(x, axis=axis)


def cumsum(x, axis=None, dtype=None):
    if axis is None:
        from .manipulation import reshape

        out = _cumsum(reshape(x, [-1]), 0)
    else:
        out = _cumsum(x, int(axis))
    if dtype is not None:
        out = out.astype(dtype)
    return out


@primitive
def _cumprod(x, axis):
    return jnp.cumprod(x, axis=axis)


def cumprod(x, dim=None, dtype=None):
    out = _cumprod(x, int(dim))
    if dtype is not None:
        out = out.astype(dtype)
    return out


@primitive
def cummax_values(x, axis):
    return jax.lax.cummax(x, axis=axis)


@primitive
def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


# ---------------------------------------------------------------------------
# linear-algebra-lite that lives in paddle.tensor.math
# ---------------------------------------------------------------------------


@primitive
def addmm(input, x, y, beta=1.0, alpha=1.0):  # noqa: A002
    return beta * input + alpha * (x @ y)


@primitive
def inner(x, y):
    return jnp.inner(x, y)


@primitive
def outer(x, y):
    return jnp.outer(x, y)


@primitive
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def isfinite(x):
    return wrap(jnp.isfinite(unwrap(x)))


def isinf(x):
    return wrap(jnp.isinf(unwrap(x)))


def isnan(x):
    return wrap(jnp.isnan(unwrap(x)))


@primitive
def _logcumsumexp(x, axis):
    return jax.lax.associative_scan(jnp.logaddexp, x, axis=axis)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """Running log(sum(exp(x))) along an axis (parity: logcumsumexp op).
    axis=None flattens first, like the reference."""
    if dtype is not None:
        from .manipulation import cast

        x = cast(x, dtype)
    if axis is None:
        from .manipulation import flatten

        return _logcumsumexp(flatten(x), 0)
    return _logcumsumexp(x, axis)


@primitive
def _renorm(x, p, axis, max_norm):
    axes = tuple(i for i in range(x.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12), 1.0)
    return x * scale


def renorm(x, p, axis, max_norm):
    """Clamp the p-norm of every sub-tensor along `axis` to max_norm
    (parity: renorm op, reference operators/renorm_op.*)."""
    return _renorm(x, float(p), axis % len(x.shape), float(max_norm))


@primitive
def _polygamma(x, n):
    return jax.scipy.special.polygamma(n, x)


def polygamma(x, n, name=None):
    """n-th derivative of digamma (parity: polygamma op)."""
    return _polygamma(x, int(n))


@primitive
def _sgn(x):
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0.0 + 0.0j, x / jnp.maximum(mag, 1e-38))
    return jnp.sign(x)


def sgn(x, name=None):
    """sign for real, x/|x| for complex (parity: paddle.sgn)."""
    return _sgn(x)


@primitive
def _nanquantile(x, q, axis, keepdim):
    return jnp.nanquantile(x, q, axis=axis, keepdims=keepdim)


def nanquantile(x, q, axis=None, keepdim=False):
    return _nanquantile(x, q, _axis(axis), keepdim)


def add_n(inputs, name=None):
    """Elementwise sum of a tensor list (parity: sum op / paddle.add_n)."""
    if isinstance(inputs, (list, tuple)):
        @primitive
        def _add_n(*xs):
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out

        return _add_n(*inputs)
    return inputs


def mm(input, mat2, name=None):  # noqa: A002
    """Alias of matmul (parity: paddle.mm)."""
    from .linalg import matmul

    return matmul(input, mat2)


@primitive
def _tensordot(x, y, axes):
    return jnp.tensordot(x, y, axes=axes)


def tensordot(x, y, axes=2, name=None):
    """Generalized tensor contraction (parity: paddle.tensordot).

    axes: int (last-n of x vs first-n of y), a flat int list (contract those
    axes of BOTH tensors, paddle semantics), or a pair of axis lists."""
    import builtins

    if isinstance(axes, (list, tuple)):
        if builtins.all(isinstance(a, (int,)) for a in axes):
            # flat list applies to both operands
            axes = (tuple(axes), tuple(axes))
        else:
            axes = (tuple(axes[0]) if isinstance(axes[0], (list, tuple)) else (axes[0],),
                    tuple(axes[1]) if isinstance(axes[1], (list, tuple)) else (axes[1],))
    return _tensordot(x, y, axes)


def tanh_(x, name=None):
    """In-place tanh (parity: paddle.tanh_)."""
    from ._primitive import inplace_guard

    inplace_guard(x, "tanh_")
    x._set_data(jnp.tanh(x._data))
    return x


@primitive
def _nanmedian(x, axis, keepdim):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return _nanmedian(x, axis, keepdim)


@primitive
def _trapezoid(y, x, dx, axis):
    if x is not None:
        return jnp.trapezoid(y, x=x, axis=axis)
    return jnp.trapezoid(y, dx=dx, axis=axis)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """trapezoid op (numerical integration; reference paddle.trapezoid)."""
    return _trapezoid(y, x, 1.0 if dx is None else float(dx), axis)


@primitive
def _take(x, index, mode):
    flat = x.reshape(-1)
    n = flat.shape[0]
    if mode == "wrap":
        idx = index % n
    elif mode == "clip":
        idx = jnp.clip(index, 0, n - 1)
    else:  # 'raise' semantics: jit can't raise; negatives count from the end
        idx = jnp.clip(index, -n, n - 1)
        idx = jnp.where(idx < 0, idx + n, idx)
    return jnp.take(flat, idx, mode="wrap" if mode == "wrap" else "clip")


def take(x, index, mode="raise", name=None):
    """Flat-index take (reference paddle.take)."""
    return _take(x, unwrap(index), mode)


def polar(abs, angle, name=None):  # noqa: A002
    """polar op: complex from magnitude+angle."""
    @primitive(name="polar")
    def _polar(r, t):
        return r * jnp.exp(1j * t.astype(jnp.result_type(t, jnp.complex64)))

    return _polar(abs, angle)


@primitive(nondiff=True)
def _shift(x, y, direction, logical):
    if direction == "left":
        return jnp.left_shift(x, y)
    if logical and jnp.issubdtype(x.dtype, jnp.signedinteger):
        # logical shift: operate on the raw bit pattern (reference
        # is_arithmetic=False semantics)
        u = x.astype(jnp.dtype(f"uint{x.dtype.itemsize * 8}"))
        return jnp.right_shift(u, y.astype(u.dtype)).astype(x.dtype)
    return jnp.right_shift(x, y)


def bitwise_left_shift(x, y, is_arithmetic=True, name=None):
    return _shift(x, unwrap(y), "left", not is_arithmetic)


def bitwise_right_shift(x, y, is_arithmetic=True, name=None):
    return _shift(x, unwrap(y), "right", not is_arithmetic)
