"""Fused rotary position embedding (RoPE) as a Pallas TPU kernel.

Parity role: the north-star capability list names "fused RoPE" among the
kernels the reference implements in CUDA (the reference's fused attention
family, /root/reference/paddle/fluid/operators/fused/fused_attention_op.cu,
plus PaddleNLP's fused_rope usage); this is the TPU-native version.

Design: NeoX-style half-split rotation on [BH, T, D] blocks. The rotate-half
is a lane roll by D/2 with a sign flip on the first half, so the whole op is
three VPU multiplies and one roll per block — one HBM read and one write
(bandwidth-bound; the unfused jnp path materializes the two halves and the
concat separately). cos/sin come in precomputed [T, D] (symmetric halves).

The backward IS the forward with negated sin (inverse rotation), so the
custom vjp reuses the same kernel.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["rope", "rope_reference", "build_rope_cache"]

BLOCK_T = 256


def build_rope_cache(t: int, d: int, base: float = 10000.0, dtype=jnp.float32):
    """cos/sin tables [T, D] with symmetric halves (NeoX half-split)."""
    inv = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = jnp.outer(jnp.arange(t, dtype=jnp.float32), inv)  # [T, D/2]
    ang = jnp.concatenate([ang, ang], axis=-1)  # symmetric halves
    return jnp.cos(ang).astype(dtype), jnp.sin(ang).astype(dtype)


def rope_reference(x, cos, sin):
    """Unfused jnp reference (and CPU fallback): NeoX half-split rotate."""
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return (x * cos + rot * sin.astype(x.dtype)).astype(x.dtype)


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref, *, d):
    x = x_ref[0].astype(jnp.float32)  # lane rotates only lower for f32
    cos = cos_ref[:]
    sin = sin_ref[:]
    rolled = pltpu.roll(x, d // 2, 1)
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    sign = jnp.where(col < d // 2, -1.0, 1.0).astype(jnp.float32)
    out = x * cos + rolled * sign * sin
    o_ref[0] = out.astype(o_ref.dtype)


def _rope_fwd_raw(x, cos, sin, block_t, interpret):
    bh, t, d = x.shape
    kern = functools.partial(_rope_kernel, d=d)
    return pl.pallas_call(
        kern,
        grid=(bh, pl.cdiv(t, block_t)),
        in_specs=[
            pl.BlockSpec((1, block_t, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((block_t, d), lambda b, i: (i, 0)),
            pl.BlockSpec((block_t, d), lambda b, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_t, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), x.dtype),
        interpret=interpret,
        name="rope_fwd",
    )(x, cos, sin)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _rope(x, cos, sin, block_t, interpret):
    return _rope_fwd_raw(x, cos, sin, block_t, interpret)


def _rope_vjp_fwd(x, cos, sin, block_t, interpret):
    return _rope_fwd_raw(x, cos, sin, block_t, interpret), (cos, sin)


def _rope_vjp_bwd(block_t, interpret, res, g):
    cos, sin = res
    # inverse rotation: the same kernel with -sin
    return _rope_fwd_raw(g, cos, -sin, block_t, interpret), None, None


_rope.defvjp(_rope_vjp_fwd, _rope_vjp_bwd)


def rope(x, cos, sin, *, block_t: int = BLOCK_T, interpret=None):
    """Apply rotary embedding to [B, H, T, D] or [BH, T, D] arrays.

    cos/sin: [T, D] from :func:`build_rope_cache`. D must be lane-friendly
    (multiple of 128 for the rolled layout); other shapes use the jnp
    reference path.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    d = x.shape[-1]
    t = x.shape[-2]
    if d % 128 != 0 or t % 128 != 0:
        return rope_reference(x, cos, sin)
    squeeze4 = x.ndim == 4
    if squeeze4:
        b, h, tt, dd = x.shape
        x = x.reshape(b * h, tt, dd)
    bt = min(block_t, x.shape[1])
    out = _rope(x, jnp.asarray(cos, jnp.float32), jnp.asarray(sin, jnp.float32),
                bt, bool(interpret))
    if squeeze4:
        out = out.reshape(b, h, tt, dd)
    return out


def _rope_cost(in_avals, out_avals, params):
    """Bandwidth-bound: one read + one write of x plus the tables; 4 VPU
    ops per element (mul, mul, mul, add — the roll is free lane traffic)."""
    from .cost_registry import aval_bytes
    x_av = in_avals[0]
    n = 1
    for s in x_av[0]:
        n *= int(s)
    bts = sum(aval_bytes(a) for a in in_avals) \
        + sum(aval_bytes(a) for a in out_avals)
    return 4.0 * n, bts


def _register_costs():
    from .cost_registry import register_kernel_cost
    register_kernel_cost("rope_fwd", _rope_cost, family="rope",
                         operand_roles=("x", "cos", "sin"))


_register_costs()
