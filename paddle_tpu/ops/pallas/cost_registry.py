"""Kernel cost registry: ``pallas_call`` name → ``(flops, bytes)`` model.

The analysis plane walks jaxprs, not kernel bodies: a ``pallas_call`` eqn
is opaque to the per-prim cost tables in :mod:`paddle_tpu.analysis.cost`,
so until r20 every kernel was priced by the loud bytes-only fallback and
tallied in ``GraphCost.unknown`` — planner v2 and the perf doctor treated
a kernel-enabled program as free memory traffic.  This registry closes
the loop: each shipped kernel registers an analytic ``(flops, bytes)``
model under the explicit ``name=`` it passes to ``pl.pallas_call``, and
``cost_eqn`` prices the eqn from the registry.  Unregistered kernels keep
the bytes-only fallback (never silently zero-costed).

The contract
------------
* A model is ``model(in_avals, out_avals, params) -> (flops, bytes)``.
  ``in_avals`` / ``out_avals`` are the walker's ``(shape, dtype, weak)``
  triples in eqn operand order (scalar-prefetch operands first when the
  kernel uses ``PrefetchScalarGridSpec``); ``params`` are the eqn's light
  params (``grid_mapping`` etc. — the ``jaxpr`` param is dropped).
* ``bytes`` is total HBM traffic the kernel actually moves — which is the
  whole point: the paged-attention kernel reads each touched K/V page
  once, while the XLA gather path it replaces materializes (and re-reads)
  the full gathered ``[B, S, H, D]`` tensor plus the score matrix.
* Registration happens at kernel-module import; the cost model pulls the
  built-in kernels in lazily via :func:`kernel_cost_model` so
  ``analysis.cost`` never imports pallas at module import time.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np

__all__ = [
    "register_kernel_cost",
    "kernel_cost_model",
    "registered_kernels",
]

CostModel = Callable[[tuple, tuple, dict], Tuple[float, float]]

_REGISTRY: Dict[str, CostModel] = {}
_BUILTIN_LOADED = False


def register_kernel_cost(name: str, model: CostModel) -> CostModel:
    """Register ``model`` under kernel ``name`` (the explicit ``name=`` the
    kernel passes to ``pl.pallas_call``).  Re-registration replaces —
    kernel modules own their names."""
    if not name:
        raise ValueError("kernel cost model needs a non-empty name")
    _REGISTRY[str(name)] = model
    return model


def _ensure_builtin():
    """Import the in-tree kernel modules once so their import-time
    registrations land before the first lookup (the analysis plane may
    price a jaxpr traced elsewhere without importing ops.pallas itself)."""
    global _BUILTIN_LOADED
    if _BUILTIN_LOADED:
        return
    _BUILTIN_LOADED = True
    from . import (  # noqa: F401
        flash_attention,
        fused_ln,
        paged_attention,
        rope,
        softmax_ce,
        swiglu,
    )


def kernel_cost_model(name: Optional[str]) -> Optional[CostModel]:
    """The registered model for kernel ``name``, or None (→ the caller
    keeps the bytes-only unknown fallback)."""
    if not name:
        return None
    _ensure_builtin()
    return _REGISTRY.get(str(name))


def registered_kernels():
    _ensure_builtin()
    return sorted(_REGISTRY)


# -- shared helpers for the in-tree models ----------------------------------
def aval_bytes(aval_info) -> int:
    shape, dtype, _ = aval_info
    if dtype is None:
        return 0
    try:
        item = np.dtype(dtype).itemsize
    except TypeError:
        item = 16
    n = 1
    for s in shape:
        n *= int(s)
    return n * item


def itemsize(aval_info) -> int:
    dtype = aval_info[1]
    if dtype is None:
        return 0
    try:
        return np.dtype(dtype).itemsize
    except TypeError:
        return 16
