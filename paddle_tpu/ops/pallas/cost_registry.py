"""Kernel cost registry: ``pallas_call`` name → ``(flops, bytes)`` model.

The analysis plane walks jaxprs, not kernel bodies: a ``pallas_call`` eqn
is opaque to the per-prim cost tables in :mod:`paddle_tpu.analysis.cost`,
so until r20 every kernel was priced by the loud bytes-only fallback and
tallied in ``GraphCost.unknown`` — planner v2 and the perf doctor treated
a kernel-enabled program as free memory traffic.  This registry closes
the loop: each shipped kernel registers an analytic ``(flops, bytes)``
model under the explicit ``name=`` it passes to ``pl.pallas_call``, and
``cost_eqn`` prices the eqn from the registry.  Unregistered kernels keep
the bytes-only fallback (never silently zero-costed).

The contract
------------
* A model is ``model(in_avals, out_avals, params) -> (flops, bytes)``.
  ``in_avals`` / ``out_avals`` are the walker's ``(shape, dtype, weak)``
  triples in eqn operand order (scalar-prefetch operands first when the
  kernel uses ``PrefetchScalarGridSpec``); ``params`` are the eqn's light
  params (``grid_mapping`` etc. — the ``jaxpr`` param is dropped).
* ``bytes`` is total HBM traffic the kernel actually moves — which is the
  whole point: the paged-attention kernel reads each touched K/V page
  once, while the XLA gather path it replaces materializes (and re-reads)
  the full gathered ``[B, S, H, D]`` tensor plus the score matrix.
* Registration happens at kernel-module import; the cost model pulls the
  built-in kernels in lazily via :func:`kernel_cost_model` so
  ``analysis.cost`` never imports pallas at module import time.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import numpy as np

__all__ = [
    "KernelMeta",
    "register_kernel_cost",
    "kernel_cost_model",
    "kernel_meta",
    "registered_kernels",
]

CostModel = Callable[[tuple, tuple, dict], Tuple[float, float]]


@dataclasses.dataclass(frozen=True)
class KernelMeta:
    """Per-kernel registry metadata the kernel doctor (r24) consumes.

    ``family`` groups variants of one algorithm ("flash_attention",
    "paged_attention", ...) so lint findings and sweep rows aggregate;
    ``operand_roles`` names the eqn operands in *pallas_call operand
    order* (scalar-prefetch operands first for PrefetchScalarGridSpec
    kernels) so coverage proofs and drift rows read as prose, not
    ``args[3]``."""

    family: str = ""
    operand_roles: Tuple[str, ...] = ()

    def to_dict(self) -> dict:
        return {"family": self.family,
                "operand_roles": list(self.operand_roles)}


_REGISTRY: Dict[str, CostModel] = {}
_META: Dict[str, KernelMeta] = {}
_BUILTIN_LOADED = False


def register_kernel_cost(name: str, model: CostModel, *,
                         family: str = "",
                         operand_roles: Tuple[str, ...] = ()) -> CostModel:
    """Register ``model`` under kernel ``name`` (the explicit ``name=`` the
    kernel passes to ``pl.pallas_call``).  Re-registration replaces —
    kernel modules own their names.  ``family``/``operand_roles`` are the
    doctor-facing metadata (see :class:`KernelMeta`); registering without
    them keeps the r20 call signature working but the kernel doctor flags
    the empty metadata as a LOW finding."""
    if not name:
        raise ValueError("kernel cost model needs a non-empty name")
    _REGISTRY[str(name)] = model
    _META[str(name)] = KernelMeta(family=str(family),
                                  operand_roles=tuple(operand_roles))
    return model


def _ensure_builtin():
    """Import the in-tree kernel modules once so their import-time
    registrations land before the first lookup (the analysis plane may
    price a jaxpr traced elsewhere without importing ops.pallas itself)."""
    global _BUILTIN_LOADED
    if _BUILTIN_LOADED:
        return
    _BUILTIN_LOADED = True
    from . import (  # noqa: F401
        flash_attention,
        fused_ln,
        paged_attention,
        rope,
        softmax_ce,
        swiglu,
    )


def kernel_cost_model(name: Optional[str]) -> Optional[CostModel]:
    """The registered model for kernel ``name``, or None (→ the caller
    keeps the bytes-only unknown fallback)."""
    if not name:
        return None
    _ensure_builtin()
    return _REGISTRY.get(str(name))


def kernel_meta(name: Optional[str]) -> Optional[KernelMeta]:
    """The :class:`KernelMeta` registered for ``name``, or None."""
    if not name:
        return None
    _ensure_builtin()
    return _META.get(str(name))


def registered_kernels() -> Dict[str, KernelMeta]:
    """Name → :class:`KernelMeta` for every registered kernel, sorted by
    name.  (r24: was a bare name list; a dict keeps ``in``/iteration
    working for existing callers while giving the doctor its metadata.)"""
    _ensure_builtin()
    return {name: _META[name] for name in sorted(_REGISTRY)}


# -- shared helpers for the in-tree models ----------------------------------
def aval_bytes(aval_info) -> int:
    shape, dtype, _ = aval_info
    if dtype is None:
        return 0
    try:
        item = np.dtype(dtype).itemsize
    except TypeError:
        item = 16
    n = 1
    for s in shape:
        n *= int(s)
    return n * item


def itemsize(aval_info) -> int:
    dtype = aval_info[1]
    if dtype is None:
        return 0
    try:
        return np.dtype(dtype).itemsize
    except TypeError:
        return 16
