"""Paged flash-decode attention as a Pallas TPU kernel (ISSUE 16).

The serving engine's paged mode (ISSUE 11/15) keeps K/V in a fixed
``[n_pages, H, page_size, D]`` pool per layer and reads it through a
padded per-slot page table ``[B, max_pages]``.  The XLA path in
``models/gpt.py`` gathers ``pool[pages]`` back into a contiguous
``[B, H, S, D]`` tensor before a masked softmax — memory-bound by
construction: the gather materializes (then re-reads) the whole live
cache plus the ``[B, H, T, S]`` score matrix every decode tick, and the
r14 perf doctor ranks exactly that ``serving.paged_attn`` row at the top
of the serving MFU-gap table.

This kernel is the FlashAttention-style (Dao et al., 2022) replacement in
the spirit of vLLM's PagedAttention (Kwon et al., SOSP 2023): the grid
runs (slot, page-table entry) with the table as a scalar-prefetch
operand, so each K/V pool block is DMA'd straight from its page — the
gathered tensor never exists — and the online-softmax accumulator in
VMEM carries ``(m, l, acc)`` across a slot's page entries.  Masking
reproduces the gather path's semantics exactly:

* query row ``r`` of slot ``b`` sits at absolute position ``pos[b] + r``
  and attends keys at absolute positions ``<= pos[b] + r`` (works for
  single-token decode ``T == 1`` and chunked prefill ``T > 1`` alike —
  the chunk's own keys are scattered into the pool before the call, same
  as the XLA path);
* padded table entries point at the reserved trash page 0, whose
  absolute positions ``entry * page_size + offset`` lie past the live
  length, so they are always masked — trash contents are never read
  unmasked, and COW-duplicated pages are read through the table like any
  other page (the kernel never writes the pool).

Forward-only by design: decode runs under ``no_grad`` (the training-side
flash kernel in :mod:`.flash_attention` owns fwd+bwd).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .cost_registry import aval_bytes, itemsize, register_kernel_cost

__all__ = ["paged_flash_attention", "paged_flash_attention_int8",
           "paged_attention_reference", "PAGED_ATTENTION_KERNEL_NAME",
           "PAGED_ATTENTION_INT8_KERNEL_NAME"]

NEG_INF = -1e30  # matches flash_attention.py / the gather path's mask fill

#: explicit ``pl.pallas_call`` name — the cost-registry key
PAGED_ATTENTION_KERNEL_NAME = "paged_flash_attention"
#: int8-pool variant (ISSUE 18): same grid, per-token dequant in VMEM
PAGED_ATTENTION_INT8_KERNEL_NAME = "paged_flash_attention_int8"


def paged_attention_reference(q, pool_k, pool_v, pages, pos, *, page_size,
                              sm_scale=None):
    """The XLA gather-path read (models/gpt.py ``_paged_attn`` after its
    scatter writes) — the bit-comparison oracle for the kernel."""
    b, h, t, d = q.shape
    mp = pages.shape[1]
    cap = mp * int(page_size)
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    pos = pos.astype(jnp.int32).reshape(-1)
    wpos = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    gk = pool_k[pages].transpose(0, 2, 1, 3, 4).reshape(b, h, cap, d)
    gv = pool_v[pages].transpose(0, 2, 1, 3, 4).reshape(b, h, cap, d)
    scores = jnp.einsum("bhtd,bhsd->bhts", q, gk.astype(q.dtype)) * sm_scale
    j = jnp.arange(cap)[None, None, None, :]
    mask = j <= wpos[:, None, :, None]
    scores = jnp.where(mask, scores, jnp.asarray(NEG_INF, scores.dtype))
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bhsd->bhtd", probs, gv.astype(q.dtype))


def _online_update(b, j, pos_ref, q_ref, k, v, o_ref, acc_ref, m_ref,
                   l_ref, *, sm_scale, page_size, n_entries):
    """One (slot, page-entry) step of the online-softmax accumulation —
    shared by the fp and int8 kernels; ``k``/``v`` arrive as f32
    ``[H, ps, D]`` (the int8 kernel dequantizes in VMEM first)."""
    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)          # [H, T, D]

    s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * sm_scale

    t = q.shape[1]
    # absolute positions: query row r writes/sits at pos[b] + r; this
    # page entry's keys sit at j * page_size + offset.  Trash-page-0
    # entries only ever appear at j with j * page_size >= live length,
    # so kpos > wpos masks them unconditionally.
    wpos = pos_ref[b] + jax.lax.broadcasted_iota(
        jnp.int32, (t, page_size), 0)
    kpos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (t, page_size), 1)
    s = jnp.where((kpos <= wpos)[None], s, NEG_INF)   # [H, T, ps]

    m_prev = m_ref[...][:, :, :1]             # [H, T, 1]
    l_prev = l_ref[...][:, :, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # first entry always holds an unmasked key (kpos 0 <= wpos >= 0), so
    # m_new is finite from j == 0 on; a fully-masked later entry yields
    # p == 0 and alpha == 1 — a no-op, exactly like the gather path's
    # exp(-1e30 - m) underflow
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == n_entries - 1)
    def _finish():
        l = l_ref[...][:, :, :1]
        o_ref[0] = (acc_ref[...] /
                    jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def _paged_kernel(pages_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, sm_scale, page_size, n_entries):
    b = pl.program_id(0)
    j = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)          # [H, ps, D]
    v = v_ref[0].astype(jnp.float32)
    _online_update(b, j, pos_ref, q_ref, k, v, o_ref, acc_ref, m_ref,
                   l_ref, sm_scale=sm_scale, page_size=page_size,
                   n_entries=n_entries)


def _paged_int8_kernel(pages_ref, pos_ref, q_ref, k_ref, v_ref, sk_ref,
                       sv_ref, o_ref, acc_ref, m_ref, l_ref, *, sm_scale,
                       page_size, n_entries):
    b = pl.program_id(0)
    j = pl.program_id(1)
    # per-token dequant inside VMEM: the pool block arrives int8 (half
    # the HBM stream of the f16 layout) and is widened only here, one
    # page at a time — no dequantized pool copy ever exists in HBM
    sk = sk_ref[0].astype(jnp.float32)        # [ps]
    sv = sv_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32) * sk[None, :, None]
    v = v_ref[0].astype(jnp.float32) * sv[None, :, None]
    _online_update(b, j, pos_ref, q_ref, k, v, o_ref, acc_ref, m_ref,
                   l_ref, sm_scale=sm_scale, page_size=page_size,
                   n_entries=n_entries)


def paged_flash_attention(q, pool_k, pool_v, pages, pos, *, page_size: int,
                          sm_scale=None, interpret=None):
    """Decode/chunk-prefill attention straight off the paged KV pool.

    ``q`` ``[B, H, T, D]`` (``T == 1`` decode, ``T > 1`` chunked prefill —
    the chunk's keys must already be scattered into the pool, as the
    engine does); ``pool_k``/``pool_v`` ``[n_pages, H, page_size, D]``
    per-layer pools; ``pages`` ``[B, max_pages]`` int32 page table (pad
    entries = trash page 0); ``pos`` ``[B]`` int32 absolute position of
    ``q``'s first row.  Returns ``[B, H, T, D]`` in ``q.dtype``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, t, d = q.shape
    n_entries = pages.shape[1]
    ps = int(page_size)
    if pool_k.shape[2] != ps or pool_v.shape[2] != ps:
        raise ValueError(
            f"pool page_size {pool_k.shape[2]} != engine page_size {ps}")
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)

    kernel = functools.partial(
        _paged_kernel, sm_scale=float(sm_scale), page_size=ps,
        n_entries=int(n_entries))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,      # pages, pos
        grid=(b, n_entries),        # entry axis innermost: scratch carries
        in_specs=[
            pl.BlockSpec((1, h, t, d), lambda b_, j, pages, pos: (b_, 0, 0, 0)),
            pl.BlockSpec((1, h, ps, d),
                         lambda b_, j, pages, pos: (pages[b_, j], 0, 0, 0)),
            pl.BlockSpec((1, h, ps, d),
                         lambda b_, j, pages, pos: (pages[b_, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, t, d),
                               lambda b_, j, pages, pos: (b_, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, t, d), jnp.float32),
            pltpu.VMEM((h, t, 128), jnp.float32),
            pltpu.VMEM((h, t, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        interpret=interpret,
        name=PAGED_ATTENTION_KERNEL_NAME,
    )(pages.astype(jnp.int32), pos.astype(jnp.int32).reshape(-1),
      q, pool_k, pool_v)


def paged_flash_attention_int8(q, pool_k, pool_v, scale_k, scale_v, pages,
                               pos, *, page_size: int, sm_scale=None,
                               interpret=None):
    """Int8-pool variant (ISSUE 18): ``pool_k``/``pool_v`` are int8
    ``[n_pages, H, page_size, D]`` with per-token f32 absmax scales
    ``scale_k``/``scale_v`` ``[n_pages, page_size]`` riding alongside.
    Each page block is DMA'd as int8 (half the f16 HBM stream) and
    dequantized in VMEM; masking/accumulation identical to the fp kernel.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, t, d = q.shape
    n_entries = pages.shape[1]
    ps = int(page_size)
    if pool_k.shape[2] != ps or pool_v.shape[2] != ps:
        raise ValueError(
            f"pool page_size {pool_k.shape[2]} != engine page_size {ps}")
    if scale_k.shape != (pool_k.shape[0], ps):
        raise ValueError(
            f"scale_k shape {scale_k.shape} != {(pool_k.shape[0], ps)}")
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)

    kernel = functools.partial(
        _paged_int8_kernel, sm_scale=float(sm_scale), page_size=ps,
        n_entries=int(n_entries))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,      # pages, pos
        grid=(b, n_entries),
        in_specs=[
            pl.BlockSpec((1, h, t, d), lambda b_, j, pages, pos: (b_, 0, 0, 0)),
            pl.BlockSpec((1, h, ps, d),
                         lambda b_, j, pages, pos: (pages[b_, j], 0, 0, 0)),
            pl.BlockSpec((1, h, ps, d),
                         lambda b_, j, pages, pos: (pages[b_, j], 0, 0, 0)),
            pl.BlockSpec((1, ps),
                         lambda b_, j, pages, pos: (pages[b_, j], 0)),
            pl.BlockSpec((1, ps),
                         lambda b_, j, pages, pos: (pages[b_, j], 0)),
        ],
        out_specs=pl.BlockSpec((1, h, t, d),
                               lambda b_, j, pages, pos: (b_, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, t, d), jnp.float32),
            pltpu.VMEM((h, t, 128), jnp.float32),
            pltpu.VMEM((h, t, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        interpret=interpret,
        name=PAGED_ATTENTION_INT8_KERNEL_NAME,
    )(pages.astype(jnp.int32), pos.astype(jnp.int32).reshape(-1),
      q, pool_k, pool_v, scale_k, scale_v)


# -- cost model (analysis/cost.py prices the pallas_call eqn from this) ----
_TRANSCENDENTAL_FLOPS = 8  # matches analysis.cost.TRANSCENDENTAL_FLOPS


def _paged_attention_cost(in_avals, out_avals, params):
    """flops: the two attention contractions over the table capacity
    S = max_pages * page_size, plus the online-softmax exp traffic.
    bytes: each TOUCHED page is streamed once per slot (B * max_pages
    K+V blocks) plus q/out/table — NOT the gather path's materialized
    [B, S, H, D] round-trip, which is the whole intensity win."""
    pages_av, pos_av, q_av, pk_av, pv_av = in_avals[:5]
    b, n_entries = (int(x) for x in pages_av[0])
    _, h, t, d = (int(x) for x in q_av[0])
    ps = int(pk_av[0][2])
    s = n_entries * ps
    flops = 4.0 * b * h * t * s * d \
        + 2.0 * _TRANSCENDENTAL_FLOPS * b * h * t * s
    kv_bytes = float(b * n_entries * h * ps * d) \
        * (itemsize(pk_av) + itemsize(pv_av))
    io = aval_bytes(q_av) + aval_bytes(pages_av) + aval_bytes(pos_av) \
        + sum(aval_bytes(o) for o in out_avals)
    return flops, kv_bytes + io


register_kernel_cost(PAGED_ATTENTION_KERNEL_NAME, _paged_attention_cost,
                     family="paged_attention",
                     operand_roles=("pages", "pos", "q", "pool_k", "pool_v"))


def _paged_attention_int8_cost(in_avals, out_avals, params):
    """Same contraction flops as the fp kernel plus the per-element
    dequant multiply; KV bytes are the int8 stream (itemsize 1) plus the
    per-token scale rows — the ~2x intensity win over the f16 pool is
    exactly what this registry row makes visible to the perf doctor."""
    pages_av, pos_av, q_av, pk_av, pv_av, sk_av, sv_av = in_avals[:7]
    b, n_entries = (int(x) for x in pages_av[0])
    _, h, t, d = (int(x) for x in q_av[0])
    ps = int(pk_av[0][2])
    s = n_entries * ps
    flops = 4.0 * b * h * t * s * d \
        + 2.0 * _TRANSCENDENTAL_FLOPS * b * h * t * s \
        + 2.0 * b * h * s * d                      # dequant multiplies
    kv_bytes = float(b * n_entries * h * ps * d) \
        * (itemsize(pk_av) + itemsize(pv_av)) \
        + float(b * n_entries * ps) * (itemsize(sk_av) + itemsize(sv_av))
    io = aval_bytes(q_av) + aval_bytes(pages_av) + aval_bytes(pos_av) \
        + sum(aval_bytes(o) for o in out_avals)
    return flops, kv_bytes + io


register_kernel_cost(PAGED_ATTENTION_INT8_KERNEL_NAME,
                     _paged_attention_int8_cost,
                     family="paged_attention",
                     operand_roles=("pages", "pos", "q", "pool_k", "pool_v",
                                    "scale_k", "scale_v"))
