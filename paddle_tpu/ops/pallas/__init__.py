"""Pallas TPU kernels — the framework's hand-written kernel library.

Parity role: replaces the reference's hand-written fused CUDA kernels
(/root/reference/paddle/fluid/operators/fused/ — fused_attention_op.cu,
fmha_ref.h, fused_dropout_helper.h) with TPU-native Pallas kernels that
tile onto the MXU/VPU and keep working sets in VMEM.

r24 adds the **kernel manifest**: one :class:`KernelCase` per shipped
``pl.pallas_call``, keyed by the same ``name=`` string the kernel passes
to ``pallas_call`` and registers in :mod:`.cost_registry`.  The manifest
is the kernel doctor's discovery surface (``python -m paddle_tpu.analysis
--kernels``): each case builds a representative call at lint-sized shapes
— chosen so every structural feature of the kernel is exercised (multi-
block grids, non-dividing tail tiles, scalar-prefetch page indirection)
— plus the concrete scalar-prefetch operands its data-dependent index
maps are proved against.  A kernel added without a manifest entry shows
up as registry-vs-manifest drift (HIGH), not silence.

:func:`differential_cases` is the companion runtime surface: per-kernel
(kernel, XLA-reference) closures over a small shape/tiling lattice —
non-dividing vocab tails, page_size 16/32, bf16 operands — that the
interpret-mode differential tests sweep (replacing the r20 ad-hoc
per-kernel comparison scaffolding).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional, Tuple

__all__ = [
    "KernelCase",
    "DifferentialCase",
    "kernel_manifest",
    "differential_cases",
]


@dataclasses.dataclass(frozen=True)
class KernelCase:
    """One shipped ``pl.pallas_call`` as the kernel doctor sees it.

    ``build()`` returns ``(fn, args)`` such that ``jax.make_jaxpr(fn)
    (*args)`` contains exactly one pallas_call eqn named ``name`` (other
    kernels appearing in the same jaxpr — e.g. the forward kernel inside
    a grad trace — are covered by their own cases).  ``scalar_prefetch``
    returns the concrete values of the eqn's ``num_index_operands``
    scalar-prefetch operands in operand order; the coverage prover
    evaluates data-dependent index maps against exactly these values, so
    they must match what ``build``'s args put in the page table.

    ``tail_masked`` documents that the kernel body masks non-dividing
    tail tiles in-kernel (cross-checked against the body's iota→compare→
    select idiom); ``data_dependent_ok`` names operand roles whose index
    maps read the prefetch arrays by design (the page indirection) — the
    prover still bounds-checks them against the example table but
    reports the data dependence as INFO, not a finding.
    """

    name: str
    build: Callable[[], tuple]
    scalar_prefetch: Callable[[], tuple] = lambda: ()
    tail_masked: bool = False
    data_dependent_ok: Tuple[str, ...] = ()
    notes: str = ""


@dataclasses.dataclass(frozen=True)
class DifferentialCase:
    """One interpret-mode kernel-vs-XLA-reference comparison point.

    ``run()`` returns ``(kernel_out, reference_out)`` as matching pytrees
    of arrays; the harness asserts allclose at ``atol``/``rtol``.
    ``kernel`` is the manifest/registry name the point exercises and
    ``label`` the lattice coordinate ("vocab200_tail", "ps32_int8", ...).
    """

    kernel: str
    label: str
    run: Callable[[], tuple]
    atol: float = 2e-6
    rtol: float = 1e-5

    @property
    def id(self) -> str:
        return f"{self.kernel}[{self.label}]"


def _rng(seed: int):
    import numpy as np

    return np.random.default_rng(seed)


# ---------------------------------------------------------------------------
# manifest builders (lint-sized; everything CPU-interpret cheap)
# ---------------------------------------------------------------------------
def _flash_args(dtype, bh=2, t=256, s=256, d=64, seed=0):
    import jax.numpy as jnp

    r = _rng(seed)
    q = jnp.asarray(r.normal(size=(bh, t, d)), dtype)
    k = jnp.asarray(r.normal(size=(bh, s, d)), dtype)
    v = jnp.asarray(r.normal(size=(bh, s, d)), dtype)
    return q, k, v


def _build_flash_fwd():
    import jax.numpy as jnp

    from .flash_attention import flash_attention

    # bf16 operands on purpose: the dtype-safety rules must SEE half-
    # precision inputs flow into f32-accumulated dots/reductions — the
    # repo's f32-stats convention, proved not assumed
    fn = functools.partial(flash_attention, causal=True, block_q=128,
                           block_k=128, interpret=True)
    return fn, _flash_args(jnp.bfloat16)


def _build_flash_bwd(which: str):
    import jax
    import jax.numpy as jnp

    from .flash_attention import flash_attention

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=128,
                               block_k=128, interpret=True).sum()

    argnums = {"dq": 0, "dkv": (1, 2)}[which]
    return jax.grad(loss, argnums=argnums), _flash_args(jnp.float32)


def _build_rope():
    import jax.numpy as jnp

    from .rope import build_rope_cache, rope

    r = _rng(1)
    x = jnp.asarray(r.normal(size=(4, 256, 128)), jnp.float32)
    cos, sin = build_rope_cache(256, 128)
    return functools.partial(rope, block_t=128, interpret=True), (x, cos, sin)


def _build_swiglu():
    import jax.numpy as jnp

    from .swiglu import swiglu

    r = _rng(2)
    x = jnp.asarray(r.normal(size=(16, 128)), jnp.float32)
    wg = jnp.asarray(r.normal(size=(128, 256)) * 0.1, jnp.float32)
    wu = jnp.asarray(r.normal(size=(128, 256)) * 0.1, jnp.float32)
    return (functools.partial(swiglu, block_m=8, block_n=128,
                              interpret=True), (x, wg, wu))


def _build_fused_ln():
    import jax.numpy as jnp

    from .fused_ln import fused_residual_dropout_ln

    r = _rng(3)
    x = jnp.asarray(r.normal(size=(16, 128)), jnp.float32)
    res = jnp.asarray(r.normal(size=(16, 128)), jnp.float32)
    gamma = jnp.ones((128,), jnp.float32)
    beta = jnp.zeros((128,), jnp.float32)
    return (functools.partial(fused_residual_dropout_ln, p=0.0, block_m=8,
                              interpret=True), (x, res, gamma, beta))


def _ce_args(n=48, vocab=200, seed=4):
    """Non-dividing vocab (200 over block_v 128 → a masked tail tile) and
    a row count that pads (48 over block_n 32) — the manifest case must
    exercise the tail machinery the doctor proves."""
    import jax.numpy as jnp

    r = _rng(seed)
    logits = jnp.asarray(r.normal(size=(n, vocab)), jnp.float32)
    labels = jnp.asarray(r.integers(0, vocab, (n,)), jnp.int32)
    return logits, labels


def _build_ce_fwd():
    from .softmax_ce import softmax_ce_loss

    return functools.partial(softmax_ce_loss, interpret=True), _ce_args()


def _build_ce_bwd():
    import jax

    from .softmax_ce import softmax_ce_loss

    logits, labels = _ce_args()

    def loss(x):
        return softmax_ce_loss(x, labels, interpret=True).sum()

    return jax.grad(loss), (logits,)


def _build_partials_fwd():
    from .softmax_ce import softmax_ce_partials

    logits, labels = _ce_args(seed=5)
    return (functools.partial(softmax_ce_partials, interpret=True),
            (logits, labels))


def _build_partials_bwd():
    import jax
    import jax.numpy as jnp

    from .softmax_ce import softmax_ce_partials

    logits, labels = _ce_args(seed=6)

    def loss(x):
        se, pk = softmax_ce_partials(x, labels, interpret=True)
        return jnp.sum(jnp.log(se)) - jnp.sum(pk)

    return jax.grad(loss), (logits,)


def _paged_pool(rng, n_pages, h, ps, d, lens, mp):
    """Pools + page table with the engine's invariants: page 0 is the
    reserved trash page, live pages are 1..; table entries past a slot's
    live pages stay 0 (masked by position in-kernel)."""
    import numpy as np

    pk = rng.normal(size=(n_pages, h, ps, d)).astype(np.float32)
    pv = rng.normal(size=(n_pages, h, ps, d)).astype(np.float32)
    pages = np.zeros((len(lens), mp), np.int32)
    nxt = iter(range(1, n_pages))
    for i, ln in enumerate(lens):
        for j in range(-(-(ln + 1) // ps)):
            pages[i, j] = next(nxt)
    pos = np.asarray(list(lens), np.int32)
    return pk, pv, pages, pos


def _paged_case_arrays(ps=16, t=4, int8=False, seed=7):
    import jax.numpy as jnp
    import numpy as np

    r = _rng(seed)
    b, h, d, mp, n_pages = 3, 4, 16, 4, 12
    lens = (5, ps + 3, 3 * ps - 1)
    pk, pv, pages, pos = _paged_pool(r, n_pages, h, ps, d, lens, mp)
    q = jnp.asarray(r.normal(size=(b, h, t, d)), jnp.float32)
    if not int8:
        return q, jnp.asarray(pk), jnp.asarray(pv), pages, pos
    # per-token absmax int8 quantization of the pools (r22 layout)
    amax_k = np.abs(pk).max(axis=(1, 3)) + 1e-6          # [n_pages, ps]
    amax_v = np.abs(pv).max(axis=(1, 3)) + 1e-6
    sk = (amax_k / 127.0).astype(np.float32)
    sv = (amax_v / 127.0).astype(np.float32)
    qk = np.clip(np.round(pk / sk[:, None, :, None]), -127, 127)
    qv = np.clip(np.round(pv / sv[:, None, :, None]), -127, 127)
    return (q, jnp.asarray(qk, jnp.int8), jnp.asarray(qv, jnp.int8),
            jnp.asarray(sk), jnp.asarray(sv), pages, pos)


def _build_paged(ps=16, t=4):
    import jax.numpy as jnp

    from .paged_attention import paged_flash_attention

    q, pk, pv, pages, pos = _paged_case_arrays(ps=ps, t=t)

    def fn(q, pk, pv):
        return paged_flash_attention(q, pk, pv, jnp.asarray(pages),
                                     jnp.asarray(pos), page_size=ps,
                                     interpret=True)

    return fn, (q, pk, pv)


def _build_paged_int8(ps=16, t=1):
    import jax.numpy as jnp

    from .paged_attention import paged_flash_attention_int8

    q, pk, pv, sk, sv, pages, pos = _paged_case_arrays(ps=ps, t=t, int8=True)

    def fn(q, pk, pv, sk, sv):
        return paged_flash_attention_int8(
            q, pk, pv, sk, sv, jnp.asarray(pages), jnp.asarray(pos),
            page_size=ps, interpret=True)

    return fn, (q, pk, pv, sk, sv)


def _paged_prefetch(ps=16, t=4, int8=False, seed=7):
    arrays = _paged_case_arrays(ps=ps, t=t, int8=int8, seed=seed)
    pages, pos = arrays[-2], arrays[-1]
    return pages, pos


_PAGED_NOTE = ("page-table indirection: K/V (and int8 scale) block index "
               "maps read pages[b, j] — proved against the case's concrete "
               "table; the runtime bound is the allocator invariant that "
               "every table entry < n_pages (0 = trash page)")


def kernel_manifest() -> Tuple[KernelCase, ...]:
    """Every shipped ``pl.pallas_call``, keyed by registry name."""
    return (
        KernelCase("flash_attention_fwd", _build_flash_fwd,
                   notes="bf16 operands, causal, 2x2x2 grid"),
        KernelCase("flash_attention_bwd_dq",
                   functools.partial(_build_flash_bwd, "dq")),
        KernelCase("flash_attention_bwd_dkv",
                   functools.partial(_build_flash_bwd, "dkv"),
                   notes="transposed grid (bh, nk, nq): dk/dv blocks are "
                         "the contiguous axis, dq revisits are the point"),
        KernelCase("rope_fwd", _build_rope),
        KernelCase("swiglu_fwd", _build_swiglu),
        KernelCase("fused_residual_dropout_ln_fwd", _build_fused_ln),
        KernelCase("softmax_ce_fwd", _build_ce_fwd, tail_masked=True,
                   notes="vocab 200 over block_v 128: masked tail tile"),
        KernelCase("softmax_ce_bwd", _build_ce_bwd, tail_masked=True),
        KernelCase("softmax_ce_partials_fwd", _build_partials_fwd,
                   tail_masked=True),
        KernelCase("softmax_ce_partials_bwd", _build_partials_bwd,
                   tail_masked=True),
        KernelCase("paged_flash_attention", _build_paged,
                   scalar_prefetch=_paged_prefetch,
                   data_dependent_ok=("pool_k", "pool_v"),
                   notes=_PAGED_NOTE),
        KernelCase("paged_flash_attention_int8",
                   functools.partial(_build_paged_int8, ps=16, t=1),
                   scalar_prefetch=functools.partial(_paged_prefetch,
                                                     ps=16, t=1, int8=True),
                   data_dependent_ok=("pool_k", "pool_v", "scale_k",
                                      "scale_v"),
                   notes=_PAGED_NOTE),
    )


# ---------------------------------------------------------------------------
# interpret-mode differential lattice (kernel vs jitted XLA reference)
# ---------------------------------------------------------------------------
def _diff_paged(ps, t, lens=None):
    import jax.numpy as jnp

    from .paged_attention import (
        paged_attention_reference,
        paged_flash_attention,
    )

    r = _rng(10 + ps + t)
    b, h, d, mp, n_pages = 3, 4, 16, 6, 20
    lens = lens or (5, ps + 3, 2 * ps + 1)
    pk, pv, pages, pos = _paged_pool(r, n_pages, h, ps, d, lens, mp)
    q = jnp.asarray(r.normal(size=(b, h, t, d)), jnp.float32)
    pk, pv = jnp.asarray(pk), jnp.asarray(pv)
    pages_j, pos_j = jnp.asarray(pages), jnp.asarray(pos)

    def run():
        import jax

        out = paged_flash_attention(q, pk, pv, pages_j, pos_j,
                                    page_size=ps, interpret=True)
        ref = jax.jit(functools.partial(paged_attention_reference,
                                        page_size=ps))(q, pk, pv, pages_j,
                                                       pos_j)
        return out, ref

    return run


def _diff_paged_int8(ps, t):
    import jax.numpy as jnp

    from .paged_attention import (
        paged_attention_reference,
        paged_flash_attention_int8,
    )

    q, pk, pv, sk, sv, pages, pos = _paged_case_arrays(
        ps=ps, t=t, int8=True, seed=20 + ps)
    pages_j, pos_j = jnp.asarray(pages), jnp.asarray(pos)
    # the XLA oracle sees the DEQUANTIZED pools: the comparison pins the
    # kernel's in-VMEM dequant + accumulation, not the quantizer
    deq_k = pk.astype(jnp.float32) * sk[:, None, :, None]
    deq_v = pv.astype(jnp.float32) * sv[:, None, :, None]

    def run():
        import jax

        out = paged_flash_attention_int8(q, pk, pv, sk, sv, pages_j, pos_j,
                                         page_size=ps, interpret=True)
        ref = jax.jit(functools.partial(paged_attention_reference,
                                        page_size=ps))(q, deq_k, deq_v,
                                                       pages_j, pos_j)
        return out, ref

    return run


def _diff_ce(n, vocab, dtype_name="float32"):
    import jax
    import jax.numpy as jnp

    from .softmax_ce import softmax_ce_loss, softmax_ce_reference

    r = _rng(30 + vocab)
    dtype = jnp.dtype(dtype_name)
    logits = jnp.asarray(r.normal(size=(n, vocab)), dtype)
    labels = jnp.asarray(r.integers(0, vocab, (n,)), jnp.int32)
    labels = labels.at[0].set(-100)       # ignore_index row

    def run():
        out = softmax_ce_loss(logits, labels, interpret=True)
        ref = jax.jit(softmax_ce_reference)(logits, labels).astype(dtype)
        g_out = jax.grad(lambda x: softmax_ce_loss(
            x, labels, interpret=True).astype(jnp.float32).sum())(logits)
        g_ref = jax.grad(lambda x: softmax_ce_reference(
            x, labels).sum())(logits).astype(dtype)
        return (out, g_out), (ref, g_ref)

    return run


def _diff_partials(n, vocab):
    import jax
    import jax.numpy as jnp

    from .softmax_ce import softmax_ce_partials

    r = _rng(40 + vocab)
    x = jnp.asarray(r.normal(size=(n, vocab)), jnp.float32)
    x = x - jnp.max(x, -1, keepdims=True)
    lab = jnp.asarray(r.integers(0, vocab, (n,)), jnp.int32)
    lab = lab.at[1].set(-1)               # off-shard / ignore row

    def ref_fn(x):
        se = jnp.sum(jnp.exp(x), -1)
        col = jnp.arange(vocab, dtype=jnp.int32)
        pk = jnp.sum(jnp.where(col == lab[:, None], x, 0.0), -1)
        return se, pk

    def run():
        out = softmax_ce_partials(x, lab, interpret=True)
        ref = jax.jit(ref_fn)(x)
        g_out = jax.grad(lambda a: _partials_scalar(a, lab))(x)
        g_ref = jax.grad(lambda a: sum(
            jnp.sum(jnp.log(r) if i == 0 else -r)
            for i, r in enumerate(ref_fn(a))))(x)
        return (out, g_out), (ref, g_ref)

    return run


def _partials_scalar(a, lab):
    import jax.numpy as jnp

    from .softmax_ce import softmax_ce_partials

    se, pk = softmax_ce_partials(a, lab, interpret=True)
    return jnp.sum(jnp.log(se)) - jnp.sum(pk)


def _diff_flash(bh, t, d, causal, dtype_name, with_grad=True):
    import jax
    import jax.numpy as jnp

    from .flash_attention import flash_attention

    dtype = jnp.dtype(dtype_name)
    q, k, v = _flash_args(dtype, bh=bh, t=t, s=t, d=d, seed=50 + t)

    def ref_fn(q, k, v):
        qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
        s = jnp.einsum("btd,bsd->bts", qf, kf) / (d ** 0.5)
        if causal:
            mask = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(mask[None], s, -1e30)
        return jnp.einsum("bts,bsd->btd",
                          jax.nn.softmax(s, -1), vf).astype(dtype)

    kern = functools.partial(flash_attention, causal=causal, block_q=128,
                             block_k=128, interpret=True)

    def run():
        out = kern(q, k, v)
        ref = jax.jit(ref_fn)(q, k, v)
        if not with_grad:
            return out, ref
        gk = jax.grad(lambda *a: kern(*a).astype(jnp.float32).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: ref_fn(*a).astype(jnp.float32).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        return (out,) + gk, (ref,) + gr

    return run


def _diff_rope():
    import jax
    import jax.numpy as jnp

    from .rope import build_rope_cache, rope, rope_reference

    r = _rng(60)
    x = jnp.asarray(r.normal(size=(4, 256, 128)), jnp.float32)
    cos, sin = build_rope_cache(256, 128)

    def run():
        out = rope(x, cos, sin, block_t=128, interpret=True)
        ref = jax.jit(rope_reference)(x, cos, sin)
        g_out = jax.grad(lambda a: rope(a, cos, sin, block_t=128,
                                        interpret=True).sum())(x)
        g_ref = jax.grad(lambda a: rope_reference(a, cos, sin).sum())(x)
        return (out, g_out), (ref, g_ref)

    return run


def _diff_swiglu(m, k, n, bm, bn):
    import jax
    import jax.numpy as jnp

    from .swiglu import swiglu, swiglu_reference

    r = _rng(70 + m)
    x = jnp.asarray(r.normal(size=(m, k)), jnp.float32)
    wg = jnp.asarray(r.normal(size=(k, n)) * 0.1, jnp.float32)
    wu = jnp.asarray(r.normal(size=(k, n)) * 0.1, jnp.float32)

    def run():
        out = swiglu(x, wg, wu, block_m=bm, block_n=bn, interpret=True)
        ref = jax.jit(swiglu_reference)(x, wg, wu)
        g_out = jax.grad(lambda a: swiglu(a, wg, wu, block_m=bm, block_n=bn,
                                          interpret=True).sum())(x)
        g_ref = jax.grad(lambda a: swiglu_reference(a, wg, wu).sum())(x)
        return (out, g_out), (ref, g_ref)

    return run


def _diff_fused_ln(p):
    import jax
    import jax.numpy as jnp

    from .fused_ln import (
        fused_residual_dropout_ln,
        fused_residual_dropout_ln_reference,
    )

    r = _rng(80)
    x = jnp.asarray(r.normal(size=(16, 128)), jnp.float32)
    res = jnp.asarray(r.normal(size=(16, 128)), jnp.float32)
    gamma = jnp.asarray(r.normal(size=(128,)), jnp.float32)
    beta = jnp.asarray(r.normal(size=(128,)), jnp.float32)
    mask = (jnp.asarray(r.random((16, 128))) > p) if p > 0 else None

    def run():
        out = fused_residual_dropout_ln(x, res, gamma, beta, p=p, mask=mask,
                                        block_m=8, interpret=True)
        ref = jax.jit(functools.partial(
            fused_residual_dropout_ln_reference, p=p))(x, res, mask, gamma,
                                                       beta)
        g_out = jax.grad(lambda a: fused_residual_dropout_ln(
            a, res, gamma, beta, p=p, mask=mask, block_m=8,
            interpret=True)[0].sum())(x)
        g_ref = jax.grad(lambda a: fused_residual_dropout_ln_reference(
            a, res, mask, gamma, beta, p)[0].sum())(x)
        return (out[0], out[1], g_out), (ref[0], ref[1], g_ref)

    return run


def differential_cases() -> Tuple[DifferentialCase, ...]:
    """The interpret-mode kernel-vs-reference lattice (ROADMAP item 1a's
    CPU-provable half: correctness across tilings; the TPU A/B supplies
    the wall-clock half)."""
    return (
        # paged flash-decode: page_size 16/32 x decode/chunked-prefill
        DifferentialCase("paged_flash_attention", "ps16_t1",
                         _diff_paged(16, 1)),
        DifferentialCase("paged_flash_attention", "ps16_t5",
                         _diff_paged(16, 5)),
        DifferentialCase("paged_flash_attention", "ps32_t1",
                         _diff_paged(32, 1)),
        DifferentialCase("paged_flash_attention", "ps32_t4",
                         _diff_paged(32, 4)),
        DifferentialCase("paged_flash_attention_int8", "ps16_t1",
                         _diff_paged_int8(16, 1), atol=0.05, rtol=0.05),
        DifferentialCase("paged_flash_attention_int8", "ps32_t1",
                         _diff_paged_int8(32, 1), atol=0.05, rtol=0.05),
        # fused softmax-CE: dividing and tail vocabs, fwd + bwd kernels
        DifferentialCase("softmax_ce_fwd", "vocab64", _diff_ce(32, 64),
                         atol=1e-5),
        DifferentialCase("softmax_ce_fwd", "vocab200_tail",
                         _diff_ce(8, 200), atol=1e-5),
        DifferentialCase("softmax_ce_fwd", "vocab384_rows50",
                         _diff_ce(50, 384), atol=1e-5),
        DifferentialCase("softmax_ce_partials_fwd", "vocab64",
                         _diff_partials(32, 64), atol=1e-5),
        DifferentialCase("softmax_ce_partials_fwd", "vocab200_tail",
                         _diff_partials(8, 200), atol=1e-5),
        # flash attention: causal/full, f32/bf16, fwd + both bwd kernels
        DifferentialCase("flash_attention_fwd", "t256_causal_f32",
                         _diff_flash(2, 256, 64, True, "float32"),
                         atol=2e-5, rtol=2e-5),
        DifferentialCase("flash_attention_fwd", "t128_full_f32",
                         _diff_flash(2, 128, 64, False, "float32"),
                         atol=2e-5, rtol=2e-5),
        DifferentialCase("flash_attention_fwd", "t128_causal_bf16",
                         _diff_flash(2, 128, 64, True, "bfloat16",
                                     with_grad=False),
                         atol=0.05, rtol=0.05),
        # rope / swiglu / fused LN
        DifferentialCase("rope_fwd", "t256_d128", _diff_rope(), atol=1e-5),
        DifferentialCase("swiglu_fwd", "m16_n256", _diff_swiglu(
            16, 128, 256, 8, 128), atol=1e-4, rtol=1e-4),
        DifferentialCase("swiglu_fwd", "m8_n128_single_block", _diff_swiglu(
            8, 128, 128, 8, 128), atol=1e-4, rtol=1e-4),
        DifferentialCase("fused_residual_dropout_ln_fwd", "p0",
                         _diff_fused_ln(0.0), atol=1e-4, rtol=1e-4),
        DifferentialCase("fused_residual_dropout_ln_fwd", "p0.3",
                         _diff_fused_ln(0.3), atol=1e-4, rtol=1e-4),
    )
