"""Pallas TPU kernels — the framework's hand-written kernel library.

Parity role: replaces the reference's hand-written fused CUDA kernels
(/root/reference/paddle/fluid/operators/fused/ — fused_attention_op.cu,
fmha_ref.h, fused_dropout_helper.h) with TPU-native Pallas kernels that
tile onto the MXU/VPU and keep working sets in VMEM.
"""
