"""Fused residual + dropout + LayerNorm as a Pallas TPU kernel.

Parity: the reference's fused_dropout_helper.h /
fused_layernorm_residual_dropout_bias.h CUDA kernels — one pass computing

    y   = residual + dropout(x)          (the pre-LN block boundary)
    out = layer_norm(y) * gamma + beta

returning BOTH ``y`` (the next residual stream) and ``out`` (the next
sublayer input), so the [T, H] intermediate never makes an extra HBM
round-trip and the mask/moments fuse with the normalization.

TPU-native choice: the dropout mask is generated OUTSIDE with the
framework's seeded jax PRNG and passed in as a bool array — keeping masks
on the unified RNG stream (deterministic replay, TP rng-tracker parity)
instead of a kernel-private curand state like the reference. XLA fuses the
bernoulli into a cheap elementwise producer; the kernel fuses everything
downstream of it.

Backward is composed in jnp from the saved (y, mask, mean, rstd) — matching
the reference's FusedDropoutLayerNormHelper<true> backward decomposition.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_residual_dropout_ln", "fused_residual_dropout_ln_reference"]

BLOCK_M = 256


def fused_residual_dropout_ln_reference(x, residual, mask, gamma, beta,
                                        p: float, epsilon: float = 1e-5):
    """Unfused jnp reference. mask: keep-mask bool (ignored when p == 0)."""
    if p > 0.0:
        y = residual + jnp.where(mask, x / (1.0 - p), 0.0).astype(x.dtype)
    else:
        y = residual + x
    yf = y.astype(jnp.float32)
    mu = yf.mean(-1, keepdims=True)
    var = ((yf - mu) ** 2).mean(-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + epsilon)
    out = ((yf - mu) * rstd * gamma + beta).astype(x.dtype)
    return out, y


def _fused_kernel(x_ref, res_ref, mask_ref, gamma_ref, beta_ref,
                  out_ref, y_ref, *, p, epsilon):
    x = x_ref[:].astype(jnp.float32)
    if p > 0.0:
        keep = mask_ref[:] != 0
        x = jnp.where(keep, x / (1.0 - p), 0.0)
    y = res_ref[:].astype(jnp.float32) + x
    mu = y.mean(-1, keepdims=True)
    var = ((y - mu) ** 2).mean(-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + epsilon)
    out = (y - mu) * rstd * gamma_ref[:].astype(jnp.float32) \
        + beta_ref[:].astype(jnp.float32)
    out_ref[:] = out.astype(out_ref.dtype)
    y_ref[:] = y.astype(y_ref.dtype)


def _fwd_raw(x, residual, mask, gamma, beta, p, epsilon, block_m, interpret):
    m, h = x.shape
    kern = functools.partial(_fused_kernel, p=p, epsilon=epsilon)
    return pl.pallas_call(
        kern,
        grid=(pl.cdiv(m, block_m),),
        in_specs=[
            pl.BlockSpec((block_m, h), lambda i: (i, 0)),
            pl.BlockSpec((block_m, h), lambda i: (i, 0)),
            pl.BlockSpec((block_m, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block_m, h), lambda i: (i, 0)),
            pl.BlockSpec((block_m, h), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, h), x.dtype),
            jax.ShapeDtypeStruct((m, h), x.dtype),
        ],
        interpret=interpret,
        name="fused_residual_dropout_ln_fwd",
    )(x, residual, mask, gamma, beta)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _fused(x, residual, mask, gamma, beta, p, epsilon, block_m, interpret):
    return _fwd_raw(x, residual, mask, gamma, beta, p, epsilon, block_m, interpret)


def _fused_vjp_fwd(x, residual, mask, gamma, beta, p, epsilon, block_m, interpret):
    out, y = _fwd_raw(x, residual, mask, gamma, beta, p, epsilon, block_m, interpret)
    return (out, y), (y, mask, gamma)


def _fused_vjp_bwd(p, epsilon, block_m, interpret, res, cts):
    y, mask, gamma = res
    g_out, g_y = cts
    yf = y.astype(jnp.float32)
    mu = yf.mean(-1, keepdims=True)
    var = ((yf - mu) ** 2).mean(-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + epsilon)
    xhat = (yf - mu) * rstd
    go = g_out.astype(jnp.float32)
    dgamma = (go * xhat).sum(0)
    dbeta = go.sum(0)
    # LN input grad
    gx = go * gamma.astype(jnp.float32)
    h = y.shape[-1]
    dy = rstd * (gx - gx.mean(-1, keepdims=True)
                 - xhat * (gx * xhat).mean(-1, keepdims=True))
    dy = dy + g_y.astype(jnp.float32)  # the y output feeds the residual stream
    d_res = dy
    if p > 0.0:
        dx = jnp.where(mask != 0, dy / (1.0 - p), 0.0)
    else:
        dx = dy
    return (dx.astype(y.dtype), d_res.astype(y.dtype), None,
            dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype))


_fused.defvjp(_fused_vjp_fwd, _fused_vjp_bwd)


def fused_residual_dropout_ln(x, residual, gamma, beta, *, p: float = 0.0,
                              epsilon: float = 1e-5, mask=None,
                              block_m: int = BLOCK_M, interpret=None):
    """``(layer_norm(residual + dropout(x)), residual + dropout(x))``.

    ``mask``: keep-mask (bool, same shape) — required when ``p > 0``;
    generate it from the framework PRNG (``jax.random.bernoulli(key, 1-p)``).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if p > 0.0 and mask is None:
        raise ValueError("p > 0 requires an explicit keep-mask")
    lead = x.shape[:-1]
    h = x.shape[-1]
    m = 1
    for s in lead:
        m *= s
    if h % 128 != 0 or m % 8 != 0:
        out, y = fused_residual_dropout_ln_reference(
            x, residual, mask, gamma, beta, p, epsilon)
        return out, y
    x2 = x.reshape(m, h)
    r2 = residual.reshape(m, h)
    mk = (mask.reshape(m, h).astype(jnp.int8) if mask is not None
          else jnp.ones((m, h), jnp.int8))
    bm = min(block_m, m)
    out, y = _fused(x2, r2, mk, gamma, beta, float(p), float(epsilon), bm,
                    bool(interpret))
    return out.reshape(*lead, h), y.reshape(*lead, h)


def _fused_ln_cost(in_avals, out_avals, params):
    """Bandwidth-bound single pass: dropout-scale + residual add + two
    moment reductions + normalize ≈ 9 VPU ops/element (rsqrt ~ the
    transcendental budget amortized over H)."""
    from .cost_registry import aval_bytes
    x_av = in_avals[0]
    n = 1
    for s in x_av[0]:
        n *= int(s)
    bts = sum(aval_bytes(a) for a in in_avals) \
        + sum(aval_bytes(a) for a in out_avals)
    return 9.0 * n, bts


def _register_costs():
    from .cost_registry import register_kernel_cost
    register_kernel_cost(
        "fused_residual_dropout_ln_fwd", _fused_ln_cost, family="fused_ln",
        operand_roles=("x", "residual", "mask", "gamma", "beta"))


_register_costs()
