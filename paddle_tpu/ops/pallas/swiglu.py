"""Fused SwiGLU gate as a Pallas TPU kernel.

Parity role: the north star names "fused swiglu" among the kernels the
reference family implements in CUDA (fused_transformer FFN fusion,
/root/reference/paddle/fluid/operators/fused/fused_transformer_op.h); this
is the TPU-native version.

Design: one kernel computes ``silu(x @ w_gate) * (x @ w_up)`` tiled over
(row, ffn-column) blocks — the two projections hit the MXU back-to-back
while the gate nonlinearity and product stay in VMEM, so the [T, F]
intermediates never round-trip to HBM (the unfused path writes both).
The down projection stays an ordinary matmul (already MXU-optimal).

Backward recomputes the two projections blockwise (flash-style) in plain
jnp — grads of matmuls are matmuls, which XLA already schedules optimally.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["swiglu", "swiglu_reference"]

BLOCK_M = 256
BLOCK_N = 512


def swiglu_reference(x, w_gate, w_up):
    a = x @ w_gate
    b = x @ w_up
    return (jax.nn.silu(a.astype(jnp.float32)) * b.astype(jnp.float32)).astype(x.dtype)


def _swiglu_kernel(x_ref, wg_ref, wu_ref, o_ref):
    x = x_ref[:]
    a = jax.lax.dot_general(x, wg_ref[:], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    b = jax.lax.dot_general(x, wu_ref[:], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[:] = (jax.nn.silu(a) * b).astype(o_ref.dtype)


def _swiglu_fwd_raw(x, wg, wu, block_m, block_n, interpret):
    m, k = x.shape
    n = wg.shape[1]
    return pl.pallas_call(
        _swiglu_kernel,
        grid=(pl.cdiv(m, block_m), pl.cdiv(n, block_n)),
        in_specs=[
            pl.BlockSpec((block_m, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((k, block_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=interpret,
        name="swiglu_fwd",
    )(x, wg, wu)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _swiglu(x, wg, wu, block_m, block_n, interpret):
    return _swiglu_fwd_raw(x, wg, wu, block_m, block_n, interpret)


def _swiglu_vjp_fwd(x, wg, wu, block_m, block_n, interpret):
    return _swiglu_fwd_raw(x, wg, wu, block_m, block_n, interpret), (x, wg, wu)


def _swiglu_vjp_bwd(block_m, block_n, interpret, res, g):
    x, wg, wu = res
    a = (x @ wg).astype(jnp.float32)
    b = (x @ wu).astype(jnp.float32)
    sig = jax.nn.sigmoid(a)
    silu_a = a * sig
    g = g.astype(jnp.float32)
    da = (g * b * (sig + silu_a * (1.0 - sig))).astype(x.dtype)
    db = (g * silu_a).astype(x.dtype)
    dx = da @ wg.T + db @ wu.T
    dwg = x.T @ da
    dwu = x.T @ db
    return dx.astype(x.dtype), dwg.astype(wg.dtype), dwu.astype(wu.dtype)


_swiglu.defvjp(_swiglu_vjp_fwd, _swiglu_vjp_bwd)


def swiglu(x, w_gate, w_up, *, block_m: int = BLOCK_M, block_n: int = BLOCK_N,
           interpret=None):
    """Fused ``silu(x @ w_gate) * (x @ w_up)`` over [..., K] inputs.

    Falls back to the jnp reference off-TPU-friendly shapes (K/N not
    lane-aligned or tiny batches).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k = x.shape[-1]
    n = w_gate.shape[1]
    lead = x.shape[:-1]
    m = 1
    for s in lead:
        m *= s
    if k % 128 != 0 or n % 128 != 0 or m % 8 != 0:
        return swiglu_reference(x, w_gate, w_up)
    x2 = x.reshape(m, k)
    bm = min(block_m, m)
    bn = min(block_n, n)
    out = _swiglu(x2, w_gate, w_up, bm, bn, bool(interpret))
    return out.reshape(*lead, n)


def _swiglu_cost(in_avals, out_avals, params):
    """Two [M,K]x[K,N] MXU projections + the fused silu*up elementwise;
    the [M,N] intermediates never touch HBM (that's the fusion win)."""
    from .cost_registry import aval_bytes
    (m, k), _, _ = in_avals[0]
    n = int(in_avals[1][0][1])
    m, k = int(m), int(k)
    flops = 4.0 * m * k * n + 10.0 * m * n  # sigmoid ~8 + mul + mul
    bts = sum(aval_bytes(a) for a in in_avals) \
        + sum(aval_bytes(a) for a in out_avals)
    return flops, bts


def _register_costs():
    from .cost_registry import register_kernel_cost
    register_kernel_cost("swiglu_fwd", _swiglu_cost, family="swiglu",
                         operand_roles=("x", "w_gate", "w_up"))


_register_costs()
