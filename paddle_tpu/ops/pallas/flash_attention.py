"""Flash attention as Pallas TPU kernels (forward + backward).

Parity: the reference's fused multi-head attention CUDA path
(/root/reference/paddle/fluid/operators/fused/fused_attention_op.cu and
fmha_ref.h) materialises the full [B,H,T,S] probability tensor in HBM.
This kernel is the TPU-native redesign: online-softmax tiling so the
working set stays in VMEM (O(T) memory), with the backward pass
recomputing probabilities blockwise from the saved logsumexp — the
standard flash-attention-2 decomposition, laid out for the 128x128 MXU.

Layout: q,k,v are [B, H, T, D] with D a multiple of 64 (D=64 measured
faster than the XLA path on v5e with whole-sequence blocks; D=128 fills
the MXU lanes exactly); other head dims use the XLA einsum path in
nn/functional_attention.py. Default blocks come from the measured policy
in flash_attention(); the grid's innermost dimension walks k blocks so
the VMEM accumulator/max/denominator scratch persists across the online
softmax sweep (TPU grids execute sequentially).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _body():
        # feed the MXU native bf16 operands with f32 accumulation — casting
        # to f32 first would force 8x-slower f32 systolic passes
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale

        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[0]
        pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # skip fully-masked k blocks above the diagonal
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
        def _():
            _body()
    else:
        _body()

    @pl.when(kj == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        m = m_ref[:, :1]
        lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe))
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    bh, t, d = q.shape
    s_len = k.shape[1]
    nq = pl.cdiv(t, block_q)
    nk = pl.cdiv(s_len, block_k)

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
        name="flash_attention_fwd",
    )(q, k, v)
    return o, lse[:, :, 0]


# ----------------------------------------------------------------------
# backward
# ----------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        lse = lse_ref[0][:, :1]
        p = jnp.exp(s - lse)
        do = do_ref[0]
        v = v_ref[0]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0][:, :1]
        ds = (p * (dp - delta)).astype(k.dtype)
        acc_ref[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32) * sm_scale

    if causal:
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
        def _():
            _body()
    else:
        _body()

    @pl.when(kj == nk - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, sm_scale, causal, block_q, block_k):
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        lse = lse_ref[0][:, :1]
        p = jnp.exp(s - lse)  # (bq, bk)
        do = do_ref[0]
        # dv += p^T @ do
        dv_acc[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        v = v_ref[0]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = delta_ref[0][:, :1]
        ds = (p * (dp - delta)).astype(q.dtype)
        # dk += ds^T @ q
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32) * sm_scale

    if causal:
        # q block participates iff its last row >= first k row
        @pl.when(qi * block_q + (block_q - 1) >= kj * block_k)
        def _():
            _body()
    else:
        _body()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    bh, t, d = q.shape
    s_len = k.shape[1]
    nq = pl.cdiv(t, block_q)
    nk = pl.cdiv(s_len, block_k)

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse_b = jnp.broadcast_to(lse[:, :, None], (bh, t, 128))
    delta_b = jnp.broadcast_to(delta[:, :, None], (bh, t, 128))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
        name="flash_attention_bwd_dq",
    )(q, k, v, do, lse_b, delta_b)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_len, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s_len, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
        name="flash_attention_bwd_dkv",
    )(q, k, v, do, lse_b, delta_b)
    return dq, dk, dv


# ----------------------------------------------------------------------
# public entry
# ----------------------------------------------------------------------
def _fit(n, cap):
    """Largest 128-multiple <= cap dividing n (the kernels have no
    tail-block masking, so blocks must divide the sequence)."""
    if n % 128:
        raise ValueError(f"flash attention needs T/S % 128 == 0, got {n}")
    b = min(n, cap)
    while n % b:
        b -= 128
    return b


# Residuals-as-inputs structure: the forward Pallas call runs on
# stop_gradient'd operands (no autodiff path through pallas_call), its
# outputs (o, lse) are tagged with jax.ad_checkpoint.checkpoint_name, and
# the gradient is attached by a custom_vjp whose residuals are exactly its
# *inputs* (q, k, v, o, lse). Under ``jax.checkpoint`` a policy that saves
# the tagged names then feeds the backward kernels directly from the saved
# values — the forward flash kernel is never re-run in backward (the
# custom_vjp "recompute" is an identity). With a plain custom_vjp the
# residuals are opaque to checkpoint policies and every remat'd layer pays
# a full forward flash replay in backward (measured +9% step time on an
# 8-layer GPT-medium block stack, benchmarks/sweep_r5a).
SAVEABLE_NAMES = ("flash_out", "flash_lse")


def saveable_policy(base=None):
    """A ``jax.checkpoint`` policy that saves the flash-attention forward
    outputs (and, with ``base``, whatever the base policy saves).

    ``remat_policy="selective"`` paths compose this with
    ``dots_with_no_batch_dims_saveable`` so neither weight matmuls nor the
    flash forward re-run in backward."""
    names = jax.checkpoint_policies.save_only_these_names(*SAVEABLE_NAMES)
    if base is None:
        return names
    return jax.checkpoint_policies.save_from_both_policies(base, names)


def granularity_policy(granularity):
    """The single granularity-name → jax.checkpoint-policy table, shared by
    the model remat path (models/gpt.py) and the pipeline schedule
    (meta_parallel/pipeline_schedule.py): 'selective' saves weight-matmul
    outputs AND the flash forward, 'core_attn' saves only the flash forward
    (reference PaddleNLP core_attn granularity), anything else saves
    nothing (full recompute)."""
    if granularity == "selective":
        return saveable_policy(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    if granularity == "core_attn":
        return saveable_policy()
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _attach(q, k, v, o, lse, sm_scale, causal, block_q, block_k, interpret):
    return o


def _attach_fwd(q, k, v, o, lse, sm_scale, causal, block_q, block_k,
                interpret):
    return o, (q, k, v, o, lse)


def _attach_bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd(sm_scale, causal, block_q, block_k, interpret, res, do)
    # o/lse enter _attach only as saved forward values; the real grad path
    # to q/k/v is dq/dk/dv above, so their cotangents are exact zeros and
    # terminate at the stop_gradient'd pallas forward
    return dq, dk, dv, jnp.zeros_like(o), jnp.zeros_like(lse)


_attach.defvjp(_attach_fwd, _attach_bwd)


def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    from jax.ad_checkpoint import checkpoint_name

    o, lse = _fwd(jax.lax.stop_gradient(q), jax.lax.stop_gradient(k),
                  jax.lax.stop_gradient(v), sm_scale, causal, block_q,
                  block_k, interpret)
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return _attach(q, k, v, o, lse, sm_scale, causal, block_q, block_k,
                   interpret)


def flash_attention(q, k, v, *, causal=False, sm_scale=None,
                    block_q=None, block_k=None, interpret=None):
    """Flash attention over [B, H, T, D] (or [BH, T, D]) arrays.

    D must be a multiple of 64 and T/S multiples of 128;
    nn/functional_attention.py guards those preconditions and falls back
    to the XLA einsum path otherwise.

    Default blocks come from v5e-measured sweeps (fwd+bwd, interleaved
    A/B vs the XLA einsum path): at D=64 small blocks lose to per-block
    overhead — whole-sequence blocks win (14.6 vs 15.6 ms XLA at
    B8 H16 T1024); at D>=128 the score matrix forces bk<=512 for VMEM and
    bq1024/bk512 wins (17.8 vs 26.8 ms XLA at B8 H16 T2048 D128).
    """
    t_len, d_head = q.shape[-2], q.shape[-1]
    s_len = k.shape[-2]

    # ragged (non-128-multiple) sequences, causal self-attention: right-pad
    # Q/K/V with zeros to the next 128 multiple. Exact because (a) padded
    # KEYS sit at positions >= the real length, so the causal mask hides
    # them from every real query; (b) padded QUERY rows are sliced from the
    # output, so their cotangent is zero and they contribute nothing to
    # dK/dV. Non-causal ragged shapes fall back to the XLA path upstream.
    t_pad = 0
    if t_len % 128 and causal and t_len == s_len:
        t_pad = (-t_len) % 128
        pad = [(0, 0)] * (q.ndim - 2) + [(0, t_pad), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        t_len = s_len = t_len + t_pad

    if block_q is None:
        block_q = _fit(t_len, 1024)
    if block_k is None:
        block_k = _fit(s_len, 1024 if d_head < 128 else 512)
    if t_len % block_q or s_len % block_k:
        raise ValueError(
            f"flash blocks must divide the sequence: T={t_len} S={s_len} "
            f"bq={block_q} bk={block_k}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # lane-pad the head dim to a 64-multiple (e.g. GPT-3 760M's D=96):
    # zero columns leave q.k^T and the value matmul exact, and the padded
    # output/grad columns are sliced away (dv/dk/dq grads of zero columns
    # are zero, so the custom vjp stays exact)
    d_pad = (-d_head) % 64
    if d_pad:
        pad = [(0, 0)] * (q.ndim - 1) + [(0, d_pad)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    squeeze4 = q.ndim == 4
    if squeeze4:
        b, h, t, d = q.shape
        s_len = k.shape[2]
        q = q.reshape(b * h, t, d)
        k = k.reshape(b * h, s_len, d)
        v = v.reshape(b * h, s_len, d)
    o = _flash(q, k, v, float(sm_scale), bool(causal),
               int(block_q), int(block_k), bool(interpret))
    if squeeze4:
        o = o.reshape(b, h, t, d)
    if d_pad:
        o = o[..., :d_head]
    if t_pad:
        o = o[..., : t_len - t_pad, :]
    return o


# ----------------------------------------------------------------------
# cost models (analysis/cost.py prices pallas_call eqns from these)
# ----------------------------------------------------------------------
_TRANSCENDENTAL_FLOPS = 8  # matches analysis.cost.TRANSCENDENTAL_FLOPS


def _attn_dims(in_avals):
    (bh, t, d), _, _ = in_avals[0]
    s = int(in_avals[1][0][1])
    return int(bh), int(t), int(s), int(d)


def _io_bytes(in_avals, out_avals):
    from .cost_registry import aval_bytes
    return sum(aval_bytes(a) for a in in_avals) \
        + sum(aval_bytes(a) for a in out_avals)


def _flash_fwd_cost(in_avals, out_avals, params):
    bh, t, s, d = _attn_dims(in_avals)
    flops = 4.0 * bh * t * s * d + 2.0 * _TRANSCENDENTAL_FLOPS * bh * t * s
    return flops, _io_bytes(in_avals, out_avals)


def _flash_bwd_dq_cost(in_avals, out_avals, params):
    bh, t, s, d = _attn_dims(in_avals)
    flops = 6.0 * bh * t * s * d + _TRANSCENDENTAL_FLOPS * bh * t * s
    return flops, _io_bytes(in_avals, out_avals)


def _flash_bwd_dkv_cost(in_avals, out_avals, params):
    bh, t, s, d = _attn_dims(in_avals)
    flops = 8.0 * bh * t * s * d + _TRANSCENDENTAL_FLOPS * bh * t * s
    return flops, _io_bytes(in_avals, out_avals)


def _register_costs():
    from .cost_registry import register_kernel_cost
    register_kernel_cost(
        "flash_attention_fwd", _flash_fwd_cost,
        family="flash_attention", operand_roles=("q", "k", "v"))
    register_kernel_cost(
        "flash_attention_bwd_dq", _flash_bwd_dq_cost,
        family="flash_attention",
        operand_roles=("q", "k", "v", "do", "lse", "delta"))
    register_kernel_cost(
        "flash_attention_bwd_dkv", _flash_bwd_dkv_cost,
        family="flash_attention",
        operand_roles=("q", "k", "v", "do", "lse", "delta"))


_register_costs()
