"""Fused softmax-cross-entropy head as Pallas TPU kernels (ISSUE 16).

The GPT loss head (``gpt.loss`` in the r14 MFU-gap table) materializes
full-vocab log-softmax logits every step: ``F.cross_entropy`` lowers to
log_softmax → gather → mask, three full passes over the ``[N, V]`` logits
plus an ``[N, V]`` intermediate.  These kernels fuse the whole head into
one streaming pass with f32 statistics (max / sum-exp / picked logit kept
in f32 VMEM scratch regardless of logits dtype — the r6 fused-f32-stats
convention), with a custom_vjp backward that recomputes softmax from the
saved log-sum-exp instead of storing it.

Two entry points mirror the two branches of
``ParallelCrossEntropy.forward``:

* :func:`softmax_ce_loss` — the non-mp branch: full-vocab loss, parity
  with ``F.cross_entropy(..., reduction="none")``.
* :func:`softmax_ce_partials` — the mp branch's local half: given
  globally max-shifted logits of THIS shard and shard-local label
  indices, one pass produces (sum-exp, picked-logit) partials; the
  ``pmax`` / ``mp_allreduce`` collectives stay outside the kernel in
  ``ParallelCrossEntropy`` (reference: c_softmax_with_cross_entropy_op).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .cost_registry import aval_bytes, register_kernel_cost

__all__ = [
    "softmax_ce_loss",
    "softmax_ce_partials",
    "softmax_ce_reference",
]

NEG_INF = -1e30


def softmax_ce_reference(logits, labels, *, ignore_index=-100):
    """F.cross_entropy(reduction="none") math — the parity oracle."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    lbl = labels.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return jnp.where(valid, -picked, 0.0)


# -- full-vocab loss (non-mp branch) ----------------------------------------
def _ce_fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref, m_ref, l_ref, p_ref, *,
                   vocab, block_v, n_cols, ignore_index):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        p_ref[...] = jnp.zeros_like(p_ref)

    x = x_ref[...].astype(jnp.float32)               # [bn, bv]
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    x = jnp.where(col < vocab, x, NEG_INF)           # vocab tail
    lbl = lab_ref[...][:, None]                      # [bn, 1] int32

    m_prev = m_ref[...][:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(x, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_ref[...][:, :1] \
        + jnp.sum(jnp.exp(x - m_new), axis=-1, keepdims=True)
    # the label's raw logit: exactly one hit across the whole row (none
    # for ignore rows — lbl never equals a column index)
    hit = jnp.sum(jnp.where(col == lbl, x, 0.0), axis=-1, keepdims=True)
    p_new = p_ref[...][:, :1] + hit
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
    p_ref[...] = jnp.broadcast_to(p_new, p_ref.shape)

    @pl.when(j == n_cols - 1)
    def _finish():
        lse = m_ref[...][:, :1] + jnp.log(l_ref[...][:, :1])
        valid = lab_ref[...][:, None] != ignore_index
        loss = jnp.where(valid, lse - p_ref[...][:, :1], 0.0)
        loss_ref[...] = jnp.broadcast_to(loss, loss_ref.shape)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _ce_bwd_kernel(x_ref, lab_ref, lse_ref, g_ref, dx_ref, *,
                   vocab, block_v, ignore_index):
    j = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    lbl = lab_ref[...][:, None]
    lse = lse_ref[...][:, :1]
    g = g_ref[...][:, :1]
    p = jnp.where(col < vocab, jnp.exp(x - lse), 0.0)
    onehot = (col == lbl).astype(jnp.float32)
    valid = (lbl != ignore_index).astype(jnp.float32)
    dx_ref[...] = ((p - onehot) * g * valid).astype(dx_ref.dtype)


def softmax_ce_loss(logits, labels, *, ignore_index=-100, interpret=None,
                    block_n=32, block_v=128):
    """Fused softmax-CE loss, ``F.cross_entropy(reduction="none")`` parity.

    ``logits`` ``[..., V]``, ``labels`` ``[...]`` int — returns per-row
    loss with ``labels``' shape in ``logits.dtype`` (statistics in f32).
    Differentiable w.r.t. ``logits`` via a fused custom_vjp backward.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    vocab = logits.shape[-1]
    lead = logits.shape[:-1]
    if not interpret and (vocab % 128 or vocab < 128):
        return softmax_ce_reference(
            logits, labels, ignore_index=ignore_index).astype(logits.dtype)

    n = 1
    for s in lead:
        n *= int(s)
    x2 = logits.reshape(n, vocab)
    lab = labels.astype(jnp.int32).reshape(n)
    bn = min(block_n, max(n, 1))
    bv = min(block_v, vocab)
    n_pad = -n % bn
    if n_pad:
        x2 = jnp.pad(x2, ((0, n_pad), (0, 0)))
        lab = jnp.pad(lab, (0, n_pad), constant_values=ignore_index)
    np_, ni, nv = n + n_pad, (n + n_pad) // bn, pl.cdiv(vocab, bv)

    def _fwd_raw(x2, lab):
        fwd = functools.partial(_ce_fwd_kernel, vocab=vocab, block_v=bv,
                                n_cols=nv, ignore_index=ignore_index)
        return pl.pallas_call(
            fwd,
            grid=(ni, nv),
            in_specs=[
                pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
                pl.BlockSpec((bn,), lambda i, j: (i,)),
            ],
            out_specs=[
                pl.BlockSpec((bn, 128), lambda i, j: (i, 0)),
                pl.BlockSpec((bn, 128), lambda i, j: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((np_, 128), jnp.float32),
                jax.ShapeDtypeStruct((np_, 128), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((bn, 128), jnp.float32)] * 3,
            interpret=interpret,
            name="softmax_ce_fwd",
        )(x2, lab)

    def _bwd_raw(x2, lab, lse, g):
        bwd = functools.partial(_ce_bwd_kernel, vocab=vocab, block_v=bv,
                                ignore_index=ignore_index)
        return pl.pallas_call(
            bwd,
            grid=(ni, nv),
            in_specs=[
                pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
                pl.BlockSpec((bn,), lambda i, j: (i,)),
                pl.BlockSpec((bn, 128), lambda i, j: (i, 0)),
                pl.BlockSpec((bn, 128), lambda i, j: (i, 0)),
            ],
            out_specs=pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((np_, vocab), x2.dtype),
            interpret=interpret,
            name="softmax_ce_bwd",
        )(x2, lab, lse, g)

    @jax.custom_vjp
    def _loss(x2):
        out, _ = _fwd_raw(x2, lab)
        return out[:, 0]

    def _loss_fwd(x2):
        out, lse = _fwd_raw(x2, lab)
        return out[:, 0], (x2, lse)

    def _loss_bwd(res, g):
        x2, lse = res
        g2 = jnp.broadcast_to(g.astype(jnp.float32)[:, None], (np_, 128))
        return (_bwd_raw(x2, lab, lse, g2),)

    _loss.defvjp(_loss_fwd, _loss_bwd)
    return _loss(x2)[:n].reshape(lead).astype(logits.dtype)


# -- mp partials (vocab-sharded branch) -------------------------------------
def _partials_fwd_kernel(x_ref, lab_ref, se_ref, pk_ref, se_acc, pk_acc, *,
                         vocab, block_v, n_cols):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        se_acc[...] = jnp.zeros_like(se_acc)
        pk_acc[...] = jnp.zeros_like(pk_acc)

    x = x_ref[...].astype(jnp.float32)
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    in_vocab = col < vocab
    lbl = lab_ref[...][:, None]          # local index, or -1 (other shard)
    # shifted logits are <= 0 globally (global max already subtracted by
    # the caller), so plain exp is stable — no online max pass needed
    se = jnp.sum(jnp.where(in_vocab, jnp.exp(x), 0.0), axis=-1,
                 keepdims=True)
    pk = jnp.sum(jnp.where(col == lbl, x, 0.0), axis=-1, keepdims=True)
    se_acc[...] = se_acc[...] + jnp.broadcast_to(se, se_acc.shape)
    pk_acc[...] = pk_acc[...] + jnp.broadcast_to(pk, pk_acc.shape)

    @pl.when(j == n_cols - 1)
    def _finish():
        se_ref[...] = se_acc[...]
        pk_ref[...] = pk_acc[...]


def _partials_bwd_kernel(x_ref, lab_ref, gse_ref, gpk_ref, dx_ref, *,
                         vocab, block_v):
    j = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    lbl = lab_ref[...][:, None]
    gse = gse_ref[...][:, :1]
    gpk = gpk_ref[...][:, :1]
    dse = jnp.where(col < vocab, jnp.exp(x), 0.0) * gse
    dpk = (col == lbl).astype(jnp.float32) * gpk
    dx_ref[...] = (dse + dpk).astype(dx_ref.dtype)


def softmax_ce_partials(shifted, local_labels, *, interpret=None,
                        block_n=32, block_v=128):
    """One-pass (sum-exp, picked-logit) partials over THIS shard's logits.

    ``shifted`` ``[..., V_local]`` logits minus the GLOBAL max (caller's
    ``pmax``); ``local_labels`` ``[...]`` int32 shard-local label index,
    or any negative value when the label lives on another shard / is the
    ignore index.  Returns ``(sum_exp, picked)`` with ``local_labels``'
    shape in f32 — the caller allreduces both and finishes
    ``log(sum_exp) - picked``.  Differentiable w.r.t. ``shifted``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    vocab = shifted.shape[-1]
    lead = shifted.shape[:-1]
    if not interpret and (vocab % 128 or vocab < 128):
        lbl = local_labels.astype(jnp.int32)
        col = jnp.arange(vocab, dtype=jnp.int32)
        se = jnp.sum(jnp.exp(shifted.astype(jnp.float32)), axis=-1)
        pk = jnp.sum(jnp.where(col == lbl[..., None],
                               shifted.astype(jnp.float32), 0.0), axis=-1)
        return se, pk

    n = 1
    for s in lead:
        n *= int(s)
    x2 = shifted.reshape(n, vocab)
    lab = local_labels.astype(jnp.int32).reshape(n)
    bn = min(block_n, max(n, 1))
    bv = min(block_v, vocab)
    n_pad = -n % bn
    if n_pad:
        x2 = jnp.pad(x2, ((0, n_pad), (0, 0)), constant_values=NEG_INF)
        lab = jnp.pad(lab, (0, n_pad), constant_values=-1)
    np_, ni, nv = n + n_pad, (n + n_pad) // bn, pl.cdiv(vocab, bv)

    def _fwd_raw(x2, lab):
        fwd = functools.partial(_partials_fwd_kernel, vocab=vocab,
                                block_v=bv, n_cols=nv)
        return pl.pallas_call(
            fwd,
            grid=(ni, nv),
            in_specs=[
                pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
                pl.BlockSpec((bn,), lambda i, j: (i,)),
            ],
            out_specs=[
                pl.BlockSpec((bn, 128), lambda i, j: (i, 0)),
                pl.BlockSpec((bn, 128), lambda i, j: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((np_, 128), jnp.float32),
                jax.ShapeDtypeStruct((np_, 128), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((bn, 128), jnp.float32)] * 2,
            interpret=interpret,
            name="softmax_ce_partials_fwd",
        )(x2, lab)

    def _bwd_raw(x2, lab, gse, gpk):
        bwd = functools.partial(_partials_bwd_kernel, vocab=vocab, block_v=bv)
        return pl.pallas_call(
            bwd,
            grid=(ni, nv),
            in_specs=[
                pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
                pl.BlockSpec((bn,), lambda i, j: (i,)),
                pl.BlockSpec((bn, 128), lambda i, j: (i, 0)),
                pl.BlockSpec((bn, 128), lambda i, j: (i, 0)),
            ],
            out_specs=pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((np_, vocab), x2.dtype),
            interpret=interpret,
            name="softmax_ce_partials_bwd",
        )(x2, lab, gse, gpk)

    @jax.custom_vjp
    def _partials(x2):
        se, pk = _fwd_raw(x2, lab)
        return se[:, 0], pk[:, 0]

    def _partials_fwd(x2):
        se, pk = _fwd_raw(x2, lab)
        return (se[:, 0], pk[:, 0]), x2

    def _partials_bwd(x2, gs):
        gse, gpk = gs
        gse2 = jnp.broadcast_to(gse.astype(jnp.float32)[:, None], (np_, 128))
        gpk2 = jnp.broadcast_to(gpk.astype(jnp.float32)[:, None], (np_, 128))
        return (_bwd_raw(x2, lab, gse2, gpk2),)

    _partials.defvjp(_partials_fwd, _partials_bwd)
    se, pk = _partials(x2)
    return se[:n].reshape(lead), pk[:n].reshape(lead)


# -- cost models ------------------------------------------------------------
_TRANSCENDENTAL_FLOPS = 8  # matches analysis.cost.TRANSCENDENTAL_FLOPS


def _rows_vocab(in_avals):
    x_av = in_avals[0]
    shape = x_av[0]
    n = 1
    for s in shape[:-1]:
        n *= int(s)
    return n, int(shape[-1]), x_av


def _ce_fwd_cost(in_avals, out_avals, params):
    n, v, x_av = _rows_vocab(in_avals)
    # one streaming pass: max + exp + sum + picked-hit per element
    flops = float(n * v) * (_TRANSCENDENTAL_FLOPS + 3)
    bts = aval_bytes(x_av) + sum(aval_bytes(a) for a in in_avals[1:]) \
        + sum(aval_bytes(a) for a in out_avals)
    return flops, bts


def _ce_bwd_cost(in_avals, out_avals, params):
    n, v, x_av = _rows_vocab(in_avals)
    flops = float(n * v) * (_TRANSCENDENTAL_FLOPS + 3)
    bts = aval_bytes(x_av) + sum(aval_bytes(a) for a in in_avals[1:]) \
        + sum(aval_bytes(a) for a in out_avals)
    return flops, bts


register_kernel_cost("softmax_ce_fwd", _ce_fwd_cost, family="softmax_ce",
                     operand_roles=("logits", "labels"))
register_kernel_cost("softmax_ce_bwd", _ce_bwd_cost, family="softmax_ce",
                     operand_roles=("logits", "labels", "lse", "g"))
register_kernel_cost("softmax_ce_partials_fwd", _ce_fwd_cost,
                     family="softmax_ce",
                     operand_roles=("logits", "labels"))
register_kernel_cost("softmax_ce_partials_bwd", _ce_bwd_cost,
                     family="softmax_ce",
                     operand_roles=("logits", "labels", "g_sum_exp",
                                    "g_picked"))
