"""The op-definition machinery: pure-jax functions become taped eager ops.

Parity: this file plays the role of the reference's entire operator dispatch
stack — ``OperatorWithKernel::RunImpl`` kernel choice
(/root/reference/paddle/fluid/framework/operator.cc:1081,1211), dygraph
``Tracer::TraceOp`` (/root/reference/paddle/fluid/imperative/tracer.cc:146) and
the generated ``core.ops.*`` fast path
(/root/reference/paddle/fluid/pybind/op_function_generator.cc:551).

TPU-native redesign: an "op" is just a pure jax function. Eager execution is
the function call itself (XLA compiles + caches per shape); gradient recording
is a ``jax.vjp`` closure pushed on the tape. There is no kernel registry, no
InferShape pass, no device transform — XLA's tracing subsumes all three.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..amp.auto_cast import amp_state, amp_wrap_fn
from ..autograd import tape
from ..tensor import Tensor

__all__ = ["primitive", "unwrap", "wrap"]


def unwrap(x):
    if isinstance(x, Tensor):
        d = x._data
        if isinstance(d, jax.Array):
            return d
        # static-mode Variable (_data is an aval): keep the wrapper so
        # record_op registers it as a graph input instead of a literal
        return x
    return x


def wrap(x, stop_gradient=True):
    return Tensor(x, stop_gradient=stop_gradient) if isinstance(x, jax.Array) else x


def _is_tensor(x):
    return isinstance(x, Tensor)


def _flatten_call(args, kwargs):
    flat, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    tensor_pos = [i for i, x in enumerate(flat) if _is_tensor(x)]
    return flat, treedef, tensor_pos


def primitive(fn: Callable = None, *, nondiff: bool = False, aux: int = 0, name: str = None):
    """Wrap a pure jax function into an eager, taped framework op.

    - Tensor args (incl. inside lists/tuples) are unwrapped to jax arrays.
    - If grad is enabled and any floating input requires grad, the call runs
      under ``jax.vjp`` and a tape Node is recorded.
    - ``nondiff``: op has no gradient (indices, comparisons, rng...).
    - ``aux``: the last ``aux`` outputs are non-differentiable extras
      (e.g. ``topk`` indices).
    """

    if fn is None:
        return functools.partial(primitive, nondiff=nondiff, aux=aux, name=name)

    op_name = name or fn.__name__

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        # static-graph recording hook (≙ the static paradigm: ops append to a
        # Program instead of executing — framework.py append_op role)
        import paddle_tpu as _pd

        if _pd._static_mode:
            from ..static import program as _sp

            if _sp.recording_active():
                # autocast applies at record time: the cast-inserting wrapper
                # is baked into the recorded closure (parity: static AMP
                # rewrite_program, contrib/mixed_precision/decorator.py:37)
                fn_rec = amp_wrap_fn(fn, op_name) if amp_state().enable else fn
                return _sp.record_op(fn_rec, op_name, args, kwargs)

        # AMP autocast hook (≙ dygraph amp_auto_cast.cc cast insertion):
        # the casting wrapper keeps casts inside the traced fn so their VJP
        # restores parameter-dtype gradients
        fn_ = amp_wrap_fn(fn, op_name) if amp_state().enable else fn

        flat, treedef, tensor_pos = _flatten_call(args, kwargs)
        in_tensors = [flat[i] for i in tensor_pos]

        need_grad = (
            not nondiff
            and tape.is_grad_enabled()
            and any(
                not t.stop_gradient and jnp.issubdtype(t._data.dtype, jnp.inexact)
                for t in in_tensors
            )
        )

        if not need_grad:
            flat2 = list(flat)
            for i in tensor_pos:
                flat2[i] = flat[i]._data
            a2, k2 = jax.tree_util.tree_unflatten(treedef, flat2)
            out = fn_(*a2, **k2)
            return jax.tree_util.tree_map(wrap, out)

        # differentiate w.r.t. floating tensors that require grad; others are
        # closed-over constants
        diff_pos = [
            i
            for i in tensor_pos
            if not flat[i].stop_gradient
            and jnp.issubdtype(flat[i]._data.dtype, jnp.inexact)
        ]
        diff_tensors = [flat[i] for i in diff_pos]

        def pure(*diff_arrs):
            flat2 = list(flat)
            for i in tensor_pos:
                flat2[i] = flat[i]._data
            for i, a in zip(diff_pos, diff_arrs):
                flat2[i] = a
            a2, k2 = jax.tree_util.tree_unflatten(treedef, flat2)
            out = fn_(*a2, **k2)
            if aux:
                outs = out if isinstance(out, tuple) else (out,)
                return outs[:-aux] if len(outs) - aux > 1 else outs[0], outs[-aux:]
            return out

        if aux:
            out, vjp_fn, aux_out = jax.vjp(
                pure, *[t._data for t in diff_tensors], has_aux=True
            )
        else:
            out, vjp_fn = jax.vjp(pure, *[t._data for t in diff_tensors])
            aux_out = ()

        out_arrays = out if isinstance(out, tuple) else (out,)
        node = tape.Node(
            vjp_fn,
            diff_tensors,
            [(a.shape, a.dtype) for a in out_arrays],
            name=op_name,
            pure_fn=pure,  # re-differentiable source for create_graph
            has_aux=bool(aux),
            tuple_out=isinstance(out, tuple),
        )
        out_tensors = []
        for pos, a in enumerate(out_arrays):
            t = Tensor(a, stop_gradient=False)
            t._node = node
            t._out_idx = pos
            out_tensors.append(t)
        aux_tensors = [wrap(a) for a in aux_out]
        results = tuple(out_tensors) + tuple(aux_tensors)
        if len(results) == 1:
            return results[0]
        return results

    wrapper.raw = fn  # the pure-jax function, for use inside jit/shard_map
    wrapper.op_name = op_name
    return wrapper


def inplace_guard(x, op_name: str):
    """Paddle-parity safety for ``*_`` in-place APIs: the vjp tape records
    input values by reference, so mutating a grad-requiring tensor would
    silently corrupt gradients (the reference raises for leaf tensors for
    the same reason). Raise instead of being wrong."""
    from ..autograd import tape as _tape

    if _tape.is_grad_enabled() and isinstance(x, Tensor) and not x.stop_gradient:
        raise ValueError(
            f"{op_name}(): in-place mutation of a tensor that requires grad "
            "is not supported (it would corrupt recorded gradients); call "
            "it under paddle.no_grad() or use the out-of-place variant")
