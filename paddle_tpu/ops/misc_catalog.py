"""Catalog tail ops — the miscellaneous reference operators that belong to
no big family (SURVEY App. A "PS/rec-sys special" generic rows + text
positional encoding).

Parity: add_position_encoding_op.h, sampling_id_op.h,
squared_l2_distance_op.h, squared_l2_norm_op.h, center_loss_op.h,
bpr_loss_op.h, fsp_op.h (flow-of-solution-procedure distillation),
cos_sim_op.h, affine_channel_op.cc, shuffle_channel_op.h,
space_to_depth_op.cc, random_crop_op.h, partial_concat_op.h,
partial_sum_op.h.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._primitive import primitive, unwrap

__all__ = [
    "add_position_encoding",
    "sampling_id",
    "squared_l2_distance",
    "squared_l2_norm",
    "center_loss",
    "bpr_loss",
    "fsp_matrix",
    "cos_sim",
    "affine_channel",
    "shuffle_channel",
    "space_to_depth",
    "random_crop",
    "partial_concat",
    "partial_sum",
    "cvm",
    "shuffle_batch",
    "data_norm",
    "batch_fc",
    "tdm_child",
    "filter_by_instag",
    "sample_logits",
]


def add_position_encoding(x, alpha=1.0, beta=1.0, name=None):
    """Sinusoidal position encoding mixed into [B, T, 2H] features
    (add_position_encoding_op.h): out[..., k] = alpha*x + beta*sin(pos/
    10000^(k/(H-1))) for the first half, cos for the second."""

    @primitive
    def _ape(x):
        b, t, e = x.shape
        half = e // 2
        pos = jnp.arange(t, dtype=jnp.float32)
        k = jnp.arange(half, dtype=jnp.float32)
        # half == 1: reference computes pos / 10000.0 directly
        denom = (jnp.power(10000.0, k / (half - 1)) if half > 1
                 else jnp.full((1,), 10000.0, jnp.float32))
        val = pos[:, None] / denom[None, :]  # [T, half]
        enc = jnp.concatenate([jnp.sin(val), jnp.cos(val)], axis=-1)
        return (alpha * x + beta * enc[None].astype(x.dtype))

    return _ape(x)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64", name=None):  # noqa: A002
    """Sample one category id per row from probabilities [B, V]
    (sampling_id_op.h: inverse-CDF on a uniform draw; driven by the
    framework's seeded PRNG)."""
    from ..random import split_key

    @primitive(nondiff=True)
    def _sid(x, key):
        u = jax.random.uniform(key, (x.shape[0], 1), jnp.float32,
                               minval=float(min), maxval=float(max))
        cdf = jnp.cumsum(x.astype(jnp.float32), axis=-1)
        idx = jnp.sum((cdf < u).astype(jnp.int32), axis=-1)
        return jnp.clip(idx, 0, x.shape[1] - 1).astype(dtype)

    return _sid(x, split_key())


def squared_l2_distance(x, y, name=None):
    """Row-wise squared L2 distance (squared_l2_distance_op.h). Returns
    (out [N, 1], sub = x - y) like the reference (sub feeds its grad; here
    AD covers it but the output surface matches)."""

    @primitive
    def _sqd(x, y):
        sub = x - y
        return jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim)),
                       keepdims=True).reshape(x.shape[0], 1), sub

    return _sqd(x, y)


def squared_l2_norm(x, name=None):
    """sum(x^2) as a 1-element tensor (squared_l2_norm_op.h)."""

    @primitive
    def _sqn(x):
        return jnp.sum(jnp.square(x)).reshape(1)

    return _sqn(x)


def center_loss(x, label, centers, alpha=0.5, update_center=True, name=None):
    """Center loss (center_loss_op.h, Wen et al.): per-sample
    0.5*||x - centers[label]||^2; centers move toward their class means by
    rate alpha with the reference's 1/(1+count) normalization. Returns
    (loss [N, 1], new_centers)."""

    @primitive
    def _cl(x, label, centers):
        lbl = label.reshape(-1).astype(jnp.int32)
        c = jnp.take(centers, lbl, axis=0)
        diff = x - c
        loss = 0.5 * jnp.sum(jnp.square(diff), axis=-1, keepdims=True)
        if not update_center:
            return loss, centers
        k = centers.shape[0]
        counts = jnp.zeros((k,), jnp.float32).at[lbl].add(1.0)
        sums = jnp.zeros_like(centers).at[lbl].add(diff.astype(centers.dtype))
        upd = sums / (1.0 + counts)[:, None]
        return loss, centers + alpha * upd

    return _cl(x, unwrap(label), centers)


def bpr_loss(input, label, name=None):  # noqa: A002
    """Bayesian Personalized Ranking loss (bpr_loss_op.h): per row,
    mean over negatives j != y of softplus(x_j - x_y)."""

    @primitive
    def _bpr(x, label):
        n, c = x.shape
        lbl = label.reshape(-1).astype(jnp.int32)
        pos = jnp.take_along_axis(x, lbl[:, None], axis=-1)
        sp = jax.nn.softplus(x - pos)  # log(1 + exp(x_j - x_pos))
        mask = jnp.arange(c)[None, :] != lbl[:, None]
        return (jnp.sum(jnp.where(mask, sp, 0.0), axis=-1,
                        keepdims=True) / (c - 1))

    return _bpr(input, unwrap(label))


def fsp_matrix(x, y, name=None):
    """Flow-of-solution-procedure matrix for distillation (fsp_op.h):
    out[n, c1, c2] = mean over H*W of x[n, c1] * y[n, c2]."""

    @primitive
    def _fsp(x, y):
        h, w = x.shape[2], x.shape[3]
        return jnp.einsum("nchw,ndhw->ncd", x, y) / (h * w)

    return _fsp(x, y)


def cos_sim(x, y, name=None):
    """Row-wise cosine similarity [N, 1] (cos_sim_op.h; y may be [1, D]
    to broadcast one reference row)."""

    @primitive
    def _cs(x, y):
        xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
        yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
        dot = jnp.sum(x * y, axis=-1, keepdims=True)
        return dot / (xn * yn)

    return _cs(x, y)


def affine_channel(x, scale, bias, data_format="NCHW", name=None):
    """Per-channel scale + bias (affine_channel_op.cc — the frozen-BN
    replacement in detection models)."""

    @primitive
    def _ac(x, scale, bias):
        if data_format == "NCHW":
            shape = (1, -1) + (1,) * (x.ndim - 2)
        else:
            shape = (1,) * (x.ndim - 1) + (-1,)
        return x * scale.reshape(shape) + bias.reshape(shape)

    return _ac(x, scale, bias)


def shuffle_channel(x, group, name=None):
    """Channel shuffle (shuffle_channel_op.h; ShuffleNet)."""

    @primitive
    def _sc(x):
        n, c, h, w = x.shape
        return (x.reshape(n, group, c // group, h, w)
                .transpose(0, 2, 1, 3, 4).reshape(n, c, h, w))

    return _sc(x)


def space_to_depth(x, blocksize, name=None):
    """Darknet reorg (space_to_depth_op.h space_to_depth_compute): despite
    the name, the reference kernel maps the CHANNEL-major input
    [B, C, H, W] (C % bs^2 == 0) to [B, C/bs^2, H*bs, W*bs] with
    out[b, c2, j*bs + off//bs, i*bs + off%bs] = x[b, off*out_c + c2, j, i].
    Behavior parity over naming."""
    bs = int(blocksize)

    @primitive
    def _s2d(x):
        n, c, h, w = x.shape
        if c % (bs * bs):
            raise ValueError(
                f"space_to_depth: channels ({c}) must be divisible by "
                f"blocksize^2 ({bs * bs}) — reference InferShape")
        out_c = c // (bs * bs)
        # k = offset * out_c + c2, offset = dy*bs + dx
        r = x.reshape(n, bs, bs, out_c, h, w)  # [b, dy, dx, c2, j, i]
        r = r.transpose(0, 3, 4, 1, 5, 2)      # [b, c2, j, dy, i, dx]
        return r.reshape(n, out_c, h * bs, w * bs)

    return _s2d(x)


def random_crop(x, shape, seed=None, name=None):
    """Random spatial crop to ``shape`` (trailing dims; random_crop_op.h),
    driven by the framework PRNG."""
    from ..random import split_key

    shape = tuple(int(s) for s in shape)

    @primitive(nondiff=True)
    def _rc(x, key):
        nd = len(shape)
        lead = x.shape[: x.ndim - nd]
        n_inst = 1
        for s in lead:
            n_inst *= s
        flat = x.reshape((n_inst,) + x.shape[x.ndim - nd:])
        keys = jax.random.split(key, n_inst * nd).reshape(n_inst, nd)

        def crop_one(inst, ks):
            starts = tuple(
                jax.random.randint(ks[i], (), 0,
                                   inst.shape[i] - shape[i] + 1).astype(jnp.int32)
                for i in range(nd))
            return jax.lax.dynamic_slice(inst, starts, shape)

        # per-instance offsets (random_crop_op.h draws per ins_idx)
        out = jax.vmap(crop_one)(flat, keys)
        return out.reshape(tuple(lead) + shape)

    return _rc(x, split_key())


def _col_slice(x, start_index, length):
    """Reference normalization (partial_concat_op.h): negative start wraps,
    length -1 means to-the-end."""
    start = start_index + x.shape[1] if start_index < 0 else start_index
    end = x.shape[1] if length < 0 else start + length
    return x[:, start:end]


def partial_concat(inputs, start_index=0, length=-1, name=None):
    """Concat the same column slice of every input (partial_concat_op.h)."""

    @primitive
    def _pc(*xs):
        return jnp.concatenate(
            [_col_slice(x, start_index, length) for x in xs], axis=1)

    return _pc(*inputs)


def partial_sum(inputs, start_index=0, length=-1, name=None):
    """Sum the same column slice of every input (partial_sum_op.h)."""

    @primitive
    def _ps(*xs):
        acc = None
        for x in xs:
            sl = _col_slice(x, start_index, length)
            acc = sl if acc is None else acc + sl
        return acc

    return _ps(*inputs)


def cvm(input, cvm_ref, use_cvm=True, name=None):  # noqa: A002
    """Click-value-model feature transform (cvm_op.h CvmComputeKernel):
    the first two columns are (show, click); with use_cvm the output keeps
    all columns with show -> log(show+1) and click -> log(click+1) -
    log(show+1) (ctr in log space); without it the two cvm columns are
    dropped. ``cvm_ref`` is the op-signature CVM input (used only by the
    backward in the reference; accepted for parity)."""

    @primitive
    def _cvm(x):
        if use_cvm:
            c0 = jnp.log(x[:, 0:1] + 1.0)
            c1 = jnp.log(x[:, 1:2] + 1.0) - c0
            return jnp.concatenate([c0, c1, x[:, 2:]], axis=1)
        return x[:, 2:]

    return _cvm(input)


def shuffle_batch(x, seed=None, startup_seed=0, name=None):
    """Random row shuffle (shuffle_batch_op.h): rows (all leading dims
    flattened) are permuted with a seeded engine. Returns (out,
    shuffle_idx, seed_out) like the reference (seed_out = seed + 1 so
    chained calls keep advancing). Uses the given int seed, else the
    framework PRNG."""
    from ..random import split_key

    if seed is not None and not isinstance(seed, (int, np.integer)):
        seed = int(np.asarray(unwrap(seed)).reshape(()))
    if seed is None:
        key = split_key()
        seed_out = 0
    else:
        key = jax.random.PRNGKey(int(seed) if seed else int(startup_seed))
        seed_out = (int(seed) if seed else int(startup_seed)) + 1
    kd = jax.random.key_data(key)

    @primitive(aux=1)
    def _shuffle(x, kd):
        key = jax.random.wrap_key_data(kd)
        lead = int(np.prod(x.shape[:-1]))
        idx = jax.random.permutation(key, lead)
        flat = x.reshape(lead, x.shape[-1])
        return jnp.take(flat, idx, axis=0).reshape(x.shape), idx

    out, idx = _shuffle(x, kd)
    return out, idx, np.int64(seed_out)


def data_norm(x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4,
              name=None):
    """Running-statistics normalization (data_norm_op.cc DataNormKernel —
    the rec-sys feature normalizer): mean = batch_sum/batch_size,
    scale = sqrt(batch_size/batch_square_sum), y = (x - mean) * scale.
    Returns (y, means, scales); the statistics tensors are updated by the
    training framework (the reference's stat accumulation lives in its
    gradient op)."""

    @primitive(aux=2)
    def _dn(x, bsz, bsum, bsq):
        means = bsum / bsz
        scales = jnp.sqrt(bsz / bsq)
        return (x - means[None, :]) * scales[None, :], means, scales

    return _dn(x, unwrap(batch_size), unwrap(batch_sum),
               unwrap(batch_square_sum))


def batch_fc(input, w, bias, name=None):  # noqa: A002
    """Per-slot batched fully connected (batch_fc_op.cu BatchedGEMM):
    input [slot_pairs, ins, in_dim] x w [slot_pairs, in_dim, out_dim]
    + bias [slot_pairs, out_dim] -> [slot_pairs, ins, out_dim]."""

    @primitive
    def _bfc(x, w, b):
        out = jnp.einsum("sni,sio->sno", x, w)
        return out + b[:, None, :]

    return _bfc(input, w, bias)


def tdm_child(x, tree_info, child_nums, name=None):
    """TDM tree-index child lookup (tdm_child_op.h TDMChildInner — the
    tree-based deep match retrieval structure, SURVEY App. A note): for
    each node id, return its ``child_nums`` child ids from the tree_info
    table (rows [item_id, layer, parent, child0..childN-1]) plus a mask of
    which children are leaf items (tree_info[child][0] != 0). Nodes
    without children (id 0 or child slot 0) emit zeros."""

    @primitive(aux=1)
    def _tdm(x, info):
        ids = x.reshape(-1).astype(jnp.int32)
        has_child = (ids != 0) & (info[ids, 3] != 0)
        children = jnp.take(info[:, 3: 3 + int(child_nums)], ids, axis=0)
        children = jnp.where(has_child[:, None], children, 0)
        is_item = (jnp.take(info[:, 0], children.astype(jnp.int32)) != 0)
        mask = jnp.where(has_child[:, None], is_item, False)
        shape = x.shape + (int(child_nums),)
        return (children.astype(jnp.int32).reshape(shape),
                mask.astype(jnp.int32).reshape(shape))

    return _tdm(x, unwrap(tree_info))


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0, ins_lengths=None, tag_lengths=None,
                     name=None):
    """Instance filtering by tag membership (filter_by_instag_op.h — the
    rec-sys multi-task router): keep every instance whose tag list
    intersects ``filter_tag``.

    Dense+lengths redesign of the LoD interface: ``ins`` [N, D] rows with
    optional ``ins_lengths`` grouping rows into instances (is_lod=True ≙
    the reference's LoD level; default one row per instance), ``ins_tag``
    flat tag ids with ``tag_lengths`` per instance. Host op (the reference
    kernel is CPU-only). Returns (out rows, index_map [kept, 3] rows of
    (out_start, in_start, length), loss_weight [kept, 1]); when nothing
    matches, one zero row filled with ``out_val_if_empty`` and loss_weight
    0 (reference empty-case contract)."""
    x = np.asarray(unwrap(ins))
    tags = np.asarray(unwrap(ins_tag), np.int64).reshape(-1)
    ftag = set(np.asarray(unwrap(filter_tag), np.int64).reshape(-1).tolist())
    n_inst = (len(ins_lengths) if (is_lod and ins_lengths is not None)
              else x.shape[0])
    il = (np.asarray(ins_lengths, np.int64) if (is_lod and ins_lengths is not None)
          else np.ones(n_inst, np.int64))
    tl = (np.asarray(tag_lengths, np.int64) if tag_lengths is not None
          else np.ones(n_inst, np.int64))
    ins_starts = np.concatenate([[0], np.cumsum(il)[:-1]])
    tag_starts = np.concatenate([[0], np.cumsum(tl)[:-1]])

    rows, maps = [], []
    out_start = 0
    for i in range(n_inst):
        t = tags[tag_starts[i]: tag_starts[i] + tl[i]]
        if ftag.intersection(t.tolist()):
            s, ln = int(ins_starts[i]), int(il[i])
            rows.append(x[s: s + ln])
            maps.append([out_start, s, ln])
            out_start += ln
    if rows:
        out = np.concatenate(rows, axis=0)
        index_map = np.asarray(maps, np.int64)
        loss_weight = np.ones((len(maps), 1), np.float32)
    else:
        out = np.full((1, x.shape[1]), out_val_if_empty, x.dtype)
        index_map = np.zeros((1, 3), np.int64)
        loss_weight = np.zeros((1, 1), np.float32)
    return out, index_map, loss_weight


def sample_logits(logits, labels, num_samples, remove_accidental_hits=True,
                  use_customized_samples=False, customized_samples=None,
                  customized_probabilities=None, seed=None, name=None):
    """Sampled-softmax helper (sample_logits_op.h SampleLogitsKernel):
    gather the true-label and sampled-class logits, knock 1e20 off sampled
    columns that collide with a row's true labels, and subtract log q so a
    plain softmax-CE over [B, num_true + num_samples] with labels 0..T-1
    trains the full-vocab softmax.

    Sampling: shared log-uniform candidates with the expected-count
    probability q(v) = 1 - (1 - p(v))^num_samples (the reference's
    SampleWithProb draws unique candidates via retries; the closed form is
    the same expectation, TF candidate-sampler convention). Pass
    ``use_customized_samples`` for exact externally-chosen candidates.
    Returns (samples [B, T+S], probabilities, sampled_logits,
    sampled_labels [B, T] = arange(T))."""
    from ..random import split_key

    lg = unwrap(logits)
    lbl = np.asarray(unwrap(labels), np.int64)
    if lbl.ndim == 1:
        lbl = lbl[:, None]
    bsz, n_true = lbl.shape
    nc = int(lg.shape[1])
    s = int(num_samples)

    if use_customized_samples:
        samples = np.asarray(unwrap(customized_samples), np.int64)
        probs = np.asarray(unwrap(customized_probabilities))
    else:
        key = (jax.random.PRNGKey(int(seed)) if seed is not None
               else split_key())
        u = np.asarray(jax.random.uniform(key, (s,)))
        log_range = np.log(nc + 1.0)
        cand = np.clip(np.exp(u * log_range).astype(np.int64) - 1, 0, nc - 1)
        samples = np.concatenate(
            [lbl, np.broadcast_to(cand, (bsz, s))], axis=1)
        p = np.log((samples + 2.0) / (samples + 1.0)) / log_range
        probs = 1.0 - np.power(1.0 - p, s)

    @primitive(aux=3)
    def _sl(lg, samples, probs):
        sam = jnp.asarray(samples, jnp.int32)
        sl = jnp.take_along_axis(lg, sam, axis=1)
        if remove_accidental_hits:
            true_part = sam[:, :n_true]                     # [B, T]
            hits = (sam[:, None, n_true:] == true_part[:, :, None]).any(1)
            sl = sl.at[:, n_true:].add(jnp.where(hits, -1e20, 0.0))
        sl = sl - jnp.log(jnp.maximum(jnp.asarray(probs, sl.dtype), 1e-30))
        sl = jnp.clip(sl, -1e10, 1e10)  # TolerableValue
        lbls = jnp.broadcast_to(jnp.arange(n_true, dtype=jnp.int64),
                                (lg.shape[0], n_true))
        return sl, jnp.asarray(samples), jnp.asarray(probs), lbls

    sl, sam, pr, lab = _sl(lg, samples, probs)
    return sam, pr, sl, lab
